"""Word frequency — the reference's hello-world pipeline
(``examples/wordfreq.cpp:64-121``): map files → collate → reduce(sum) →
sort by count → top-N.

Two paths through the same MapReduce algebra:

* :func:`wordfreq` — host-callback path, byte-string words as keys
  (exactly the reference's flow: fileread callback emitting one KV per word,
  sum reduce, descending value sort, gather to 1, print top N).
* :func:`wordfreq_interned` — device path: words interned to u64 ids
  (BytesColumn.intern) so collate/reduce run columnar; the id→word
  dictionary decodes the top-N at the end.  This is what scales on TPU.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.column import BytesColumn, DenseColumn
from ..core.mapreduce import MapReduce
from .common import top_n
from ..utils.io import read_words


def _fileread(itask, filename, kv, ptr):
    """Emit (word, 1) per word of the file (reference fileread,
    examples/wordfreq.cpp:125-151)."""
    with open(filename, "rb") as f:
        for w in read_words(f.read()):
            kv.add(w, 1)


def _sum(key, values, kv, ptr):
    """(word, [1,1,...]) → (word, count) (reference sum,
    examples/wordfreq.cpp:158-162)."""
    kv.add(key, sum(values))


def wordfreq(files: Sequence[str], ntop: int = 10, comm=None,
             quiet: bool = True) -> Tuple[int, int, List[Tuple[bytes, int]]]:
    """Returns (nwords_total, nunique, top list of (word, count))."""
    mr = MapReduce(comm)
    nwords = mr.map_files(list(files), _fileread)
    mr.collate()
    nunique = mr.reduce(_sum)
    top = [(k, int(v)) for k, v in top_n(mr, ntop)]
    if not quiet:
        print(f"{nwords} total words, {nunique} unique words")
        for w, c in top:
            print(f"{c} {w.decode(errors='replace')}")
    return nwords, nunique, top


def wordfreq_interned(files: Sequence[str], ntop: int = 10, comm=None
                      ) -> Tuple[int, int, List[Tuple[bytes, int]]]:
    """Device-path wordfreq: u64-interned words, columnar count reduce."""
    import jax.numpy as jnp

    from ..ops.segment import kmv_segment_ids, segment_reduce

    from .. import native

    mr = MapReduce(comm)
    vocab = {}

    def _guard(h, w):
        prev = vocab.get(h)
        if prev is not None and prev != w:
            raise ValueError(
                "64-bit intern collision between %r and %r" % (prev, w))
        vocab[h] = w

    def fileread_ids(itask, filename, kv, ptr):
        with open(filename, "rb") as f:
            raw = f.read()
        if native.available():
            # zero per-token Python: C++ tokenizer + in-place range
            # interning; only each file's UNIQUE words slice out for the
            # vocab (the decode dict for the top-N output)
            data = np.frombuffer(raw, np.uint8)
            starts, lens = native.tokenize(data)
            ids = native.intern_ranges(data, starts, lens)
            uniq, first = np.unique(ids, return_index=True)
            for h, fi in zip(uniq.tolist(), first.tolist()):
                _guard(h, raw[starts[fi]:starts[fi] + lens[fi]])
            kv.add_batch(ids, np.ones(len(ids), np.int64))
            return
        col, table = BytesColumn(read_words(raw)).intern()
        for h, w in table.items():  # cross-file collision guard
            _guard(h, w)
        kv.add_batch(col, np.ones(len(col.data), np.int64))

    nwords = mr.map_files(list(files), fileread_ids)
    mr.collate()
    from ..ops.reduces import count
    nunique = mr.reduce(count, batch=True)
    mr.gather(1)
    mr.sort_values(-1)
    top = []

    def take(k, v, ptr):
        if len(top) < ntop:
            top.append((vocab[int(k)], int(v)))

    mr.scan_kv(take)
    return nwords, nunique, top
