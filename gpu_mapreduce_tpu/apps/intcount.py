"""IntCount — integer-key counting over binary data.

Reference: ``cpu/IntCount.cpp`` — each rank freads a 128 MB binary file,
adds every 4-byte window as an int key with value 1 (``:179-180``), then
``aggregate`` + ``convert`` (the measured stages; the count reduce is
present but commented out, ``:79-92``).  The workload is a pure shuffle/
group stress: maximum key cardinality, minimum per-key payload.

TPU-native redesign: the file view is one ``np.frombuffer`` u32 column
(no per-int loop), counting is a vectorised ``count`` reduce, and on a
mesh the aggregate rides the ICI collective shuffle.  We also finish the
job (count + optional top-N) rather than stopping at convert.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.mapreduce import MapReduce
from ..oink.kernels import count
from .common import top_n


def _map_file(itask, filename, kv, ptr):
    data = np.fromfile(filename, dtype=np.uint32)
    kv.add_batch(data.astype(np.uint64),
                 np.ones(len(data), np.uint32))


def intcount(paths: Sequence[str], ntop: int = 0, comm=None
             ) -> Tuple[int, int, List[Tuple[int, int]]]:
    """Count u32 keys across binary files.  Returns (nints, nunique,
    top) where top is the ntop most frequent (key, count) pairs."""
    mr = MapReduce(comm)
    nints = mr.map_files(list(paths), _map_file)
    mr.aggregate(None)
    mr.convert()
    nunique = mr.reduce(count, batch=True)
    top: List[Tuple[int, int]] = []
    if ntop:
        top = [(int(k), int(v)) for k, v in top_n(mr, ntop)]
    return nints, nunique, top
