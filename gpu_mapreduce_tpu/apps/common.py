"""Shared app helpers."""

from __future__ import annotations

from typing import List, Tuple


def top_n(mr, ntop: int) -> List[Tuple[object, object]]:
    """Gather to one shard, sort by value descending, take the first ntop
    (key, value) pairs — the reference's top-N tail (gather(1) +
    sort_values + bounded print, examples/wordfreq.cpp:100-116)."""
    mr.gather(1)
    mr.sort_values(-1)
    top: List[Tuple[object, object]] = []

    def take(k, v, ptr):
        if len(top) < ntop:
            top.append((k, v))

    mr.scan_kv(take)
    return top
