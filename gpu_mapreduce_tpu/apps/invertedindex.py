"""InvertedIndex — the reference's flagship GPU application, TPU-native.

Pipeline (reference ``cuda/InvertedIndex.cu:140-202``, call stack SURVEY.md
§3.6): find every ``<a href="..."`` URL in an HTML corpus (device kernels),
emit (url, doc) pairs; ``aggregate`` shuffles URLs across chips;
``convert`` groups; ``reduce`` writes ``url \\t file file...`` lines to
per-proc output files (``:463-513``).

TPU re-design of the map stage (round 2).  The reference dispatches four
GPU stages per 64 MB chunk plus a host kv->add loop (mark 4 ms + copy_if
14 ms + length 8 ms + add 18 ms, ``cuda/InvertedIndex.cu:337-384``).  Here
the WHOLE corpus map stage is ONE fused XLA program over a u32-resident
buffer:

    mark (word-packed Pallas kernel, 4 bytes/lane)
    → compact (jnp.nonzero on the 4×-smaller word mask)
    → URL windows as unaligned u32 loads (no byte arrays on device)
    → closing-quote scan + masked lookup3 → u64 URL ids ON DEVICE
    → doc ids by searchsorted over file offsets
    → valid-row packing

Device-resident output: the packed (url_id, doc_id) columns feed the mesh
backend's sharded KV directly — no device→host round trip anywhere in the
map stage.  URL *bytes* are sliced from the host copy of the corpus only
when an output dictionary is actually needed; the device and host interns
produce bit-identical u64 ids (ops/hash.py), so the tiers interoperate.

One dispatch instead of ~4/chunk matters doubly here: each dispatch to the
chip costs ~10s of ms of launch latency in tunneled setups, and XLA can
overlap/fuse the stages it can see.
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.mapreduce import MapReduce
from .. import native
from ..ops.hash import hash_bytes64_masked
from ..ops.pallas.match import (DEFAULT_COMPACT, MARK_PAGE_WORDS,
                                bytes_view_u32,
                                compact_word_matches, first_byte_pos,
                                mark_words_pallas, mark_words_xla,
                                mask_words_to_length, unaligned_words)
from ..utils.io import findfiles
from ..utils.platform import is_tpu_backend

PATTERN = b'<a href="'
QUOTE = ord('"')
MAX_URL = 256               # longest URL matched; window-gather cost on the
                            # device path is ∝ this (26ns/byte-lane on v5e),
                            # so keep it at the realistic URL bound, not the
                            # reference's unbounded scan
URL_DICT_MAX = 64 << 20     # auto-build the url-bytes dict below this size

_GAP = MAX_URL + len(PATTERN)  # zero gap between files: no cross-file
                               # matches, and a URL window never bleeds
                               # into the next file (reference scans each
                               # file separately)
_BS = 4096                     # rows per lax.map step in the window stage


def _floor_pow2(n: int) -> int:
    return 1 << (max(1, n).bit_length() - 1)


def _env_knobs():
    """On-chip A/B knobs, read at BUILDER-call time — outside every
    lru_cache/jit cache, so toggling one of these within a process takes
    effect on the next run() instead of silently reusing the old trace:

    MR_COMPACT       'blocked' (default) | 'scatter' | 'searchsorted'
    MR_WINDOW_BS     rows per lax.map window step, floored to a power of
                     two (caps are powers of two, so the reshape divides)
    MR_MARK_PAGE_WORDS  Pallas mark page size (ops/pallas/match.py)
    """
    compact = os.environ.get("MR_COMPACT", DEFAULT_COMPACT)
    bs_raw = int(os.environ.get("MR_WINDOW_BS", _BS))
    page_words = int(os.environ.get("MR_MARK_PAGE_WORDS",
                                    MARK_PAGE_WORDS))
    # fail FAST on nonsense values, like MR_COMPACT does on a typo — a
    # zero page size would only surface as a ZeroDivisionError deep in
    # the mark paging and silently mismeasure an A/B run (ADVICE r4)
    if bs_raw <= 0:
        raise ValueError(f"MR_WINDOW_BS={bs_raw}: must be > 0")
    if page_words <= 0:
        raise ValueError(f"MR_MARK_PAGE_WORDS={page_words}: must be > 0")
    return compact, _floor_pow2(bs_raw), page_words


def _build_corpus(files: Sequence[str]):
    """Concatenate files with zero gaps; returns (bytes, file data starts).

    Byte offsets travel as int32 on device (i32 is what the VPU lanes and
    the compaction scatter want); one corpus is therefore capped at 2 GiB —
    callers with more data run multiple corpora (the reference likewise
    works in per-process file batches, cuda/InvertedIndex.cu:284-287)."""
    pieces: List[np.ndarray] = []
    starts = np.zeros(len(files), np.int64)
    gap = np.zeros(_GAP, np.uint8)
    off = 0
    for i, f in enumerate(files):
        with open(f, "rb") as fh:
            data = np.frombuffer(fh.read(), np.uint8)
        starts[i] = off
        pieces.append(data)
        pieces.append(gap)
        off += len(data) + _GAP
    if off >= (1 << 31):
        raise ValueError(
            f"corpus is {off} bytes; the fused device path indexes bytes "
            f"with int32 — split the file list into < 2 GiB batches")
    corpus = (np.concatenate(pieces) if pieces
              else np.zeros(0, np.uint8))
    return corpus, starts.astype(np.int32)


_W_SHORT = 16      # 64-byte first-tier URL window (covers typical URLs)


def _extract_fn(cap: int, use_pallas: bool, interpret: bool):
    """The fused map stage (see module docstring).  jit re-specialises per
    (corpus words, nfiles) shape; `cap` is the static hit capacity.

    The URL window gather is the dominant cost (~26 ns per gathered lane
    on v5e), so it is TWO-TIER: a 64-byte window first — enough for
    almost every real URL — then a second 256-byte gather over only the
    rows whose closing quote was not in the first window.  A long-tail
    overflow (more than cap/4 such rows) is returned so the caller can
    retry with the full window for every row."""
    return _extract_build(cap, use_pallas, interpret, False, *_env_knobs())


def _extract_wide_fn(cap: int, use_pallas: bool, interpret: bool):
    """Fallback: full 256-byte windows for every row (used when the
    long-tail capacity overflows — long-URL-dense corpora)."""
    return _extract_build(cap, use_pallas, interpret, True, *_env_knobs())


def _extract_core(words, file_starts, *, cap: int, use_pallas: bool,
                  interpret: bool, wide: bool,
                  compact: str = DEFAULT_COMPACT,
                  bs: int = _BS, page_words: int = MARK_PAGE_WORDS):
    """The fused map-stage computation over ONE shard's corpus words.
    Shared by the single-device jit (_extract_build) and the mesh SPMD
    program (_extract_mesh_fn) — identical math, so the tiers and the
    mesh shards produce bit-identical ids.  compact/bs/page_words are
    the A/B knobs (_env_knobs) — part of every builder cache key."""
    bs = min(_floor_pow2(bs), cap)
    nw = MAX_URL // 4
    w1 = nw if wide else _W_SHORT
    cap_long = max(8, cap // 4)

    def _hash2(win, length):
        l0 = jnp.maximum(length, 0)
        wm = mask_words_to_length(win, l0)
        ids = hash_bytes64_masked(wm, l0)
        # independent id family: any real u64 intern collision shows as
        # one id with two alt-ids (checked after packing, no bytes kept)
        alt = hash_bytes64_masked(wm, l0, 0x9E3779B9, 0x85EBCA6B)
        return ids, alt

    m = words.shape[0]
    nbytes = 4 * m
    wmask = (mark_words_pallas(words, PATTERN, interpret=interpret,
                               page_words=page_words)
             if use_pallas else mark_words_xla(words, PATTERN))
    starts, nhits = compact_word_matches(wmask, nbytes, cap, mode=compact)
    ustarts = starts + np.int32(len(PATTERN))

    def body(st):
        win = unaligned_words(words, st, w1)
        length = first_byte_pos(win, QUOTE)
        ids, alt = _hash2(win, length)
        return ids, alt, length

    ids, alts, lengths = lax.map(body, ustarts.reshape(-1, bs))
    ids = ids.reshape(-1)
    alts = alts.reshape(-1)
    lengths = lengths.reshape(-1)

    if wide:
        nlong = jnp.int32(0)
    else:
        # long tail: quote beyond the 64-byte window → re-gather 256 B.
        # Under lax.cond since r4: at PUMA density nlong is 0 and the
        # skipped branch saves cap/4 rows x 65-word random gathers — the
        # skip is exact because the nlong==0 regather was a no-op anyway
        # (every lidx == cap scatters with mode="drop").
        is_long = (lengths < 0) & (starts < nbytes)
        nlong = jnp.sum(is_long.astype(jnp.int32))

        def _regather(ids, alts, lengths):
            pos = jnp.cumsum(is_long.astype(jnp.int32)) - 1
            tgt = jnp.where(is_long & (pos < cap_long), pos, cap_long)
            lidx = jnp.full(cap_long, cap, jnp.int32).at[tgt].set(
                jnp.arange(cap, dtype=jnp.int32), mode="drop")
            lst = jnp.where(lidx < cap,
                            jnp.take(ustarts, jnp.minimum(lidx, cap - 1)),
                            jnp.int32(nbytes))
            lwin = unaligned_words(words, lst, nw)
            lln = first_byte_pos(lwin, QUOTE)
            lln = jnp.where(lln >= _W_SHORT * 4, lln, jnp.int32(-1))
            lids, lalt = _hash2(lwin, lln)
            return (ids.at[lidx].set(lids, mode="drop"),
                    alts.at[lidx].set(lalt, mode="drop"),
                    lengths.at[lidx].set(lln, mode="drop"))

        ids, alts, lengths = lax.cond(
            nlong > 0, _regather, lambda i, a, l: (i, a, l),
            ids, alts, lengths)
        # nlong returns RAW (callers compare against cap_long): the
        # stats must show the second gather ran even below the
        # wide-retry threshold
    docs = (jnp.searchsorted(file_starts, starts, side="right")
            .astype(jnp.int32) - 1)
    valid = (starts < nbytes) & (lengths >= 0)
    npairs = jnp.sum(valid.astype(jnp.int32))
    order = jnp.argsort(~valid, stable=True)   # valid rows first
    pack = lambda x: jnp.take(x, order, axis=0)
    pids, palts = pack(ids), pack(alts)
    # collision check fused into the same dispatch (one id sort over
    # cap rows — cheap next to the corpus passes, and it saves a
    # round trip per run); multi-batch runs re-check globally
    ncoll = _count_collisions(pids, palts, jnp.arange(cap) < npairs)
    return (pids, palts, pack(docs).astype(jnp.uint32),
            pack(ustarts), pack(lengths), nhits, npairs, ncoll, nlong)


@functools.lru_cache(maxsize=None)
def _extract_build(cap: int, use_pallas: bool, interpret: bool,
                   wide: bool = False, compact: str = DEFAULT_COMPACT,
                   bs: int = _BS, page_words: int = MARK_PAGE_WORDS):
    return jax.jit(functools.partial(
        _extract_core, cap=cap, use_pallas=use_pallas,
        interpret=interpret, wide=wide, compact=compact, bs=bs,
        page_words=page_words))


def _extract_mesh_fn(mesh, cap: int, use_pallas: bool, interpret: bool,
                     wide: bool):
    """Per-device ingestion (VERDICT r2 #2) — see _extract_mesh_build;
    this uncached wrapper resolves the A/B env knobs into the cache key."""
    return _extract_mesh_build(mesh, cap, use_pallas, interpret, wide,
                               *_env_knobs())


@functools.lru_cache(maxsize=None)
def _extract_mesh_build(mesh, cap: int, use_pallas: bool, interpret: bool,
                        wide: bool, compact: str, bs: int, page_words: int):
    """Per-device ingestion (VERDICT r2 #2): ONE SPMD program runs the
    fused extract on every shard's own corpus block — the reference's
    'each rank maps its own files on its own GPU'
    (cuda/InvertedIndex.cu:284-312) as a shard_map.  Global inputs:
    words [P*W] (each shard's padded corpus), fstarts [P*F], doc base
    [P]; outputs are the packed per-shard columns [P*cap] plus [P]
    per-shard stats, all row-sharded — nothing materialises on the
    controller."""
    from ..parallel.mesh import row_spec
    rspec = row_spec(mesh)

    def body(words, fstarts, base):
        (ids, alts, docs, ustarts, lengths, nhits, npairs, ncoll,
         nlong) = _extract_core(words, fstarts, cap=cap,
                                use_pallas=use_pallas,
                                interpret=interpret, wide=wide,
                                compact=compact, bs=bs,
                                page_words=page_words)
        docs = docs + base[0].astype(jnp.uint32)
        # ONE [4] stats vector per shard: the cap-retry loop pulls it with
        # a single device_get instead of four per-array transfers — over
        # the tunnel each round-trip sits inside the TIMED map stage
        stats = jnp.stack([nhits, npairs, ncoll, nlong]).astype(jnp.int32)
        return (ids, alts, docs, ustarts, lengths, stats)

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, which the checker would otherwise reject
    sm = jax.shard_map(body, mesh=mesh, in_specs=(rspec, rspec, rspec),
                       out_specs=(rspec,) * 6, check_vma=False)
    return jax.jit(sm)


def _count_collisions(ids, alts, valid):
    """Traceable: #ids carrying two different alt-ids among valid rows —
    a real 64-bit intern collision (shared by the fused extract and the
    multi-batch global check)."""
    order = jnp.lexsort((alts, jnp.where(valid, ids, jnp.uint64(0)),
                         ~valid))
    a = jnp.take(ids, order)
    b = jnp.take(alts, order)
    v = jnp.take(valid, order)
    return jnp.sum(((a[1:] == a[:-1]) & (b[1:] != b[:-1])
                    & v[1:] & v[:-1]).astype(jnp.int32))


def _balance_files(files: Sequence[str], P: int):
    """Split the file list into P CONTIGUOUS chunks of ~equal bytes (the
    reference's consecutive per-proc file ranges,
    cuda/InvertedIndex.cu:284-287).  Returns [(first_index, files,
    sizes)]*P — the shared policy of parallel/ingest.balance_by_bytes
    (one implementation, so the two ingest paths cannot diverge)."""
    from ..parallel.ingest import balance_by_bytes
    return balance_by_bytes(files, P)


def _bucket_words(nwords: int) -> int:
    """Round a shard corpus word count up to a size bucket so shards (and
    successive rounds) share one compiled SPMD program: next power of two
    below 1M words, else next 1M-word (4 MB) multiple — ≤0.4% padding at
    the 1 GiB batch cap."""
    n = max(nwords, 64)
    if n <= (1 << 20):
        return 1 << (n - 1).bit_length()
    g = 1 << 20
    return -(-n // g) * g


# Per-message cap for corpus H2D (words; 8 MW = 32 MB).  The round-4 TPU
# window transferred the 8 MB proof corpus fine but the bench died at its
# single 256 MB shard transfer (raise on the pallas attempt, silent hang on
# the xla retry) — consistent with the axon tunnel failing on large single
# messages.  Each shard's block therefore travels as bounded device_put
# chunks concatenated ON the target device; MR_H2D_CHUNK_WORDS overrides.
H2D_CHUNK_WORDS = 1 << 23


def _h2d_sharded(words_host, W: int, P: int, sharding):
    """Build the row-sharded global corpus [P*W] from per-shard host
    buffers, each transferred to its own device in ≤H2D_CHUNK_WORDS
    messages (no [P*W] host concatenation, no unbounded single transfer)."""
    chunk_w = int(os.environ.get("MR_H2D_CHUNK_WORDS", H2D_CHUNK_WORDS))
    if chunk_w <= 0:
        raise ValueError(f"MR_H2D_CHUNK_WORDS={chunk_w}: must be > 0")
    dmap = sharding.addressable_devices_indices_map((P * W,))
    shards = []
    for dev, idx in dmap.items():
        p = (idx[0].start or 0) // W
        host = words_host[p]
        if W > chunk_w:
            parts = [jax.device_put(host[o:o + chunk_w], dev)
                     for o in range(0, W, chunk_w)]
            shards.append(jnp.concatenate(parts))
        else:
            shards.append(jax.device_put(host, dev))
    return jax.make_array_from_single_device_arrays(
        (P * W,), sharding, shards)


def _shard_blocks(arr, P: int):
    """Per-shard host copies of a row-sharded global array [P*cap] —
    device_get of each addressable shard, no global gather."""
    cap = arr.shape[0] // P
    out = [None] * P
    for sh in arr.addressable_shards:
        p = (sh.index[0].start or 0) // cap
        out[p] = np.asarray(sh.data)
    return out


def _mesh_collision_count(checks) -> int:
    """Global cross-shard/cross-round intern-collision count over per-
    round sharded (ids, alts, counts) triples — one jitted sort, XLA
    inserts the gather collectives; only the scalar reaches the host."""
    ids = [c[0] for c in checks]
    alts = [c[1] for c in checks]
    valids = []
    for ids_g, _, counts in checks:
        cap = ids_g.shape[0] // len(counts)
        v = (np.arange(cap)[None, :] < counts[:, None]).reshape(-1)
        valids.append(jnp.asarray(v))

    @jax.jit
    def count(ids, alts, valids):
        return _count_collisions(jnp.concatenate(ids),
                                 jnp.concatenate(alts),
                                 jnp.concatenate(valids))

    return int(count(ids, alts, valids))


def _url_dict_wanted(files, want_urls: bool) -> bool:
    """One policy for both tiers: keep URL bytes when output needs them
    or the corpus is small (URL_DICT_MAX)."""
    return want_urls or sum(os.path.getsize(f) for f in files) \
        <= URL_DICT_MAX


def _host_collision_count(ids: np.ndarray, alts: np.ndarray) -> int:
    """#ids carrying two different alt-ids (u64 intern collisions) —
    host twin of _count_collisions, shared by both tiers."""
    order = np.lexsort((alts, ids))
    a, b = ids[order], alts[order]
    return int(((a[1:] == a[:-1]) & (b[1:] != b[:-1])).sum())


def _assemble_parts(parts):
    """Merge per-batch packed device columns into one packed column set.
    Single batch (the common case) is zero-copy; multi-batch concatenates
    the valid row slices on device and re-pads to a power-of-two cap."""
    if len(parts) == 1:
        return parts[0]
    ntot = sum(p[3] for p in parts)
    cap = max(8, 1 << (ntot - 1).bit_length()) if ntot else 8

    def cat(i):
        pieces = [p[i][:p[3]] for p in parts]
        tail = cap - ntot
        if tail:
            pieces.append(jnp.zeros((tail,), pieces[0].dtype))
        return jnp.concatenate(pieces)

    return cat(0), cat(1), cat(2), ntot


class StageTimer:
    """Cumulative wall-clock per pipeline stage (reference instrument:
    gettimeofday/cudaEvent pairs around each kernel,
    cuda/InvertedIndex.cu:337,360,369,384).

    Thread-safe: the native map tier runs callbacks from mapstyle-2
    worker threads.  ``times`` sums per-invocation durations (CPU-time-
    like under parallelism).  Stages mapped to a *group* additionally
    maintain an online span union — :meth:`wall` returns the elapsed
    time during which at least one thread was inside any stage of the
    group (the honest parallel metric; equals the plain sum when
    serial).  Computed with an active-thread counter, O(1) memory —
    no span list to grow with task count."""

    def __init__(self, groups: Optional[Dict[str, str]] = None):
        import threading
        self.times: Dict[str, float] = {}
        self._groups = groups or {}        # stage name → group name
        self._gactive: Dict[str, tuple] = {}  # group → (depth, t_enter)
        self._gwall: Dict[str, float] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str):
        g = self._groups.get(name)
        # each stage is also a tracer span (obs/), so an app run under
        # MRTPU_TRACE shows its pipeline stages next to the MR-op spans;
        # the with-statement keeps exception attribution and the
        # thread-local span stack correct when a stage raises
        from ..obs import get_tracer
        with get_tracer().span("stage." + name, cat="app"):
            t0 = time.perf_counter()
            if g is not None:
                with self._lock:
                    depth, ts = self._gactive.get(g, (0, 0.0))
                    self._gactive[g] = (depth + 1, t0 if depth == 0 else ts)
            try:
                yield
            finally:
                t1 = time.perf_counter()
                with self._lock:
                    self.times[name] = self.times.get(name, 0.0) + t1 - t0
                    if g is not None:
                        depth, ts = self._gactive[g]
                        if depth == 1:
                            self._gwall[g] = self._gwall.get(g, 0.0) \
                                + t1 - ts
                        self._gactive[g] = (depth - 1, ts)

    def wall(self, group: str) -> float:
        """Accumulated span-union seconds of the named group."""
        with self._lock:
            return self._gwall.get(group, 0.0)


class InvertedIndex:
    """Builds an inverted URL→documents index over the MapReduce algebra."""

    def __init__(self, comm=None, use_pallas: Optional[bool] = None,
                 interpret: Optional[bool] = None,
                 engine: Optional[str] = None,
                 mapstyle: Optional[int] = None):
        """engine: 'pallas' (TPU kernels, default), 'xla' (jnp fallback),
        or 'native' (the C++ scanner of native/mrnative.cpp — the moral
        equivalent of the reference's cpu/InvertedIndex.cpp FSM baseline,
        and the host fallback when no accelerator is worth dispatching
        to).  mapstyle: map-task scheduling; the native engine defaults
        to 2 (thread-pool work queue — file reads, the C++ scan and the
        batch hashing all release the GIL, so files scan in parallel
        like the reference's one-rank-per-core MPI layout)."""
        import threading
        backend = jax.default_backend()
        if engine is None:
            engine = "pallas" if (use_pallas or use_pallas is None) \
                else "xla"
        if engine == "native" and not native.available():
            raise RuntimeError(f"native engine unavailable: "
                               f"{native.build_error()}")
        self.engine = engine
        self.use_pallas = engine == "pallas"
        bb = os.environ.get("MR_BATCH_BYTES")
        if bb:
            # lowered per-corpus cap: proves the multi-batch ingestion
            # machinery on a flaky tunnel without shipping 2 GiB through
            # it.  LOWER-only: raising past the class cap would overflow
            # the int32 byte offsets the 1<<30 invariant protects.
            self._BATCH_BYTES = min(int(bb), InvertedIndex._BATCH_BYTES)
        if interpret is None:
            # CPU tests interpret the kernel; real hardware (including the
            # axon plugin backend) must compile via Mosaic — interpret mode
            # on chip would silently invalidate any benchmark number
            interpret = not is_tpu_backend(backend)
        self.interpret = interpret
        self.comm = comm
        self.mapstyle = (2 if engine == "native" else 0) \
            if mapstyle is None else mapstyle
        self._urls: Dict[int, bytes] = {}
        # mesh runs shard the url dict BY DESTINATION SHARD (the same
        # hash%P the aggregate routes keys with), so per-shard output
        # decodes from its own dict and no global url dict ever
        # assembles on the controller (VERDICT r3 #7)
        self.shard_urls: Optional[List[Dict[int, bytes]]] = None
        self.docs: List[str] = []
        self.npairs = 0
        # scan+hash form the "map_kernels" wall group: bench.py compares
        # its span union against the reference's 44 ms kernel boundary
        self.timer = StageTimer(groups={"native_scan": "map_kernels",
                                        "host_add": "map_kernels"})
        self._intern_lock = threading.Lock()
        self._compact_lock = threading.Lock()
        self._keep_bytes = True
        # sorted runs of unique (id, alt-id) pairs when the url dict is
        # skipped — compacted on a doubling trigger so host memory stays
        # bounded by the UNIQUE url count on exactly the large-corpus
        # path (ADVICE r2); see _fold_id_check
        self._chk_tails: List[tuple] = []     # raw (ids, alts) batches
        self._chk_sorted: Optional[tuple] = None   # standing deduped run
        self._chk_raw = self._chk_base = 0
        self._reset_stats()

    def _reset_stats(self):
        # map-stage machinery counters, surfaced by bench.py's detail
        # record (VERDICT r2 #9): batches processed, hit-capacity
        # retries, wide-window fallbacks, largest RAW long-tail count
        self.stats = {"nbatches": 0, "cap_retries": 0,
                      "wide_fallbacks": 0, "nlong_max": 0}

    # -- map stage: native (host C++) tier --------------------------------
    # device alt-id seed family (see _extract_build): the host twin uses
    # the same seeds so both tiers' collision checks are comparable
    _ALT_HI, _ALT_LO = 0x9E3779B9, 0x85EBCA6B

    def _map_file_native(self, itask, filename, kv, ptr):
        """Thread-safe under mapstyle 2: doc id is the task id (docs are
        preset in run()), the url dict is lock-guarded, and everything
        between — read, C++ scan, batch hash — releases the GIL."""
        with open(filename, "rb") as f:
            data = np.frombuffer(f.read(), dtype=np.uint8)
        doc_id = itask
        if len(data) == 0:
            return
        with self.timer.stage("native_scan"):
            starts, lengths = native.find_hrefs(data)
        # device path drops URLs whose terminator is not WITHIN its
        # MAX_URL-byte window (max representable length MAX_URL-1); match
        # that instead of silently truncating
        lengths = np.where(lengths >= MAX_URL, -1, lengths)
        with self.timer.stage("host_add"):
            keep = lengths >= 0  # unterminated href: reference runs off; we drop
            kst, kln = starts[keep], lengths[keep]
            if self._keep_bytes:
                # zero-copy: hash URLs straight out of the file buffer
                # (the native engine implies the C++ runtime is loaded)
                ids = native.intern_ranges(data, kst, kln)
                urls = [data[s:s + l].tobytes()
                        for s, l in zip(kst.tolist(), kln.tolist())]
                with self._intern_lock:
                    self._intern(ids, urls)
            else:
                # no url dict (URL_DICT_MAX policy, like the device
                # tier): an independent alt-id family is folded into the
                # running unique set so u64 intern collisions are still
                # detected without holding per-file arrays; both
                # families hash in one pass over the URL bytes
                ids, alts = native.intern_ranges2(data, kst, kln,
                                                  self._ALT_HI,
                                                  self._ALT_LO)
                self._fold_id_check(ids, alts)
            kv.add_batch(ids, np.full(len(ids), doc_id, dtype=np.uint32))

    # compaction trigger floor: below this many accumulated pairs a
    # compact costs less than the bookkeeping it saves
    _CHK_MIN_COMPACT = 1 << 16

    def _fold_id_check(self, ids, alts):
        """Record a batch of (id, alt) pairs for collision checking; a
        collision is one id carrying two alt values.  Hot-loop cost is
        ONE lock-guarded list append — ALL sorting/checking happens in
        :meth:`_compact_chk_runs`, triggered when the accumulated raw
        pairs exceed twice the last compacted (deduped) size and once
        at map close, so any collision still surfaces before ``run()``
        returns.  Amortised O(N log N) total; host memory stays bounded
        by ~2× the unique pair count plus one batch (the ADVICE r2
        bound) — duplicates only accelerate the next compaction.  r3's
        per-batch LSM probe of every run paid ~60% of ``host_add`` on
        the 256 MB bench (VERDICT r3 weak #1); r4 moved the remaining
        per-batch sort here too."""
        if not len(ids):
            return
        with self._intern_lock:
            self._chk_tails.append((ids, alts))
            self._chk_raw += len(ids)
            # _chk_raw counts TAILS only (the standing run left it when
            # compaction went merge-based), so fire when tails reach
            # the run size: resident ≈ 2x unique, the ADVICE r2 bound
            trigger = self._chk_raw > max(self._chk_base,
                                          self._CHK_MIN_COMPACT)
        if trigger:
            self._compact_chk_runs()

    # mrlint: disable=lock-unguarded-mutation — only called from run()'s
    # single-threaded phases: before map_files spawns the mapper pool
    # and after it joins; the locked sites are the pool's
    def _reset_chk(self, counters: bool) -> None:
        """Drop the url-dict check accumulators between phases
        (``counters=True`` also zeroes the cumulative raw/base stats —
        the start-of-run reset; the post-compaction reset keeps them)."""
        self._chk_tails = []
        self._chk_sorted = None
        if counters:
            self._chk_raw = self._chk_base = 0

    def _compact_chk_runs(self):
        """Fold the recorded raw tails into the standing sorted deduped
        run, raising if any id carries two distinct alt values.  Only
        the TAIL is sorted (O(T log T)); the standing run merges in by
        rank — two searchsorteds + scatters, O(N + T log N) — instead
        of re-sorting everything (the at-volume profile showed the
        repeated full sorts dominating ``host_add`` at 2 GiB).  Sorting
        by id alone suffices: within an equal-id region any two
        distinct alts produce some unequal adjacent pair whatever the
        alt order, and the merged adjacent check also catches
        run-vs-tail collisions.  The tail list is swapped out under
        ``_intern_lock`` but the sort/merge runs OUTSIDE it, so
        mapstyle-2 mapper threads keep appending during a compaction
        (r4 review); ``_compact_lock`` keeps compactions serial."""
        with self._compact_lock:
            with self._intern_lock:
                tails, self._chk_tails = self._chk_tails, []
            if not tails:
                return
            ti = np.concatenate([t[0] for t in tails])
            ta = np.concatenate([t[1] for t in tails])
            taken = len(ti)
            o = np.argsort(ti)               # introsort: 5x stable on u64
            ti, ta = ti[o], ta[o]
            if self._chk_sorted is not None:
                ri, ra = self._chk_sorted
                n, t = len(ri), len(ti)
                # merge by rank: run elements first on ties, so the two
                # position families are disjoint and cover [0, n+t)
                pos_r = np.searchsorted(ti, ri, side="left") \
                    + np.arange(n, dtype=np.int64)
                pos_t = np.searchsorted(ri, ti, side="right") \
                    + np.arange(t, dtype=np.int64)
                mi = np.empty(n + t, ri.dtype)
                ma = np.empty(n + t, ra.dtype)
                mi[pos_r], ma[pos_r] = ri, ra
                mi[pos_t], ma[pos_t] = ti, ta
            else:
                mi, ma = ti, ta
            same = mi[1:] == mi[:-1]
            if (same & (ma[1:] != ma[:-1])).any():
                raise ValueError("64-bit URL intern collision(s) detected")
            keep = np.ones(len(mi), bool)
            keep[1:] = ~same                 # exact-duplicate pairs ok
            mi, ma = mi[keep], ma[keep]
            with self._intern_lock:
                self._chk_sorted = (mi, ma)
                self._chk_raw -= taken
                self._chk_base = len(mi)

    @property
    def urls(self) -> Dict[int, bytes]:
        """Merged id→bytes view over every tier's dict (sharded mesh
        dicts + the host tier's).  Merge-on-access: the hot paths use
        the per-shard dicts directly; this exists for cross-engine
        comparisons and debugging."""
        if self.shard_urls is None:
            return self._urls
        merged: Dict[int, bytes] = {}
        for d in self.shard_urls:
            merged.update(d)
        merged.update(self._urls)
        return merged

    def _intern(self, ids, urls):
        for h, url in zip(ids.tolist(), urls):
            prev = self._urls.get(h)
            if prev is not None and prev != url:
                raise ValueError(
                    f"64-bit URL intern collision: {prev!r} vs {url!r}")
            self._urls[h] = url

    def _intern_dest(self, dest, ids, urls):
        """Intern (id, bytes) into the per-destination-shard dicts —
        ``dest`` is the same hash%P the aggregate will route keys with,
        so shard d's output file later decodes every one of its groups
        from ``shard_urls[d]`` alone."""
        sd = self.shard_urls
        for d, h, url in zip(dest.tolist(), ids.tolist(), urls):
            prev = sd[d].get(h)
            if prev is not None and prev != url:
                raise ValueError(
                    f"64-bit URL intern collision: {prev!r} vs {url!r}")
            sd[d][h] = url

    # -- map stage: fused device tier -------------------------------------
    _BATCH_BYTES = 1 << 30   # per-corpus cap: byte offsets are int32

    def _file_batches(self, files, sizes=None):
        """Greedy contiguous file batches under the int32 corpus cap (the
        reference likewise works per-process file batches,
        cuda/InvertedIndex.cu:284-287).  ``sizes``: optional pre-statted
        byte counts aligned with ``files``."""
        if sizes is None:
            sizes = [os.path.getsize(f) for f in files]
        batches, cur, size = [], [], 0
        for f, fbytes in zip(files, sizes):
            fsz = int(fbytes) + _GAP
            if fsz > self._BATCH_BYTES:
                raise ValueError(
                    f"{f}: single file of {fsz} bytes exceeds the device "
                    f"corpus cap ({self._BATCH_BYTES})")
            if cur and size + fsz > self._BATCH_BYTES:
                batches.append(cur)
                cur, size = [], 0
            cur.append(f)
            size += fsz
        if cur:
            batches.append(cur)
        return batches

    def _map_corpus_mesh(self, mesh, files, kv, want_urls: bool):
        """Mesh-SPMD map stage: every shard ingests ITS contiguous slice
        of the file list and runs the fused extract on ITS device — the
        controller never assembles a global corpus (VERDICT r2 #2).  A
        shard's slice larger than the int32 corpus cap processes in
        rounds; each round appends one ShardedKV frame."""
        from ..parallel.mesh import mesh_axis_size, row_sharding
        from ..parallel.sharded import ShardedKV
        P = mesh_axis_size(mesh)
        self.docs = list(files)
        keep_bytes = _url_dict_wanted(files, want_urls)
        if keep_bytes:
            self.shard_urls = [{} for _ in range(P)]
        batch_lists = []
        for start, chunk, sizes in _balance_files(files, P):
            bl, base = [], start
            for b in (self._file_batches(chunk, sizes) if chunk else []):
                bl.append((base, b))
                base += len(b)
            batch_lists.append(bl)
        nrounds = max((len(b) for b in batch_lists), default=0)
        if nrounds == 0:
            return
        sharding = row_sharding(mesh)
        checks = []     # per-round (ids, alts, counts) for the global check
        for r in range(nrounds):
            per = []    # (doc_base, corpus, fstarts) per shard
            for p in range(P):
                if r < len(batch_lists[p]):
                    base, batch = batch_lists[p][r]
                    with self.timer.stage("read"):
                        corpus, fstarts = _build_corpus(batch)
                    self.stats["nbatches"] += 1
                    per.append((base, corpus, fstarts))
                else:
                    per.append((0, np.zeros(0, np.uint8),
                                np.zeros(0, np.int32)))
            max_bytes = max(len(c[1]) for c in per)
            if max_bytes == 0:
                continue
            W = _bucket_words(-(-max_bytes // 4))
            F = max(max(len(c[2]) for c in per), 1)
            words_host = []
            fstarts_host = np.full((P, F), np.int32(4 * W), np.int32)
            base_host = np.zeros(P, np.uint32)
            for p, (base, corpus, fstarts) in enumerate(per):
                w = bytes_view_u32(corpus)
                wp = np.zeros(W, np.uint32)
                wp[:len(w)] = w
                words_host.append(wp)
                fstarts_host[p, :len(fstarts)] = fstarts
                base_host[p] = base
            with self.timer.stage("h2d"):
                words_g = _h2d_sharded(words_host, W, P, sharding)
                fstarts_g = jax.device_put(fstarts_host.reshape(-1),
                                           sharding)
                base_g = jax.device_put(base_host, sharding)
                # timing-attribution sync only (keeps h2d out of the
                # timed map stage); MRTPU_DEFER_SYNC=1 defers it to the
                # extract's own stats pull so H2D overlaps dispatch
                from ..exec import maybe_block
                maybe_block(words_g)

            cap = max(8, 1 << (max(1, max_bytes // 1024) - 1).bit_length())
            wide = False
            with self.timer.stage("map_device"):
                while True:
                    fn = _extract_mesh_fn(mesh, cap, self.use_pallas,
                                          self.interpret, wide)
                    (ids, alts, docs, ustarts, lengths,
                     stats_g) = fn(words_g, fstarts_g, base_g)
                    nhits_h, npairs_h, ncoll_h, nlong_h = \
                        np.asarray(jax.device_get(stats_g)).reshape(P, 4).T
                    mx = int(nhits_h.max())
                    self.stats["nlong_max"] = max(self.stats["nlong_max"],
                                                  int(nlong_h.max()))
                    if mx > cap:
                        cap = max(8, 1 << (mx - 1).bit_length())  # retry
                        self.stats["cap_retries"] += 1
                    elif int(nlong_h.max()) > max(8, cap // 4):
                        wide = True   # a shard is long-URL-dense
                        self.stats["wide_fallbacks"] += 1
                    else:
                        break
                if int(ncoll_h.sum()):
                    raise ValueError(
                        f"{int(ncoll_h.sum())} 64-bit URL intern "
                        f"collision(s) detected")
            counts = npairs_h.astype(np.int32)
            kv.add_frame(ShardedKV(mesh, ids, docs, counts))
            if P > 1 or nrounds > 1:
                checks.append((ids, alts, counts))

            if keep_bytes:
                with self.timer.stage("url_dict"):
                    from ..parallel.shuffle import default_hash
                    us = _shard_blocks(ustarts, P)
                    ln = _shard_blocks(lengths, P)
                    ih = _shard_blocks(ids, P)
                    for p, (base, corpus, fstarts) in enumerate(per):
                        n = int(counts[p])
                        if n:
                            ids_p = ih[p][:n]
                            urls = [corpus[s:s + l].tobytes()
                                    for s, l in zip(us[p][:n].tolist(),
                                                    ln[p][:n].tolist())]
                            # route each id to the shard the aggregate
                            # will send its key to: the url bytes land
                            # in that destination's dict, never in one
                            # controller-global dict (VERDICT r3 #7)
                            dest = np.asarray(default_hash(ids_p)) % P
                            self._intern_dest(dest, ids_p, urls)

        if checks:
            with self.timer.stage("map_device"):
                ncoll = _mesh_collision_count(tuple(checks))
                if ncoll:
                    raise ValueError(
                        f"{ncoll} 64-bit URL intern collision(s) detected "
                        f"(distinct URLs share a u64 id)")

    def _map_corpus_device(self, files, kv, want_urls: bool):
        mesh = self._mesh()
        if mesh is not None:
            return self._map_corpus_mesh(mesh, files, kv, want_urls)
        # serial-backend path: device extract, host KV (the mesh backend
        # takes the SPMD path above)
        self.docs = list(files)
        parts = []          # per batch: (ids, alts, docs, npairs) device
        corpora = []        # per batch: (corpus, ustarts, lengths, ids)
        doc_base = 0
        keep_bytes = _url_dict_wanted(files, want_urls)
        for batch in self._file_batches(files):
            with self.timer.stage("read"):
                corpus, fstarts = _build_corpus(batch)
            if len(corpus) == 0:
                doc_base += len(batch)
                continue
            self.stats["nbatches"] += 1
            with self.timer.stage("h2d"):
                words = jax.device_put(jnp.asarray(bytes_view_u32(corpus)))
                fstarts_d = jax.device_put(jnp.asarray(fstarts))
                # see _map_corpus_mesh: timing sync, deferrable via
                # MRTPU_DEFER_SYNC (the stats device_get below is the
                # real barrier)
                from ..exec import maybe_block
                maybe_block(words)

            # ~1 href/KB is the PUMA-style density; an overflow retries
            # with the exact power-of-two capacity
            cap = max(8, 1 << (max(1, len(corpus) // 1024) - 1).bit_length())
            wide = False
            with self.timer.stage("map_device"):
                while True:
                    fn = (_extract_wide_fn if wide else _extract_fn)(
                        cap, self.use_pallas, self.interpret)
                    (ids, alts, docs, ustarts, lengths, nhits, npairs,
                     ncoll, nlong) = fn(words, fstarts_d)
                    nhits, npairs, ncoll, nlong = map(
                        int, jax.device_get((nhits, npairs, ncoll, nlong)))
                    self.stats["nlong_max"] = max(self.stats["nlong_max"],
                                                  nlong)
                    if nhits > cap:
                        cap = max(8, 1 << (nhits - 1).bit_length())  # retry
                        self.stats["cap_retries"] += 1
                    elif nlong > max(8, cap // 4):
                        wide = True   # long-URL-dense corpus: full windows
                        self.stats["wide_fallbacks"] += 1
                    else:
                        break
                if ncoll:
                    raise ValueError(
                        f"{ncoll} 64-bit URL intern collision(s) detected")
                if doc_base:
                    docs = docs + np.uint32(doc_base)
            parts.append((ids, alts, docs, npairs))
            if keep_bytes:
                corpora.append((corpus, ustarts, lengths, ids, npairs))
            doc_base += len(batch)

        if not parts:
            return
        with self.timer.stage("map_device"):
            multi = len(parts) > 1
            ids, alts, docs, npairs = _assemble_parts(parts)
            ids_h = np.asarray(ids[:npairs])
            alts_h = np.asarray(alts[:npairs])
            kv.add_batch(ids_h, np.asarray(docs[:npairs]))
            ncoll = _host_collision_count(ids_h, alts_h) if multi else 0
            if ncoll:
                raise ValueError(
                    f"{ncoll} 64-bit URL intern collision(s) detected "
                    f"(distinct URLs share a u64 id)")

        if keep_bytes:
            with self.timer.stage("url_dict"):
                for corpus, ustarts, lengths, bids, n in corpora:
                    st, ln, idh = (np.asarray(ustarts[:n]),
                                   np.asarray(lengths[:n]),
                                   np.asarray(bids[:n]))
                    urls = [corpus[s:s + l].tobytes()
                            for s, l in zip(st.tolist(), ln.tolist())]
                    self._intern(idh, urls)

    def _mesh(self):
        from ..parallel.backend import MeshBackend
        mr = getattr(self, "_mr", None)
        if mr is not None and isinstance(mr.backend, MeshBackend):
            return mr.backend.mesh
        return None

    # -- full pipeline ---------------------------------------------------
    def run(self, paths: Sequence[str], outdir: Optional[str] = None,
            nfiles: Optional[int] = None) -> Tuple[int, int]:
        """Returns (total hits, unique urls).  Writes `url \\t files` lines
        to outdir/part-<proc> when outdir is given (reference myreduce,
        cuda/InvertedIndex.cu:463-513)."""
        mr = MapReduce(self.comm, mapstyle=self.mapstyle)
        self._mr = mr
        self._reset_stats()
        files = findfiles(list(paths))
        if nfiles is not None:
            files = files[:nfiles]
        with self.timer.stage("map"):
            if self.engine == "native":
                # doc ids are task ids (stable under the mapstyle-2
                # work queue's out-of-order execution)
                self.docs = list(files)
                self._keep_bytes = _url_dict_wanted(files,
                                                    outdir is not None)
                self._reset_chk(counters=True)
                self.stats["nbatches"] = len(files)
                # collisions surface inside _fold_id_check as files map,
                # or in the close-out compaction below (cross-batch);
                # the compaction stays in the host_add/map_kernels timed
                # group — it is real map-stage work (VERDICT r3 #2)
                self.npairs = mr.map_files(files, self._map_file_native)
                if self._chk_tails:
                    with self.timer.stage("host_add"):
                        self._compact_chk_runs()
                self._reset_chk(counters=False)
            else:
                self.npairs = mr.map(
                    1, lambda itask, kv, ptr: self._map_corpus_device(
                        files, kv, want_urls=outdir is not None))
        with self.timer.stage("aggregate"):
            mr.aggregate()
        with self.timer.stage("convert"):
            mr.convert()

        out = None
        nurl = [0]
        url_lookup = None   # bound once if the one-file fallback runs

        def emit_host(key, values, kv, ptr):
            nurl[0] += 1
            if out is not None:
                url = url_lookup[int(key)].decode(errors="replace")
                names = " ".join(self.docs[int(v)] for v in sorted(set(values)))
                out.write(f"{url}\t{names}\n")
            kv.add(key, len(values))

        def emit_batch(fr, kv, ptr):
            # vectorised count per group for both tiers: sharded frames
            # reduce on device; host KMVFrames already carry the count
            # (nvalues) — no per-group Python either way
            from ..core.frame import KMVFrame
            if isinstance(fr, KMVFrame):
                nurl[0] += len(fr)
                kv.add_batch(fr.key, fr.nvalues.astype(np.int64))
                return
            from ..parallel.group import reduce_sharded
            counted = reduce_sharded(fr, "count")
            nurl[0] += len(counted)
            kv.add_frame(counted)

        try:
            if outdir:
                os.makedirs(outdir, exist_ok=True)
                from ..parallel.sharded import ShardedKMV
                frames = list(mr.kmv.frames()) if mr.kmv is not None else []
                if len(frames) == 1 and isinstance(frames[0], ShardedKMV):
                    # per-shard part files from per-shard data — the
                    # reference's part-%05d per proc
                    # (cuda/InvertedIndex.cu:463-513); counts still
                    # reduce on device afterwards
                    with self.timer.stage("reduce"):
                        self._write_parts_sharded(outdir, frames[0])
                        mr.reduce(emit_batch, batch=True)
                    self.mr = mr
                    return self.npairs, nurl[0]
                url_lookup = self.urls          # merged view, built once
                out = open(os.path.join(outdir, "part-00000"), "w")
            with self.timer.stage("reduce"):
                if out is None:     # counting only: vectorised both tiers
                    mr.reduce(emit_batch, batch=True)
                else:               # url/doc name output: per-group host
                    mr.reduce(emit_host)
        finally:
            if out is not None:
                out.close()
        self.mr = mr
        return self.npairs, nurl[0]

    def _write_parts_sharded(self, outdir: str, fr) -> None:
        """Write ``part-<shard>`` from each shard's OWN groups, decoding
        URL bytes from that destination's url dict (or the host tier's
        global dict when the ingest side was not sharded).  Shards pull
        to host one at a time — the whole dataset never assembles on
        the controller (reference per-proc reduce output,
        cuda/InvertedIndex.cu:463-513; VERDICT r3 #7)."""
        for p in range(fr.nprocs):
            lookup = (self.shard_urls[p] if self.shard_urls is not None
                      else self._urls)
            hf = fr.shard_to_host(p)
            with open(os.path.join(outdir, f"part-{p:05d}"), "w") as out:
                for k, vals in hf.groups():
                    url = lookup[int(k)].decode(errors="replace")
                    names = " ".join(self.docs[int(v)]
                                     for v in sorted(set(vals)))
                    out.write(f"{url}\t{names}\n")

