"""InvertedIndex — the reference's flagship GPU application, TPU-native.

Pipeline (reference ``cuda/InvertedIndex.cu:140-202``, call stack SURVEY.md
§3.6): per HTML file, find every ``<a href="..."`` URL (device kernels),
emit (url, filename) pairs; ``aggregate`` shuffles URLs across chips;
``convert`` groups; ``reduce`` writes ``url \\t file file...`` lines to
per-proc output files (``:463-513``).

Device stages (Pallas/XLA, ops/pallas/match.py): mark → compact →
url_lengths.  The host loop then interns URL bytes to u64 ids and bulk-adds
(url_id, doc_id) — the analogue of the reference's host ``kv->add`` loop
(``:385-388``), but batched.  File *names* are u32 doc ids into a host
table, not repeated strings.
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.mapreduce import MapReduce
from .. import native
from ..ops.hash import hash_bytes64_batch
from ..ops.pallas.match import url_lengths
from ..utils.io import findfiles
from ..utils.platform import is_tpu_backend

PATTERN = b'<a href="'
QUOTE = ord('"')
MAX_URL = 1024


CHUNK = 1 << 26            # 64 MB — the reference's per-chunk unit
MIN_CHUNK = 1 << 17        # small files pad to pow2 ≥ 128 KB
OVERLAP = len(PATTERN) + MAX_URL


@functools.lru_cache(maxsize=None)
def _mark_count_fn(pattern: bytes, use_pallas: bool, interpret: bool):
    """Compiled (per chunk-shape, cached) mark+count.  The buffer is
    chunk+overlap bytes; matches starting in the overlap tail belong to the
    next chunk and are masked off."""

    @jax.jit
    def run(buf, nvalid):
        from ..ops.pallas.match import mark_pallas, mark_xla
        mask = (mark_pallas(buf, pattern, interpret=interpret) if use_pallas
                else mark_xla(buf, pattern))
        own = jnp.arange(buf.shape[0]) < nvalid
        mask = jnp.where(own, mask.astype(jnp.int32), 0)
        return mask, jnp.sum(mask)

    return run


@functools.lru_cache(maxsize=None)
def _compact_len_fn(cap: int):
    @jax.jit
    def run(buf, mask):
        from ..ops.pallas.match import compact_matches
        starts, _ = compact_matches(mask, cap)
        starts = starts + len(PATTERN)
        lengths, _ = url_lengths(buf, starts, QUOTE, MAX_URL)
        return starts, lengths

    return run


def _chunk_iter(data: np.ndarray):
    """Yield (padded chunk+overlap buffer, base offset, valid bytes)."""
    n = len(data)
    chunk = MIN_CHUNK
    while chunk < min(n, CHUNK):
        chunk <<= 1
    for base in range(0, n, chunk):
        nvalid = min(chunk, n - base)
        buf = np.zeros(chunk + OVERLAP, np.uint8)
        take = min(chunk + OVERLAP, n - base)
        buf[:take] = data[base:base + take]
        yield buf, base, nvalid


class StageTimer:
    """Cumulative wall-clock per pipeline stage (reference instrument:
    gettimeofday/cudaEvent pairs around each kernel,
    cuda/InvertedIndex.cu:337,360,369,384)."""

    def __init__(self):
        self.times: Dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.times[name] = (self.times.get(name, 0.0)
                                + time.perf_counter() - t0)


def _device_extract(data: np.ndarray, use_pallas: bool, interpret: bool,
                    timer: Optional[StageTimer] = None):
    """One file's bytes → (starts, lengths) host arrays, chunked through
    shape-cached compiled kernels (one compile per pow2 chunk size).

    When ``timer`` is given, extra device syncs attribute time to stages;
    untimed callers keep the fully async dispatch path."""
    sync = jax.block_until_ready if timer is not None else (lambda x: x)
    timer = timer or StageTimer()
    all_starts, all_lengths = [], []
    for buf_np, base, nvalid in _chunk_iter(data):
        with timer.stage("h2d"):
            buf = sync(jnp.asarray(buf_np))
        with timer.stage("mark"):
            mask, nhits = _mark_count_fn(PATTERN, use_pallas, interpret)(
                buf, nvalid)
            nhits = int(nhits)
        if nhits == 0:
            continue
        cap = max(8, 1 << (nhits - 1).bit_length())
        with timer.stage("compact_len"):
            starts, lengths = sync(_compact_len_fn(cap)(buf, mask))
        with timer.stage("d2h"):
            all_starts.append(np.asarray(starts[:nhits], np.int64) + base)
            all_lengths.append(np.asarray(lengths[:nhits]))
    if not all_starts:
        return np.zeros(0, np.int64), np.zeros(0, np.int32)
    return np.concatenate(all_starts), np.concatenate(all_lengths)


class InvertedIndex:
    """Builds an inverted URL→documents index over the MapReduce algebra."""

    def __init__(self, comm=None, use_pallas: Optional[bool] = None,
                 interpret: Optional[bool] = None,
                 engine: Optional[str] = None):
        """engine: 'pallas' (TPU kernels, default), 'xla' (jnp fallback),
        or 'native' (the C++ scanner of native/mrnative.cpp — the moral
        equivalent of the reference's cpu/InvertedIndex.cpp FSM baseline,
        and the host fallback when no accelerator is worth dispatching
        to)."""
        backend = jax.default_backend()
        if engine is None:
            engine = "pallas" if (use_pallas or use_pallas is None) \
                else "xla"
        if engine == "native" and not native.available():
            raise RuntimeError(f"native engine unavailable: "
                               f"{native.build_error()}")
        self.engine = engine
        self.use_pallas = engine == "pallas"
        if interpret is None:
            # CPU tests interpret the kernel; real hardware (including the
            # axon plugin backend) must compile via Mosaic — interpret mode
            # on chip would silently invalidate any benchmark number
            interpret = not is_tpu_backend(backend)
        self.interpret = interpret
        self.comm = comm
        self.urls: Dict[int, bytes] = {}
        self.docs: List[str] = []
        self.npairs = 0
        self.timer = StageTimer()

    # -- map stage -------------------------------------------------------
    def _map_file(self, itask, filename, kv, ptr):
        with open(filename, "rb") as f:
            data = np.frombuffer(f.read(), dtype=np.uint8)
        doc_id = len(self.docs)
        self.docs.append(filename)
        if len(data) == 0:
            return
        if self.engine == "native":
            with self.timer.stage("native_scan"):
                starts, lengths = native.find_hrefs(data)
            # device path drops URLs with no terminator within MAX_URL;
            # match that instead of silently truncating
            lengths = np.where(lengths > MAX_URL, -1, lengths)
        else:
            starts, lengths = _device_extract(data, self.use_pallas,
                                              self.interpret, self.timer)
        with self.timer.stage("host_add"):
            keep = lengths >= 0  # unterminated href: reference runs off; we drop
            urls = [data[st:st + ln].tobytes()
                    for st, ln in zip(starts[keep], lengths[keep])]
            ids = hash_bytes64_batch(urls)  # native C++ batch intern
            for h, url in zip(ids.tolist(), urls):
                prev = self.urls.get(h)
                if prev is not None and prev != url:
                    raise ValueError(
                        f"64-bit URL intern collision: {prev!r} vs {url!r}")
                self.urls[h] = url
            kv.add_batch(ids, np.full(len(ids), doc_id, dtype=np.uint32))

    # -- full pipeline ---------------------------------------------------
    def run(self, paths: Sequence[str], outdir: Optional[str] = None,
            nfiles: Optional[int] = None) -> Tuple[int, int]:
        """Returns (total hits, unique urls).  Writes `url \\t files` lines
        to outdir/part-<proc> when outdir is given (reference myreduce,
        cuda/InvertedIndex.cu:463-513)."""
        mr = MapReduce(self.comm)
        files = findfiles(list(paths))
        if nfiles is not None:
            files = files[:nfiles]
        with self.timer.stage("map"):
            self.npairs = mr.map_files(files, self._map_file)
        with self.timer.stage("aggregate"):
            mr.aggregate()
        with self.timer.stage("convert"):
            mr.convert()

        out = None
        nurl = [0]

        def emit(key, values, kv, ptr):
            nurl[0] += 1
            if out is not None:
                url = self.urls[int(key)].decode(errors="replace")
                names = " ".join(self.docs[int(v)] for v in sorted(set(values)))
                out.write(f"{url}\t{names}\n")
            kv.add(key, len(values))

        try:
            if outdir:
                os.makedirs(outdir, exist_ok=True)
                out = open(os.path.join(outdir, "part-00000"), "w")
            with self.timer.stage("reduce"):
                mr.reduce(emit)
        finally:
            if out is not None:
                out.close()
        self.mr = mr
        return self.npairs, nurl[0]
