"""Multi-world (``-partition``) runs — the OINK Universe.

Reference: ``oink/universe.{h,cpp}`` (world bookkeeping: NxM / P specs,
``add_world`` ``universe.cpp:55-88``, ``consistent`` ``:94-99``) and
``oink/oink.cpp:46-57,138-236`` (the -partition switch, MPI_Comm_split
into per-world communicators, per-world ``screen.N``/log files, the
universe-level banner).

TPU redesign.  The reference's "procs" are MPI ranks; ours are mesh
devices under one controller.  ``-partition`` therefore splits the
DEVICE LIST into consecutive sub-meshes (the MPI_Comm_split analog:
world i owns devices [root_proc[i], root_proc[i]+procs_per_world[i])) and
runs one interpreter per world in its OWN THREAD — worlds progress
concurrently, each driving its sub-mesh, the way the reference's worlds
are concurrent MPI jobs.  ULOOP work-sharing coordinates through a
mutex-guarded shared counter instead of the reference's
``tmp.oink.variable`` rename-lock file (variables.WorldContext).

Per-world files follow the reference naming: default screen →
``screen.N`` (oink.cpp:170-174), ``-screen base`` → ``base.N``;
``-log base`` → ``base.N``.  Default log → ``log.oink.N`` (the reference
writes ``log.lammps.N`` here, oink.cpp:188 — an upstream LAMMPS leftover
we deliberately normalise).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from ..core.runtime import MRError
from .variables import UloopCounter, WorldContext


class Universe:
    """World layout over ``nprocs`` procs (reference Universe class)."""

    def __init__(self, nprocs: int):
        self.nprocs = nprocs
        self.procs_per_world: List[int] = []
        self.root_proc: List[int] = []

    @property
    def nworlds(self) -> int:
        return len(self.procs_per_world)

    def add_world(self, spec: Optional[str]):
        """None → 1 world, all procs; ``NxM`` → N worlds of M procs;
        ``P`` → 1 world of P procs (universe.cpp:55-88)."""
        if spec is None:
            n, nper = 1, self.nprocs
        elif "x" in spec:
            a, b = spec.split("x", 1)
            n, nper = int(a), int(b)
        else:
            n, nper = 1, int(spec)
        for _ in range(n):
            root = 0 if not self.root_proc else \
                self.root_proc[-1] + self.procs_per_world[-1]
            self.procs_per_world.append(nper)
            self.root_proc.append(root)

    def consistent(self) -> bool:
        return sum(self.procs_per_world) == self.nprocs


def _world_comm(comm, universe: Universe, iworld: int):
    """Sub-mesh of world ``iworld`` (the MPI_Comm_split analog,
    oink.cpp:165)."""
    if comm is None:
        return None
    from ..parallel.mesh import make_mesh
    lo = universe.root_proc[iworld]
    hi = lo + universe.procs_per_world[iworld]
    return make_mesh(devices=list(comm.devices.flat)[lo:hi])


def _world_filename(base: Optional[str], default: str, iworld: int
                    ) -> Optional[str]:
    """Reference naming: ``none`` → no file; explicit base → base.N;
    unset → default.N (oink.cpp:168-202)."""
    if base == "none":
        return None
    return f"{base or default}.{iworld}"


def run_universe(infile: str, partition_specs: Sequence[str], comm=None,
                 logname: Optional[str] = None,
                 screenname: Optional[str] = None,
                 echo: Optional[str] = None,
                 varsets: Sequence = (), uscreen=None) -> "Universe":
    """Run ``infile`` once per world, concurrently.

    ``comm``: the full mesh to split (None → 1 proc, serial worlds).
    ``logname``/``screenname``: CLI -log/-screen values ("none" → off).
    ``varsets``: [(name, [values...])] from -var switches.
    ``uscreen``: universe-level stream (None → stdout)."""
    import sys

    from .script import OinkScript

    if comm is None:
        nprocs = 1
    else:
        from ..parallel.mesh import mesh_axis_size
        nprocs = mesh_axis_size(comm)
    universe = Universe(nprocs)
    for spec in partition_specs:
        universe.add_world(spec)
    if not universe.procs_per_world:
        universe.add_world(None)
    if not universe.consistent():
        raise MRError("Processor partitions are inconsistent")

    if uscreen is None:
        uscreen = sys.stdout
    ulock = threading.Lock()

    def uemit(text: str):
        if uscreen is not False and uscreen is not None:
            with ulock:
                uscreen.write(text)
                uscreen.flush()

    uemit(f"Running on {universe.nworlds} partitions of processors\n")

    counter = UloopCounter(universe.nworlds)

    def on_advance(nextindex: int, iworld: int):
        # the reference's universe-level progress line
        # (variable.cpp:367-374; it prints nextindex+1)
        uemit(f"Increment via next: value {nextindex + 1} on partition "
              f"{iworld}\n")

    errors: List[tuple] = []

    def run_world(iworld: int):
        # EVERYTHING is inside the try: a failed screen/log open or
        # sub-mesh build must land in `errors`, not vanish into the
        # thread's default excepthook while the universe reports success
        screen: object = False
        interp = None
        try:
            world = WorldContext(iworld, universe.nworlds, counter,
                                 on_advance)
            wcomm = _world_comm(comm, universe, iworld)
            screenfile = _world_filename(screenname, "screen", iworld)
            logfile = _world_filename(logname, "log.oink", iworld)
            screen = open(screenfile, "w") if screenfile else False
            interp = OinkScript(comm=wcomm, screen=screen, logfile=logfile,
                                world=world)
            interp._emit(f"Processor partition = {iworld}\n")
            if echo:
                interp.cmd_echo([echo])
            for name, vals in varsets:
                interp.variables.set([name, "index"] + list(vals))
            interp.run_file(infile)
        except BaseException as e:  # surfaced after join
            errors.append((iworld, e))
        finally:
            if interp is not None:
                interp.close()
            if screen:
                screen.close()

    threads = [threading.Thread(target=run_world, args=(i,),
                                name=f"oink-world-{i}")
               for i in range(universe.nworlds)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        errors.sort(key=lambda t: t[0])
        detail = "; ".join(f"world {i}: {e}" for i, e in errors)
        raise MRError(f"{len(errors)} world(s) failed: {detail}") \
            from errors[0][1]
    return universe
