"""Shared OINK kernels — the reusable map/reduce callbacks of
``oink/map_*.cpp`` / ``oink/reduce_*.cpp``, batch-first.

Data conventions (reference ``oink/typedefs.h:22-40``):

* VERTEX = uint64 → a ``[n]`` u64 column;
* EDGE = {vi, vj} → a ``[n, 2]`` u64 column (struct-of-rows, fixed width —
  the TPU fast path, SURVEY.md §7);
* WEIGHT = float64 → ``[n]`` f64 column;
* NULL values → ``[n]`` u8 zeros.

Every kernel here is a *batch* callback (``mr.map_mr(..., batch=True)`` /
``mr.reduce(..., batch=True)``): it receives a whole KVFrame/KMVFrame and
emits columns, so pipelines stay vectorised end-to-end.  Host per-pair
equivalents are what the reference runs; the semantics match 1:1.
"""

from __future__ import annotations

import numpy as np

from ..core.frame import KVFrame

# ---------------------------------------------------------------------------
# file parsers (reference map_read_*.cpp — host I/O, vectorised parse)
# ---------------------------------------------------------------------------


def _null(n: int) -> np.ndarray:
    return np.zeros(n, np.uint8)


def host_kv(fr) -> KVFrame:
    """Normalise a batch-map input to a host KVFrame (mesh backend hands
    ShardedKV; the reference's analog is request_page's disk→mem read)."""
    return fr if isinstance(fr, KVFrame) else fr.to_host()


def host_kmv(fr):
    """Normalise a batch-reduce input to a host KMVFrame."""
    from ..core.frame import KMVFrame
    return fr if isinstance(fr, KMVFrame) else fr.to_host()


def kv_keys(fr) -> np.ndarray:
    return np.asarray(host_kv(fr).key.to_host().data)


def kv_values(fr) -> np.ndarray:
    return np.asarray(host_kv(fr).value.to_host().data)


def kmv_keys(fr) -> np.ndarray:
    return np.asarray(host_kmv(fr).key.to_host().data)


def kmv_values(fr) -> np.ndarray:
    return np.asarray(host_kmv(fr).values.to_host().data)


def seg_ids(fr) -> np.ndarray:
    """Row → group-index map for a KMVFrame's flat value column."""
    fr = host_kmv(fr)
    return np.repeat(np.arange(len(fr)), np.asarray(fr.nvalues))


def group_min_rows(seg: np.ndarray, *keys: np.ndarray):
    """Per-group lexicographic argmin: for rows labelled by ``seg``
    (ascending group ids), return ``(groups, rows)`` — each present group
    and the index of its minimal row by ``keys[0]``, ties broken by
    ``keys[1]``, ...  One idiom for every 'best row per group' reduce
    (sssp's pick_shortest/update_adjacent) so tie-breaking can never
    diverge between call sites."""
    order = np.lexsort(tuple(reversed(keys)) + (seg,))
    gseg = seg[order]
    first = np.ones(len(gseg), bool)
    first[1:] = gseg[1:] != gseg[:-1]
    return gseg[first], order[first]


def group_any(cond: np.ndarray, fr) -> np.ndarray:
    """Per-group OR over a KMV frame's flat value rows — the shared segment
    primitive behind luby's winner/loser votes, tri_find's has-edge test,
    and cc_find's zone joins."""
    offs = np.asarray(host_kmv(fr).offsets)[:-1]
    return np.maximum.reduceat(cond.astype(np.uint8), offs).astype(bool)


def _parse_cols(filename: str, dtypes) -> list:
    """Whitespace table → one exact-dtype array per column (u64 vertex ids
    parse as integers, never through float — ids ≥ 2^53 stay exact).
    Routed through the native C++ parser when built (ingestion is a host
    hot path; the reference parses in C callbacks, oink/map_read_*.cpp)."""
    with open(filename, "rb") as f:
        raw = f.read()
    from .. import native
    if native.available() and all(dt in (np.uint64, np.float64)
                                  for dt in dtypes):
        try:
            return native.parse_table(raw, dtypes)
        except ValueError as e:
            raise ValueError(f"{filename}: {e}")
    toks = np.asarray(raw.split())
    ncols = len(dtypes)
    if len(toks) % ncols:
        raise ValueError(f"{filename}: token count not divisible by {ncols}")
    table = toks.reshape(-1, ncols)
    return [table[:, i].astype(dt) for i, dt in enumerate(dtypes)]


def read_edge(itask, filename, kv, ptr):
    """'vi vj' lines → key=[vi,vj], value=NULL (map_read_edge.cpp:15-25)."""
    vi, vj = _parse_cols(filename, (np.uint64, np.uint64))
    kv.add_batch(np.stack([vi, vj], 1), _null(len(vi)))


def read_edge_weight(itask, filename, kv, ptr):
    """'vi vj wt' lines → key=[vi,vj], value=weight
    (map_read_edge_weight.cpp)."""
    vi, vj, w = _parse_cols(filename, (np.uint64, np.uint64, np.float64))
    kv.add_batch(np.stack([vi, vj], 1), w)


def read_edge_label(itask, filename, kv, ptr):
    """'vi vj label' lines → key=[vi,vj], value=int label
    (map_read_edge_label.cpp)."""
    vi, vj, lab = _parse_cols(filename, (np.uint64, np.uint64, np.int64))
    kv.add_batch(np.stack([vi, vj], 1), lab)


def read_vertex_value(itask, filename, kv, ptr):
    """'v u' lines → key=v, value=u, both u64 (cc_stats input: Vi Zi
    pairs, oink/cc_stats.cpp CCStats::read)."""
    v, u = _parse_cols(filename, (np.uint64, np.uint64))
    kv.add_batch(v, u)


def read_vertex_weight(itask, filename, kv, ptr):
    """'v weight' lines → key=v, value=weight (map_read_vertex_weight.cpp)."""
    v, w = _parse_cols(filename, (np.uint64, np.float64))
    kv.add_batch(v, w)


def read_words(itask, filename, kv, ptr):
    """whitespace words → key=word bytes, value=NULL (map_read_words.cpp)."""
    with open(filename, "rb") as f:
        words = f.read().split()
    if ptr is not None and isinstance(ptr, list):
        ptr.append(filename)  # nfiles counter (reference int* ptr)
    kv.add_batch(words, _null(len(words)))


# ---------------------------------------------------------------------------
# edge/vertex maps (batch: fn(frame, kv, ptr))
# ---------------------------------------------------------------------------

def _dev(name):
    from ..parallel import devkernels
    return getattr(devkernels, name)


def edge_to_vertices(fr, kv, ptr):
    """Eij:NULL → Vi:NULL and Vj:NULL (map_edge_to_vertices.cpp)."""
    from ..parallel.devkernels import is_sharded_kv, skv_map
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _dev("edge_to_vertices_dev")))
        return
    e = kv_keys(fr)
    both = np.concatenate([e[:, 0], e[:, 1]])
    kv.add_batch(both, _null(len(both)))


def edge_to_vertex(fr, kv, ptr):
    """Eij:NULL → Vi:NULL only (map_edge_to_vertex.cpp)."""
    from ..parallel.devkernels import is_sharded_kv, skv_map
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _dev("edge_to_vertex_dev")))
        return
    e = kv_keys(fr)
    kv.add_batch(e[:, 0], _null(len(e)))


def edge_to_vertex_pair(fr, kv, ptr):
    """Eij:NULL → Vi:Vj (map_edge_to_vertex_pair.cpp)."""
    from ..parallel.devkernels import is_sharded_kv, skv_map
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _dev("edge_to_vertex_pair_dev")))
        return
    e = kv_keys(fr)
    kv.add_batch(e[:, 0], e[:, 1])


def edge_both_directions(fr, kv, ptr):
    """Eij:NULL → Vi:Vj and Vj:Vi — the adjacency expansion shared by
    neighbor (oink/neighbor.cpp:84-116) and tri_find's map_edge_vert
    (oink/tri_find.cpp:104-112)."""
    from ..parallel.devkernels import is_sharded_kv, skv_map
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _dev("edge_both_directions_dev")))
        return
    e = kv_keys(fr)
    kv.add_batch(np.concatenate([e[:, 0], e[:, 1]]),
                 np.concatenate([e[:, 1], e[:, 0]]))


def edge_upper(fr, kv, ptr):
    """Canonicalise to Vi<Vj, drop self-loops (map_edge_upper.cpp:15-24)."""
    from ..parallel.devkernels import is_sharded_kv, skv_map
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _dev("edge_upper_dev")))
        return
    e = kv_keys(fr)
    keep = e[:, 0] != e[:, 1]
    e = e[keep]
    lo = np.minimum(e[:, 0], e[:, 1])
    hi = np.maximum(e[:, 0], e[:, 1])
    kv.add_batch(np.stack([lo, hi], 1), _null(len(e)))


def invert(fr, kv, ptr):
    """K:V → V:K (map_invert.cpp)."""
    from ..parallel.devkernels import is_sharded_kv, skv_map
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _dev("invert_dev")))
        return
    fr = host_kv(fr)
    kv.add_batch(fr.value, fr.key)


def add_weight(fr, kv, ptr):
    """Eij:NULL → Eij:1.0 (map_add_weight.cpp — unit edge weights)."""
    from ..parallel.devkernels import is_sharded_kv, skv_map
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _dev("add_weight_dev")))
        return
    fr = host_kv(fr)
    kv.add_batch(fr.key, np.ones(len(fr), np.float64))


# ---------------------------------------------------------------------------
# reduces — re-exported from ops/reduces.py, which dispatches on frame kind
# (local KMVFrame vs mesh ShardedKMV) so commands run on both backends
# ---------------------------------------------------------------------------

from ..ops.reduces import count, cull, max_values, min_values, sum_values  # noqa: E402,F401


def value_histogram(mr) -> list:
    """The shared histogram tail of histo/degree_stats
    (oink/histo.cpp:59-66, oink/degree_stats.cpp:52-61): invert to
    value:key, group, count, gather, sort descending.  Consumes mr's KV;
    returns [(value, count)] sorted by value descending."""
    mr.map_mr(mr, invert, batch=True)
    mr.collate()
    mr.reduce(count, batch=True)
    mr.gather(1)
    mr.sort_keys(-1)
    stats = []
    mr.scan_kv(lambda k, v, p: stats.append((int(k), int(v))))
    return stats


# ---------------------------------------------------------------------------
# printers (reference per-command print callbacks)
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# name → kernel registries (what oink/Make.py generates as style_map.h /
# style_reduce.h: script text like `mre map/mr mre add_weight` resolves its
# callback through these, reference oink/mrmpi.cpp:354-466)
# ---------------------------------------------------------------------------

MAP_FILE_KERNELS = {
    "read_edge": read_edge,
    "read_edge_weight": read_edge_weight,
    "read_edge_label": read_edge_label,
    "read_vertex_value": read_vertex_value,
    "read_vertex_weight": read_vertex_weight,
    "read_words": read_words,
}

MAP_MR_KERNELS = {
    "edge_to_vertices": edge_to_vertices,
    "edge_to_vertex": edge_to_vertex,
    "edge_to_vertex_pair": edge_to_vertex_pair,
    "edge_both_directions": edge_both_directions,
    "edge_upper": edge_upper,
    "invert": invert,
    "add_weight": add_weight,
}

REDUCE_KERNELS = {
    "count": count,
    "cull": cull,
    "sum": sum_values,
    "min": min_values,
    "max": max_values,
}


def hash_lookup3(keys):
    """The library default key→proc hash, by name (reference scripts pass
    NULL for the same thing; mrmpi.cpp:354-466 resolves named hashes)."""
    from ..parallel.shuffle import default_hash
    return default_hash(keys)


def hash_identity(keys):
    """Low word of the key as the hash — deterministic placement for
    tests/scripts (shard = key % nprocs)."""
    import jax.numpy as jnp
    k = keys[:, 0] if keys.ndim > 1 else keys
    return k.astype(jnp.uint32)


HASH_KERNELS = {
    "lookup3": hash_lookup3,
    "identity": hash_identity,
}


def print_edge(k, v, fp):
    fp.write(f"{k[0]} {k[1]}\n")


def print_vertex(k, v, fp):
    fp.write(f"{k}\n")


def print_vertex_value(k, v, fp):
    fp.write(f"{k} {v}\n")


def print_edge_value(k, v, fp):
    fp.write(f"{k[0]} {k[1]} {v}\n")
