"""Script variables — the LAMMPS-style variable engine of OINK.

Reference: ``oink/variable.{h,cpp}`` — styles INDEX/LOOP/WORLD/UNIVERSE/
ULOOP/STRING/EQUAL (``variable.cpp:31``), ``retrieve()`` (string value of
$x substitution), ``next()`` (advance loop variables, signalling
exhaustion for the jump/next idiom), and the EQUAL-style formula
evaluator with C-like precedence, math functions, and the ``time``/
``nprocs`` specials (``variable.cpp:560-1010``).

Redesigns vs the reference:

* the evaluator is a recursive-descent parser over a token list instead
  of the reference's dual value/operator stack machine — same grammar,
  same precedence table (``variable.cpp:60-69``), no ``eval()``;
* WORLD/UNIVERSE/ULOOP are multi-world styles.  The reference splits
  MPI_COMM_WORLD into partitions and coordinates ULOOP through a lock
  file on shared disk (``variable.cpp:186-240``, ``next()``
  ``variable.cpp:345-383``).  Here a :class:`WorldContext` carries the
  world index/count and a lock-protected shared counter (worlds are
  threads of one controller, so the lock file becomes a mutex — same
  claim-the-next-index semantics).  Without a context the table runs
  single-world (iworld 0, nworlds 1), which reproduces the reference's
  serial behaviour exactly: UNIVERSE/ULOOP start at 0 and each ``next``
  claims 1, 2, ... like LOOP.
"""

from __future__ import annotations

import math
import random as _random
import re
import threading
from typing import Callable, Dict, List, Optional

from ..core.runtime import MRError

_STYLES = ("index", "loop", "world", "universe", "uloop", "string", "equal")


class WorldContext:
    """This world's place in the universe (reference Universe fields
    ``iworld``/``nworlds`` + the ``tmp.oink.variable`` lock file).

    One instance per world; ``counter`` is SHARED between the worlds of
    one universe (the runner passes the same object to all).  The
    counter starts at ``nworlds`` — world i implicitly owns index i, the
    first ``next`` anywhere claims ``nworlds``, exactly the number the
    reference seeds its lock file with (``variable.cpp:215-219``)."""

    def __init__(self, iworld: int = 0, nworlds: int = 1,
                 counter: Optional["UloopCounter"] = None,
                 on_advance: Optional[Callable[[int, int], None]] = None):
        self.iworld = iworld
        self.nworlds = nworlds
        self.counter = counter if counter is not None \
            else UloopCounter(nworlds)
        self.on_advance = on_advance   # (nextindex, iworld) → universe log

    def uloop_next(self) -> int:
        nextindex = self.counter.claim()
        if self.on_advance is not None:
            self.on_advance(nextindex, self.iworld)
        return nextindex

    def uloop_seed(self, name: str, generation: int):
        """(Re)seed the shared counter at variable definition so a
        SECOND uloop loop later in the script starts fresh instead of
        resuming the exhausted counter (the reference rewrites its lock
        file with nworlds at every definition, variable.cpp:215-219).

        Reseeding is once per (variable, definition-generation): the
        FIRST world to define it wins, later worlds' definitions are
        no-ops — unlike a naive proc-0 reset, a straggler world defining
        the variable after others already claimed indices cannot rewind
        the counter and hand an index out twice."""
        self.counter.seed(name, generation, self.nworlds)


class UloopCounter:
    """The shared next-index source (the reference's lock file, made a
    mutex: rename()-as-lock → threading.Lock, variable.cpp:350-366)."""

    def __init__(self, start: int):
        self._next = start
        self._lock = threading.Lock()
        self._gens: Dict[str, int] = {}   # var name → seeded generation

    def claim(self) -> int:
        with self._lock:
            n = self._next
            self._next += 1
            return n

    def seed(self, name: str, generation: int, start: int):
        """Reset to ``start`` the first time (name, generation) is seen;
        the same definition executed by the other worlds is a no-op."""
        with self._lock:
            if self._gens.get(name, 0) < generation:
                self._gens[name] = generation
                self._next = start


class _Var:
    def __init__(self, style: str, values: List[str], which: int = 0,
                 offset: int = 0, pad: int = 0):
        self.style = style
        self.values = values          # INDEX/WORLD/UNIVERSE/STRING: strings
        self.num = len(values)        # LOOP/ULOOP: overridden below
        self.which = which
        self.offset = offset
        self.pad = pad


class Variables:
    """The variable table; one per interpreter (reference Variable class).

    ``specials`` maps EQUAL keywords to zero-arg callables — the
    interpreter installs ``time`` (elapsed seconds of the last command,
    ``oink/input.cpp:458-464``) and ``nprocs``."""

    def __init__(self, world: Optional[WorldContext] = None):
        self._vars: Dict[str, _Var] = {}
        self.specials: Dict[str, Callable[[], float]] = {}
        self._rng: Optional[_random.Random] = None
        self.world = world if world is not None else WorldContext()
        self._uni_gen: Dict[str, int] = {}  # this table's definition count

    # -- the `variable` command (reference Variable::set) ------------------
    def set(self, args: List[str]):
        if len(args) < 2:
            raise MRError("Illegal variable command")
        name, style = args[0], args[1]
        if style == "delete":
            if len(args) != 2:
                raise MRError("Illegal variable command")
            self._vars.pop(name, None)
            return
        if style not in _STYLES:
            raise MRError(f"Illegal variable command: unknown style "
                          f"{style!r}")
        if name in self._vars:
            old = self._vars[name].style
            if style in ("string", "equal"):
                # STRING/EQUAL may be reset in place (variable.cpp:228-259)
                if old != style:
                    raise MRError("Cannot redefine variable as a "
                                  "different style")
            else:
                return  # INDEX/LOOP/...: first definition wins

        if style in ("index", "world", "universe"):
            if len(args) < 3:
                raise MRError("Illegal variable command")
            v = _Var(style, args[2:])
            if style == "world":
                # one value per partition (variable.cpp:166-168)
                if v.num != self.world.nworlds:
                    raise MRError("World variable count doesn't match # "
                                  "of partitions")
                v.which = self.world.iworld
            elif style == "universe":
                if v.num < self.world.nworlds:
                    raise MRError("Universe/uloop variable count < # of "
                                  "partitions")
                v.which = self.world.iworld
                self._check_uni_lengths(v)
                self._seed_uni(name)
        elif style in ("loop", "uloop"):
            rest = args[2:]
            pad = 0
            if rest and rest[-1] == "pad":
                rest = rest[:-1]
                pad = 1
            if len(rest) == 1:
                # ULOOP is 0-based in the reference (offset stays 0,
                # variable.cpp:196-201 + retrieve :405-407); LOOP is
                # 1-based (offset = nfirst = 1, :128-134)
                nfirst, nlast = (0, int(rest[0]) - 1) \
                    if style == "uloop" else (1, int(rest[0]))
            elif len(rest) == 2 and style == "loop":
                nfirst, nlast = int(rest[0]), int(rest[1])
            else:
                raise MRError("Illegal variable command")
            if nfirst > nlast or nlast < 0 or \
                    (style == "loop" and nlast <= 0):
                raise MRError("Illegal variable command")
            # pad width: digits of N (for uloop the count, variable.cpp
            # :203-206; for loop the last value, :135-141)
            v = _Var(style, [], offset=nfirst,
                     pad=len(str(nlast + 1 if style == "uloop" else nlast))
                     if pad else 0)
            v.num = nlast - nfirst + 1
            if style == "uloop":
                if v.num < self.world.nworlds:
                    raise MRError("Universe/uloop variable count < # of "
                                  "partitions")
                v.which = self.world.iworld
                self._check_uni_lengths(v)
                self._seed_uni(name)
        elif style == "string":
            if len(args) != 3:
                raise MRError("Illegal variable command")
            v = _Var(style, [args[2]])
        else:  # equal
            if len(args) != 3:
                raise MRError("Illegal variable command")
            v = _Var(style, [args[2]])
        self._vars[name] = v

    def _seed_uni(self, name: str):
        """Definition-time counter seed: this table's Nth definition of
        ``name`` maps to shared generation N (all worlds run the same
        script, so their generations line up)."""
        self._uni_gen[name] = self._uni_gen.get(name, 0) + 1
        self.world.uloop_seed(name, self._uni_gen[name])

    def _check_uni_lengths(self, v: _Var):
        """All universe/uloop variables must agree on num (they advance
        in lockstep off one counter — variable.cpp:221-224)."""
        for other in self._vars.values():
            if other is not v and other.style in ("universe", "uloop") \
                    and other.num != v.num:
                raise MRError("All universe/uloop variables must have "
                              "same # of values")

    # -- retrieval (reference Variable::retrieve) ---------------------------
    def find(self, name: str) -> Optional[_Var]:
        return self._vars.get(name)

    def retrieve(self, name: str) -> Optional[str]:
        v = self._vars.get(name)
        if v is None or v.which >= v.num:
            return None
        if v.style in ("index", "world", "universe", "string"):
            return v.values[v.which]
        if v.style in ("loop", "uloop"):
            n = v.which + v.offset
            return f"{n:0{v.pad}d}" if v.pad else str(n)
        # equal: evaluate on every retrieval (reference %.10g format)
        return f"{self.evaluate(v.values[0]):.10g}"

    def retrieve_count(self, name: str) -> int:
        v = self._vars.get(name)
        if v is None:
            raise MRError(f"variable {name!r} is unknown")
        return v.num

    def retrieve_single(self, name: str, nth: int) -> str:
        v = self._vars[name]
        if v.style in ("index", "world", "universe", "string"):
            return v.values[nth]
        n = nth + v.offset
        return f"{n:0{v.pad}d}" if v.pad else str(n)

    def equal_style(self, name: str) -> bool:
        v = self._vars.get(name)
        return v is not None and v.style == "equal"

    # -- the `next` command (reference Variable::next) ----------------------
    def next(self, names: List[str]) -> bool:
        """Advance the listed loop variables.  Returns True when any is
        exhausted (the variable is removed and the caller skips its next
        jump — input.cpp:726-728)."""
        if not names:
            raise MRError("Illegal next command")
        styles = set()
        for n in names:
            v = self._vars.get(n)
            if v is None:
                raise MRError("Invalid variable in next command")
            styles.add("uni" if v.style in ("universe", "uloop")
                       else v.style)
        if len(styles) > 1:
            raise MRError("All variables in next command must be same "
                          "style")
        style = styles.pop()
        if style in ("string", "equal", "world"):
            raise MRError("Invalid variable style with next command")
        exhausted = False
        if style == "uni":
            # claim the next unprocessed index from the universe-shared
            # counter; every listed variable jumps to it
            # (variable.cpp:345-383)
            nextindex = self.world.uloop_next()
            for n in names:
                v = self._vars[n]
                v.which = nextindex
                if v.which >= v.num:
                    exhausted = True
                    del self._vars[n]
            return exhausted
        for n in names:
            v = self._vars[n]
            v.which += 1
            if v.which >= v.num:
                exhausted = True
                del self._vars[n]
        return exhausted

    # ------------------------------------------------------------------
    # EQUAL-style formula evaluation (reference variable.cpp:560-1010)
    # grammar: || < && < == != < < <= > >= < + - < * / < ^ < unary -/!
    # operands: number, PI, time, nprocs, v_name, fn(args...), (expr)
    # ------------------------------------------------------------------

    _TOKEN_RE = re.compile(r"""
        \s*(?:
          (?P<num>\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?)
        | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
        | (?P<op>\|\||&&|==|!=|<=|>=|[-+*/^()!<>,])
        )""", re.VERBOSE)

    _FUNCS = {
        "sqrt": (1, math.sqrt), "exp": (1, math.exp),
        "ln": (1, math.log), "log": (1, math.log10),
        "sin": (1, math.sin), "cos": (1, math.cos),
        "tan": (1, math.tan), "asin": (1, math.asin),
        "acos": (1, math.acos), "atan": (1, math.atan),
        "atan2": (2, math.atan2), "ceil": (1, math.ceil),
        "floor": (1, math.floor),
        "round": (1, lambda a: math.ceil(a) if a - math.floor(a) >= 0.5
                  else math.floor(a)),          # MYROUND, variable.cpp:29
    }

    def _tokens(self, s: str) -> List[str]:
        out, pos = [], 0
        while pos < len(s):
            m = self._TOKEN_RE.match(s, pos)
            if m is None:
                if s[pos:].strip() == "":
                    break
                raise MRError(f"Invalid syntax in variable formula: "
                              f"{s[pos:]!r}")
            out.append(m.group("num") or m.group("name") or m.group("op"))
            pos = m.end()
        return out

    def evaluate(self, formula: str) -> float:
        toks = self._tokens(formula)
        pos = [0]

        def peek():
            return toks[pos[0]] if pos[0] < len(toks) else None

        def take():
            t = peek()
            pos[0] += 1
            return t

        def expect(t):
            if take() != t:
                raise MRError(f"Expected {t!r} in variable formula")

        def atom() -> float:
            t = take()
            if t is None:
                raise MRError("Invalid variable formula")
            if t == "(":
                v = or_expr()
                expect(")")
                return v
            if t == "-":
                return -atom()
            if t == "!":
                return 0.0 if atom() != 0.0 else 1.0
            if t[0].isdigit() or t[0] == ".":
                return float(t)
            if t == "PI":
                return math.pi
            if t in self.specials:
                return float(self.specials[t]())
            if t in ("random", "normal"):
                expect("(")
                a = or_expr(); expect(",")
                b = or_expr(); expect(",")
                c = or_expr(); expect(")")
                if self._rng is None:
                    self._rng = _random.Random(int(c))
                return (self._rng.uniform(a, b) if t == "random"
                        else b * self._rng.gauss(0.0, 1.0) + a)
            if t in self._FUNCS:
                nargs, fn = self._FUNCS[t]
                expect("(")
                args = [or_expr()]
                for _ in range(nargs - 1):
                    expect(",")
                    args.append(or_expr())
                expect(")")
                return float(fn(*args))
            if t.startswith("v_"):
                val = self.retrieve(t[2:])
                if val is None:
                    raise MRError(f"Invalid variable reference {t!r} in "
                                  f"variable formula")
                return float(val)
            raise MRError(f"Invalid keyword {t!r} in variable formula")

        def power() -> float:
            v = atom()
            if peek() == "^":           # right-associative
                take()
                return v ** power()
            return v

        def _level(sub, ops) -> float:
            v = sub()
            while peek() in ops:
                op = take()
                r = sub()
                v = ops[op](v, r)
            return v

        def mul_expr():
            return _level(power, {"*": lambda a, b: a * b,
                                  "/": lambda a, b: a / b})

        def add_expr():
            return _level(mul_expr, {"+": lambda a, b: a + b,
                                     "-": lambda a, b: a - b})

        def cmp_expr():
            return _level(add_expr, {
                "<": lambda a, b: float(a < b),
                "<=": lambda a, b: float(a <= b),
                ">": lambda a, b: float(a > b),
                ">=": lambda a, b: float(a >= b)})

        def eq_expr():
            return _level(cmp_expr, {"==": lambda a, b: float(a == b),
                                     "!=": lambda a, b: float(a != b)})

        def and_expr():
            return _level(eq_expr,
                          {"&&": lambda a, b: float(bool(a) and bool(b))})

        def or_expr():
            return _level(and_expr,
                          {"||": lambda a, b: float(bool(a) or bool(b))})

        try:
            result = or_expr()
        except (ZeroDivisionError, OverflowError, ValueError) as e:
            raise MRError(f"Error in variable formula {formula!r}: {e}")
        if peek() is not None:
            raise MRError(f"Invalid variable formula {formula!r}")
        return result

    def evaluate_boolean(self, s: str) -> float:
        return self.evaluate(s)
