"""Script variables — the LAMMPS-style variable engine of OINK.

Reference: ``oink/variable.{h,cpp}`` — styles INDEX/LOOP/WORLD/UNIVERSE/
ULOOP/STRING/EQUAL (``variable.cpp:31``), ``retrieve()`` (string value of
$x substitution), ``next()`` (advance loop variables, signalling
exhaustion for the jump/next idiom), and the EQUAL-style formula
evaluator with C-like precedence, math functions, and the ``time``/
``nprocs`` specials (``variable.cpp:560-1010``).

Redesigns vs the reference:

* the evaluator is a recursive-descent parser over a token list instead
  of the reference's dual value/operator stack machine — same grammar,
  same precedence table (``variable.cpp:60-69``), no ``eval()``;
* WORLD/UNIVERSE/ULOOP exist for script parity but run single-world:
  WORLD picks its first value, UNIVERSE/ULOOP behave as INDEX/LOOP (the
  reference splits MPI_COMM_WORLD into partitions and coordinates ULOOP
  through a lock file, ``variable.cpp:186-240`` — a multi-job scheduling
  device, not a data-parallel one; our mesh parallelism lives below the
  MapReduce API instead).
"""

from __future__ import annotations

import math
import random as _random
import re
from typing import Callable, Dict, List, Optional

from ..core.runtime import MRError

_STYLES = ("index", "loop", "world", "universe", "uloop", "string", "equal")


class _Var:
    def __init__(self, style: str, values: List[str], which: int = 0,
                 offset: int = 0, pad: int = 0):
        self.style = style
        self.values = values          # INDEX/WORLD/UNIVERSE/STRING: strings
        self.num = len(values)        # LOOP/ULOOP: overridden below
        self.which = which
        self.offset = offset
        self.pad = pad


class Variables:
    """The variable table; one per interpreter (reference Variable class).

    ``specials`` maps EQUAL keywords to zero-arg callables — the
    interpreter installs ``time`` (elapsed seconds of the last command,
    ``oink/input.cpp:458-464``) and ``nprocs``."""

    def __init__(self):
        self._vars: Dict[str, _Var] = {}
        self.specials: Dict[str, Callable[[], float]] = {}
        self._rng: Optional[_random.Random] = None

    # -- the `variable` command (reference Variable::set) ------------------
    def set(self, args: List[str]):
        if len(args) < 2:
            raise MRError("Illegal variable command")
        name, style = args[0], args[1]
        if style == "delete":
            if len(args) != 2:
                raise MRError("Illegal variable command")
            self._vars.pop(name, None)
            return
        if style not in _STYLES:
            raise MRError(f"Illegal variable command: unknown style "
                          f"{style!r}")
        if name in self._vars:
            old = self._vars[name].style
            if style in ("string", "equal"):
                # STRING/EQUAL may be reset in place (variable.cpp:228-259)
                if old != style:
                    raise MRError("Cannot redefine variable as a "
                                  "different style")
            else:
                return  # INDEX/LOOP/...: first definition wins

        if style in ("index", "world", "universe"):
            if len(args) < 3:
                raise MRError("Illegal variable command")
            v = _Var(style, args[2:])
            if style == "world":
                v.which = 0        # single world (see module docstring)
        elif style in ("loop", "uloop"):
            rest = args[2:]
            pad = 0
            if rest and rest[-1] == "pad":
                rest = rest[:-1]
                pad = 1
            if len(rest) == 1:
                nfirst, nlast = 1, int(rest[0])
            elif len(rest) == 2 and style == "loop":
                nfirst, nlast = int(rest[0]), int(rest[1])
            else:
                raise MRError("Illegal variable command")
            if nfirst > nlast or nlast <= 0:
                raise MRError("Illegal variable command")
            v = _Var(style, [], offset=nfirst,
                     pad=len(str(nlast)) if pad else 0)
            v.num = nlast - nfirst + 1
        elif style == "string":
            if len(args) != 3:
                raise MRError("Illegal variable command")
            v = _Var(style, [args[2]])
        else:  # equal
            if len(args) != 3:
                raise MRError("Illegal variable command")
            v = _Var(style, [args[2]])
        self._vars[name] = v

    # -- retrieval (reference Variable::retrieve) ---------------------------
    def find(self, name: str) -> Optional[_Var]:
        return self._vars.get(name)

    def retrieve(self, name: str) -> Optional[str]:
        v = self._vars.get(name)
        if v is None or v.which >= v.num:
            return None
        if v.style in ("index", "world", "universe", "string"):
            return v.values[v.which]
        if v.style in ("loop", "uloop"):
            n = v.which + v.offset
            return f"{n:0{v.pad}d}" if v.pad else str(n)
        # equal: evaluate on every retrieval (reference %.10g format)
        return f"{self.evaluate(v.values[0]):.10g}"

    def retrieve_count(self, name: str) -> int:
        v = self._vars.get(name)
        if v is None:
            raise MRError(f"variable {name!r} is unknown")
        return v.num

    def retrieve_single(self, name: str, nth: int) -> str:
        v = self._vars[name]
        if v.style in ("index", "world", "universe", "string"):
            return v.values[nth]
        n = nth + v.offset
        return f"{n:0{v.pad}d}" if v.pad else str(n)

    def equal_style(self, name: str) -> bool:
        v = self._vars.get(name)
        return v is not None and v.style == "equal"

    # -- the `next` command (reference Variable::next) ----------------------
    def next(self, names: List[str]) -> bool:
        """Advance the listed loop variables.  Returns True when any is
        exhausted (the variable is removed and the caller skips its next
        jump — input.cpp:726-728)."""
        if not names:
            raise MRError("Illegal next command")
        styles = set()
        for n in names:
            v = self._vars.get(n)
            if v is None:
                raise MRError("Invalid variable in next command")
            styles.add("uni" if v.style in ("universe", "uloop")
                       else v.style)
        if len(styles) > 1:
            raise MRError("All variables in next command must be same "
                          "style")
        style = styles.pop()
        if style in ("string", "equal", "world"):
            raise MRError("Invalid variable style with next command")
        exhausted = False
        for n in names:
            v = self._vars[n]
            v.which += 1
            if v.which >= v.num:
                exhausted = True
                del self._vars[n]
        return exhausted

    # ------------------------------------------------------------------
    # EQUAL-style formula evaluation (reference variable.cpp:560-1010)
    # grammar: || < && < == != < < <= > >= < + - < * / < ^ < unary -/!
    # operands: number, PI, time, nprocs, v_name, fn(args...), (expr)
    # ------------------------------------------------------------------

    _TOKEN_RE = re.compile(r"""
        \s*(?:
          (?P<num>\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?)
        | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
        | (?P<op>\|\||&&|==|!=|<=|>=|[-+*/^()!<>,])
        )""", re.VERBOSE)

    _FUNCS = {
        "sqrt": (1, math.sqrt), "exp": (1, math.exp),
        "ln": (1, math.log), "log": (1, math.log10),
        "sin": (1, math.sin), "cos": (1, math.cos),
        "tan": (1, math.tan), "asin": (1, math.asin),
        "acos": (1, math.acos), "atan": (1, math.atan),
        "atan2": (2, math.atan2), "ceil": (1, math.ceil),
        "floor": (1, math.floor),
        "round": (1, lambda a: math.ceil(a) if a - math.floor(a) >= 0.5
                  else math.floor(a)),          # MYROUND, variable.cpp:29
    }

    def _tokens(self, s: str) -> List[str]:
        out, pos = [], 0
        while pos < len(s):
            m = self._TOKEN_RE.match(s, pos)
            if m is None:
                if s[pos:].strip() == "":
                    break
                raise MRError(f"Invalid syntax in variable formula: "
                              f"{s[pos:]!r}")
            out.append(m.group("num") or m.group("name") or m.group("op"))
            pos = m.end()
        return out

    def evaluate(self, formula: str) -> float:
        toks = self._tokens(formula)
        pos = [0]

        def peek():
            return toks[pos[0]] if pos[0] < len(toks) else None

        def take():
            t = peek()
            pos[0] += 1
            return t

        def expect(t):
            if take() != t:
                raise MRError(f"Expected {t!r} in variable formula")

        def atom() -> float:
            t = take()
            if t is None:
                raise MRError("Invalid variable formula")
            if t == "(":
                v = or_expr()
                expect(")")
                return v
            if t == "-":
                return -atom()
            if t == "!":
                return 0.0 if atom() != 0.0 else 1.0
            if t[0].isdigit() or t[0] == ".":
                return float(t)
            if t == "PI":
                return math.pi
            if t in self.specials:
                return float(self.specials[t]())
            if t in ("random", "normal"):
                expect("(")
                a = or_expr(); expect(",")
                b = or_expr(); expect(",")
                c = or_expr(); expect(")")
                if self._rng is None:
                    self._rng = _random.Random(int(c))
                return (self._rng.uniform(a, b) if t == "random"
                        else b * self._rng.gauss(0.0, 1.0) + a)
            if t in self._FUNCS:
                nargs, fn = self._FUNCS[t]
                expect("(")
                args = [or_expr()]
                for _ in range(nargs - 1):
                    expect(",")
                    args.append(or_expr())
                expect(")")
                return float(fn(*args))
            if t.startswith("v_"):
                val = self.retrieve(t[2:])
                if val is None:
                    raise MRError(f"Invalid variable reference {t!r} in "
                                  f"variable formula")
                return float(val)
            raise MRError(f"Invalid keyword {t!r} in variable formula")

        def power() -> float:
            v = atom()
            if peek() == "^":           # right-associative
                take()
                return v ** power()
            return v

        def _level(sub, ops) -> float:
            v = sub()
            while peek() in ops:
                op = take()
                r = sub()
                v = ops[op](v, r)
            return v

        def mul_expr():
            return _level(power, {"*": lambda a, b: a * b,
                                  "/": lambda a, b: a / b})

        def add_expr():
            return _level(mul_expr, {"+": lambda a, b: a + b,
                                     "-": lambda a, b: a - b})

        def cmp_expr():
            return _level(add_expr, {
                "<": lambda a, b: float(a < b),
                "<=": lambda a, b: float(a <= b),
                ">": lambda a, b: float(a > b),
                ">=": lambda a, b: float(a >= b)})

        def eq_expr():
            return _level(cmp_expr, {"==": lambda a, b: float(a == b),
                                     "!=": lambda a, b: float(a != b)})

        def and_expr():
            return _level(eq_expr,
                          {"&&": lambda a, b: float(bool(a) and bool(b))})

        def or_expr():
            return _level(and_expr,
                          {"||": lambda a, b: float(bool(a) or bool(b))})

        try:
            result = or_expr()
        except (ZeroDivisionError, OverflowError, ValueError) as e:
            raise MRError(f"Error in variable formula {formula!r}: {e}")
        if peek() is not None:
            raise MRError(f"Invalid variable formula {formula!r}")
        return result

    def evaluate_boolean(self, s: str) -> float:
        return self.evaluate(s)
