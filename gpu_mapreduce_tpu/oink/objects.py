"""OINK object manager — named/temporary MapReduce objects + I/O descriptors.

Re-designs ``oink/object.{h,cpp}``: the registry of wrapped MR objects that
commands create, consume, and hand back to the script layer.

* named MRs persist across commands (``mr`` script objects); temporaries
  from :meth:`create_mr` die at :meth:`cleanup` (``object.cpp`` MRwrap
  lifecycle, ``oink/object.h:91-98``);
* input descriptors (``-i`` in scripts, ``oink/object.h:117-155``) are
  either file path globs (command reads them with a parser callback) or an
  existing named MR (used directly — commands copy-on-write if permanent,
  mirroring ``obj->permanent(mr) ⇒ copy_mr``);
* output descriptors (``-o``) carry a file path (the command's print
  callback writes it) and/or a name to register the result MR under;
* per-script MR defaults (the ``set`` command, ``oink/object.h:100-113``):
  verbosity/timer/memsize/outofcore/minpage/maxpage/freepage/zeropage/
  fpath applied to every MR the manager creates.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Union

from ..core.mapreduce import MapReduce
from ..core.runtime import MRError


@dataclass
class InputDescriptor:
    paths: Optional[List[str]] = None     # file/glob mode
    mr_name: Optional[str] = None         # named-MR mode


@dataclass
class OutputDescriptor:
    path: Optional[str] = None            # write file via print callback
    mr_name: Optional[str] = None         # register result as named MR


class ObjectManager:
    """Holds named MRs, temporaries, descriptors, and MR defaults."""

    # settings the `set` script command may override (doc: oinkdoc/set.txt;
    # `fuse` is ours — plan/ fused pipelines, doc/plan.md — as is
    # `onfault`, the ft/ failed-map-input policy, doc/reliability.md)
    MR_SETTINGS = ("verbosity", "timer", "memsize", "outofcore", "minpage",
                   "maxpage", "freepage", "zeropage", "fpath", "fuse",
                   "onfault")

    def __init__(self, comm=None):
        self.comm = comm
        self.named: Dict[str, MapReduce] = {}
        self._temps: List[MapReduce] = []
        self._anon_names: List[str] = []
        self._anon_counter = 0
        self.defaults: Dict[str, object] = {}
        self.pinned: Dict[str, object] = {}
        self.inputs: List[InputDescriptor] = []
        self.outputs: List[OutputDescriptor] = []

    # -- settings ----------------------------------------------------------
    def set_default(self, name: str, value):
        if name not in self.MR_SETTINGS:
            raise MRError(f"unknown set parameter {name!r}")
        if name in self.pinned and value != self.pinned[name]:
            # serve/ tenancy: budget settings the daemon seeded are not
            # the tenant's to change — a script `set maxpage 100000`
            # must fail its session loudly, not escape its allowance
            raise MRError(f"setting {name!r} is pinned by the server "
                          f"(tenant budget; doc/serve.md)")
        self.defaults[name] = value

    def pin(self, **settings):
        """Install settings as defaults AND lock them: later
        ``set_default`` calls (the script `set` command) for these keys
        raise instead of overriding — the serve/ tenant-budget
        enforcement point."""
        for name, value in settings.items():
            self.set_default(name, value)
            self.pinned[name] = value

    # -- MR lifecycle ------------------------------------------------------
    def create_mr(self) -> MapReduce:
        mr = MapReduce(self.comm, **self.defaults)
        self._temps.append(mr)
        return mr

    def permanent(self, mr: MapReduce) -> bool:
        return any(m is mr for m in self.named.values())

    def copy_mr(self, mr: MapReduce) -> MapReduce:
        cp = mr.copy()
        self._temps.append(cp)
        return cp

    def name_mr(self, name: str, mr: MapReduce):
        self.named[name] = mr
        self._temps = [m for m in self._temps if m is not mr]

    def get_mr(self, name: str) -> MapReduce:
        if name not in self.named:
            raise MRError(f"no MapReduce object named {name!r}")
        return self.named[name]

    def free_mr(self, mr: MapReduce):
        """Free a temporary's data mid-command (iterative commands create
        MRs per round; deferring to cleanup() would grow memory linearly
        with iteration count)."""
        if mr.kv is not None:
            mr.kv.free()
            mr.kv = None
        if mr.kmv is not None:
            mr.kmv.free()
            mr.kmv = None
        self._temps = [m for m in self._temps if m is not mr]

    def delete_mr(self, name: str):
        mr = self.named.pop(name, None)
        if mr is not None:
            if mr.kv is not None:
                mr.kv.free()
            if mr.kmv is not None:
                mr.kmv.free()

    def cleanup(self):
        """Free temporaries and drop anonymous input registrations
        (reference Object::cleanup).  Anonymous MRs are caller-owned, so
        only the registry entry is released, not their data."""
        for mr in self._temps:
            if mr.kv is not None:
                mr.kv.free()
            if mr.kmv is not None:
                mr.kmv.free()
        self._temps = []
        for name in self._anon_names:
            self.named.pop(name, None)
        self._anon_names = []
        self.inputs = []
        self.outputs = []

    # -- descriptors -------------------------------------------------------
    def add_input(self, source: Union[str, "os.PathLike",
                                      Sequence[str], MapReduce]):
        """Add the next -i descriptor: path(s) or a named MR (by name)."""
        if isinstance(source, os.PathLike):
            source = os.fspath(source)
        if isinstance(source, MapReduce):
            self._anon_counter += 1
            name = f"_anon{self._anon_counter}"
            self.named[name] = source
            self._anon_names.append(name)
            self.inputs.append(InputDescriptor(mr_name=name))
        elif isinstance(source, str) and source in self.named:
            self.inputs.append(InputDescriptor(mr_name=source))
        else:
            paths = [source] if isinstance(source, str) else list(source)
            self.inputs.append(InputDescriptor(paths=paths))

    def add_output(self, path: Optional[str] = None,
                   mr_name: Optional[str] = None):
        self.outputs.append(OutputDescriptor(path=path, mr_name=mr_name))

    # -- the command-facing protocol (reference obj->input/obj->output) ----
    def input(self, index: int, parser: Optional[Callable] = None,
              ptr=None) -> MapReduce:
        """Resolve -i descriptor #index (1-based).  File mode runs
        ``parser(itask, filename, kv, ptr)`` over the paths; MR mode
        returns the named MR as-is (reference oink/object.cpp add_input)."""
        if index > len(self.inputs):
            raise MRError(f"command input {index} not provided")
        d = self.inputs[index - 1]
        if d.mr_name is not None:
            return self.get_mr(d.mr_name)
        if parser is None:
            raise MRError("file input requires a parser callback")
        mr = self.create_mr()
        mr.map_files(d.paths, parser, ptr)
        return mr

    def output(self, index: int, mr: MapReduce,
               printer: Optional[Callable] = None, ptr=None):
        """Handle -o descriptor #index: write ``printer(key, value, fp)``
        lines to the path if given; register mr under the name if given.
        Missing descriptor ⇒ no-op (commands always call output; scripts
        decide, reference oink/object.cpp:237-370).

        A mesh-resident dataset on P>1 shards writes PER-SHARD files —
        ``path.<p>``, or the first ``%`` in the path replaced by the
        shard id (the reference's expandpath postpend/substitute rules,
        oink/object.cpp:900-941) — each from its own shard block, so
        output never funnels the dataset through the controller.  Host
        datasets (and P==1) keep the exact single path: our serial tier
        intentionally omits the reference's ``.0`` suffix so script
        goldens address one file."""
        mr._flush_plan()   # a pending fused plan must land before we read
        if index > len(self.outputs):
            return
        d = self.outputs[index - 1]
        if d.path is not None:
            _ensure_parent(d.path)
            fr = _mesh_frame(mr)
            if fr is not None and fr.nprocs > 1:
                for p in range(fr.nprocs):
                    if "%" in d.path:
                        path = d.path.replace("%", str(p), 1)
                    else:
                        path = f"{d.path}.{p}"
                    host = fr.shard_to_host(p)
                    with open(path, "w") as fp:
                        rows = (host.pairs() if hasattr(host, "pairs")
                                else host.groups())
                        if printer is None:
                            for k, v in rows:
                                fp.write(f"{k} {v}\n")
                        else:
                            for k, v in rows:
                                printer(k, v, fp)
            else:
                with open(d.path, "w") as fp:
                    if printer is None:
                        mr_dump(mr, fp)
                    else:
                        for k, v in _iter_pairs(mr):
                            printer(k, v, fp)
        if d.mr_name is not None:
            self.name_mr(d.mr_name, mr)


def _ensure_parent(path: str) -> None:
    """Create an -o path's parent directory: `set prepend sub` (and the
    serve/ session re-rooting built on it) names nested output paths
    whose directories the script never mkdir'd."""
    parent = os.path.dirname(path)
    if parent:
        try:
            os.makedirs(parent, exist_ok=True)
        except OSError:
            pass    # the open() that follows reports the real error


def _mesh_frame(mr: MapReduce):
    """The mr's single mesh-resident frame, or None (host/serial data,
    multi-frame datasets, or no data)."""
    from ..parallel.sharded import ShardedKMV, ShardedKV
    ds = mr.kv if mr.kv is not None else mr.kmv
    if ds is None or ds.nframes != 1:
        return None
    fr = next(iter(ds.frames()))
    return fr if isinstance(fr, (ShardedKV, ShardedKMV)) else None


def _iter_pairs(mr: MapReduce):
    """Yield (key, value) per KV pair, or (key, [values]) per KMV group when
    the MR holds a KMV (e.g. neighbor's adjacency lists)."""
    if mr.kv is not None:
        for fr in mr.kv.frames():
            yield from fr.pairs()
    elif mr.kmv is not None:
        for fr in mr.kmv.frames():
            yield from fr.groups()


def mr_dump(mr: MapReduce, fp):
    for k, v in _iter_pairs(mr):
        fp.write(f"{k} {v}\n")
