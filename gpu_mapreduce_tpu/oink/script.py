"""The OINK input-script interpreter.

Reference: ``oink/input.{h,cpp}`` — line reader with ``&`` continuation,
quote-aware ``#`` comments and ``$``/``${}`` variable substitution
(``input.cpp:258-379``), built-ins clear/echo/if/include/jump/label/log/
next/print/shell/variable (``input.cpp:497-796``), the OINK commands
input/mr/output/set, CommandStyle registry dispatch with ``-i``/``-o``
switch parsing (``input.cpp:417-468``), and named-MR method dispatch
(``input.cpp:473-484``).  Plus the ``oink/oink.cpp`` command-line
switches ``-in/-log/-screen/-echo/-var``.

Single-process redesign notes: the reference reads lines on rank 0 and
MPI_Bcasts them (``input.cpp:130-148``) — here the interpreter is host
Python driving device-parallel MapReduce objects, so no line broadcast
exists; command timing keeps the reference's semantics (elapsed seconds
of the last command, exposed as the ``time`` EQUAL keyword) without the
barriers.  ``-partition`` multi-world runs split the device mesh into
per-world sub-meshes driven by concurrent interpreter threads — see
``universe.py``.
"""

from __future__ import annotations

import os
import shutil
import sys
import time as _time
from typing import List, Optional, TextIO

from ..core.runtime import MRError
from .command import COMMANDS
from .mrscript import MRScriptDispatch, expand_path_variable
from .objects import ObjectManager
from .variables import Variables


class OinkScript:
    """One interpreter instance: variable table + object manager + log.

    ``comm``: optional mesh (forwarded to every MR the script creates).
    ``screen``: None → stdout, False → silent, or a file-like.
    ``obj``: a caller-owned :class:`ObjectManager` — the serve/ daemon
    hands each session its own namespace (pre-loaded with tenant budget
    defaults), so two concurrent sessions both running ``mr x`` never
    collide; when given, its ``comm`` wins."""

    def __init__(self, comm=None, screen=None, logfile: Optional[str] = None,
                 world=None, obj: Optional[ObjectManager] = None):
        self.obj = obj if obj is not None else ObjectManager(comm=comm)
        self.variables = Variables(world=world)
        self.dispatch = MRScriptDispatch(self.obj, self.variables)
        self.screen: Optional[TextIO]
        if screen is None:
            self.screen = sys.stdout
        elif screen is False:
            self.screen = None
        else:
            self.screen = screen
        self.logfile: Optional[TextIO] = open(logfile, "w") if logfile \
            else None
        self.echo_screen = False       # reference default: echo log only
        self.echo_log = True
        self.deltatime = 0.0           # `time` keyword (input.cpp:463)
        self.variables.specials["time"] = lambda: self.deltatime
        self.variables.specials["nprocs"] = lambda: self._nprocs()
        # label scanning + file stack (reference label_active/infiles)
        self._label_active = False
        self._labelstr = ""
        self._jump_skip = False
        self._jump_to: Optional[tuple] = None   # (filename-or-SELF, lines)
        # ft/ journaling + resume state (doc/reliability.md): a journal
        # armed by MRTPU_JOURNAL records every completed command and
        # auto-checkpoints the named MRs; resume replays the recorded
        # lines, skipping the first _ft_skip command EXECUTIONS
        # (builtins re-run so loop variables and jumps reproduce), then
        # restores the MRs from _ft_restore and continues live
        from ..ft.journal import from_env as _ft_from_env
        self._ft_journal = _ft_from_env(script_mode=True)
        self._ft_skip = 0
        self._ft_restore: Optional[tuple] = None   # (ckpt record, dir)
        self._ft_resuming = False
        # resume_into sets this when the restored checkpoint was taken
        # on a DIFFERENT mesh width than this interpreter runs — the
        # serve/ daemon surfaces it as meta.resharded (degraded mode)
        self._ft_resharded = False
        self._ft_depth = 0
        self._ft_pending_begin: Optional[tuple] = None
        # post-command hooks: callables invoked with the script after
        # EVERY completed non-builtin command (after its journal record
        # + auto-checkpoint).  The serve/ mesh autoscaler's live
        # promotion rides here; a raising hook is dropped, never fatal.
        self.post_cmd: List = []

    def _nprocs(self) -> int:
        # query the backend directly — creating (and leaking until the
        # next command cleanup) a temp MR per `$p` substitution
        # accumulated live objects
        if not hasattr(self, "_nprocs_cache"):
            comm = self.obj.comm
            if comm is None or isinstance(comm, int):
                self._nprocs_cache = 1
            else:
                from ..parallel.mesh import mesh_axis_size
                self._nprocs_cache = mesh_axis_size(comm)
        return self._nprocs_cache

    def close(self):
        if self.logfile:
            self.logfile.close()
            self.logfile = None

    # ------------------------------------------------------------------
    # output plumbing
    # ------------------------------------------------------------------
    def _emit(self, text: str):
        if self.screen is not None:
            self.screen.write(text)
        if self.logfile is not None:
            self.logfile.write(text)

    def _echo(self, line: str):
        if self._label_active:
            return
        if self.echo_screen and self.screen is not None:
            self.screen.write(line + "\n")
        if self.echo_log and self.logfile is not None:
            self.logfile.write(line + "\n")

    # ------------------------------------------------------------------
    # driving (reference Input::file / Input::one)
    # ------------------------------------------------------------------
    def run_file(self, filename: str):
        with open(filename) as f:
            lines = f.read().splitlines()
        self._run_script(lines, filename)

    def run_string(self, text: str):
        self._run_script(text.splitlines(), "<string>")

    def _run_script(self, lines: List[str], name: str):
        """Top-level driver: with a journal armed, the outermost run
        stages its lines as the pending ``begin`` record — written
        LAZILY at the first completed command, so a script that only
        runs builtins (e.g. the one-line `resume <dir>` runbook entry
        with MRTPU_JOURNAL still pointing at the same directory) never
        writes a bogus begin that would shadow the real script's on the
        next resume.  Nested runs (``include``) don't re-begin."""
        j = self._ft_journal
        if j is not None and self._ft_depth == 0 and not self._ft_resuming:
            self._ft_pending_begin = (list(lines), name)
        self._ft_depth += 1
        try:
            if self._ft_depth == 1:
                # request-scoped trace context (obs/context.py): a
                # top-level script run is ONE request — its spans,
                # journal records and quarantine records all carry one
                # trace_id.  ensure_scope reuses an enclosing context
                # (a serve/ session wrapping this script stays one
                # request) and no-ops under MRTPU_PROFILE=0; nested
                # include/jump runs arrive at depth > 1 and never
                # re-scope
                from ..obs.context import ensure_scope
                with ensure_scope(label=f"oink:{name}"):
                    self._run_lines(lines, name)
            else:
                self._run_lines(lines, name)
        finally:
            self._ft_depth -= 1

    def _run_lines(self, lines: List[str], filename: str):
        i = 0
        while i < len(lines):
            # '&' continuation (input.cpp:117-126)
            line = lines[i]
            while line.rstrip().endswith("&") and i + 1 < len(lines):
                line = line.rstrip()[:-1] + lines[i + 1]
                i += 1
            i += 1
            self.one(line)
            if self._jump_to is not None:
                target, tlines = self._jump_to
                self._jump_to = None
                if target == "SELF":
                    i = 0          # rewind (input.cpp:672)
                else:
                    self._run_lines(tlines, target)
                    return
        if self._label_active:
            raise MRError("Label wasn't found in input script")

    def one(self, line: str) -> Optional[str]:
        """Parse + execute a single command line; returns the command
        word (reference Input::one)."""
        self._echo(line)
        stripped = _strip_comment(line)
        if not self._label_active:
            stripped = self._substitute(stripped)
        words = _split_args(stripped)
        if not words:
            return None
        command, args = words[0], words[1:]
        if self._label_active and command != "label":
            return None
        self._execute(command, args)
        return command

    # ------------------------------------------------------------------
    # substitution (reference Input::substitute) — quote-aware $x / ${x}
    # ------------------------------------------------------------------
    def _substitute(self, s: str) -> str:
        out = []
        quote = ""
        i = 0
        while i < len(s):
            c = s[i]
            if c == "$" and not quote:
                if i + 1 < len(s) and s[i + 1] == "{":
                    j = s.find("}", i + 2)
                    if j < 0:
                        raise MRError("Invalid variable name")
                    name = s[i + 2:j]
                    i = j + 1
                else:
                    if i + 1 >= len(s):
                        raise MRError("Invalid variable name")
                    name = s[i + 1]
                    i += 2
                value = self.variables.retrieve(name)
                if value is None:
                    raise MRError(f"Substitution for illegal variable "
                                  f"{name!r}")
                out.append(value)
                continue
            if quote and c == quote:
                quote = ""
            elif not quote and c in "\"'":
                quote = c
            out.append(c)
            i += 1
        return "".join(out)

    # ------------------------------------------------------------------
    # dispatch (reference Input::execute_command)
    # ------------------------------------------------------------------
    _BUILTINS = ("clear", "echo", "if", "include", "jump", "label", "log",
                 "next", "print", "shell", "variable",
                 "input", "mr", "output", "set", "resume")

    def _execute(self, command: str, args: List[str]):
        if command in self._BUILTINS:
            # resume replay: builtins re-run so loop variables and
            # control flow reproduce — EXCEPT `shell`, whose arbitrary
            # filesystem side effects (mv/rm) already happened before
            # the checkpoint and must not replay
            if self._ft_skip > 0 and command == "shell":
                return
            getattr(self, "cmd_" + command)(args)
            return
        if self._ft_skip > 0:
            # resume replay: the first _ft_skip command EXECUTIONS are
            # already durable in the restore checkpoint — skip them,
            # then load the checkpointed MRs.  ANY non-builtin word
            # counts: a skipped registered command may be what names
            # the MR a later prefix line dispatches on (`-o NULL x`
            # then `x ...`), so `x` not being in obj.named yet is
            # expected, not an unknown command
            self._ft_skip -= 1
            if self._ft_skip == 0:
                self._ft_apply_restore()
            return
        # the pending begin lands BEFORE the first command starts: a
        # crash mid-command-1 must still leave a resumable journal,
        # while a builtins-only script (the `resume <dir>` one-liner)
        # never writes one
        self._ft_flush_begin()
        if command in COMMANDS:
            self._run_registered(command, args)
            self._ft_cmd_done(command)
            return
        if command in self.obj.named:
            from ..obs import get_tracer
            t0 = _time.perf_counter()
            with get_tracer().span(f"oink.{command}", cat="oink",
                                   args=" ".join(args)):
                self.dispatch.run(command, args)
            self.deltatime = _time.perf_counter() - t0
            self._ft_cmd_done(command)
            return
        raise MRError(f"Unknown command: {command}")

    def _ft_flush_begin(self):
        j = self._ft_journal
        if j is not None and self._ft_pending_begin is not None:
            lines, name = self._ft_pending_begin
            self._ft_pending_begin = None
            j.begin(lines, name)

    def _ft_cmd_done(self, command: str):
        """Journal one COMPLETED command (record follows the fact) and
        auto-checkpoint every MRTPU_CKPT_EVERY commands.

        Also the command-round cancellation barrier and the generic
        post-command hook point: hooks run AFTER the journal/checkpoint
        landed (the serve/ mesh autoscaler promotes here — a clean
        host-side point between commands), then a cancelled request
        stops — with the checkpoint already durable, which is what
        leaves the session directory resumable at this exact boundary
        (doc/serve.md#deadlines-and-cancel)."""
        j = self._ft_journal
        if j is not None:
            self._ft_flush_begin()
            j.cmd_done(command)
            j.maybe_checkpoint(self.obj)
        for hook in list(self.post_cmd):
            try:
                hook(self)
            except Exception:
                # an observer hook must never kill the script it rides
                # (guarded remove: the hook may have removed itself
                # before raising)
                if hook in self.post_cmd:
                    self.post_cmd.remove(hook)
        from ..obs.context import barrier_check
        barrier_check()

    def _ft_apply_restore(self):
        rec, self._ft_restore = self._ft_restore, None
        if not rec:
            return
        ckpt, dir = rec
        from ..ft.journal import restore_mrs
        restore_mrs(self.obj, ckpt, dir)

    def cmd_resume(self, args):
        """resume <dir> — replay the op journal under <dir> from its
        last durable checkpoint into THIS interpreter (ft/journal.py;
        doc/reliability.md has the runbook)."""
        if len(args) != 1:
            raise MRError("Illegal resume command")
        from ..ft.journal import resume_into
        resume_into(self, args[0])

    def _run_registered(self, name: str, args: List[str]):
        """-i/-o switch split + params + run (input.cpp:429-468)."""
        iarg = 0
        while iarg < len(args) and args[iarg] not in ("-i", "-o"):
            iarg += 1
        params, rest = args[:iarg], args[iarg:]
        cmd = COMMANDS[name](self.obj, screen=self.screen
                             if self.screen is not None else False)
        cmd.params(params)
        i = 0
        ninput_args = 0
        while i < len(rest):
            if rest[i] == "-i":
                j = i + 1
                while j < len(rest) and rest[j] not in ("-i", "-o"):
                    j += 1
                for a in rest[i + 1:j]:
                    self._add_input(a)
                ninput_args += j - i - 1
                i = j
            elif rest[i] == "-o":
                j = i + 1
                while j < len(rest) and rest[j] not in ("-i", "-o"):
                    j += 1
                pairs = rest[i + 1:j]
                if len(pairs) % 2:
                    raise MRError("Invalid command switch: -o takes "
                                  "file/name pairs")
                for k in range(0, len(pairs), 2):
                    f, n = pairs[k], pairs[k + 1]
                    self.obj.add_output(
                        path=None if f == "NULL"
                        else self._expandpath(f, output=True),
                        mr_name=None if n == "NULL" else n)
                i = j
            else:
                raise MRError("Invalid command switch")
        # one arg per input descriptor, arity checked like the reference
        # (command.cpp:21-27 "Mismatch in command inputs") — silently
        # dropping extras hid a two-file `-i f1 f2` on a 1-input command
        # (r5 verify); a multi-file input goes through a v_name variable
        if ninput_args and ninput_args != cmd.ninputs:
            raise MRError(
                f"Mismatch in command inputs: {name} takes "
                f"{cmd.ninputs}, got {ninput_args} (use a v_name "
                f"variable for a multi-file input)")
        from ..obs import get_tracer
        t0 = _time.perf_counter()
        try:
            # every script command is one span (obs/): a script's trace
            # reads as oink.<command> parents over the MR-op spans
            with get_tracer().span(f"oink.{name}", cat="oink",
                                   args=" ".join(params)):
                cmd.run()
        finally:
            self.obj.cleanup()
        self.deltatime = _time.perf_counter() - t0

    def _expandpath(self, path: str, output: bool = False) -> str:
        """prepend + '%' substitution (reference expandpath,
        object.cpp:913-960): output paths always expand '%' to the proc
        id (0 under one controller); input paths only when `set
        substitute` is on."""
        if output or getattr(self, "_path_substitute", 0):
            path = path.replace("%", "0")
        pre = getattr(self, "_path_prepend", None)
        if pre:
            path = os.path.join(pre, path)
        return path

    def _add_input(self, arg: str):
        """-i arg: named MR, v_name multi-path variable (object.cpp
        add_input v_ handling, :450-462), or a path."""
        if arg in self.obj.named:
            self.obj.add_input(arg)
            return
        paths = expand_path_variable(self.variables, arg)
        if paths is not None:
            self.obj.add_input([self._expandpath(p) for p in paths])
            return
        self.obj.add_input(self._expandpath(arg))

    # ------------------------------------------------------------------
    # built-ins (reference input.cpp:497-796)
    # ------------------------------------------------------------------
    def cmd_clear(self, args):
        if args:
            raise MRError("Illegal clear command")
        self.obj.cleanup()
        for name in list(self.obj.named):
            self.obj.delete_mr(name)
        defaults = dict(self.obj.defaults)
        pinned = dict(self.obj.pinned)
        self.obj = ObjectManager(comm=self.obj.comm)
        # `set` defaults — and the serve/ tenant-budget pins — survive
        # a clear: a script-level clear must not be able to shed the
        # budget wiring the daemon seeded (doc/serve.md)
        self.obj.defaults.update(defaults)
        self.obj.pinned.update(pinned)
        self.dispatch = MRScriptDispatch(self.obj, self.variables)

    def cmd_echo(self, args):
        modes = {"none": (False, False), "screen": (True, False),
                 "log": (False, True), "both": (True, True)}
        if len(args) != 1 or args[0] not in modes:
            raise MRError("Illegal echo command")
        self.echo_screen, self.echo_log = modes[args[0]]

    def cmd_if(self, args):
        """if "bool" then "cmd" ... elif "bool" "cmd" ... else "cmd" ...
        (input.cpp:527-640; each command is a quoted full line)."""
        if len(args) < 3 or args[1] != "then":
            raise MRError("Illegal if command")

        def block_end(start):
            j = start
            while j < len(args) and args[j] not in ("elif", "else"):
                j += 1
            return j

        cond = self.variables.evaluate_boolean(self._substitute(args[0]))
        first, last = 2, block_end(2)
        while True:
            if cond != 0.0:
                cmds = args[first:last]
                if not cmds:
                    raise MRError("Illegal if command")
                for c in cmds:
                    self.one(c)
                return
            if last >= len(args):
                return
            if args[last] == "elif":
                if last + 2 > len(args):
                    raise MRError("Illegal if command")
                cond = self.variables.evaluate_boolean(
                    self._substitute(args[last + 1]))
                first = last + 2
            else:  # else
                cond = 1.0
                first = last + 1
            last = block_end(first)

    def cmd_include(self, args):
        if len(args) != 1:
            raise MRError("Illegal include command")
        self.run_file(args[0])

    def cmd_jump(self, args):
        if not 1 <= len(args) <= 2:
            raise MRError("Illegal jump command")
        if self._jump_skip:
            self._jump_skip = False
            return
        if len(args) == 2:
            self._label_active = True
            self._labelstr = args[1]
        if args[0] == "SELF":
            self._jump_to = ("SELF", None)
        else:
            with open(args[0]) as f:
                self._jump_to = (args[0], f.read().splitlines())

    def cmd_label(self, args):
        if len(args) != 1:
            raise MRError("Illegal label command")
        if self._label_active and self._labelstr == args[0]:
            self._label_active = False

    def cmd_log(self, args):
        if len(args) != 1:
            raise MRError("Illegal log command")
        if self.logfile:
            self.logfile.close()
        self.logfile = None if args[0] == "none" else open(args[0], "w")

    def cmd_next(self, args):
        if self.variables.next(args):
            self._jump_skip = True

    def cmd_print(self, args):
        if len(args) != 1:
            raise MRError("Illegal print command")
        self._emit(self._substitute(args[0]) + " \n")

    def cmd_shell(self, args):
        """The reference's deliberately-restricted verb set — cd/mkdir/
        mv/rm/rmdir via libc calls, never system() (input.cpp:751-791)."""
        if not args:
            raise MRError("Illegal shell command")
        verb = args[0]
        if verb == "cd":
            if len(args) != 2:
                raise MRError("Illegal shell command")
            os.chdir(args[1])
        elif verb == "mkdir":
            if len(args) < 2:
                raise MRError("Illegal shell command")
            for d in args[1:]:
                os.makedirs(d, exist_ok=True)
        elif verb == "mv":
            if len(args) != 3:
                raise MRError("Illegal shell command")
            shutil.move(args[1], args[2])
        elif verb == "rm":
            if len(args) < 2:
                raise MRError("Illegal shell command")
            for f in args[1:]:
                try:
                    os.unlink(f)
                except FileNotFoundError:
                    pass
        elif verb == "rmdir":
            if len(args) < 2:
                raise MRError("Illegal shell command")
            for d in args[1:]:
                try:
                    os.rmdir(d)
                except FileNotFoundError:
                    pass
        else:
            raise MRError("Illegal shell command")

    def cmd_variable(self, args):
        self.variables.set(args)

    # -- OINK object commands (input.cpp:799-831) --------------------------
    def cmd_mr(self, args):
        """mr ID [verbosity [timer [memsize [outofcore]]]]
        (object.cpp add_mr)."""
        if not 1 <= len(args) <= 5:
            raise MRError("Illegal mr command")
        name = args[0]
        if not all(c.isalnum() or c == "_" for c in name):
            raise MRError("MR ID must be alphanumeric or underscore "
                          "characters")
        if name in self.obj.named:
            raise MRError("ID in mr command is already in use")
        mr = self.obj.create_mr()
        for key, val in zip(("verbosity", "timer", "memsize", "outofcore"),
                            args[1:]):
            mr.set(**{key: int(val)})
        self.obj.name_mr(name, mr)

    def cmd_set(self, args):
        """set keyword value ... (object.cpp Object::set).  `scratch`
        maps to our fpath spill-dir setting; `prepend`/`substitute`
        shape -i/-o path resolution (expandpath, object.cpp:913-960)."""
        if len(args) % 2:
            raise MRError("Illegal set command")
        for i in range(0, len(args), 2):
            key, val = args[i], args[i + 1]
            if key == "scratch":
                self.obj.set_default("fpath", val)
            elif key == "onfault":
                # string-valued ft/ policy (fail|retry|skip)
                self.obj.set_default("onfault", val)
            elif key == "prepend":
                root = getattr(self, "_path_root", None)
                if root is not None:
                    # serve/ sessions anchor ALL relative output under
                    # their own directory: the script's prepend idiom
                    # keeps working, re-rooted inside the sandbox; an
                    # absolute prepend would silently move -o files
                    # out of the session (losing them from the result
                    # and the crash-replay golden), so it fails loudly
                    if os.path.isabs(val):
                        raise MRError(
                            "absolute prepend is pinned by the server "
                            "(session outputs stay in the session "
                            "directory; doc/serve.md)")
                    val = os.path.join(root, val)
                self._path_prepend = val
            elif key == "substitute":
                self._path_substitute = int(val)
            else:
                self.obj.set_default(key, int(val))

    def cmd_input(self, args):
        """input N keyword value ... — per-slot descriptor settings.  We
        accept and store them; only 'prepend'/'substitute' alter path
        resolution here (reference object.cpp user_input's full set
        drives the byte-chunk map variants)."""
        if len(args) < 3:
            raise MRError("Illegal input command")
        self.obj.user_input_settings = getattr(
            self.obj, "user_input_settings", {})
        self.obj.user_input_settings[int(args[0])] = dict(
            zip(args[1::2], args[2::2]))

    def cmd_output(self, args):
        if len(args) < 3:
            raise MRError("Illegal output command")
        self.obj.user_output_settings = getattr(
            self.obj, "user_output_settings", {})
        self.obj.user_output_settings[int(args[0])] = dict(
            zip(args[1::2], args[2::2]))


# ---------------------------------------------------------------------------
# line chopping helpers (reference Input::parse)
# ---------------------------------------------------------------------------

def _strip_comment(line: str) -> str:
    quote = ""
    for i, c in enumerate(line):
        if c == "#" and not quote:
            return line[:i]
        if quote and c == quote:
            quote = ""
        elif not quote and c in "\"'":
            quote = c
    return line


def _split_args(line: str) -> List[str]:
    """Whitespace split with single/double-quoted strings as one arg
    (input.cpp:289-321)."""
    out: List[str] = []
    i, n = 0, len(line)
    while i < n:
        while i < n and line[i].isspace():
            i += 1
        if i >= n:
            break
        if line[i] in "\"'":
            q = line[i]
            j = line.find(q, i + 1)
            if j < 0:
                raise MRError("Unbalanced quotes in input line")
            out.append(line[i + 1:j])
            i = j + 1
        else:
            j = i
            while j < n and not line[j].isspace():
                j += 1
            out.append(line[i:j])
            i = j
    return out


# ---------------------------------------------------------------------------
# command line front end (reference oink/oink.cpp switches + main.cpp)
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    """oink-style driver: ``python -m gpu_mapreduce_tpu.oink.script
    [-in file] [-log file|none] [-screen file|none] [-echo style]
    [-partition NxM ...] [-var name value...]``
    (reference oink.cpp:45-125)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    infile = None
    logname: Optional[str] = "log.oink"
    lograw: Optional[str] = None      # the explicit -log value, if any
    screen: object = None
    screenraw: Optional[str] = None
    echo = None
    varsets = []
    partition: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-in", "-i"):
            infile = argv[i + 1]
            i += 2
        elif a in ("-log", "-l"):
            lograw = argv[i + 1]
            logname = None if lograw == "none" else lograw
            i += 2
        elif a in ("-screen", "-sc"):
            screenraw = argv[i + 1]
            i += 2
        elif a in ("-echo", "-e"):
            echo = argv[i + 1]
            i += 2
        elif a in ("-partition", "-p"):
            i += 1
            while i < len(argv) and not argv[i].startswith("-"):
                partition.append(argv[i])
                i += 1
            if not partition:
                raise SystemExit("Invalid command-line argument: "
                                 "-partition needs world specs")
        elif a in ("-var", "-v"):
            name = argv[i + 1]
            vals = []
            i += 2
            while i < len(argv) and not argv[i].startswith("-"):
                vals.append(argv[i])
                i += 1
            varsets.append((name, vals))
        else:
            raise SystemExit(f"Invalid command-line argument: {a}")
    if partition:
        # multi-world run (reference oink.cpp:99-100 requires -in)
        if not infile:
            raise SystemExit("Must use -in switch with multiple partitions")
        from .universe import Universe, run_universe

        # the reference gets its proc count from mpirun; ours comes from
        # the visible device list — build a mesh exactly as large as the
        # partition specs demand (worlds then split it)
        probe = Universe(0)
        for spec in partition:
            probe.add_world(spec)
        total = sum(probe.procs_per_world)
        if total <= 1:
            comm = None
        else:
            import jax

            from ..parallel.mesh import make_mesh
            if len(jax.devices()) < total:
                raise SystemExit(
                    f"Processor partitions are inconsistent: specs need "
                    f"{total} procs, {len(jax.devices())} devices visible")
            comm = make_mesh(total)
        run_universe(infile, partition, comm=comm, logname=lograw,
                     screenname=screenraw, echo=echo, varsets=varsets)
        return 0
    if screenraw is not None:
        screen = False if screenraw == "none" else open(screenraw, "w")
    interp = OinkScript(screen=screen, logfile=logname)
    if echo:
        interp.cmd_echo([echo])
    for name, vals in varsets:
        interp.variables.set([name, "index"] + vals)
    try:
        if infile:
            interp.run_file(infile)
        else:
            interp.run_string(sys.stdin.read())
    finally:
        interp.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
