"""Named-MR method dispatch — ``<MRname> <method> args...`` script syntax.

Reference: ``oink/mrmpi.cpp:37-349`` exposes every MapReduce library
method on named script objects, resolving callback names through the
generated ``style_*.h`` fn-pointer registries (``mrmpi.cpp:354-466``).
Here the registries are the dicts in :mod:`.kernels` and dispatch is a
method table; semantics per entry match the reference case-by-case
(delete/copy/add/aggregate/broadcast/clone/close/collapse/collate/
compress/convert/gather/map variants/open/print/reduce/scan/scrunch/
sort_*/stats/set).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.runtime import MRError
from . import kernels
from .objects import ObjectManager


def _lookup(table: dict, name: str, what: str):
    if name not in table:
        raise MRError(f"unknown {what} kernel {name!r} (registered: "
                      f"{sorted(table)})")
    return table[name]


def expand_path_variable(variables, arg: str):
    """v_name → list of one path per variable value, or None if arg is not
    a known path variable (the shared v_ idiom of -i descriptors and
    map/file, reference object.cpp:450-462 / mrmpi.cpp:127-140)."""
    if variables is None or not arg.startswith("v_"):
        return None
    vname = arg[2:]
    if variables.find(vname) is None:
        return None
    if variables.equal_style(vname):
        raise MRError("Command input is equal-style variable")
    n = variables.retrieve_count(vname)
    return [variables.retrieve_single(vname, i) for i in range(n)]


def _collapse_key(type_: str, value: str):
    if type_ == "int":
        return np.int64(value)
    if type_ == "uint64":
        return np.uint64(value)
    if type_ == "double":
        return np.float64(value)
    if type_ == "str":
        return value.encode()
    raise MRError("Illegal MR object collapse command")


class MRScriptDispatch:
    """Runs one `<MRname> <method> args` line against the ObjectManager."""

    def __init__(self, obj: ObjectManager, variables=None):
        self.obj = obj
        self.variables = variables

    def run(self, name: str, args: List[str]) -> None:
        if not args:
            raise MRError("Illegal MapReduce object command")
        mr = self.obj.get_mr(name)
        method, rest = args[0], args[1:]
        fn = getattr(self, "m_" + method.replace("/", "_"), None)
        if fn is None:
            raise MRError(f"Unknown MR object method {method!r}")
        fn(name, mr, rest)

    # -- lifecycle ---------------------------------------------------------
    def m_delete(self, name, mr, a):
        if a:
            raise MRError("Illegal MR object delete command")
        self.obj.delete_mr(name)

    def m_copy(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object copy command")
        if a[0] in self.obj.named:
            raise MRError("MR object created by copy already exists")
        self.obj.name_mr(a[0], mr.copy())

    def m_add(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object add command")
        mr.add(self.obj.get_mr(a[0]))

    # -- shuffle / grouping ------------------------------------------------
    def m_aggregate(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object aggregate command")
        mr.aggregate(None if a[0] == "NULL" else
                     _lookup(kernels.HASH_KERNELS, a[0], "hash"))

    def m_broadcast(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object broadcast command")
        mr.broadcast(int(a[0]))

    def m_clone(self, name, mr, a):
        mr.clone()

    def m_close(self, name, mr, a):
        mr.close()

    def m_open(self, name, mr, a):
        mr.open(addflag=1 if a else 0)

    def m_collapse(self, name, mr, a):
        if len(a) != 2:
            raise MRError("Illegal MR object collapse command")
        mr.collapse(_collapse_key(a[0], a[1]))

    def m_collate(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object collate command")
        mr.collate(None if a[0] == "NULL" else
                   _lookup(kernels.HASH_KERNELS, a[0], "hash"))

    def m_compress(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object compress command")
        mr.compress(_lookup(kernels.REDUCE_KERNELS, a[0], "reduce"),
                    batch=True)

    def m_convert(self, name, mr, a):
        mr.convert()

    def m_gather(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object gather command")
        mr.gather(int(a[0]))

    def m_scrunch(self, name, mr, a):
        if len(a) != 3:
            raise MRError("Illegal MR object scrunch command")
        mr.scrunch(int(a[0]), _collapse_key(a[1], a[2]))

    # -- map variants (reference mrmpi.cpp:116-260) ------------------------
    def _paths(self, arg: str) -> List[str]:
        return expand_path_variable(self.variables, arg) or [arg]

    def m_map_task(self, name, mr, a):
        if len(a) not in (2, 3):
            raise MRError("Illegal MR object map/task command")
        raise MRError("map/task requires a registered task kernel; none "
                      "are defined (the reference's style_map.h has no "
                      "nmap-style entries either beyond rmat_generate, "
                      "which is the rmat command here)")

    def m_map_file(self, name, mr, a):
        if len(a) not in (2, 3):
            raise MRError("Illegal MR object map/file command")
        fn = _lookup(kernels.MAP_FILE_KERNELS, a[1], "map/file")
        mr.map_files(self._paths(a[0]), fn, addflag=1 if len(a) == 3 else 0)

    def m_map_mr(self, name, mr, a):
        if len(a) not in (2, 3):
            raise MRError("Illegal MR object map/mr command")
        src = self.obj.get_mr(a[0])
        fn = _lookup(kernels.MAP_MR_KERNELS, a[1], "map/mr")
        mr.map_mr(src, fn, addflag=1 if len(a) == 3 else 0, batch=True)

    # -- reduce / scan -----------------------------------------------------
    def m_reduce(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object reduce command")
        mr.reduce(_lookup(kernels.REDUCE_KERNELS, a[0], "reduce"),
                  batch=True)

    def m_scan_kv(self, name, mr, a):
        mr.print()

    def m_scan_kmv(self, name, mr, a):
        mr.print()

    def m_save(self, name, mr, a):
        """save <dir> — checkpoint the dataset (capability improvement;
        the reference persists only via print-to-file text)."""
        if len(a) != 1:
            raise MRError("Illegal MR object save command")
        mr.save(a[0])

    def m_load(self, name, mr, a):
        """load <dir> — restore a checkpointed dataset."""
        if len(a) != 1:
            raise MRError("Illegal MR object load command")
        mr.load(a[0])

    def m_print(self, name, mr, a):
        """print [proc nstride kflag vflag] (reference mrmpi.cpp print
        case; proc selects which rank prints — single controller here, so
        it is accepted and ignored)."""
        if len(a) not in (0, 4):
            raise MRError("Illegal MR object print command")
        if a:
            mr.print(nstride=int(a[1]), kflag=int(a[2]), vflag=int(a[3]))
        else:
            mr.print()

    # -- sorts -------------------------------------------------------------
    def m_sort_keys(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object sort_keys command")
        mr.sort_keys(int(a[0]))

    def m_sort_values(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object sort_values command")
        mr.sort_values(int(a[0]))

    def m_sort_multivalues(self, name, mr, a):
        if len(a) != 1:
            raise MRError("Illegal MR object sort_multivalues command")
        mr.sort_multivalues(int(a[0]))

    # -- stats / settings --------------------------------------------------
    def m_stats(self, name, mr, a):
        level = int(a[0]) if a else 1
        if mr.kv is not None:
            mr.kv_stats(level)
        if mr.kmv is not None:
            mr.kmv_stats(level)

    def m_set(self, name, mr, a):
        if len(a) != 2:
            raise MRError("Illegal MR object set command")
        key = a[0]
        val = a[1] if key in ("fpath", "onfault") else int(a[1])
        mr.set(**{key: val})
