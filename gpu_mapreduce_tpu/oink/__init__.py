"""OINK — the scripting/command layer over the MapReduce algebra
(reference ``oink/``, SURVEY.md §2.4-2.5)."""

from .command import COMMANDS, Command, command, run_command
from .objects import InputDescriptor, ObjectManager, OutputDescriptor
from . import commands  # registers the built-in command suite
from .script import OinkScript
from .variables import Variables

__all__ = ["COMMANDS", "Command", "command", "run_command",
           "ObjectManager", "InputDescriptor", "OutputDescriptor",
           "OinkScript", "Variables"]
