"""wordfreq command (oink/wordfreq.cpp:28-100): word counts + top-N.

self.top holds the final (word, count) list; output 1 gets the full
word:count KV."""

from __future__ import annotations

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import count, read_words


@command("wordfreq")
class WordFreq(Command):
    ninputs = 1
    noutputs = 1

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal wordfreq command")
        self.ntop = int(args[0])

    def run(self):
        obj = self.obj
        files: list = []
        mr = obj.input(1, read_words, files)
        nwords = mr.kv_stats(0)[0]
        if obj.permanent(mr):
            mr = obj.copy_mr(mr)
        mr.collate()
        nunique = mr.reduce(count, batch=True)
        obj.output(1, mr, _print_word_count)

        self.top = []
        if self.ntop:
            if obj.permanent(mr):
                mr = obj.copy_mr(mr)
            mr.gather(1)
            mr.sort_values(-1)

            def take(k, v, ptr):
                if len(self.top) < self.ntop:
                    self.top.append((k, int(v)))

            mr.scan_kv(take)
        self.nfiles, self.nwords, self.nunique = len(files), nwords, nunique
        self.message(f"WordFreq: {len(files)} files, {nwords} words, "
                     f"{nunique} unique")
        for w, c in self.top:
            self.message(f"  {c} {w.decode(errors='replace')}")
        obj.cleanup()


def _print_word_count(k, v, fp):
    word = k.decode(errors="replace") if isinstance(k, bytes) else k
    fp.write(f"{word} {v}\n")
