"""dump_plan command: print the recorded/executed plans of this process
— stages, fusion groups (fused vs eager, which segment op), cache key
and whether the plan cache hit.

No reference analog (the reference is eager by construction); this is
the scripted exit point of the plan/ subsystem, next to dump_trace::

    set fuse 1
    mr A
    A map/file v_files wf_read
    A collate NULL
    A reduce count
    A stats                       # barrier: plan executes here
    dump_plan -                   # '-' → screen, else a file path

Plans only exist when fusion ran (``set fuse 1``, ``MRTPU_FUSE=1`` or a
``pipeline()`` block in library code); with none recorded the command
says so instead of writing an empty file.
"""

from __future__ import annotations

from ...core.runtime import MRError
from ..command import Command, command


def format_plans(history: list) -> str:
    """Human-readable multi-line rendering of plan.cache.plan_history()."""
    if not history:
        return "(no plans recorded — set fuse 1 / MRTPU_FUSE=1)"
    lines = []
    for i, h in enumerate(history):
        lines.append(f"plan {i}: {' -> '.join(h['stages'])}")
        lines.append(f"  cache: {'HIT' if h['cache_hit'] else 'miss'}"
                     + (f"  key: {h['cache_key']}" if h.get("cache_key")
                        else ""))
        for j, g in enumerate(h["groups"]):
            tag = g["kind"] if g["fused"] else "eager"
            rop = f" reduce_op={g['reduce_op']}" if g.get("reduce_op") \
                else ""
            lines.append(f"  group {j} [{tag}{rop}]: "
                         + "; ".join(g["stages"]))
    return "\n".join(lines)


@command("dump_plan")
class DumpPlan(Command):
    ninputs = 0
    noutputs = 0

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal dump_plan command")
        self.path = args[0]

    def run(self):
        from ...plan import plan_history
        history = plan_history()
        text = format_plans(history)
        if self.path == "-":
            self.message(text)
        else:
            with open(self.path, "w") as f:
                f.write(text + "\n")
            self.message(f"DumpPlan: {len(history)} plans -> {self.path}")
