"""stream — the OINK surface of the standing-query engine
(stream/engine.py, doc/streaming.md).

One subcommand per invocation; the stream's directory IS the handle —
every invocation re-opens it and resumes from the last committed
micro-batch (exactly-once, ft/ journal):

* ``stream open <dir> <source...> [parser=words] [reduce=count]
  [window=N]`` — create (or re-open) the query; the spec persists in
  ``<dir>/stream.json`` so later subcommands need only the directory.
* ``stream poll <dir>``     — drain everything pending NOW (forced
  cut: deterministic scripts don't wait on the time trigger).
* ``stream status <dir>``   — one status line + the JSON detail.
* ``stream snapshot <dir> [outfile]`` — the resident dataset's
  deterministic text snapshot (sorted ``key value`` lines), printed or
  written to ``outfile``.
* ``stream close <dir>``    — final drain (unterminated tail line
  included) + the terminal ``stream_close`` record.

Scripts that mention ``stream`` are never memoized (serve/memo.py):
a standing query's answer is a moving target, not a pure function of
its text.
"""

from __future__ import annotations

import json
import os

from ...core.runtime import MRError
from ..command import Command, command

_SUBS = ("open", "poll", "status", "snapshot", "close")
_SPEC_KEYS = ("parser", "reduce", "window")


@command("stream")
class StreamCmd(Command):
    ninputs = 0
    noutputs = 0

    def params(self, args):
        if len(args) < 2 or args[0] not in _SUBS:
            raise MRError("Illegal stream command: stream "
                          "<open|poll|status|snapshot|close> <dir> ...")
        self.sub = args[0]
        self.dir = args[1]
        self.rest = list(args[2:])
        if self.sub == "open" and not any("=" not in a
                                          for a in self.rest):
            raise MRError("Illegal stream command: open needs at "
                          "least one source file/directory")
        if self.sub != "open" and self.sub != "snapshot" and self.rest:
            raise MRError(f"Illegal stream command: {self.sub} takes "
                          f"no extra arguments")
        if self.sub == "snapshot" and len(self.rest) > 1:
            raise MRError("Illegal stream command: snapshot takes at "
                          "most one output file")

    def _spec_path(self) -> str:
        return os.path.join(self.dir, "stream.json")

    def _load_spec(self) -> dict:
        try:
            with open(self._spec_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            raise MRError(f"no stream at {self.dir!r} (run "
                          f"'stream open' first)") from None

    def _open_engine(self, spec: dict):
        from ...stream import Stream
        return Stream(self.dir, spec["sources"],
                      parser=spec.get("parser", "words"),
                      reduce=spec.get("reduce", "count"),
                      window=int(spec.get("window") or 0),
                      comm=self.obj.comm,
                      settings=self.obj.defaults)

    def run(self):
        if self.sub == "open":
            spec = {"parser": "words", "reduce": "count", "window": 0}
            sources = []
            for a in self.rest:
                key, _, val = a.partition("=")
                if val and key in _SPEC_KEYS:
                    spec[key] = int(val) if key == "window" else val
                else:
                    sources.append(os.path.abspath(a))
            spec["sources"] = sources
            s = self._open_engine(spec)     # validates parser/reduce
            os.makedirs(self.dir, exist_ok=True)
            tmp = self._spec_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump(spec, f)
            os.replace(tmp, self._spec_path())
            st = s.status()
            s.suspend()
            self.stream_status = st
            self.message(
                f"Stream: open {self.dir} ({spec['parser']}/"
                f"{spec['reduce']}, {len(sources)} sources"
                + (f", resumed at batch {st['batches']}"
                   if st["resumed"] else "") + ")")
            return
        spec = self._load_spec()
        s = self._open_engine(spec)
        if self.sub == "poll":
            rows = s.drain()
            st = s.status()
            s.suspend()
            self.stream_status = st
            self.message(f"Stream: {rows} rows in "
                         f"{st['batches']} batches total, "
                         f"{st['pending_bytes']} bytes pending")
        elif self.sub == "status":
            st = s.status()
            s.suspend()
            self.stream_status = st
            self.message(f"Stream: {st['state']}, "
                         f"{st['batches']} batches, {st['rows']} rows, "
                         f"lag {st['lag_s']:.3f}s")
            out = json.dumps(st, indent=2, sort_keys=True, default=str)
            if self.screen is None or self.screen is True:
                print(out)
            elif self.screen is not False:
                self.screen.write(out + "\n")
        elif self.sub == "snapshot":
            text = s.snapshot()
            st = s.status()
            s.suspend()
            self.stream_status = st
            if self.rest:
                tmp = self.rest[0] + ".tmp"
                with open(tmp, "w") as f:
                    f.write(text)
                os.replace(tmp, self.rest[0])
                self.message(f"Stream: snapshot of "
                             f"{st['rows']} rows -> {self.rest[0]}")
            else:
                self.message(f"Stream: snapshot at batch "
                             f"{st['batches']}")
                if self.screen is None or self.screen is True:
                    print(text, end="")
                elif self.screen is not False:
                    self.screen.write(text)
        else:                               # close
            st = s.close(drain=True)
            self.stream_status = st
            self.message(f"Stream: closed after {st['batches']} "
                         f"batches, {st['rows']} rows")
        self.obj.cleanup()
