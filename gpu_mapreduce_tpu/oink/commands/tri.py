"""tri_find / neigh_tri — Cohen's MapReduce triangle enumeration.

Reference: ``oink/tri_find.cpp:43-81`` (degree-augment edges, low-degree
vertex emits angles, join angles with original edges) and
``oink/neigh_tri.cpp:40-69`` (per-vertex neighbor+triangle files).

All kernels are batch/vectorised: the O(d²) angle emission builds its pair
index arrays with repeat/cumsum instead of nested loops, and the
valuebytes-discriminated unions of the reference become tagged ``[tag,a,b]``
u64 rows (tag 0 = original edge / plain neighbor, tag 1 = angle / triangle
edge)."""

from __future__ import annotations

import os

import numpy as np

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import (_parse_cols, edge_both_directions, host_kmv, kmv_keys,
                       kmv_values, kv_keys, kv_values, read_edge, seg_ids,
                       sum_values)


import jax.numpy as jnp

from ...parallel.devkernels import (is_sharded_kmv, is_sharded_kv,
                                    kmv_row_state, seg_max_u64, skmv_map,
                                    skv_map)
from ...parallel.sharded import round_cap


def _first_degree_dev(uk, nv, vo, vals, gc, vc):
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    g = jnp.maximum(seg, 0)
    nb = vals.astype(jnp.uint64)
    center = jnp.take(uk, g).astype(jnp.uint64)
    d = jnp.take(nv, g).astype(jnp.uint64)
    lo = jnp.minimum(center, nb)
    hi = jnp.maximum(center, nb)
    is_i = center < nb
    zero = jnp.zeros_like(d)
    oval = jnp.stack([jnp.where(is_i, d, zero),
                      jnp.where(is_i, zero, d)], 1)
    return jnp.stack([lo, hi], 1), oval, rows_valid


def first_degree(fr, kv, ptr):
    """Per-vertex group (neighbors list, size d): emit canonical edge →
    (d,0) or (0,d) depending on which endpoint the center is
    (reduce_first_degree, oink/tri_find.cpp:116-159)."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _first_degree_dev))
        return
    fr = host_kmv(fr)
    nb = kmv_values(fr).astype(np.uint64)            # [n] neighbor ids
    center = np.repeat(kmv_keys(fr).astype(np.uint64), fr.nvalues)
    d = np.repeat(np.asarray(fr.nvalues).astype(np.uint64), fr.nvalues)
    lo = np.minimum(center, nb)
    hi = np.maximum(center, nb)
    is_i = center < nb
    zero = np.zeros(len(nb), np.uint64)
    di = np.where(is_i, d, zero)
    dj = np.where(is_i, zero, d)
    kv.add_batch(np.stack([lo, hi], 1), np.stack([di, dj], 1))


def _low_degree_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    low_is_i = (v[:, 0] < v[:, 1]) | ((v[:, 0] == v[:, 1]) &
                                      (k[:, 0] < k[:, 1]))
    return (jnp.where(low_is_i, k[:, 0], k[:, 1]),
            jnp.where(low_is_i, k[:, 1], k[:, 0]), valid)


def low_degree(fr, kv, ptr):
    """(Eij:(Di,Dj)) → lower-degree endpoint : other endpoint; degree tie
    broken toward Vi (map_low_degree, oink/tri_find.cpp:185-207)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _low_degree_dev))
        return
    e = kv_keys(fr)
    deg = kv_values(fr)
    low_is_i = (deg[:, 0] < deg[:, 1]) | ((deg[:, 0] == deg[:, 1]) &
                                          (e[:, 0] < e[:, 1]))
    kv.add_batch(np.where(low_is_i, e[:, 0], e[:, 1]),
                 np.where(low_is_i, e[:, 1], e[:, 0]))


def _nsq_angles_dev(uk, nv, vo, vals, gc, vc, out_cap):
    vcap = vals.shape[0]
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    g = jnp.maximum(seg, 0)
    end = jnp.take(vo + nv, g)                       # group end row
    rem = jnp.where(rows_valid,
                    end - jnp.arange(vcap, dtype=jnp.int32) - 1, 0)
    rem = jnp.maximum(rem, 0)
    j_idx = jnp.repeat(jnp.arange(vcap), rem, total_repeat_length=out_cap)
    off = jnp.concatenate([jnp.zeros(1, rem.dtype), jnp.cumsum(rem)])
    total = off[-1]
    pos = jnp.arange(out_cap)
    valid_out = pos < total
    k_idx = jnp.clip(pos - jnp.take(off, j_idx) + j_idx + 1, 0, vcap - 1)
    nb = vals.astype(jnp.uint64)
    vj = jnp.take(nb, j_idx)
    vk = jnp.take(nb, k_idx)
    center = jnp.take(uk, jnp.take(g, j_idx)).astype(jnp.uint64)
    lo = jnp.minimum(vj, vk)
    hi = jnp.maximum(vj, vk)
    one = jnp.ones(out_cap, jnp.uint64)
    oval = jnp.stack([one, center, one - 1], 1)
    return jnp.stack([lo, hi], 1), oval, valid_out


def nsq_angles(fr, kv, ptr):
    """Per-center group: every unordered neighbor pair (Vj,Vk) is an "angle"
    (a triangle missing the Vj-Vk edge): emit canonical (Vj,Vk) → [1,center,0]
    (reduce_nsq_angles, oink/tri_find.cpp:211-276, the O(d²) kernel)."""
    if is_sharded_kmv(fr):
        # static expansion cap: worst shard's Σ d(d-1)/2, from the group
        # sizes (one host fetch of the int32 size column, not the data)
        P, gcap = fr.nprocs, fr.gcap
        nv = np.asarray(fr.nvalues).reshape(P, gcap).astype(np.int64)
        m = np.arange(gcap)[None, :] < fr.gcounts[:, None]
        nv = np.where(m, nv, 0)
        per_shard = (nv * (nv - 1) // 2).sum(axis=1)
        out_cap = round_cap(int(max(1, per_shard.max())))
        kv.add_frame(skmv_map(fr, _nsq_angles_dev, static=(out_cap,)))
        return
    fr = host_kmv(fr)
    nb = kmv_values(fr).astype(np.uint64)
    n = len(nb)
    seg = seg_ids(fr)
    end = np.asarray(fr.offsets)[1:][seg]            # group end per row
    rem = (end - np.arange(n) - 1).astype(np.int64)  # later rows in group
    j_idx = np.repeat(np.arange(n), rem)
    off = np.concatenate([[0], np.cumsum(rem)])
    k_idx = np.arange(int(rem.sum())) - off[j_idx] + j_idx + 1
    vj, vk = nb[j_idx], nb[k_idx]
    center = kmv_keys(fr).astype(np.uint64)[seg[j_idx]]
    lo = np.minimum(vj, vk)
    hi = np.maximum(vj, vk)
    one = np.ones(len(lo), np.uint64)
    kv.add_batch(np.stack([lo, hi], 1),
                 np.stack([one, center, np.zeros(len(lo), np.uint64)], 1))


def _edge_null_tagged_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    return k, jnp.zeros((k.shape[0], 3), jnp.uint64), valid


def edge_null_tagged(fr, kv, ptr):
    """Eij:NULL → Eij:[0,0,0] — original-edge marker rows for the angle
    join (the reference reuses valuebytes==0)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _edge_null_tagged_dev))
        return
    e = kv_keys(fr)
    kv.add_batch(e, np.zeros((len(e), 3), np.uint64))


def _emit_triangles_dev(uk, nv, vo, vals, gc, vc):
    gcap = uk.shape[0]
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    g = jnp.maximum(seg, 0)
    is_edge = rows_valid & (vals[:, 0] == 0)
    has_edge = seg_max_u64(jnp.ones(vals.shape[0], jnp.uint64), seg,
                           is_edge, gcap) > 0
    take = rows_valid & (vals[:, 0] != 0) & jnp.take(has_edge, g)
    e = jnp.take(uk, g, axis=0).astype(jnp.uint64)     # [vcap, 2]
    okey = jnp.stack([vals[:, 1], e[:, 0], e[:, 1]], 1)
    return okey, jnp.zeros(vals.shape[0], jnp.uint8), take


def emit_triangles(fr, kv, ptr):
    """Per-edge group of tagged rows: if an original-edge marker is present,
    every angle row (center Vi) completes a triangle (Vi,Vj,Vk)
    (reduce_emit_triangles, oink/tri_find.cpp:280-...)."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _emit_triangles_dev))
        return
    fr = host_kmv(fr)
    vals = kmv_values(fr)                            # [n,3] tagged
    seg = seg_ids(fr)
    is_edge = vals[:, 0] == 0
    has_edge = np.zeros(len(fr), bool)
    has_edge[seg[is_edge]] = True
    take = (~is_edge) & has_edge[seg]
    e = kmv_keys(fr).astype(np.uint64)[seg[take]]    # [m,2] the (Vj,Vk) edge
    center = vals[take, 1]
    kv.add_batch(np.stack([center, e[:, 0], e[:, 1]], 1),
                 np.zeros(len(center), np.uint8))


def print_tri(k, v, fp):
    fp.write(f"{k[0]} {k[1]} {k[2]}\n")


@command("tri_find")
class TriFind(Command):
    """tri_find: enumerate all triangles of an edge list; output one
    (Vi,Vj,Vk) line per triangle, Vi = the low-degree "center" vertex that
    emitted the angle (oink/tri_find.cpp:43-81).

    Engines: ``fused`` (default) — vectorised degree-ordered wedge
    matching (models/tri.py: index arithmetic + batched searchsorted
    membership, no shuffled angle materialisation); ``composed`` — the
    reference's 6-stage MR pipeline below (GPUMR_TRI_ENGINE=composed).
    Identical triangle sets."""

    ninputs = 1
    noutputs = 1
    engine: str | None = None   # None → GPUMR_TRI_ENGINE env (or fused)

    def params(self, args):
        if args:
            raise MRError("Illegal tri_find command")

    def run(self):
        engine = self.engine or os.environ.get("GPUMR_TRI_ENGINE", "fused")
        if engine not in ("fused", "composed"):
            raise MRError(f"tri_find: unknown engine {engine!r} "
                          f"(use 'fused' or 'composed')")
        if engine == "composed":
            return self._run_composed()
        obj = self.obj
        mre = obj.input(1, read_edge)

        # device staging (VERDICT r2 #2): rank vertices on device; only
        # int32 rank columns reach the host wedge walk (whose membership
        # probes run jitted on the accelerator already)
        from ...parallel.staging import stage_graph
        sg = stage_graph(mre, obj.comm)
        if sg is not None:
            from ...models.tri import triangles_ranked
            valid = np.asarray(sg.valid)
            tris = triangles_ranked(np.asarray(sg.src)[valid],
                                    np.asarray(sg.dst)[valid],
                                    sg.n, sg.verts)
        else:
            ecols: list = []
            mre.scan_kv(lambda fr, p: ecols.append(kv_keys(fr)),
                        batch=True)
            e = (np.concatenate(ecols) if ecols
                 else np.zeros((0, 2), np.uint64)).astype(np.uint64)

            from ...models.tri import triangles
            tris = triangles(e)

        self.ntri = len(tris)
        mrt = obj.create_mr()
        mrt.map(1, lambda i, kv, p: kv.add_batch(
            tris, np.zeros(len(tris), np.uint8)))
        obj.output(1, mrt, print_tri)
        self.message(f"Tri_find: {self.ntri} triangles")
        obj.cleanup()

    def _run_composed(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mre.aggregate()   # mesh: shard once; all stages below stay
        #                   device-resident (serial: no-op)
        mrt = obj.create_mr()

        # augment edges with endpoint degrees: mrt = (Eij, (Di, Dj))
        mrt.map_mr(mre, edge_both_directions, batch=True)
        mrt.collate()
        mrt.reduce(first_degree, batch=True)
        mrt.collate()
        mrt.reduce(sum_values, batch=True)

        # angles from the low-degree endpoint, joined with original edges
        mrt.map_mr(mrt, low_degree, batch=True)
        mrt.collate()
        mrt.reduce(nsq_angles, batch=True)
        tmp = obj.create_mr()
        tmp.map_mr(mre, edge_null_tagged, batch=True)
        mrt.add(tmp)
        mrt.collate()
        ntri = mrt.reduce(emit_triangles, batch=True)

        self.ntri = ntri
        obj.output(1, mrt, print_tri)
        self.message(f"Tri_find: {ntri} triangles")
        obj.cleanup()


# ---------------------------------------------------------------------------
# neigh_tri
# ---------------------------------------------------------------------------

def read_adjacency(itask, filename, kv, ptr):
    """'vi vj vk ...' adjacency lines → (vi : [0,vj,0]) tagged neighbor rows
    (NeighTri::nread, oink/neigh_tri.cpp:76-92)."""
    rows_v, rows_n = [], []
    with open(filename) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            vi = int(toks[0])
            for t in toks[1:]:
                rows_v.append(vi)
                rows_n.append(int(t))
    v = np.asarray(rows_v, np.uint64)
    nb = np.asarray(rows_n, np.uint64)
    zero = np.zeros(len(v), np.uint64)
    kv.add_batch(v, np.stack([zero, nb, zero], 1))


def read_tri(itask, filename, kv, ptr):
    """'vi vj vk' triangle lines → key [vi,vj,vk] : NULL
    (NeighTri::tread, oink/neigh_tri.cpp:96-109)."""
    vi, vj, vk = _parse_cols(filename, (np.uint64,) * 3)
    kv.add_batch(np.stack([vi, vj, vk], 1), np.zeros(len(vi), np.uint8))


def tri_to_vertex_edges(fr, kv, ptr):
    """(Vi,Vj,Vk):NULL → each corner : [1, other1, other2] tagged
    triangle-edge rows (NeighTri::map1, oink/neigh_tri.cpp:143-160)."""
    t = kv_keys(fr)
    one = np.ones(len(t), np.uint64)
    kv.add_batch(
        np.concatenate([t[:, 0], t[:, 1], t[:, 2]]),
        np.concatenate([np.stack([one, t[:, 1], t[:, 2]], 1),
                        np.stack([one, t[:, 0], t[:, 2]], 1),
                        np.stack([one, t[:, 0], t[:, 1]], 1)]))


@command("neigh_tri")
class NeighTri(Command):
    """neigh_tri dirname: per-vertex files dirname/<Vi> listing the vertex's
    neighbors ("vi vj" lines) and its triangles' opposite edges ("vj vk"
    lines) (oink/neigh_tri.cpp:40-69).  Inputs: 1 = adjacency file(s),
    2 = triangle file(s) from tri_find."""

    ninputs = 2
    noutputs = 0  # output is the dirname arg, matching the reference

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal neigh_tri command")
        self.dirname = args[0]

    def run(self):
        obj = self.obj
        mrn = obj.input(1, read_adjacency)
        mrt = obj.input(2, read_tri)
        mrnplus = obj.copy_mr(mrn)
        mrnplus.map_mr(mrt, tri_to_vertex_edges, batch=True, addflag=1)
        mrnplus.collate()

        os.makedirs(self.dirname, exist_ok=True)
        nvert = [0]

        def write_vertex(key, vals, ptr):
            vi = int(key)
            with open(os.path.join(self.dirname, str(vi)), "w") as fp:
                for tag, a, b in vals:
                    if int(tag) == 0:
                        fp.write(f"{vi} {int(a)}\n")
                    else:
                        fp.write(f"{int(a)} {int(b)}\n")
            nvert[0] += 1

        mrnplus.scan_kmv(write_vertex)
        self.nvert = nvert[0]
        self.message(f"Neigh_tri: {self.nvert} vertex files in "
                     f"{self.dirname}")
        obj.cleanup()
