"""edge_upper / vertex_extract / neighbor commands.

Reference: ``oink/edge_upper.cpp:28-65`` (canonicalise to upper triangle +
dedupe), ``oink/vertex_extract.cpp:28-60`` (unique vertex list from
weighted edges), ``oink/neighbor.cpp:28-115`` (adjacency lists)."""

from __future__ import annotations

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import (cull, edge_both_directions, edge_to_vertices,
                       edge_upper, print_edge, print_vertex, read_edge,
                       read_edge_weight)


@command("edge_upper")
class EdgeUpper(Command):
    ninputs = 1
    noutputs = 1

    def params(self, args):
        if args:
            raise MRError("Illegal edge_upper command")

    def run(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mr = obj.create_mr()
        nedge = mre.kv_stats(0)[0]
        mr.map_mr(mre, edge_upper, batch=True)
        mr.collate()
        unique = mr.reduce(cull, batch=True)
        self.nedge, self.nunique = nedge, unique
        obj.output(1, mr, print_edge)
        self.message(f"EdgeUpper: {nedge} original edges, {unique} final edges")
        obj.cleanup()


@command("vertex_extract")
class VertexExtract(Command):
    ninputs = 1
    noutputs = 1

    def params(self, args):
        if args:
            raise MRError("Illegal vertex_extract command")

    def run(self):
        obj = self.obj
        mre = obj.input(1, read_edge_weight)
        mrv = obj.create_mr()
        mrv.map_mr(mre, edge_to_vertices, batch=True)
        mrv.collate()
        self.nvert = mrv.reduce(cull, batch=True)
        obj.output(1, mrv, print_vertex)
        obj.cleanup()


@command("neighbor")
class Neighbor(Command):
    """Adjacency-list construction.  The reference packs each neighbor list
    into one variable-length KV value (``neighbor.cpp:84-116``); columnar
    frames keep it as the grouped KMV instead — same lists, zero copies."""

    ninputs = 1
    noutputs = 1

    def params(self, args):
        if args:
            raise MRError("Illegal neighbor command")

    def run(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mrn = obj.create_mr()
        mrn.map_mr(mre, edge_both_directions, batch=True)
        self.nvert = mrn.collate()
        obj.output(1, mrn, _print_neighbors)
        obj.cleanup()


def _print_neighbors(k, vals, fp):
    fp.write(" ".join([str(k)] + [str(v) for v in vals]) + "\n")
