"""dump_metrics command: write the live metrics registry snapshot.

The scripted exit point of the obs/metrics layer (``dump_trace``'s
twin)::

    dump_metrics metrics.json       # structured registry snapshot
    dump_metrics metrics.prom       # Prometheus exposition text

A ``.prom`` / ``.txt`` suffix selects the Prometheus text format;
anything else writes the JSON snapshot.  The command arms the registry
if nothing else has (so a script that only wants an end-of-run snapshot
needs no environment setup) — but metrics fed by spans only cover ops
run AFTER the registry was armed.
"""

from __future__ import annotations

import json

from ...core.runtime import MRError
from ..command import Command, command


@command("dump_metrics")
class DumpMetrics(Command):
    ninputs = 0
    noutputs = 0

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal dump_metrics command")
        self.path = args[0]

    def run(self):
        from ...obs import metrics as _metrics
        armed = _metrics.enabled()
        _metrics.enable_metrics()
        if self.path.endswith((".prom", ".txt")):
            body = _metrics.prometheus_text()
            n = sum(1 for ln in body.splitlines()
                    if ln.startswith("# TYPE"))
        else:
            snap = _metrics.snapshot()
            body = json.dumps(snap, indent=2, default=str)
            n = len(snap)
        with open(self.path, "w") as f:
            f.write(body if body.endswith("\n") else body + "\n")
        note = "" if armed else \
            " (registry armed just now — earlier ops are not in " \
            "span-fed metrics)"
        self.message(f"DumpMetrics: {n} metrics -> {self.path}{note}")
