"""degree / degree_stats / degree_weight commands.

Reference: ``oink/degree.cpp:36-75`` (vertex degree counts),
``oink/degree_stats.cpp:35-64`` (degree histogram via invert→count),
``oink/degree_weight.cpp:28-100`` (1/degree edge weights from a degree file
+ edge file)."""

from __future__ import annotations

import numpy as np

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import (count, edge_to_vertex, edge_to_vertices,
                       print_edge_value, print_vertex_value, read_edge,
                       read_vertex_weight, value_histogram)


@command("degree")
class Degree(Command):
    """degree dupflag: dupflag=1 ⇒ edge list already holds both directions
    (count Vi only); else count both endpoints (oink/degree.cpp:46-49)."""

    ninputs = 1
    noutputs = 1

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal degree command")
        self.duplicate = int(args[0])

    def run(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mrv = obj.create_mr()
        nedge = mre.kv_stats(0)[0]
        if self.duplicate == 1:
            mrv.map_mr(mre, edge_to_vertex, batch=True)
        else:
            mrv.map_mr(mre, edge_to_vertices, batch=True)
        mrv.collate()
        nvert = mrv.reduce(count, batch=True)
        self.nvert, self.nedge = nvert, nedge
        obj.output(1, mrv, print_vertex_value)
        self.message(f"Degree: {nvert} vertices, {nedge} edges")
        obj.cleanup()


@command("degree_stats")
class DegreeStats(Command):
    """degree_stats dupflag: degree histogram printed descending
    (oink/degree_stats.cpp:35-64).  self.stats = [(degree, nvertices)]."""

    ninputs = 1

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal degree_stats command")
        self.duplicate = int(args[0])

    def run(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mr = obj.create_mr()
        nedge = mre.kv_stats(0)[0]
        if self.duplicate == 1:
            mr.map_mr(mre, edge_to_vertex, batch=True)
        else:
            mr.map_mr(mre, edge_to_vertices, batch=True)
        mr.collate()
        nvert = mr.reduce(count, batch=True)
        self.nvert, self.nedge = nvert, nedge
        self.message(f"DegreeStats: {nvert} vertices, {nedge} edges")
        self.stats = value_histogram(mr)
        for degree, nv in self.stats:
            self.message(f"  {degree} {nv}")
        obj.cleanup()


@command("degree_weight")
class DegreeWeight(Command):
    """degree_weight: edges + a 'vertex degree' file → Eij : 1/degree(Vi)
    (oink/degree_weight.cpp).

    The reference mixes neighbor-id and degree values in one KV and
    discriminates by valuebytes; columnar frames need one dtype, so we tag
    rows instead: value = [tag, payload] u64 with tag 0=neighbor, 1=degree
    — same join, fixed lanes."""

    ninputs = 2
    noutputs = 1

    def params(self, args):
        if args:
            raise MRError("Illegal degree_weight command")

    def run(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mrd = obj.input(2, read_vertex_weight)
        mrewt = obj.create_mr()
        nvert = mrd.kv_stats(0)[0]

        def edges_tagged(fr, kv, ptr):
            e = np.asarray(fr.key.to_host().data)
            val = np.stack([np.zeros(len(e), np.uint64), e[:, 1]], 1)
            kv.add_batch(e[:, 0], val)

        def degrees_tagged(fr, kv, ptr):
            v = np.asarray(fr.key.to_host().data)
            d = np.asarray(fr.value.to_host().data).astype(np.uint64)
            val = np.stack([np.ones(len(v), np.uint64), d], 1)
            kv.add_batch(v, val)

        mrewt.map_mr(mre, edges_tagged, batch=True)
        tmp = obj.create_mr()
        tmp.map_mr(mrd, degrees_tagged, batch=True)
        mrewt.add(tmp)
        mrewt.collate()

        def inverse_degree(fr, kv, ptr):
            vals = np.asarray(fr.values.to_host().data)   # [n, 2]
            keys = np.asarray(fr.key.to_host().data)      # [g] u64
            seg = np.repeat(np.arange(len(fr)), fr.nvalues)
            deg = np.zeros(len(fr), np.float64)
            isdeg = vals[:, 0] == 1
            deg[seg[isdeg]] = vals[isdeg, 1].astype(np.float64)
            nb = ~isdeg
            if np.any(deg[seg[nb]] == 0):
                missing = np.unique(keys[seg[nb]][deg[seg[nb]] == 0])
                raise MRError(
                    f"degree_weight: {len(missing)} edge source vertices "
                    f"missing from the degree file (e.g. {missing[0]})")
            vi = keys[seg[nb]]
            vj = vals[nb, 1]
            w = 1.0 / deg[seg[nb]]
            kv.add_batch(np.stack([vi, vj], 1), w)

        nedge = mrewt.reduce(inverse_degree, batch=True)
        self.nvert, self.nedge = nvert, nedge
        obj.output(1, mrewt, print_edge_value)
        self.message(f"DegreeWeight: {nvert} vertices, {nedge} edges")
        obj.cleanup()
