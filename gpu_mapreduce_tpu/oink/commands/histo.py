"""histo — generic key-frequency histogram (oink/histo.cpp:28-80):
unique keys + counts to output 1, then count-of-counts printed
descending."""

from __future__ import annotations

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import count, print_vertex_value, value_histogram


@command("histo")
class Histo(Command):
    ninputs = 1
    noutputs = 1

    def params(self, args):
        if args:
            raise MRError("Illegal histo command")

    def run(self):
        obj = self.obj
        mr = obj.input(1)
        ntotal = mr.kv_stats(0)[0]
        if obj.permanent(mr):
            mr = obj.copy_mr(mr)
        mr.collate()
        nunique = mr.reduce(count, batch=True)
        obj.output(1, mr, print_vertex_value)
        if obj.permanent(mr):
            mr = obj.copy_mr(mr)
        self.ntotal, self.nunique = ntotal, nunique
        self.message(f"Histo: {ntotal} total keys, {nunique} unique")
        self.stats = value_histogram(mr)
        for c, nk in self.stats:
            self.message(f"  {c} {nk}")
        obj.cleanup()
