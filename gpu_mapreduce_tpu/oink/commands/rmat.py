"""rmat / rmat2 — R-MAT matrix generation commands.

Reference: ``oink/rmat.cpp:37-96`` (generate → collate → cull loop until
2^N·Nz unique edges) and ``oink/rmat2.cpp:36-76`` (variant that aggregates
each round into a separate MR and ``add``s it into the accumulator —
demonstrating the aggregate/convert decomposition).  Generation itself is
the vectorised device kernel ``models/rmat.py`` instead of the reference's
serial drand48 walk."""

from __future__ import annotations

import jax
import numpy as np

from ...core.runtime import MRError
from ...models.rmat import rmat_edges
from ..command import Command, command
from ..kernels import cull, print_edge


class _RmatBase(Command):
    noutputs = 1

    def params(self, args):
        if len(args) != 8:
            raise MRError(f"Illegal {self.name} command")
        self.nlevels = int(args[0])
        self.nnonzero = int(args[1])
        self.abcd = tuple(float(a) for a in args[2:6])
        self.frac = float(args[6])
        self.seed = int(args[7])
        if abs(sum(self.abcd) - 1.0) > 1e-12:
            raise MRError("RMAT a,b,c,d must sum to 1")
        if self.frac >= 1.0:
            raise MRError("RMAT fraction must be < 1")
        self.order = 1 << self.nlevels

    def _generate(self, key, nremain: int) -> np.ndarray:
        """One round of device edge generation, trimmed to nremain rows.
        The generation shape is the SAME every round (pow2 of the total
        edge count, not of the shrinking remainder) so the jitted
        generator compiles once per command, not once per cull round."""
        m = max(8, 1 << (self.order * self.nnonzero - 1).bit_length())
        vi, vj = rmat_edges(key, m, self.nlevels, np.asarray(self.abcd),
                            self.frac, noisy=self.frac > 0.0)
        return np.stack([np.asarray(vi)[:nremain],
                         np.asarray(vj)[:nremain]], axis=1)


@command("rmat")
class RMAT(_RmatBase):
    """rmat N Nz a b c d frac seed (oink/rmat.cpp)."""

    def run(self):
        obj = self.obj
        mr = obj.create_mr()
        ntotal = self.order * self.nnonzero
        nremain = ntotal
        niterate = 0
        root = jax.random.PRNGKey(self.seed)
        while nremain:
            niterate += 1
            root, sub = jax.random.split(root)
            edges = self._generate(sub, nremain)
            mr.map(1, lambda i, kv, p: kv.add_batch(
                edges, np.zeros(len(edges), np.uint8)), addflag=1)
            nunique = mr.collate()
            mr.reduce(cull, batch=True)
            nremain = ntotal - nunique
        self.nunique = ntotal
        self.niterate = niterate
        obj.output(1, mr, print_edge)
        self.message(f"RMAT: {self.order} rows, {ntotal} non-zeroes, "
                     f"{niterate} iterations")
        obj.cleanup()


@command("rmat2")
class RMAT2(_RmatBase):
    """rmat2 N Nz a b c d frac seed (oink/rmat2.cpp): per-round aggregate
    into a fresh MR, add into the accumulator, convert+cull."""

    def run(self):
        obj = self.obj
        mr = obj.create_mr()
        mrnew = obj.create_mr()
        ntotal = self.order * self.nnonzero
        nremain = ntotal
        niterate = 0
        root = jax.random.PRNGKey(self.seed)
        while nremain:
            niterate += 1
            root, sub = jax.random.split(root)
            edges = self._generate(sub, nremain)
            mrnew.map(1, lambda i, kv, p: kv.add_batch(
                edges, np.zeros(len(edges), np.uint8)))
            mrnew.aggregate()
            mr.add(mrnew)
            nunique = mr.convert()
            mr.reduce(cull, batch=True)
            nremain = ntotal - nunique
        self.nunique = ntotal
        self.niterate = niterate
        obj.output(1, mr, print_edge)
        self.message(f"RMAT2: {self.order} rows, {ntotal} non-zeroes, "
                     f"{niterate} iterations")
        obj.cleanup()
