"""pagerank — damped PageRank over a directed edge list.

The reference names this command but ships an empty iteration body
(``oink/pagerank.cpp:53-56``, SURVEY.md §2.5) — it reads weighted edges,
builds the vertex list, loops ``maxiter`` times doing nothing, and prints
the *edges*.  This implementation supplies the real algorithm from the
composition pattern, backed by the flagship TPU model
(:mod:`gpu_mapreduce_tpu.models.pagerank`): dense ranks, on-device
``lax.while_loop`` convergence, mesh-sharded edges + one ICI psum per
iteration when the ObjectManager carries a mesh.

Script syntax (reference ``PageRank::params``): ``pagerank tol maxiter
alpha``.  Edge weights are accepted in the input ('vi vj [wt]') for
script parity but rank follows link structure only (classic PageRank).
Output: 'v rank' per vertex; self.ranks = {v: rank}.
"""

from __future__ import annotations

import numpy as np

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import kv_keys, read_edge, read_edge_weight
from ...models.pagerank import pagerank, pagerank_sharded


def _read_edges_sniff(itask, filename, kv, ptr):
    """'vi vj' or 'vi vj wt' lines → key=[vi,vj], value=NULL — the command
    accepts both the reference's weighted input and plain edge lists."""
    first = []
    with open(filename, "rb") as f:
        for line in f:
            first = line.split()
            if first:
                break
    if len(first) == 3:
        read_edge_weight(itask, filename, kv, ptr)
    else:
        read_edge(itask, filename, kv, ptr)


@command("pagerank")
class PageRankCommand(Command):
    """pagerank tol maxiter alpha (oink/pagerank.cpp:67-75)."""

    ninputs = 1
    noutputs = 1

    def params(self, args):
        if len(args) != 3:
            raise MRError("Illegal pagerank command")
        self.tolerance = float(args[0])
        self.maxiter = int(args[1])
        self.alpha = float(args[2])

    def run(self):
        obj = self.obj
        mre = obj.input(1, _read_edges_sniff)

        edges: list = []
        mre.scan_kv(lambda fr, p: edges.append(kv_keys(fr)), batch=True)
        e = (np.concatenate(edges) if edges
             else np.zeros((0, 2), np.uint64))
        # compact arbitrary u64 ids to dense 0..n-1 for the dense-rank model
        verts, inv = np.unique(e.reshape(-1), return_inverse=True)
        n = len(verts)
        if n == 0:
            raise MRError("pagerank: empty edge list")
        src, dst = inv.reshape(-1, 2)[:, 0], inv.reshape(-1, 2)[:, 1]

        from jax.sharding import Mesh
        mesh = obj.comm if isinstance(obj.comm, Mesh) else None
        if mesh is not None:
            ranks, iters = pagerank_sharded(
                mesh, src, dst, n, tol=self.tolerance,
                maxiter=self.maxiter, damping=self.alpha)
        else:
            ranks, iters = pagerank(src, dst, n, tol=self.tolerance,
                                    maxiter=self.maxiter,
                                    damping=self.alpha)
            ranks, iters = np.asarray(ranks), int(iters)

        self.ranks = {int(v): float(r) for v, r in zip(verts, ranks)}
        self.niterate = iters
        self.nvert = n

        mrr = obj.create_mr()
        mrr.map(1, lambda i, kv, p: kv.add_batch(
            verts, ranks.astype(np.float64)))
        obj.output(1, mrr, lambda k, v, fp: fp.write(f"{k} {v:.8g}\n"))
        self.message(f"PageRank: {n} vertices, {len(src)} edges, "
                     f"{iters} iterations")
        obj.cleanup()
