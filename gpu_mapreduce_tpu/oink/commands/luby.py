"""luby_find — Luby maximal independent set.

Reference: ``oink/luby_find.cpp:53-95`` (run loop) and its four reduce
callbacks (``reduce_edge_winner`` 140, ``reduce_vert_winner`` 186,
``reduce_vert_loser`` 238, ``reduce_vert_emit`` 289).

Round semantics (identical to the reference composition):

1. **edge_winner** — an edge is alive iff no endpoint was flagged last
   round; alive edge picks its winner = endpoint with smaller (rand, id)
   and emits ``(v : [other, won])`` both directions;
2. **vert_winner** — a vertex that wins *all* its alive edges is a
   round-winner; it tells every neighbour so;
3. **vert_loser** — a vertex with a round-winner neighbour is a loser; it
   tells every neighbour so;
4. **vert_emit** — a vertex whose neighbours are *all* losers joins the
   MIS (this covers round-winners and vertices isolated by removals) and
   the edge list for the next round is rebuilt with dead-markers on any
   edge touching a loser.  Loop until edge_winner emits nothing.

Two TPU-first redesigns vs the reference:

* the reference assigns each vertex a random via ``srand48(v+seed)`` and
  *carries* it through every shuffle in ERAND/VRAND/VFLAG structs,
  discriminating record kinds by ``valuebytes``; our vertex random is a
  pure splitmix64 function of (v, seed) recomputed where needed, so every
  value is one fixed-width ``[other, tag]`` u64 row — no variable-size
  struct zoo, and the shuffles move half the bytes;
* each reduce is one vectorised segment pass (``np.maximum.reduceat``
  over group offsets) instead of a per-group callback.
"""

from __future__ import annotations

import os

import numpy as np

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import (group_any, host_kmv, kmv_keys, kmv_values, kv_keys,
                       print_vertex, read_edge, seg_ids)

_U = np.uint64


def vertex_rand(v: np.ndarray, seed: int) -> np.ndarray:
    """Deterministic per-vertex random in [0,1): splitmix64(v+seed) →
    top-53-bit float (the reference's srand48(v+seed)/drand48,
    oink/luby_find.cpp:130-134 — consistent across every use of v)."""
    x = v.astype(np.uint64) + _U(seed & 0xFFFFFFFFFFFFFFFF)
    with np.errstate(over="ignore"):
        x = (x + _U(0x9E3779B97F4A7C15))
        z = x
        z = (z ^ (z >> _U(30))) * _U(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> _U(27))) * _U(0x94D049BB133111EB)
        z = z ^ (z >> _U(31))
    return (z >> _U(11)).astype(np.float64) / float(1 << 53)


# ---------------------------------------------------------------------------
# round kernels (batch reduces).  Host bodies below; device bodies (per
# shard, jitted under shard_map) alongside — the mesh backend never pulls
# a frame to the controller inside the round loop.
# ---------------------------------------------------------------------------

import jax.numpy as jnp

from ...parallel.devkernels import (is_sharded_kmv, is_sharded_kv,
                                    kmv_row_state, seg_max_u64, skmv_map,
                                    skv_map)


def _vertex_rand_dev(v, seed):
    """jnp twin of vertex_rand — identical splitmix64 bits.  ``seed`` is a
    traced u64 scalar so a seed sweep re-uses one compiled kernel."""
    x = v.astype(jnp.uint64) + seed.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    z = x
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    z = z ^ (z >> jnp.uint64(31))
    return (z >> jnp.uint64(11)).astype(jnp.float64) / float(1 << 53)


def _seg_any(cond, seg, valid, gcap):
    return seg_max_u64(cond.astype(jnp.uint64), seg, valid, gcap) > 0


def _edge_winner_dev(uk, nv, vo, vals, gc, vc, seed):
    # seed arrives as a traced u64 scalar (skmv_map `extra`)
    gcap = uk.shape[0]
    seg, rows_valid, groups_valid = kmv_row_state(nv, vo, vals, gc, vc)
    flag = vals if vals.ndim == 1 else vals[:, 0]
    dead = _seg_any(flag != 0, seg, rows_valid, gcap)
    alive = groups_valid & ~dead
    ri = _vertex_rand_dev(uk[:, 0], seed)
    rj = _vertex_rand_dev(uk[:, 1], seed)
    vi_wins = (ri < rj) | ((ri == rj) & (uk[:, 0] < uk[:, 1]))
    w = jnp.where(vi_wins, uk[:, 0], uk[:, 1])
    l = jnp.where(vi_wins, uk[:, 1], uk[:, 0])
    one = jnp.ones(gcap, jnp.uint64)
    okey = jnp.concatenate([w, l])
    oval = jnp.concatenate([jnp.stack([l, one], 1),
                            jnp.stack([w, one - 1], 1)])
    return okey, oval, jnp.concatenate([alive, alive])


def _vert_winner_dev(uk, nv, vo, vals, gc, vc):
    gcap = uk.shape[0]
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    lost_any = _seg_any(vals[:, 1] == 0, seg, rows_valid, gcap)
    tag = (~jnp.take(lost_any, jnp.maximum(seg, 0))).astype(jnp.uint64)
    okey = vals[:, 0]
    oval = jnp.stack([jnp.take(uk, jnp.maximum(seg, 0)), tag], 1)
    return okey, oval, rows_valid


def _vert_loser_dev(uk, nv, vo, vals, gc, vc):
    gcap = uk.shape[0]
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    loser = _seg_any(vals[:, 1] == 1, seg, rows_valid, gcap)
    tag = jnp.take(loser, jnp.maximum(seg, 0)).astype(jnp.uint64)
    okey = vals[:, 0]
    oval = jnp.stack([jnp.take(uk, jnp.maximum(seg, 0)), tag], 1)
    return okey, oval, rows_valid


def _vert_emit_mis_dev(uk, nv, vo, vals, gc, vc):
    """Per-group: all neighbours losers ⇒ group key joins the MIS."""
    gcap = uk.shape[0]
    seg, rows_valid, groups_valid = kmv_row_state(nv, vo, vals, gc, vc)
    survivor_nb = _seg_any(vals[:, 1] == 0, seg, rows_valid, gcap)
    mis = groups_valid & ~survivor_nb
    return uk, jnp.zeros(gcap, jnp.uint8), mis


def _vert_emit_edges_dev(uk, nv, vo, vals, gc, vc):
    """Per row: rebuild the canonical edge with the loser tag as marker."""
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    v = jnp.take(uk, jnp.maximum(seg, 0))
    u = vals[:, 0]
    okey = jnp.stack([jnp.minimum(v, u), jnp.maximum(v, u)], 1)
    return okey, vals[:, 1], rows_valid


def edge_winner(fr, kv, ptr):
    """KMV edge:[flags] → (v : [other, key-won]) per alive edge, both
    directions (reduce_edge_winner, oink/luby_find.cpp:140-182)."""
    if is_sharded_kmv(fr):
        seed = jnp.uint64(int(ptr) & 0xFFFFFFFFFFFFFFFF)
        kv.add_frame(skmv_map(fr, _edge_winner_dev, extra=(seed,)))
        return
    fr = host_kmv(fr)
    if len(fr) == 0:
        return
    e = kmv_keys(fr)                        # [g, 2]
    vals = kmv_values(fr)                   # [n] u8 NULL (round 1) / u64 tag
    dead = group_any(vals != 0, fr)
    e = e[~dead]
    if len(e) == 0:
        return
    seed = ptr
    ri, rj = vertex_rand(e[:, 0], seed), vertex_rand(e[:, 1], seed)
    vi_wins = (ri < rj) | ((ri == rj) & (e[:, 0] < e[:, 1]))
    w = np.where(vi_wins, e[:, 0], e[:, 1])
    l = np.where(vi_wins, e[:, 1], e[:, 0])
    one = np.ones(len(e), _U)
    kv.add_batch(np.concatenate([w, l]),
                 np.concatenate([np.stack([l, one], 1),
                                 np.stack([w, one - 1], 1)]))


def vert_winner(fr, kv, ptr):
    """Group per v of [other, won]: v wins all edges ⇒ round-winner; emit
    (other : [v, v-is-round-winner]) (reduce_vert_winner)."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _vert_winner_dev))
        return
    fr = host_kmv(fr)
    if len(fr) == 0:
        return
    vals = kmv_values(fr)                   # [n, 2]
    seg = seg_ids(fr)
    lost_any = group_any(vals[:, 1] == 0, fr)
    tag = (~lost_any[seg]).astype(_U)
    kv.add_batch(vals[:, 0], np.stack([kmv_keys(fr)[seg], tag], 1))


def vert_loser(fr, kv, ptr):
    """Group per v of [other, other-is-round-winner]: any winner neighbour
    ⇒ v is a loser; emit (other : [v, v-is-loser]) (reduce_vert_loser)."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _vert_loser_dev))
        return
    fr = host_kmv(fr)
    if len(fr) == 0:
        return
    vals = kmv_values(fr)
    seg = seg_ids(fr)
    loser = group_any(vals[:, 1] == 1, fr)
    tag = loser[seg].astype(_U)
    kv.add_batch(vals[:, 0], np.stack([kmv_keys(fr)[seg], tag], 1))


def vert_emit(fr, kv, ptr):
    """Group per v of [other, other-is-loser]: all neighbours losers ⇒ v
    joins the MIS (into the open accumulator MR via ptr); rebuild next
    round's edges with the loser tag as dead-marker
    (reduce_vert_emit, oink/luby_find.cpp:289-344)."""
    mrv = ptr
    if is_sharded_kmv(fr):
        mrv.kv.add_frame(skmv_map(fr, _vert_emit_mis_dev))
        kv.add_frame(skmv_map(fr, _vert_emit_edges_dev))
        return
    fr = host_kmv(fr)
    if len(fr) == 0:
        return
    vals = kmv_values(fr)
    seg = seg_ids(fr)
    vkeys = kmv_keys(fr)
    survivor_nb = group_any(vals[:, 1] == 0, fr)
    mis = vkeys[~survivor_nb]
    if len(mis):
        mrv.kv.add_batch(mis, np.zeros(len(mis), np.uint8))
    v, u = vkeys[seg], vals[:, 0]
    edges = np.stack([np.minimum(v, u), np.maximum(v, u)], 1)
    kv.add_batch(edges, vals[:, 1])


def _copy_edge_dev(k, v, c):
    valid = (jnp.arange(k.shape[0]) < c) & (k[:, 0] != k[:, 1])
    return k, jnp.zeros(k.shape[0], jnp.uint8), valid


def copy_edge(fr, kv, ptr):
    """Eij:NULL → Eij:NULL working copy, self-loops dropped — a self-loop
    vertex can never win its own edge and would cycle forever (the
    reference's map_vert_random carries them into the same livelock;
    we guard like edge_upper does)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _copy_edge_dev))
        return
    e = kv_keys(fr)
    e = e[e[:, 0] != e[:, 1]]
    kv.add_batch(e, np.zeros(len(e), np.uint8))


# ---------------------------------------------------------------------------
# command
# ---------------------------------------------------------------------------

@command("luby_find")
class LubyFind(Command):
    """luby_find seed: maximal independent set of an undirected edge list;
    output is one MIS vertex per line (oink/luby_find.cpp:53-115).

    Engines: ``fused`` (default) — the whole round loop in one jitted
    ``lax.while_loop`` over a dense state vector with the SAME splitmix64
    per-vertex priorities as the composed engine (models/luby.py);
    ``composed`` — the reference's 5-stage MR round below
    (GPUMR_LUBY_ENGINE=composed).  Both are valid MIS constructions;
    selected sets can differ because the composed engine's winner rule is
    edge-local per round."""

    ninputs = 1
    noutputs = 1
    engine: str | None = None   # None → GPUMR_LUBY_ENGINE env (or fused)

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal luby_find command")
        self.seed = int(args[0])

    def run(self):
        engine = self.engine or os.environ.get("GPUMR_LUBY_ENGINE", "fused")
        if engine not in ("fused", "composed"):
            raise MRError(f"luby_find: unknown engine {engine!r} "
                          f"(use 'fused' or 'composed')")
        if engine == "composed":
            return self._run_composed()
        obj = self.obj
        mre = obj.input(1, read_edge)

        from jax.sharding import Mesh
        mesh = obj.comm if isinstance(obj.comm, Mesh) else None
        # device staging (VERDICT r2 #2): vertex ranking on device;
        # self-loops dropped in the valid mask, matching the host path's
        # pre-unique filter
        from ...parallel.staging import stage_graph
        sg = stage_graph(mre, obj.comm, drop_self=True)
        if sg is not None and sg.n == 0:
            # a self-loop-only graph (drop_self left no vertices): empty
            # state falls through to the shared epilogue — no edge pull
            verts, state, iters = sg.verts, np.zeros(0, np.int8), 0
        elif sg is not None:
            from ...models.luby import _luby_sharded_fn
            verts, n = sg.verts, sg.n
            prio = vertex_rand(verts, self.seed)
            state_d, iters = _luby_sharded_fn(mesh, n, max(n, 1))(
                sg.src, sg.dst, sg.valid, jnp.asarray(prio))
            state, iters = np.asarray(state_d), int(iters)
        else:
            ecols: list = []
            mre.scan_kv(lambda fr, p: ecols.append(kv_keys(fr)),
                        batch=True)
            e = (np.concatenate(ecols) if ecols
                 else np.zeros((0, 2), np.uint64)).astype(np.uint64)
            e = e[e[:, 0] != e[:, 1]]        # self-loops never block a MIS
            verts, inv = np.unique(e.reshape(-1), return_inverse=True)
            n = len(verts)
            if n == 0:
                self.nset, self.niterate = 0, 0
                mrv = obj.create_mr()
                obj.output(1, mrv, print_vertex)
                self.message("Luby_find: 0 MIS vertices in 0 iterations")
                obj.cleanup()
                return
            src = inv.reshape(-1, 2)[:, 0]
            dst = inv.reshape(-1, 2)[:, 1]
            prio = vertex_rand(verts, self.seed)

            from ...models.luby import luby_mis, luby_mis_sharded
            if mesh is not None:
                state, iters = luby_mis_sharded(mesh, src, dst, prio, n)
            else:
                state, iters = luby_mis(src.astype(np.int32),
                                        dst.astype(np.int32),
                                        jnp.asarray(prio), n)
                state, iters = np.asarray(state), int(iters)

        mis = verts[state == 1]
        self.nset, self.niterate = int(len(mis)), int(iters)
        mrv = obj.create_mr()
        mrv.map(1, lambda i, kv, p: kv.add_batch(
            mis, np.zeros(len(mis), np.uint8)))
        obj.output(1, mrv, print_vertex)
        self.message(f"Luby_find: {self.nset} MIS vertices in "
                     f"{self.niterate} iterations")
        obj.cleanup()

    def _run_composed(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mre.aggregate()   # mesh: shard once; the round loop below then
        #                   stays device-resident (serial: no-op)
        mrv = obj.create_mr()
        mrw = obj.create_mr()

        mrw.map_mr(mre, copy_edge, batch=True)
        mrw.clone()

        niterate = 0
        mrv.open()
        while True:
            n = mrw.reduce(edge_winner, ptr=self.seed, batch=True)
            if n == 0:
                break
            mrw.collate()
            mrw.reduce(vert_winner, batch=True)
            mrw.collate()
            mrw.reduce(vert_loser, batch=True)
            mrw.collate()
            mrw.reduce(vert_emit, ptr=mrv, batch=True)
            mrw.collate()
            niterate += 1
        nset = mrv.close()

        self.nset, self.niterate = nset, niterate
        obj.output(1, mrv, print_vertex)
        self.message(f"Luby_find: {nset} MIS vertices in {niterate} "
                     f"iterations")
        obj.cleanup()
