"""dump_trace command: write the Chrome trace-event JSON of everything
the in-memory span ring has recorded so far.

No reference analog — the reference's only observability is printf
(``src/mapreduce.cpp:2937-3066``); this is the scripted exit point of the
obs/ tracing layer::

    dump_trace trace.json          # load in Perfetto / chrome://tracing

Tracing must be on (MRTPU_TRACE env var, or any earlier enable) for
events to exist; with tracing off the command still writes a valid,
empty trace and says so.
"""

from __future__ import annotations

from ...core.runtime import MRError
from ..command import Command, command


@command("dump_trace")
class DumpTrace(Command):
    ninputs = 0
    noutputs = 0

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal dump_trace command")
        self.path = args[0]

    def run(self):
        from ...obs import get_tracer, write_chrome_trace
        tr = get_tracer()
        n = write_chrome_trace(self.path, tr.events())
        note = "" if tr.enabled else \
            " (tracing disabled — set MRTPU_TRACE to record spans)"
        self.message(f"DumpTrace: {n} events -> {self.path}{note}")
