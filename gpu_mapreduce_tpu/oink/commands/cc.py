"""cc_find / cc_stats — label-propagation connected components.

Reference: ``oink/cc_find.cpp:38-109`` (zone propagation until no zone pair
changes) and ``oink/cc_stats.cpp:37-63`` (component-size histogram).

The reference discriminates edge-vs-zone values by ``valuebytes`` and splits
oversized zones across procs with hi-bit + procID packing
(``oink/cc_find.cpp:48-55``, ``map_invert_multi``/``map_zone_multi``).  The
TPU build keeps fixed-width lanes instead: values are tagged ``[tag, a, b]``
u64 rows (tag 0 = edge payload, tag 1 = zone payload), and zone reassignment
is one vectorised segment reduce, so the big-zone splitting machinery (the
``nthresh`` knob) is unnecessary — ``nthresh`` is accepted for script parity
and ignored.  Zone winner = min zone id, so the fixpoint labels every
component with its minimum vertex id (deterministic across backends)."""

from __future__ import annotations

import os

import numpy as np

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import (count, edge_to_vertices, host_kmv, invert, kmv_keys,
                       kmv_values, kv_keys, kv_values, print_vertex_value,
                       read_edge, read_vertex_value, seg_ids, value_histogram)


# ---------------------------------------------------------------------------
# batch kernels (reference cc_find.cpp:129-260 callbacks, vectorised).
# Each has a host body (KVFrame/KMVFrame) and a device body (per-shard
# jittable under shard_map, parallel/devkernels.py) — on the mesh backend a
# whole cc iteration runs shuffle → segment ops → emit entirely in HBM.
# ---------------------------------------------------------------------------

import jax.numpy as jnp

from ...parallel.devkernels import (U64MAX, is_sharded_kmv, is_sharded_kv,
                                    kmv_row_state, seg_max_u64, seg_min_u64,
                                    skmv_map, skv_map)


def _u64z(n):
    return jnp.zeros(n, jnp.uint64)


def _self_zone_dev(uk, nv, vo, vals, gc, vc):
    valid = jnp.arange(uk.shape[0]) < gc
    return uk, uk, valid


def self_zone(fr, kv, ptr):
    """V:[..] group → V:V — every vertex starts in its own zone
    (reduce_self_zone, cc_find.cpp:132-137)."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _self_zone_dev))
        return
    k = kmv_keys(fr)
    kv.add_batch(k, k)


def _edge_vert_tagged_dev(k, v, c):
    n = k.shape[0]
    valid = jnp.arange(n) < c
    tag0 = jnp.stack([_u64z(n), k[:, 0], k[:, 1]], 1)
    okey = jnp.concatenate([k[:, 0], k[:, 1]])
    oval = jnp.concatenate([tag0, tag0])
    return okey, oval, jnp.concatenate([valid, valid])


def edge_vert_tagged(fr, kv, ptr):
    """Eij:NULL → Vi:[0,vi,vj] and Vj:[0,vi,vj] (map_edge_vert,
    cc_find.cpp:141-148, tagged instead of sized)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _edge_vert_tagged_dev))
        return
    e = kv_keys(fr)
    val = np.concatenate([
        np.stack([np.zeros(len(e), np.uint64), e[:, 0], e[:, 1]], 1)] * 2)
    kv.add_batch(np.concatenate([e[:, 0], e[:, 1]]), val)


def _zone_tagged_dev(k, v, c):
    n = k.shape[0]
    valid = jnp.arange(n) < c
    oval = jnp.stack([jnp.ones(n, jnp.uint64), v.astype(jnp.uint64),
                      _u64z(n)], 1)
    return k, oval, valid


def zone_tagged(fr, kv, ptr):
    """V:zone → V:[1,zone,0] (the mrv contribution to the join)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _zone_tagged_dev))
        return
    k = kv_keys(fr)
    z = kv_values(fr)
    zeros = np.zeros(len(k), np.uint64)
    kv.add_batch(k, np.stack([np.ones(len(k), np.uint64),
                              z.astype(np.uint64), zeros], 1))


def _edge_zone_dev(uk, nv, vo, vals, gc, vc):
    gcap = uk.shape[0]
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    is_zone = vals[:, 0] == 1
    zone_of = seg_max_u64(vals[:, 1], seg, rows_valid & is_zone, gcap)
    okey = vals[:, 1:3]
    oval = jnp.take(zone_of, jnp.maximum(seg, 0))
    return okey, oval, rows_valid & ~is_zone


def edge_zone(fr, kv, ptr):
    """Per-vertex group: find the zone row, emit (Eij : zone) per edge row
    (reduce_edge_zone, cc_find.cpp:152-186)."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _edge_zone_dev))
        return
    fr = host_kmv(fr)
    vals = kmv_values(fr)                      # [n, 3] tagged
    seg = seg_ids(fr)
    is_zone = vals[:, 0] == 1
    zone_of = np.zeros(len(fr), np.uint64)
    zone_of[seg[is_zone]] = vals[is_zone, 1]
    is_edge = ~is_zone
    kv.add_batch(vals[is_edge, 1:3], zone_of[seg[is_edge]])


def _zone_winner_dev(uk, nv, vo, vals, gc, vc):
    gcap = uk.shape[0]
    seg, rows_valid, groups_valid = kmv_row_state(nv, vo, vals, gc, vc)
    zmin = seg_min_u64(vals, seg, rows_valid, gcap)
    zmax = seg_max_u64(vals, seg, rows_valid, gcap)
    changed = groups_valid & (zmin != zmax)
    return zmax, zmin, changed


def zone_winner(fr, kv, ptr):
    """Per-edge group of zone ids: if the two endpoint zones differ, emit
    (loser_zone : winner_zone), winner = min (reduce_zone_winner,
    cc_find.cpp:190-219).  Emits nothing when converged."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _zone_winner_dev))
        return
    fr = host_kmv(fr)
    vals = kmv_values(fr).astype(np.uint64)    # [n] zone per edge copy
    zmin = np.minimum.reduceat(vals, fr.offsets[:-1])
    zmax = np.maximum.reduceat(vals, fr.offsets[:-1])
    changed = zmin != zmax
    kv.add_batch(zmax[changed], zmin[changed])


def _invert_zone_tagged_dev(k, v, c):
    n = k.shape[0]
    valid = jnp.arange(n) < c
    oval = jnp.stack([_u64z(n), k, _u64z(n)], 1)
    return v.astype(jnp.uint64), oval, valid


def invert_zone_tagged(fr, kv, ptr):
    """V:zone → zone:[0,v,0] — membership rows for reassignment
    (map_invert_multi, cc_find.cpp:223-238, without the hi-bit split)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _invert_zone_tagged_dev))
        return
    k = kv_keys(fr)
    z = kv_values(fr).astype(np.uint64)
    zeros = np.zeros(len(k), np.uint64)
    kv.add_batch(z, np.stack([zeros, k, zeros], 1))


def _winner_tagged_dev(k, v, c):
    n = k.shape[0]
    valid = jnp.arange(n) < c
    oval = jnp.stack([jnp.ones(n, jnp.uint64), v.astype(jnp.uint64),
                      _u64z(n)], 1)
    return k, oval, valid


def winner_tagged(fr, kv, ptr):
    """loser_zone:winner → loser_zone:[1,winner,0] (map_zone_multi,
    cc_find.cpp:242-...)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _winner_tagged_dev))
        return
    k = kv_keys(fr)
    w = kv_values(fr).astype(np.uint64)
    zeros = np.zeros(len(k), np.uint64)
    kv.add_batch(k, np.stack([np.ones(len(k), np.uint64), w, zeros], 1))


def _zone_reassign_dev(uk, nv, vo, vals, gc, vc):
    gcap = uk.shape[0]
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    is_win = vals[:, 0] == 1
    win_zone = seg_min_u64(vals[:, 1], seg, rows_valid & is_win, gcap)
    new_zone = jnp.where(win_zone != U64MAX, win_zone, uk)
    okey = vals[:, 1]
    oval = jnp.take(new_zone, jnp.maximum(seg, 0))
    return okey, oval, rows_valid & ~is_win


def zone_reassign(fr, kv, ptr):
    """Per-zone group: members move to min winner zone if any winner row
    present, else stay (reduce_zone_reassign)."""
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _zone_reassign_dev))
        return
    fr = host_kmv(fr)
    vals = kmv_values(fr)                      # [n, 3]
    seg = seg_ids(fr)
    zones = kmv_keys(fr).astype(np.uint64)
    is_win = vals[:, 0] == 1
    new_zone = zones.copy()
    if np.any(is_win):
        wseg = seg[is_win]
        order = np.lexsort((vals[is_win, 1], wseg))
        wseg_s, wval_s = wseg[order], vals[is_win, 1][order]
        first = np.ones(len(wseg_s), bool)
        first[1:] = wseg_s[1:] != wseg_s[:-1]
        new_zone[wseg_s[first]] = wval_s[first]
    is_mem = ~is_win
    kv.add_batch(vals[is_mem, 1], new_zone[seg[is_mem]])


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------

@command("cc_find")
class CCFind(Command):
    """cc_find nthresh: connected components of an edge list; output is
    (Vi, Zi) with Zi = min vertex id of Vi's component
    (oink/cc_find.cpp:38-109).

    Two engines, same fixpoint (min-vertex-id zones):

    * ``fused`` (default) — the whole convergence loop is ONE jitted
      ``lax.while_loop`` (models/cc.py): two segment-mins + pointer
      jumping per round, edges mesh-sharded, labels replicated, one
      pmin over ICI per round.  ~1000× the composed engine on XLA,
      where each MR stage is a compiled program.
    * ``composed`` — the reference's 9-stage MapReduce composition
      (below), kept as the parity demonstration of the op algebra's
      device tier; select with GPUMR_CC_ENGINE=composed (or by setting
      ``CCFind.engine``)."""

    ninputs = 1
    noutputs = 1
    engine: str | None = None   # None → GPUMR_CC_ENGINE env (or fused)

    def params(self, args):
        if len(args) != 1:
            raise MRError("Illegal cc_find command")
        self.nthresh = int(args[0])  # accepted for parity; see module doc

    def run(self):
        engine = self.engine or os.environ.get("GPUMR_CC_ENGINE", "fused")
        if engine not in ("fused", "composed"):
            raise MRError(f"cc_find: unknown engine {engine!r} "
                          f"(use 'fused' or 'composed')")
        if engine == "composed":
            return self._run_composed()
        obj = self.obj
        mre = obj.input(1, read_edge)

        from jax.sharding import Mesh
        mesh = obj.comm if isinstance(obj.comm, Mesh) else None
        # device staging (VERDICT r2 #2): shard the edge KV once, rank
        # vertices ON DEVICE — the O(E) edge columns never reach the
        # controller; only n and the [n] id table do
        from ...parallel.staging import stage_graph
        sg = stage_graph(mre, obj.comm)
        # (sg.n == 0 cannot happen here: empty datasets return None and
        # without drop_self every valid edge row has real endpoints)
        if sg is not None:
            from ...models.cc import _cc_sharded_fn
            labels_d, iters = _cc_sharded_fn(mesh, sg.n, max(sg.n, 1))(
                sg.src, sg.dst, sg.valid)
            verts = sg.verts
            labels, iters = np.asarray(labels_d), int(iters)
        else:
            edges: list = []
            mre.scan_kv(lambda fr, p: edges.append(kv_keys(fr)),
                        batch=True)
            e = (np.concatenate(edges) if edges
                 else np.zeros((0, 2), np.uint64))
            verts, inv = np.unique(e.reshape(-1), return_inverse=True)
            n = len(verts)
            if n == 0:
                self.ncc, self.niterate = 0, 0
                mrv = obj.create_mr()
                obj.output(1, mrv, print_vertex_value)
                self.message("CC_find: 0 components in 0 iterations")
                obj.cleanup()
                return
            src = inv.reshape(-1, 2)[:, 0]
            dst = inv.reshape(-1, 2)[:, 1]

            from ...models.cc import cc, cc_sharded
            if mesh is not None:
                labels, iters = cc_sharded(mesh, src, dst, n)
            else:
                labels, iters = cc(src.astype(np.int32),
                                   dst.astype(np.int32), n)
                labels, iters = np.asarray(labels), int(iters)

        zones = verts[labels]               # min vertex id per component
        self.ncc = int(len(np.unique(labels)))
        self.niterate = int(iters)
        mrv = obj.create_mr()
        mrv.map(1, lambda i, kv, p: kv.add_batch(verts, zones))
        obj.output(1, mrv, print_vertex_value)
        self.message(f"CC_find: {self.ncc} components in "
                     f"{self.niterate} iterations")
        obj.cleanup()

    def _run_composed(self):
        obj = self.obj
        mre = obj.input(1, read_edge)
        mre.aggregate()   # mesh: shard the edge list once; every iteration
        #                   below then stays device-resident (serial: no-op)
        mrv = obj.create_mr()

        mrv.map_mr(mre, edge_to_vertices, batch=True)
        mrv.collate()
        mrv.reduce(self_zone, batch=True)

        niterate = 0
        while True:
            niterate += 1
            mrz = obj.create_mr()
            mrz.map_mr(mre, edge_vert_tagged, batch=True)
            tmp = obj.create_mr()
            tmp.map_mr(mrv, zone_tagged, batch=True)
            mrz.add(tmp)
            obj.free_mr(tmp)
            mrz.collate()
            mrz.reduce(edge_zone, batch=True)
            mrz.collate()
            nchanged = mrz.reduce(zone_winner, batch=True)
            if not nchanged:
                obj.free_mr(mrz)
                break
            tmp = obj.create_mr()
            tmp.map_mr(mrv, invert_zone_tagged, batch=True)
            tmp2 = obj.create_mr()
            tmp2.map_mr(mrz, winner_tagged, batch=True)
            tmp.add(tmp2)
            tmp.collate()
            tmp.reduce(zone_reassign, batch=True)
            obj.free_mr(mrz)
            obj.free_mr(tmp2)
            obj.free_mr(mrv)
            mrv = tmp

        mrt = obj.create_mr()
        mrt.map_mr(mrv, invert, batch=True)
        ncc = mrt.collate()
        self.ncc, self.niterate = ncc, niterate
        obj.output(1, mrv, print_vertex_value)
        self.message(f"CC_find: {ncc} components in {niterate} iterations")
        obj.cleanup()


@command("cc_stats")
class CCStats(Command):
    """cc_stats: histogram of component sizes from (Vi, Zi) pairs
    (oink/cc_stats.cpp:37-63).  self.stats = [(size, ncomponents)]
    descending by size."""

    ninputs = 1

    def params(self, args):
        if args:
            raise MRError("Illegal cc_stats command")

    def run(self):
        obj = self.obj
        mrv = obj.input(1, read_vertex_value)
        mr = obj.create_mr()
        nvert = mr.map_mr(mrv, invert, batch=True)
        ncc = mr.collate()
        mr.reduce(count, batch=True)
        self.nvert, self.ncc = nvert, ncc
        self.message(f"CCStats: {ncc} components, {nvert} vertices")
        self.stats = value_histogram(mr)
        for size, n in self.stats:
            self.message(f"  {size} {n}")
        obj.cleanup()
