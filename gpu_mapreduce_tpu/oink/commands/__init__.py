"""Command plugin modules — importing registers each with the
COMMANDS registry (the generated style_command.h of the reference)."""

from . import (cc, degree, dump_metrics, dump_plan, dump_trace,  # noqa: F401
               edges, histo, invertedindex, luby, pagerank, rmat, sssp,
               stream, tri, wordfreq)
