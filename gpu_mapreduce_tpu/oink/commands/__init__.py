"""Command plugin modules — importing registers each with the
COMMANDS registry (the generated style_command.h of the reference)."""

from . import (cc, degree, edges, histo, luby, pagerank, rmat,  # noqa: F401
               sssp, tri, wordfreq)
