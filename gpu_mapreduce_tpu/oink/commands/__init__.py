"""Command plugin modules — importing registers each with the
COMMANDS registry (the generated style_command.h of the reference)."""

from . import (cc, degree, dump_plan, dump_trace, edges, histo,  # noqa: F401
               luby, pagerank, rmat, sssp, tri, wordfreq)
