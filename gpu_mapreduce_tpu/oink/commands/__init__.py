"""Command plugin modules — importing registers each with the
COMMANDS registry (the generated style_command.h of the reference)."""

from . import (cc, degree, dump_trace, edges, histo, luby,  # noqa: F401
               pagerank, rmat, sssp, tri, wordfreq)
