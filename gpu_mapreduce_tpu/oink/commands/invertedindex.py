"""invertedindex command — the flagship app (apps/invertedindex.py)
behind the script/serve surface.

``invertedindex -i v_files [-o dir]`` runs the full URL→documents
pipeline on the session's backend (mesh or serial); with ``-o`` the
per-shard ``part-*`` index files land under the named directory
(reference myreduce, cuda/InvertedIndex.cu:463-513).  The result
message carries the (files, pairs, unique urls) triple — deterministic
across fuse/wire/mesh-width, which is what the serve tier's result
memoization byte-exactness contract leans on.
"""

from __future__ import annotations

from ...core.runtime import MRError
from ..command import Command, command


@command("invertedindex")
class InvertedIndexCmd(Command):
    ninputs = 1
    noutputs = 1

    def params(self, args):
        if args:
            raise MRError("Illegal invertedindex command")

    def run(self):
        obj = self.obj
        if not obj.inputs or obj.inputs[0].paths is None:
            raise MRError("invertedindex requires a file input (-i)")
        paths = obj.inputs[0].paths
        outdir = None
        if obj.outputs and obj.outputs[0].path is not None:
            outdir = obj.outputs[0].path
        from ...apps.invertedindex import InvertedIndex
        app = InvertedIndex(comm=obj.comm)
        self.npairs, self.nurl = app.run(paths, outdir=outdir)
        self.nfiles = len(app.docs)
        self.message(f"InvertedIndex: {self.nfiles} files, "
                     f"{self.npairs} pairs, {self.nurl} unique urls")
        obj.cleanup()
