"""sssp — single-source shortest paths by MapReduce Bellman-Ford relaxation.

Reference: ``oink/sssp.cpp:49-180`` (per-source BFS loop) with callbacks
``reorganize_edges`` 187, ``add_source`` 205, ``pick_shortest_distances``
244, ``update_adjacent_distances`` 299, and the DISTANCE/EDGEVALUE structs
of ``oink/sssp.h`` (pred vertex, f32 weight, current flag).

Iteration (identical to the reference composition): candidate distances in
``mrpath`` are shuffled to their vertex, merged into the per-vertex state
``mrvert``; ``pick_shortest`` keeps the best (distance, pred) per vertex
and re-emits changed vertices; changed distances join the pre-aggregated
adjacency ``mredge`` and ``update_adjacent`` relaxes each out-edge into
the next round's candidates.  Converges when no vertex distance changes.

TPU-first redesigns vs the reference:

* the reference discriminates edge-vs-distance values by ``valuebytes``
  (``sssp.cpp:318,341``); we keep one fixed-width lane: every value is a
  ``[tag, a, b, c]`` f64 row — edge ``[0, vj, wt, 0]``, distance
  ``[1, pred, dist, current]``.  Vertex ids stay exact through f64 up to
  2^53 (RMAT-26 ids are < 2^27);
* both relaxation reduces are single vectorised segment passes
  (lexsort + reduceat), not per-group callbacks;
* source selection: the reference seeds srand48 but actually takes the
  first ``ncnt`` keys in arbitrary shuffle order (``sssp.cpp:363-375``);
  we order vertices by (splitmix64(v+seed), v) — random *and*
  deterministic across runs/backends;
* output: the reference prints ``mrpath`` after convergence, which is
  empty by construction (the loop exits only when pick_shortest emitted
  nothing); we print the converged ``mrvert`` state — one
  ``v dist pred`` line per vertex, inf for unreachable (DISTANCE's
  FLT_MAX default, oink/sssp.h:52);
* no-predecessor sentinel: the reference memsets pred to vertex id 0
  (``sssp.h:51``, ``sssp.cpp:384``) and then skips relaxing edges back
  to the predecessor — silently wrong when a real vertex 0 is adjacent
  to the source.  We use -1.0 internally (no u64 vertex maps to it) and
  print 0 for it, keeping the reference's output convention without the
  miss.
"""

from __future__ import annotations

import os

import numpy as np

from ...core.runtime import MRError
from ..command import Command, command
from ..kernels import (cull, edge_to_vertices, group_min_rows, host_kmv,
                       kmv_keys, kmv_values, kv_keys, kv_values,
                       read_edge_weight, seg_ids)
from .luby import vertex_rand

TAG_EDGE, TAG_DIST = 0.0, 1.0
NO_PRED = -1.0                   # see module docstring: sentinel, not id 0


# ---------------------------------------------------------------------------
# batch kernels — host bodies plus per-shard device bodies (shard_map), so
# the mesh relaxation loop never materialises a frame on the controller.
# ---------------------------------------------------------------------------

import jax.numpy as jnp

from ...parallel.devkernels import (is_sharded_kmv, is_sharded_kv,
                                    kmv_row_state, seg_lex_min2, seg_max_u64,
                                    seg_min_with, skmv_map, skv_map)

_INF = jnp.float64(jnp.inf)


def _reorganize_edges_dev(k, v, c):
    n = k.shape[0]
    valid = jnp.arange(n) < c
    oval = jnp.stack([jnp.zeros(n, jnp.float64),
                      k[:, 1].astype(jnp.float64),
                      v.astype(jnp.float64), jnp.zeros(n, jnp.float64)], 1)
    return k[:, 0], oval, valid


def reorganize_edges(fr, kv, ptr):
    """Eij:wt → vi:[0, vj, wt, 0] (reference reorganize_edges,
    oink/sssp.cpp:187-199 — directed out-edges keyed by source)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _reorganize_edges_dev))
        return
    e = kv_keys(fr)
    wt = kv_values(fr).astype(np.float64)
    rows = np.stack([np.full(len(e), TAG_EDGE),
                     e[:, 1].astype(np.float64), wt,
                     np.zeros(len(e))], 1)
    kv.add_batch(e[:, 0], rows)


def _init_distance_dev(k, v, c):
    n = k.shape[0]
    valid = jnp.arange(n) < c
    row = jnp.asarray(np.array([TAG_DIST, NO_PRED, np.inf, 1.0]))
    return k, jnp.tile(row, (n, 1)), valid


def init_distance(fr, kv, ptr):
    """v:* → v:[1, NO_PRED, inf, 1] (initialize_vertex_distances,
    oink/sssp.cpp:231-237; DISTANCE() default wt=FLT_MAX, pred sentinel
    corrected per module docstring)."""
    if is_sharded_kv(fr):
        kv.add_frame(skv_map(fr, _init_distance_dev))
        return
    k = kv_keys(fr)
    rows = np.tile(np.array([TAG_DIST, NO_PRED, np.inf, 1.0]), (len(k), 1))
    kv.add_batch(k, rows)


def _pick_shortest_state(uk, nv, vo, vals, gc, vc):
    """Per group: winner (min dist, pred) state row, every valid group."""
    gcap = uk.shape[0]
    seg, rows_valid, groups_valid = kmv_row_state(nv, vo, vals, gc, vc)
    wdist, wpred = seg_lex_min2(vals[:, 2], vals[:, 1], seg, rows_valid,
                                gcap, _INF, _INF)
    out = jnp.stack([jnp.ones(gcap, jnp.float64), wpred, wdist,
                     jnp.ones(gcap, jnp.float64)], 1)
    return uk, out, groups_valid


def _pick_shortest_changed(uk, nv, vo, vals, gc, vc):
    """Per group: the winner row again, but only where it differs from the
    group's previous current row (or no current row existed)."""
    gcap = uk.shape[0]
    seg, rows_valid, groups_valid = kmv_row_state(nv, vo, vals, gc, vc)
    wdist, wpred = seg_lex_min2(vals[:, 2], vals[:, 1], seg, rows_valid,
                                gcap, _INF, _INF)
    is_cur = rows_valid & (vals[:, 3] == 1.0)
    pdist = seg_min_with(vals[:, 2], seg, is_cur, gcap, _INF)
    ppred = seg_min_with(vals[:, 1], seg, is_cur, gcap, _INF)
    has_prev = seg_max_u64(jnp.ones(vals.shape[0], jnp.uint64), seg,
                           is_cur, gcap) > 0
    neq = lambda x, y: ~((x == y) | (jnp.isnan(x) & jnp.isnan(y)))
    changed = groups_valid & (~has_prev | neq(wdist, pdist)
                              | neq(wpred, ppred))
    out = jnp.stack([jnp.ones(gcap, jnp.float64), wpred, wdist,
                     jnp.ones(gcap, jnp.float64)], 1)
    return uk, out, changed


def pick_shortest(fr, kv, ptr):
    """Per-vertex group of distance rows: keep min (dist, pred); emit the
    winner (current=1) back to the vertex state, and into the open
    candidate MR iff it differs from the previous current row
    (pick_shortest_distances, oink/sssp.cpp:244-293)."""
    mrpath = ptr
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _pick_shortest_state))
        mrpath.kv.add_frame(skmv_map(fr, _pick_shortest_changed))
        return
    fr = host_kmv(fr)
    if len(fr) == 0:
        return
    vals = kmv_values(fr)                   # [n, 4] all TAG_DIST
    seg = seg_ids(fr)
    dist, pred, cur = vals[:, 2], vals[:, 1], vals[:, 3]

    # winner per group = lexicographic min (dist, pred); every group has
    # rows, so the present-groups array is exactly arange(len(fr))
    _, win = group_min_rows(seg, dist, pred)

    # previous current row per group (exactly one: init_distance seeds one
    # and every round re-emits one; duplicates from the path merge are
    # byte-identical so any is fine)
    cur_rows = np.flatnonzero(cur == 1.0)
    prev = np.full(len(fr), -1)
    prev[seg[cur_rows]] = cur_rows

    keys = kmv_keys(fr)
    out = np.stack([np.full(len(fr), TAG_DIST), pred[win], dist[win],
                    np.ones(len(fr))], 1)
    kv.add_batch(keys, out)

    changed = (dist[win] != dist[prev]) | (pred[win] != pred[prev])
    changed |= prev < 0
    if np.any(changed):
        mrpath.kv.add_batch(keys[changed], out[changed])


def _update_adjacent_edges(uk, nv, vo, vals, gc, vc):
    """Per row: re-emit the adjacency rows unchanged."""
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    is_edge = vals[:, 0] == TAG_EDGE
    okey = jnp.take(uk, jnp.maximum(seg, 0))
    return okey, vals, rows_valid & is_edge


def _update_adjacent_relax(uk, nv, vo, vals, gc, vc):
    """Per edge row: relax with the group's best arriving distance."""
    gcap = uk.shape[0]
    seg, rows_valid, _ = kmv_row_state(nv, vo, vals, gc, vc)
    is_dist = rows_valid & (vals[:, 0] == TAG_DIST)
    bdist, bpred = seg_lex_min2(vals[:, 2], vals[:, 1], seg, is_dist,
                                gcap, _INF, _INF)
    has_dist = seg_max_u64(jnp.ones(vals.shape[0], jnp.uint64), seg,
                           is_dist, gcap) > 0
    g = jnp.maximum(seg, 0)
    is_edge = rows_valid & (vals[:, 0] == TAG_EDGE)
    vj = vals[:, 1]
    vi = jnp.take(uk, g).astype(jnp.float64)
    relax = (is_edge & jnp.take(has_dist, g) & (vj != jnp.take(bpred, g))
             & (vj != vi) & jnp.isfinite(jnp.take(bdist, g)))
    okey = vj.astype(jnp.uint64)
    n = vals.shape[0]
    oval = jnp.stack([jnp.ones(n, jnp.float64), vi,
                      jnp.take(bdist, g) + vals[:, 2],
                      jnp.zeros(n, jnp.float64)], 1)
    return okey, oval, relax


def update_adjacent(fr, kv, ptr):
    """Per-vertex group of edge rows + changed-distance rows: re-emit the
    adjacency; if a distance arrived, relax every out-edge into the open
    candidate MR — skipping the predecessor and self-loops
    (update_adjacent_distances, oink/sssp.cpp:299-360)."""
    mrpath = ptr
    if is_sharded_kmv(fr):
        kv.add_frame(skmv_map(fr, _update_adjacent_edges))
        mrpath.kv.add_frame(skmv_map(fr, _update_adjacent_relax))
        return
    fr = host_kmv(fr)
    if len(fr) == 0:
        return
    vals = kmv_values(fr)                   # [n, 4] mixed tags
    seg = seg_ids(fr)
    keys = kmv_keys(fr)
    is_dist = vals[:, 0] == TAG_DIST
    is_edge = ~is_dist

    # re-emit adjacency rows
    kv.add_batch(keys[seg[is_edge]], vals[is_edge])

    if not np.any(is_dist):
        return
    # best arriving distance per group
    dseg, ddist, dpred = seg[is_dist], vals[is_dist, 2], vals[is_dist, 1]
    groups, rows = group_min_rows(dseg, ddist, dpred)
    best_dist = np.full(len(fr), np.inf)
    best_pred = np.zeros(len(fr))
    best_dist[groups] = ddist[rows]
    best_pred[groups] = dpred[rows]
    has_dist = np.zeros(len(fr), bool)
    has_dist[dseg] = True

    eseg = seg[is_edge]
    vj = vals[is_edge, 1]
    wt = vals[is_edge, 2]
    vi = keys[seg[is_edge]].astype(np.float64)
    relax = (has_dist[eseg] & (vj != best_pred[eseg]) & (vj != vi)
             & np.isfinite(best_dist[eseg]))
    if np.any(relax):
        nk = vj[relax].astype(np.uint64)
        rows = np.stack([np.full(len(nk), TAG_DIST), vi[relax],
                         best_dist[eseg][relax] + wt[relax],
                         np.zeros(len(nk))], 1)
        mrpath.kv.add_batch(nk, rows)


# ---------------------------------------------------------------------------
# command
# ---------------------------------------------------------------------------

@command("sssp")
class SSSPCommand(Command):
    """sssp ncnt seed: shortest paths from ncnt deterministic-random
    sources over a directed weighted edge list (oink/sssp.cpp).  Output
    per source: 'v dist pred' lines (path suffixed .<i> when ncnt > 1);
    self.results[source] = {v: (dist, pred)}.

    Engines (same contract — any pred realising the shortest distance):
    ``fused`` (default) — whole Bellman-Ford relaxation in one jitted
    ``lax.while_loop`` with the source as a traced operand, so every
    source of the ncnt experiment reuses ONE compiled program
    (models/sssp.py); ``composed`` — the reference's per-round MR
    composition below (GPUMR_SSSP_ENGINE=composed)."""

    ninputs = 1
    noutputs = 1
    engine: str | None = None   # None → GPUMR_SSSP_ENGINE env (or fused)

    def params(self, args):
        if len(args) != 2:
            raise MRError("Illegal sssp command")
        self.ncnt = int(args[0])
        self.seed = int(args[1])

    def run(self):
        engine = self.engine or os.environ.get("GPUMR_SSSP_ENGINE", "fused")
        if engine not in ("fused", "composed"):
            raise MRError(f"sssp: unknown engine {engine!r} "
                          f"(use 'fused' or 'composed')")
        if engine == "composed":
            return self._run_composed()
        obj = self.obj
        mredge = obj.input(1, read_edge_weight)

        from jax.sharding import Mesh
        mesh = obj.comm if isinstance(obj.comm, Mesh) else None
        # device staging (VERDICT r2 #2): vertex ranking on device; the
        # weight column is row-sharded aligned with the ranked endpoints
        # (need_weights guards against interned byte values, whose u64
        # ids are not numbers)
        from ...parallel.staging import stage_graph
        sg = stage_graph(mredge, obj.comm, need_weights=True)
        # (sg.n == 0 cannot happen: empty datasets return None and
        # without drop_self every valid edge row has real endpoints)
        if sg is not None:
            from ...models.sssp import _bf_sharded_fn
            verts, n = sg.verts, sg.n
            fn = _bf_sharded_fn(mesh, n, max(n, 1))

            def bf(sidx):
                dist, pred, it = fn(sg.src, sg.dst, sg.weights, sg.valid,
                                    jnp.int32(sidx))
                return np.asarray(dist), np.asarray(pred), int(it)
        else:
            ecols: list = []
            mredge.scan_kv(lambda fr, p: ecols.append(
                (kv_keys(fr), kv_values(fr))), batch=True)
            if ecols:
                e = np.concatenate([c[0] for c in ecols]).astype(np.uint64)
                w = np.concatenate([c[1] for c in ecols]).astype(np.float64)
            else:
                e = np.zeros((0, 2), np.uint64)
                w = np.zeros(0, np.float64)
            verts, inv = np.unique(e.reshape(-1), return_inverse=True)
            n = len(verts)
            if n == 0:
                raise MRError("sssp: empty edge list")
            src = inv.reshape(-1, 2)[:, 0]
            dst = inv.reshape(-1, 2)[:, 1]

            from ...models.sssp import bellman_ford, prepare_bellman_ford
            if mesh is not None:
                # pad + upload the edges ONCE; every source reuses the
                # compiled program and the device-resident arrays
                bf = prepare_bellman_ford(mesh, src, dst, w, n)
            else:
                s32 = src.astype(np.int32)
                d32 = dst.astype(np.int32)
                w_h = jnp.asarray(w)

                def bf(sidx):
                    dist, pred, it = bellman_ford(s32, d32, w_h, n,
                                                  jnp.int32(sidx))
                    return np.asarray(dist), np.asarray(pred), int(it)

        # deterministic-random source list (same ranking as composed)
        order = np.lexsort((verts, vertex_rand(verts, self.seed)))
        sources = verts[order][:self.ncnt].tolist()

        self.results = {}
        self.niters = {}
        outd = obj.outputs[0] if obj.outputs else None
        dist = np.full(n, np.inf)
        pred = np.full(n, -1, np.int64)
        for cnt, source in enumerate(sources):
            sidx = int(np.searchsorted(verts, np.uint64(source)))
            dist, pred, niter = bf(sidx)
            # dict/file view: -1 (source/unreachable) renders as 0 like
            # the composed output path (np.maximum(..., 0))
            predv = np.where(pred >= 0, verts[np.maximum(pred, 0)],
                             np.uint64(0))
            res = {int(v): (float(d), int(p))
                   for v, d, p in zip(verts, dist, predv)}
            self.results[source] = res
            self.niters[source] = niter
            nlabeled = int(np.isfinite(dist).sum())
            self.message(f"SSSP: source {source}: {niter} iterations, "
                         f"{nlabeled} vertices labeled")
            if outd is not None and outd.path is not None:
                path = (f"{outd.path}.{cnt}" if self.ncnt > 1
                        else outd.path)
                with open(path, "w") as fp:
                    for v in sorted(res):
                        d, p = res[v]
                        fp.write(f"{v} {d:g} {p}\n")
        if outd is not None and outd.mr_name is not None:
            # named-MR rows keep the composed engine's persisted shape:
            # [TAG_DIST, pred (original id, NO_PRED sentinel intact),
            # dist, current=1] — a consumer can tell "no predecessor"
            # from "predecessor is vertex 0" (see module docstring)
            predf = np.where(pred >= 0,
                             verts[np.maximum(pred, 0)].astype(np.float64),
                             NO_PRED)
            mrv = obj.create_mr()
            rows = np.stack([np.full(n, TAG_DIST), predf, dist,
                             np.ones(n)], axis=1)
            mrv.map(1, lambda i, kv, p: kv.add_batch(verts, rows))
            obj.name_mr(outd.mr_name, mrv)
        obj.cleanup()

    def _run_composed(self):
        obj = self.obj
        mredge = obj.input(1, read_edge_weight)
        mredge.aggregate()   # mesh: shard once; the relaxation loop stays
        #                      device-resident (serial: no-op)

        # vertex universe (no singletons, pre-aggregated — sssp.cpp:63-66)
        mrvert = obj.create_mr()
        mrvert.map_mr(mredge, edge_to_vertices, batch=True)
        mrvert.collate()
        mrvert.reduce(cull, batch=True)

        # deterministic-random source list (see module docstring)
        vcols: list = []
        mrvert.scan_kv(lambda fr, p: vcols.append(kv_keys(fr)), batch=True)
        varr = np.unique(np.concatenate(vcols).astype(np.uint64))
        order = np.lexsort((varr, vertex_rand(varr, self.seed)))
        sources = varr[order][:self.ncnt].tolist()

        # adjacency keyed by source vertex, pre-aggregated (sssp.cpp:75-76)
        mradj = obj.create_mr()
        mradj.map_mr(mredge, reorganize_edges, batch=True)
        mradj.aggregate()

        self.results = {}
        self.niters = {}
        outd = obj.outputs[0] if obj.outputs else None
        for cnt, source in enumerate(sources):
            mrvert.map_mr(mrvert, init_distance, batch=True)
            mredge_w = obj.create_mr()
            mredge_w.add(mradj)

            mrpath = obj.create_mr()
            src_row = np.array([[TAG_DIST, NO_PRED, 0.0, 0.0]])
            mrpath.map(1, lambda i, kv, p: kv.add_batch(
                np.array([source], np.uint64), src_row))

            niter = 0
            while True:
                mrpath.aggregate()
                mrvert.add(mrpath)
                obj.free_mr(mrpath)
                mrpath = obj.create_mr()
                mrpath.open()
                mrvert.compress(pick_shortest, ptr=mrpath, batch=True)
                nchanged = mrpath.close()
                niter += 1
                if nchanged == 0:
                    break
                mredge_w.add(mrpath)
                obj.free_mr(mrpath)
                mrpath = obj.create_mr()
                mrpath.open()
                mredge_w.compress(update_adjacent, ptr=mrpath, batch=True)
                mrpath.close()
            obj.free_mr(mrpath)
            obj.free_mr(mredge_w)

            cols: list = []
            mrvert.scan_kv(lambda fr, p: cols.append(
                (kv_keys(fr), kv_values(fr))), batch=True)
            res = {}
            for ks, vs in cols:
                res.update(zip(
                    ks.astype(np.uint64).tolist(),
                    zip(vs[:, 2].tolist(),
                        np.maximum(vs[:, 1], 0).astype(np.int64).tolist())))
            self.results[source] = res
            self.niters[source] = niter
            nlabeled = sum(1 for d, _ in res.values() if np.isfinite(d))
            self.message(f"SSSP: source {source}: {niter} iterations, "
                         f"{nlabeled} vertices labeled")
            if outd is not None and outd.path is not None:
                path = (f"{outd.path}.{cnt}" if self.ncnt > 1
                        else outd.path)
                with open(path, "w") as fp:
                    for v in sorted(res):
                        d, p = res[v]
                        fp.write(f"{v} {d:g} {p}\n")
        if outd is not None and outd.mr_name is not None:
            obj.name_mr(outd.mr_name, mrvert)
        obj.cleanup()
