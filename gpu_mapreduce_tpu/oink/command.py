"""Command base class + registry.

The reference registers commands at build time: ``oink/Make.py`` regex-scans
headers for ``CommandStyle(name,Class)`` macros and generates
``style_command.h`` (SURVEY.md §2.4).  The Python-native equivalent is a
decorator registry — same plugin model, no codegen.

A command declares ``ninputs``/``noutputs`` and implements
``params(args)`` + ``run()`` (reference ``oink/command.{h,cpp}``); it talks
to data through ``self.obj`` (the ObjectManager), exactly like the
reference's ``obj->input/output/create_mr/cleanup`` protocol.
"""

from __future__ import annotations

from typing import Dict, List, Type

from ..core.runtime import MRError
from .objects import ObjectManager

COMMANDS: Dict[str, Type["Command"]] = {}


def command(name: str):
    """Register a Command subclass (the CommandStyle macro)."""
    def deco(cls):
        cls.name = name
        COMMANDS[name] = cls
        return cls
    return deco


class Command:
    name: str = ""
    ninputs = 0
    noutputs = 0

    def __init__(self, obj: ObjectManager, screen=None):
        self.obj = obj
        self.screen = screen  # None → print to stdout
        self.result_msg = ""

    # -- overridables ------------------------------------------------------
    def params(self, args: List[str]):
        if args:
            raise MRError(f"Illegal {self.name} command")

    def run(self):
        raise NotImplementedError

    # -- helpers -----------------------------------------------------------
    def message(self, msg: str):
        """Result message (reference error->message on rank 0)."""
        self.result_msg = msg
        if self.screen is None or self.screen is True:
            print(msg)
        elif self.screen is not False:
            self.screen.write(msg + "\n")


def run_command(name: str, args: List[str] = (), obj: ObjectManager = None,
                inputs=(), outputs=(), screen=None) -> Command:
    """Programmatic command invocation (what the script interpreter and
    tests call).  ``inputs``: path-or-MR per -i slot; ``outputs``:
    (path, mr_name) tuples per -o slot."""
    if name not in COMMANDS:
        raise MRError(f"unknown command {name!r}")
    if obj is None:
        obj = ObjectManager()
    cmd = COMMANDS[name](obj, screen=screen)
    cmd.params(list(args))
    for src in inputs:
        obj.add_input(src)
    for out in outputs:
        if isinstance(out, tuple):
            obj.add_output(*out)
        else:
            obj.add_output(path=out)
    try:
        cmd.run()
    finally:
        obj.cleanup()  # a failed run must not leak descriptors/temps
    return cmd
