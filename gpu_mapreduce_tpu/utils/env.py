"""Crash-proof env-knob parsing — THE knob registry.

Observability knobs share one rule (doc/settings.md): a malformed value
must degrade with a stderr warning, never crash the run it was meant to
observe.  Every ``MRTPU_*``/``SOAK_*`` knob reads through one of the
three helpers here so the warn-and-fall-back behavior cannot drift
between sites — ``env_knob`` for numerics, ``env_str`` for
paths/specs, ``env_flag`` for booleans.  mrlint's ``knob-registry``
rule fails CI on any raw ``os.environ`` read of a reserved-namespace
knob outside this module, and on any knob without a doc/settings.md
row (doc/lint.md).
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Optional, TypeVar

T = TypeVar("T")

_TRUE = frozenset(("1", "true", "yes", "on"))
_FALSE = frozenset(("0", "false", "no", "off"))


def env_knob(name: str, cast: Callable[[str], T], default: T) -> T:
    """``cast(os.environ[name])``, or ``default`` (with one stderr
    line) when the variable is unset, empty, or malformed."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError) as e:
        print(f"{name} ignored: {e!r}", file=sys.stderr)
        return default


def env_str(name: str, default: Optional[str] = "") -> Optional[str]:
    """The string knob read (paths, schedules, spec strings): the raw
    value, or ``default`` when unset or empty.  No parsing — callers
    own the value's grammar; they route here so the registry (and the
    knob-registry lint rule) sees every consumption site."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw


def env_flag(name: str, default: bool) -> bool:
    """Boolean knob: 1/true/yes/on and 0/false/no/off (case-
    insensitive); unset, empty, or malformed values degrade to
    ``default`` — malformed with one stderr line, same contract as
    :func:`env_knob`."""
    def cast(raw: str) -> bool:
        v = raw.strip().lower()
        if v in _TRUE:
            return True
        if v in _FALSE:
            return False
        raise ValueError(f"not a boolean flag: {raw!r}")
    return env_knob(name, cast, default)
