"""Crash-proof numeric env-knob parsing.

Observability knobs share one rule (doc/settings.md): a malformed value
must degrade with a stderr warning, never crash the run it was meant to
observe.  Every numeric MRTPU_*/SOAK_* knob parses through here so the
warn-and-fall-back behavior cannot drift between sites.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, TypeVar

T = TypeVar("T")


def env_knob(name: str, cast: Callable[[str], T], default: T) -> T:
    """``cast(os.environ[name])``, or ``default`` (with one stderr
    line) when the variable is unset, empty, or malformed."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError) as e:
        print(f"{name} ignored: {e!r}", file=sys.stderr)
        return default
