"""Shared BASELINE.json publishing — one implementation of the
read/merge/write pattern soak.py, weakscale.py and record_scale.py
each hand-rolled (backend-qualified keys so no harness clobbers
another's records)."""

import json
import os
from typing import Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def read_published(key: str, path: Optional[str] = None):
    """The current published.<key> record, or {} (same file layout
    owner as publish — harnesses merge partial runs through this)."""
    if path is None:
        path = os.path.join(_ROOT, "BASELINE.json")
    try:
        with open(path) as f:
            return json.load(f).get("published", {}).get(key, {})
    except (FileNotFoundError, ValueError):
        return {}


def publish(key: str, record, path: Optional[str] = None) -> None:
    """Merge ``record`` under published.<key> of the REPO's
    BASELINE.json (cwd-independent by default).

    A missing or corrupt baseline must not crash a harness at the very
    end of a long capture and lose the run (ADVICE r3) — but starting
    fresh over a CORRUPT file would silently destroy every previously
    published record (r4 review), so the unparsable file is moved aside
    to ``<path>.corrupt`` for repair first.  The write itself is
    tmp+rename so a crash mid-dump can no longer produce such a file."""
    if path is None:
        path = os.path.join(_ROOT, "BASELINE.json")
    try:
        with open(path) as f:
            base = json.load(f)
    except FileNotFoundError:
        base = {}
    except ValueError:
        os.replace(path, path + ".corrupt")   # preserve for repair
        base = {}
    base.setdefault("published", {})[key] = record
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(base, f, indent=2)
    os.replace(tmp, path)
