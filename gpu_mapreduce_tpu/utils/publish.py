"""Shared BASELINE.json publishing — one implementation of the
read/merge/write pattern soak.py, weakscale.py and record_scale.py
each hand-rolled (backend-qualified keys so no harness clobbers
another's records)."""

import json


def publish(key: str, record, path: str = "BASELINE.json") -> None:
    with open(path) as f:
        base = json.load(f)
    base.setdefault("published", {})[key] = record
    with open(path, "w") as f:
        json.dump(base, f, indent=2)
