"""Shared BASELINE.json publishing — one implementation of the
read/merge/write pattern soak.py, weakscale.py and record_scale.py
each hand-rolled (backend-qualified keys so no harness clobbers
another's records)."""

import json
import os
from typing import Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def read_published(key: str, path: Optional[str] = None):
    """The current published.<key> record, or {} (same file layout
    owner as publish — harnesses merge partial runs through this)."""
    if path is None:
        path = os.path.join(_ROOT, "BASELINE.json")
    try:
        with open(path) as f:
            return json.load(f).get("published", {}).get(key, {})
    except (FileNotFoundError, ValueError):
        return {}


def publish(key: str, record, path: Optional[str] = None) -> None:
    """Merge ``record`` under published.<key> of the REPO's
    BASELINE.json (cwd-independent by default)."""
    if path is None:
        path = os.path.join(_ROOT, "BASELINE.json")
    try:
        with open(path) as f:
            base = json.load(f)
    except (FileNotFoundError, ValueError):
        # a missing or corrupt baseline must not crash a harness at the
        # very end of a long capture and lose the run (ADVICE r3);
        # mirror read_published's tolerance and start a fresh file
        base = {}
    base.setdefault("published", {})[key] = record
    with open(path, "w") as f:
        json.dump(base, f, indent=2)
