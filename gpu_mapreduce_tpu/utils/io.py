"""File discovery and chunked ingestion.

Re-implements the reference's map-over-files machinery
(``src/mapreduce.cpp:2812-2931``): recursive directory expansion
(``findfiles``), file-of-filenames mode (``readflag=1``), and the chunked
reader that splits files on a separator char/string with a ``delta``
lookahead so chunk boundaries land on separators
(``map_chunks``/``map_file_wrapper``, ``src/mapreduce.cpp:1312-1552``).

All of this is host-side I/O (it was in the reference too — user callbacks
did fopen); no MPI bcast of the file list is needed since ingestion is
driven from the single controller process and data is *sharded later* by
``aggregate()``.
"""

from __future__ import annotations

import glob
import os
from typing import Iterator, List, Optional, Sequence, Tuple


def findfiles(paths: Sequence[str], recurse: bool = False,
              readflag: bool = False) -> List[str]:
    """Expand paths → flat file list (reference findfiles,
    src/mapreduce.cpp:2812-2848; readflag file-of-filenames 2857-2906)."""
    out: List[str] = []
    for p in paths:
        if any(c in p for c in "*?[") and not os.path.exists(p):
            hits = sorted(glob.glob(p))
            if not hits:
                raise FileNotFoundError(p)
            out.extend(findfiles(hits, recurse, readflag))
            continue
        if os.path.isdir(p):
            for entry in sorted(os.listdir(p)):
                full = os.path.join(p, entry)
                if os.path.isdir(full):
                    if recurse:
                        out.extend(findfiles([full], recurse, readflag))
                elif os.path.isfile(full):
                    out.append(full)
        elif os.path.isfile(p):
            if readflag:
                with open(p) as f:
                    names = [ln.strip() for ln in f if ln.strip()]
                out.extend(names)
            else:
                out.append(p)
        else:
            raise FileNotFoundError(p)
    return out


def file_chunks(filename: str, nchunks: int, sep: bytes = b"\n",
                delta: int = 80) -> Iterator[bytes]:
    """Split one file into ~nchunks pieces ending on `sep`.

    Mirrors map_file_wrapper (src/mapreduce.cpp:1486-1552): each task reads
    its slice plus a `delta` lookahead, then trims so every chunk ends just
    past a separator and no byte is lost or duplicated.  `sep` may be a
    single char or a multi-byte string (sepchar vs sepstr variants).
    """
    size = os.path.getsize(filename)
    if size == 0 or nchunks <= 0:
        return
    chunksize = max(1, (size + nchunks - 1) // nchunks)
    with open(filename, "rb") as f:
        start = 0
        while start < size:
            f.seek(start)
            want = min(chunksize, size - start)
            buf = f.read(want + delta * 64)
            if start + len(buf) >= size:  # last chunk: take it all
                yield buf[: size - start]
                break
            # find separator at/after the nominal boundary
            cut = buf.find(sep, want - 1)
            if cut < 0:
                # separator beyond lookahead: extend search to EOF
                rest = f.read()
                buf += rest
                cut = buf.find(sep, want - 1)
                if cut < 0:
                    yield buf
                    break
            cut += len(sep)
            yield buf[:cut]
            start += cut


def read_words(chunk: bytes, whitespace: bytes = b" \t\n\r\f\v") -> List[bytes]:
    """Whitespace tokenizer (the oink read_words map callback,
    oink/map_read_words.cpp)."""
    table = bytes.maketrans(whitespace, b" " * len(whitespace))
    return chunk.translate(table).split()
