"""Durable file primitives — rename is not enough.

Every crash-safe writer in this tree follows tmp + fsync + rename, which
guarantees the final path never holds a torn file.  What rename alone
does NOT guarantee is that the new DIRECTORY ENTRY survives a power cut:
POSIX only promises the entry is durable once the parent directory
itself has been fsync'd.  A checkpoint shard that a manifest already
references, a fleet lease a peer's expiry decision reads, a journal
file a resume depends on — all can silently vanish on crash-after-
rename, which is exactly the failure class the writers exist to close.

This module is the ONE place the rename-durability discipline lives:

* :func:`fsync_dir` — fsync a directory fd (no-op where the platform
  refuses, e.g. some network filesystems raise EINVAL on dir fds).
* :func:`atomic_replace` — ``os.replace`` + parent-dir fsync.
* :func:`atomic_write_json` — tmp + flush + fsync + replace + dir
  fsync; the lease/heartbeat/manifest writer.

Callers that already fsync'd the tmp file's CONTENTS only need the
replace + dir step; the content fsync stays at the call site so the
write path reads top-to-bottom there.
"""

from __future__ import annotations

import json
import os


def fsync_dir(path: str) -> None:
    """fsync the directory at ``path`` so renames/creates inside it are
    durable.  Best-effort: platforms/filesystems that reject directory
    fsync (EINVAL/EBADF on some NFS mounts) degrade silently — the
    rename itself already happened, so behavior is never worse than the
    pre-fsync code."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_replace(tmp: str, path: str) -> None:
    """``os.replace(tmp, path)`` + parent-dir fsync: the new name is
    durable when this returns, not just present."""
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))


def atomic_write_json(path: str, obj: dict) -> None:
    """Durable whole-file JSON write: tmp + content fsync + atomic
    replace + parent-dir fsync.  A reader never sees a torn file AND a
    crash immediately after return cannot un-write it — the contract
    heartbeats, leases and checkpoint manifests are built on."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    atomic_replace(tmp, path)


def read_json(path: str):
    """Best-effort JSON read: the parsed dict, or None on a missing,
    torn, or non-dict file (a torn read must never crash an expiry or
    resume decision — absence is the safe verdict)."""
    try:
        with open(path) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None
