"""End-to-end integrity of durable artifacts: digest on write, verify
on read.

The reference trusts its page files completely — a bit flip in a spool
file is silently sorted into the output (SURVEY.md §5 has no integrity
story at all).  Every durable artifact this repo writes now carries a
checksum stamped at write time and checked on the read path:

* **checkpoint frames** (``core/checkpoint.py``) — per-frame file
  digests plus per-shard row digests in the manifest; a bit-flipped
  frame raises :class:`IntegrityError` at load instead of feeding
  garbage into the restore, and ``ft.resume`` falls back to the
  previous kept checkpoint generation;
* **spill runs** (``core/external.py`` via ``exec/spill.atomic_save``)
  — the run's writer records the exact bytes it put on disk; the k-way
  merge verifies the file before its first block is consumed, and a
  mismatch retries under the existing ``spill.read`` budget (the
  transient-vs-fatal machinery decides the disposition);
* **journal records** (``ft/journal.py``) — every JSONL record carries
  a crc of its own payload; a torn or bit-flipped record is quarantined
  by ``read_journal`` (skipped + counted) instead of replaying garbage.

Every detection increments ``mrtpu_integrity_failures_total{artifact}``
(artifact: ``checkpoint`` | ``spill`` | ``journal``) whether or not the
metrics registry was armed first — corruption evidence must never
depend on observability ordering.

Digests are crc32 (zlib — always available; the label is explicit in
the stamp so a future crc32c/sha256 upgrade stays readable:
``"crc32:xxxxxxxx"``).  Verification is governed by ``MRTPU_VERIFY``
(default **on**; ``MRTPU_VERIFY=0`` skips the read-side checks —
stamps are always written, so the knob can be flipped on later without
rewriting artifacts).  Artifacts written before this layer carry no
stamp and verify as a no-op (back-compat).
"""

from __future__ import annotations

import zlib
from typing import Optional

_LABEL = "crc32"


class IntegrityError(OSError):
    """A durable artifact failed its checksum.  Subclasses ``OSError``
    on purpose: the ft/ classifier treats OSError as transient, so a
    corrupt spill run retries under the ``spill.read`` budget (page-
    cache flukes recover; persistent corruption exhausts the budget and
    surfaces as a loud MRError naming the site)."""

    def __init__(self, artifact: str, path: str, expected: str,
                 actual: str):
        super().__init__(
            f"integrity: {artifact} {path!r} checksum mismatch "
            f"(expected {expected}, read {actual})")
        self.artifact = artifact
        self.path = path
        self.ft_site = {"spill": "spill.read",
                        "checkpoint": "checkpoint.save"}.get(artifact,
                                                             artifact)


def verify_enabled() -> bool:
    """The ``MRTPU_VERIFY`` knob: read-side checksum verification,
    default ON (stamping is always on — it is the cheap half)."""
    from .env import env_flag
    return env_flag("MRTPU_VERIFY", True)


def digest_bytes(data) -> str:
    """Stamp of a bytes-like payload."""
    return f"{_LABEL}:{zlib.crc32(bytes(data)) & 0xFFFFFFFF:08x}"


def array_digest(*arrays) -> str:
    """Stamp of one or more ndarrays' raw row bytes (C-order), chained
    — the per-shard digest of checkpoint manifests."""
    import numpy as np
    c = 0
    for arr in arrays:
        a = np.ascontiguousarray(np.asarray(arr))
        c = zlib.crc32(a.view(np.uint8).reshape(-1).data, c)
    return f"{_LABEL}:{c & 0xFFFFFFFF:08x}"


def file_digest(path: str, chunk: int = 1 << 20) -> str:
    """Stream-crc of a file's bytes in bounded pieces (a multi-GB spill
    run verifies without spiking resident memory)."""
    c = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            c = zlib.crc32(block, c)
    return f"{_LABEL}:{c & 0xFFFFFFFF:08x}"


class ChecksumWriter:
    """Wrap a binary file handle, crc-ing every byte written through it
    — the write-side stamp costs no read-back pass.  Only sequential
    writers may use it (``np.save`` is; zip-based ``np.savez`` seeks
    and must digest via :func:`file_digest` instead)."""

    def __init__(self, fh):
        self._fh = fh
        self._crc = 0

    def write(self, data) -> int:
        b = bytes(data)
        self._crc = zlib.crc32(b, self._crc)
        return self._fh.write(b)

    def digest(self) -> str:
        return f"{_LABEL}:{self._crc & 0xFFFFFFFF:08x}"

    def __getattr__(self, name):
        return getattr(self._fh, name)


def record_integrity_failure(artifact: str) -> None:
    """Bump ``mrtpu_integrity_failures_total{artifact}``.  Direct
    counter feed (like ``obs.metrics.note_trace_rotated``): corruption
    counts even before ``enable_metrics`` armed the bridges, and a
    metrics bug must never mask the detection that reported it."""
    try:
        from ..obs.metrics import get_registry
        get_registry().counter(
            "mrtpu_integrity_failures_total",
            "durable artifacts that failed checksum verification on "
            "read, by artifact kind", ("artifact",)).inc(artifact=artifact)
    except Exception:
        pass


def verify_file(path: str, expected: Optional[str], artifact: str) -> None:
    """Verify a file against its recorded stamp: no-op when the stamp
    is absent (pre-integrity artifact) or ``MRTPU_VERIFY=0``; raises
    :class:`IntegrityError` (and counts the failure) on mismatch."""
    if expected is None or not verify_enabled():
        return
    actual = file_digest(path)
    if actual != expected:
        record_integrity_failure(artifact)
        raise IntegrityError(artifact, path, expected, actual)
