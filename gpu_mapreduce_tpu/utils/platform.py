"""Platform pinning against TPU-plugin config overrides.

The axon TPU plugin's ``register()`` forces ``jax_platforms="axon,cpu"``
via ``jax.config``, which silently beats the ``JAX_PLATFORMS`` environment
variable.  Anything that must honour the env var (the driver's CPU
multi-chip dry-run, the test suite's fake 8-device cluster, bench.py's
fallback) needs to sync ``jax.config`` back — this is the one shared
implementation (round-1 review: three hand-rolled copies drifted).
"""

import os

TPU_BACKENDS = ("tpu", "axon")


def pin_platform(force: str | None = None) -> None:
    """Sync ``jax.config`` to ``force`` or the JAX_PLATFORMS env var.

    No-op when neither is set, leaving the plugin default (real TPU)
    alone.  Safe to call any time before first device access.
    """
    want = force or os.environ.get("JAX_PLATFORMS")
    if want:
        if force:
            os.environ["JAX_PLATFORMS"] = force
        import jax

        jax.config.update("jax_platforms", want)


def is_tpu_backend(name: str) -> bool:
    return name in TPU_BACKENDS
