"""Content-addressed store (CAS): the fleet's shared dedup substrate.

One directory of immutable chunks named by the sha256 of their bytes,
shared by every replica of a serve fleet.  Three tiers ride on it
(doc/perf.md#the-caching-tier):

* the **persistent plan cache** (``plan/cache.PersistentPlanCache``)
  keeps compiled-plan speculation state under ``<root>/plan/`` and the
  XLA executable cache under ``<root>/xla/`` so a restarted replica's
  first warm-shaped request recompiles nothing;
* **job-result memoization** (``serve/memo.py``) keeps verified result
  records under ``<root>/memo/`` so a byte-identical resubmission is
  served without executing a single op;
* **checkpoint/spill chunk dedup** (:func:`dedup_file`): page-chunk
  files written by ``core/checkpoint.py`` and ``exec/spill.py`` are
  re-homed as hardlinks to their content object, so N replicas
  checkpointing the same resident dataset pay the bytes once.

Refcounting is the filesystem's: every consumer of a chunk holds a
hardlink to it, so an object's ``st_nlink`` IS its reference count plus
one (the store's own link).  Releasing a reference is ``os.unlink`` of
the consumer's path — idempotent, crash-safe, and the count can never
go negative by construction.  GC removes objects whose only remaining
link is the store's own (``st_nlink == 1``) after a grace period, with
a journaled intent record written by the caller FIRST so a kill -9
mid-sweep finishes on restart (``serve/daemon._gc_cache``).

Integrity: objects are self-verifying (name = sha256 of content).
Reads under ``MRTPU_VERIFY`` (default on) re-hash and a mismatch bumps
``mrtpu_integrity_failures_total{artifact="cas"}``, quarantines the
chunk, and reports a miss — callers fall back to recompute, never to a
wrong answer.

Everything here is a pure optimisation: any failure (cross-device
link, read-only root, concurrent GC) degrades to the uncached path.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, List, Optional

from .env import env_flag, env_str
from .integrity import record_integrity_failure, verify_enabled


def cas_root() -> Optional[str]:
    """The store root: ``MRTPU_CAS_DIR`` wins; a fleet
    (``MRTPU_FLEET_DIR``) defaults to ``<fleet>/cas`` so every replica
    shares one store; otherwise the tier is off (None)."""
    root = env_str("MRTPU_CAS_DIR", "")
    if root:
        return root
    fleet = env_str("MRTPU_FLEET_DIR", "")
    if fleet:
        return os.path.join(fleet, "cas")
    return None


def cas_enabled() -> bool:
    """``MRTPU_CAS`` (default on) gates every tier at once — the
    one-knob kill switch when a shared store misbehaves."""
    return env_flag("MRTPU_CAS", True) and cas_root() is not None


def sha256_bytes(data) -> str:
    return hashlib.sha256(bytes(data)).hexdigest()


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class CASStore:
    """One content-addressed chunk directory (see module docstring).
    Thread-safe; safe for concurrent use by multiple processes (every
    mutation is an atomic link/rename/unlink)."""

    def __init__(self, root: str):
        self.root = root
        self.objects = os.path.join(root, "objects")
        self.quarantine_dir = os.path.join(root, "quarantine")
        self._lock = threading.Lock()
        # process-local telemetry (mrctl cache / /v1/stats)
        self.dedup_hits = 0      # chunks that already existed on put
        self.stores = 0          # chunks newly written
        self.reads = 0
        self.quarantined = 0
        self.gc_removed = 0
        self.gc_bytes = 0

    # -- paths -------------------------------------------------------------
    def _opath(self, digest: str) -> str:
        return os.path.join(self.objects, digest[:2], digest)

    # -- writes ------------------------------------------------------------
    def put_bytes(self, data: bytes) -> str:
        """Store a chunk; returns its digest.  Existing chunks are not
        rewritten (the dedup hit)."""
        digest = sha256_bytes(data)
        path = self._opath(digest)
        if os.path.exists(path):
            with self._lock:
                self.dedup_hits += 1
            return digest
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            self.stores += 1
        return digest

    def adopt_file(self, path: str, digest: Optional[str] = None) -> str:
        """Adopt an existing file as a chunk WITHOUT copying: hardlink
        it into the store (the file keeps working at its own path; the
        object shares its inode).  Returns the digest."""
        digest = digest or sha256_file(path)
        opath = self._opath(digest)
        if not os.path.exists(opath):
            os.makedirs(os.path.dirname(opath), exist_ok=True)
            try:
                os.link(path, opath)
                with self._lock:
                    self.stores += 1
            except FileExistsError:
                with self._lock:
                    self.dedup_hits += 1
        else:
            with self._lock:
                self.dedup_hits += 1
        return digest

    def dedup_file(self, path: str) -> Optional[str]:
        """Re-home a freshly written chunk file through the store: if
        its content already exists, atomically replace ``path`` with a
        hardlink to the shared object (freeing the duplicate bytes);
        otherwise adopt it as the object.  Returns the digest, or None
        when dedup was impossible (cross-device root, permissions) —
        the file is untouched and correct either way."""
        try:
            digest = sha256_file(path)
            opath = self._opath(digest)
            if os.path.exists(opath):
                st_obj = os.stat(opath)
                st_f = os.stat(path)
                if (st_obj.st_ino, st_obj.st_dev) == \
                        (st_f.st_ino, st_f.st_dev):
                    return digest        # already the same inode
                tmp = f"{path}.cas.{os.getpid()}.{threading.get_ident()}"
                os.link(opath, tmp)
                os.replace(tmp, path)    # atomic: readers never gap
                with self._lock:
                    self.dedup_hits += 1
            else:
                self.adopt_file(path, digest)
            return digest
        except OSError:
            return None

    def materialize(self, digest: str, dest: str) -> bool:
        """Hardlink (fallback: copy) a chunk to ``dest``; False when
        the chunk is absent or corrupt.  The verified-read path: the
        chunk is re-hashed under MRTPU_VERIFY before use."""
        data = self.get_bytes(digest)
        if data is None:
            return False
        opath = self._opath(digest)
        tmp = f"{dest}.cas.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            try:
                os.link(opath, tmp)
            except OSError:
                with open(tmp, "wb") as f:    # cross-device fallback
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, dest)
            return True
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    # -- reads -------------------------------------------------------------
    def get_bytes(self, digest: str) -> Optional[bytes]:
        """Verified read: None when absent — or when corrupt, in which
        case the chunk is quarantined and
        ``mrtpu_integrity_failures_total{artifact="cas"}`` bumps (the
        caller recomputes; a bit-flip can never become a wrong
        answer)."""
        path = self._opath(digest)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        with self._lock:
            self.reads += 1
        if verify_enabled() and sha256_bytes(data) != digest:
            record_integrity_failure("cas")
            self._quarantine(digest)
            return None
        return data

    def contains(self, digest: str) -> bool:
        return os.path.exists(self._opath(digest))

    def refcount(self, digest: str) -> int:
        """External references = hardlinks beyond the store's own."""
        try:
            return max(0, os.stat(self._opath(digest)).st_nlink - 1)
        except OSError:
            return 0

    def _quarantine(self, digest: str) -> None:
        """Move a corrupt chunk aside (evidence for the operator) so
        the next writer can re-store clean bytes under the same name."""
        try:
            os.makedirs(self.quarantine_dir, exist_ok=True)
            os.replace(self._opath(digest),
                       os.path.join(self.quarantine_dir, digest))
        except OSError:
            try:
                os.remove(self._opath(digest))
            except OSError:
                pass
        with self._lock:
            self.quarantined += 1

    # -- GC ----------------------------------------------------------------
    def gc_candidates(self, grace_s: float,
                      now: Optional[float] = None) -> List[str]:
        """Digests safe to sweep: no external hardlink (``st_nlink ==
        1``) and untouched past the grace period (a chunk stored but
        not yet linked by its writer must not vanish mid-publish)."""
        now = time.time() if now is None else now
        out: List[str] = []
        try:
            shards = os.listdir(self.objects)
        except OSError:
            return out
        for shard in shards:
            sdir = os.path.join(self.objects, shard)
            try:
                names = os.listdir(sdir)
            except OSError:
                continue
            for name in names:
                if name.endswith(".tmp") or ".tmp." in name:
                    continue
                try:
                    st = os.stat(os.path.join(sdir, name))
                except OSError:
                    continue
                if st.st_nlink <= 1 and now - st.st_mtime >= grace_s:
                    out.append(name)
        return out

    def gc_finish(self, digests: List[str]) -> int:
        """Second half of a journaled sweep (idempotent — also the
        kill -9 recovery path): re-check each candidate is STILL
        unreferenced, then unlink.  A chunk re-linked since the intent
        record was written survives; refcounts cannot go negative
        because releasing is only ever an unlink of one's own link."""
        removed = 0
        for digest in digests:
            path = self._opath(digest)
            try:
                st = os.stat(path)
            except OSError:
                continue                 # already gone: idempotent
            if st.st_nlink > 1:
                continue                 # re-referenced since intent
            try:
                os.unlink(path)
            except OSError:
                continue
            removed += 1
            with self._lock:
                self.gc_removed += 1
                self.gc_bytes += st.st_size
        return removed

    # -- telemetry ---------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        chunks = 0
        nbytes = 0
        try:
            for shard in os.listdir(self.objects):
                sdir = os.path.join(self.objects, shard)
                try:
                    for name in os.listdir(sdir):
                        if ".tmp" in name:
                            continue
                        try:
                            nbytes += os.stat(
                                os.path.join(sdir, name)).st_size
                        except OSError:
                            continue
                        chunks += 1
                except OSError:
                    continue
        except OSError:
            pass
        with self._lock:
            return {"enabled": 1, "chunks": chunks, "bytes": nbytes,
                    "dedup_hits": self.dedup_hits, "stores": self.stores,
                    "reads": self.reads, "quarantined": self.quarantined,
                    "gc_removed": self.gc_removed,
                    "gc_bytes": self.gc_bytes}


_STORE: Optional[CASStore] = None
_STORE_ROOT: Optional[str] = None
_STORE_LOCK = threading.Lock()


def cas_store() -> Optional[CASStore]:
    """The process singleton, re-rooted if the env changed (tests);
    None when the tier is disarmed."""
    global _STORE, _STORE_ROOT
    if not cas_enabled():
        return None
    root = cas_root()
    with _STORE_LOCK:
        if _STORE is None or _STORE_ROOT != root:
            _STORE = CASStore(root)
            _STORE_ROOT = root
        return _STORE


def reset_store() -> None:
    """Test isolation: drop the singleton (counters restart)."""
    global _STORE, _STORE_ROOT
    with _STORE_LOCK:
        _STORE = None
        _STORE_ROOT = None
