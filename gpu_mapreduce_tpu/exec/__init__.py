"""Async overlapped execution — the fourth pillar next to eager, fused
(plan/) and observed (obs/) execution.

The reference hides host work behind device work for free: every MPI
rank reads, sorts and spills its own pages while its neighbours compute
(``src/mapreduce.cpp:1102-1225``).  A single-controller JAX port loses
that overlap — ingest reads every chunk before the first device dispatch,
spill writes block the op that triggered them, and nothing ever donates a
dead device buffer.  This package restores the overlap on the three hot
paths, all behind env knobs so any of them can be disabled for a golden
eager run:

* **ingest prefetch** (:mod:`.prefetch`): a bounded double-buffered
  producer thread reads + tokenizes chunk N+1 while chunk N's frames
  assemble/intern (``parallel/ingest.mesh_map_files``/``mesh_map_chunks``
  and the serial ``MapReduce._map_chunks`` path).  Depth knob
  ``MRTPU_PREFETCH`` (default 1 = double buffering, 0 = off);
  backpressure through the queue bounds residency at ~(depth+1) chunks.
* **background spill** (:mod:`.spill`): ``core/external.py`` run writes
  move to a writer thread with a durability barrier at run-handoff (the
  merge's reader blocks on the run's ready-event, so it can never see a
  half-written run; writes land via tmp-file + ``os.replace`` so a crash
  mid-write leaves no torn ``.npy`` under the final name).
  ``MRTPU_SPILL_BG`` (default 1).
* **buffer donation + deferred sync** (helpers here): the shuffle's
  phase-1/phase-2 and the plan/ fused programs donate their dead input
  buffers (``jax.jit(donate_argnums=...)``) so XLA aliases instead of
  re-materialising — ``MRTPU_DONATE`` (default 1); and the per-op
  ``block_until_ready`` timing syncs can be deferred to the natural
  barriers (``MRTPU_DEFER_SYNC=1``, default 0 because exact per-stage
  attribution is what the bench headline quotes).

Every overlap reports: ``exec.prefetch`` / ``exec.spill_write`` obs
spans, a ``mrtpu_overlap_ratio{path}`` gauge (obs/metrics.py) and the
``mr.stats()["exec"]`` section (:func:`exec_stats`).  The overlap ratio
of a path is ``hidden / busy``: the fraction of background work time the
foreground never waited for (1.0 = fully hidden, 0.0 = serialized).

See ``doc/perf.md`` for the knob table and donation caveats.
"""

from __future__ import annotations

import threading

from ..utils.env import env_knob


def donated_jit(fn, argnums):
    """THE donation-wrapping rule, one copy (shuffle + fuser builders):
    ``jax.jit`` with the given ``donate_argnums`` (empty = plain jit).
    Callers only pass argnums whose donation is actually ALIASABLE
    (output of the same byte size exists — see the call sites), so
    jax's "Some donated buffers were not usable" warning never fires
    and needs no suppression; an unaliasable buffer simply isn't
    donated, which is the same no-op without the noise."""
    import jax
    argnums = tuple(argnums)
    if not argnums:
        return jax.jit(fn)
    return jax.jit(fn, donate_argnums=argnums)


def prefetch_depth() -> int:
    """Ingest prefetch queue depth (``MRTPU_PREFETCH``): 0 disables,
    1 (default) double-buffers, N keeps up to N chunks in flight."""
    return max(0, env_knob("MRTPU_PREFETCH", int, 1))


def spill_bg_enabled() -> bool:
    """Background spill writer (``MRTPU_SPILL_BG``, default on)."""
    return env_knob("MRTPU_SPILL_BG", int, 1) != 0


def donate_enabled() -> bool:
    """Device-buffer donation in the shuffle/fused programs
    (``MRTPU_DONATE``, default on)."""
    return env_knob("MRTPU_DONATE", int, 1) != 0


def can_donate(frame) -> bool:
    """THE donate-eligibility rule, one copy (shuffle + fuser callers):
    the knob is on, the frame is not shared with another dataset
    (``_shared`` — add_kv/copy/map_mr mark it; deleting a shared
    frame's arrays would corrupt the sibling), and key/value are not
    literally the same array (double donation)."""
    return (donate_enabled()
            and not getattr(frame, "_shared", False)
            and frame.key is not frame.value)


def defer_sync() -> bool:
    """``MRTPU_DEFER_SYNC=1``: skip per-op ``block_until_ready`` timing
    syncs so eager chains only sync at real barriers (count pulls, host
    reads).  Default off — exact per-stage attribution is what the bench
    headline quotes; see doc/perf.md."""
    return env_knob("MRTPU_DEFER_SYNC", int, 0) != 0


def maybe_block(x):
    """``jax.block_until_ready(x)`` unless deferred-sync mode is on.
    Use at per-op sync points that exist only for timing attribution —
    never at correctness barriers (those must call jax directly)."""
    if defer_sync():
        return x
    import jax
    return jax.block_until_ready(x)


# ---------------------------------------------------------------------------
# overlap accounting: per-path cumulative busy/hidden seconds
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
# path → {"busy_s", "wait_s", "items"}; busy = background-thread work,
# wait = foreground time spent blocked on that background work
_OVERLAP: dict = {}


def note_overlap(path: str, busy_s: float = 0.0, wait_s: float = 0.0,
                 items: int = 0) -> None:
    """Accumulate overlap telemetry for one path ("ingest.files",
    "ingest.chunks", "ingest.serial", "spill") and refresh the
    ``mrtpu_overlap_ratio{path}`` gauge.  Crash-proof like every obs
    feed: telemetry must never fail the op it observes."""
    with _LOCK:
        rec = _OVERLAP.setdefault(
            path, {"busy_s": 0.0, "wait_s": 0.0, "items": 0})
        rec["busy_s"] += max(0.0, busy_s)
        rec["wait_s"] += max(0.0, wait_s)
        rec["items"] += items
        ratio = _ratio(rec)
    try:
        from ..obs import metrics as _metrics
        if _metrics.enabled():
            _metrics.get_registry().gauge(
                "mrtpu_overlap_ratio",
                "fraction of background work hidden behind foreground "
                "work, per overlap path (1 = fully overlapped)",
                ("path",)).set(ratio, path=path)
    except Exception:
        pass


def _ratio(rec: dict) -> float:
    busy = rec["busy_s"]
    if busy <= 0.0:
        return 0.0
    return round(max(0.0, min(1.0, (busy - rec["wait_s"]) / busy)), 6)


def exec_stats() -> dict:
    """The ``mr.stats()["exec"]`` section: per-path cumulative overlap
    telemetry plus the active knob values."""
    with _LOCK:
        paths = {p: {**rec, "busy_s": round(rec["busy_s"], 6),
                     "wait_s": round(rec["wait_s"], 6),
                     "overlap_ratio": _ratio(rec)}
                 for p, rec in _OVERLAP.items()}
    return {"overlap": paths,
            "knobs": {"prefetch": prefetch_depth(),
                      "spill_bg": spill_bg_enabled(),
                      "donate": donate_enabled(),
                      "defer_sync": defer_sync()}}


def reset_stats() -> None:
    """Test isolation: drop the cumulative overlap telemetry."""
    with _LOCK:
        _OVERLAP.clear()


from .prefetch import prefetch_iter                        # noqa: E402
from .spill import SpillWriter                             # noqa: E402

__all__ = [
    "prefetch_depth", "spill_bg_enabled", "donate_enabled", "can_donate",
    "defer_sync", "donated_jit",
    "maybe_block", "note_overlap", "exec_stats", "reset_stats",
    "prefetch_iter", "SpillWriter",
]
