"""Background spill writer with a durability barrier.

``core/external.py`` pass 1 sorts each frame and spills it as a run;
serially the op that triggered the spill blocks for the full write.
This writer moves the write to a daemon thread so sort-of-run-k overlaps
write-of-run-k-1, with two hard guarantees:

* **durability barrier at run-handoff**: every submitted write returns a
  :class:`Pending`; the merge's reader calls ``wait()`` before its first
  read of that run, so a half-written run is unobservable.  A writer
  failure re-raises at the barrier (never swallowed).
* **no torn file under the final name**: callers write via
  :func:`atomic_save` — tmp file + ``os.replace`` — so even a process
  crash mid-write leaves only a ``*.tmp`` sibling, never a torn ``.npy``
  a later run could load (the crash-during-spill test's contract).

The submit queue is bounded (default 2 pending writes) so a fast sorter
cannot pile unwritten frames in memory — the page-budget property the
external machinery exists for.  Writer busy time feeds
``note_overlap("spill", ...)`` / ``mrtpu_overlap_ratio{path="spill"}``;
each write emits an ``exec.spill_write`` span.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np


def atomic_save(path: str, arr: np.ndarray, allow_pickle: bool = False
                ) -> str:
    """``np.save`` through a tmp sibling + ``os.replace`` so the final
    path only ever holds a complete file.  ``path`` must already carry
    its ``.npy`` suffix (saving through a file handle stops np.save
    appending one to the tmp name).  Returns the crc stamp of the
    exact bytes written (utils/integrity.py) — np.save writes strictly
    sequentially, so the stamp costs no read-back pass; readers verify
    it before consuming the file (``core/external._Run``)."""
    from ..utils.fsio import atomic_replace
    from ..utils.integrity import ChecksumWriter
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        cw = ChecksumWriter(f)
        np.save(cw, arr, allow_pickle=allow_pickle)
        f.flush()
        os.fsync(f.fileno())
    # replace + parent-dir fsync (utils/fsio): without the dir fsync a
    # crash after the rename can lose the directory entry of a run a
    # manifest already references — file durable, name not
    atomic_replace(tmp, path)
    # chunk dedup (utils/cas.py): when the fleet's content store is
    # armed, re-home the run file as a hardlink to its content object —
    # replicas spilling identical pages (replayed sessions, shared
    # inputs) pay the bytes once.  The crc stamp is unchanged (same
    # bytes); failure leaves the plain file.
    try:
        from ..utils.cas import cas_store
        _store = cas_store()
        if _store is not None:
            _store.dedup_file(path)
    except Exception:
        pass
    return cw.digest()


class Pending:
    """Handle of one submitted write: ``wait()`` is the durability
    barrier — returns once the write is fully on disk, re-raising any
    writer-side failure."""

    __slots__ = ("_done", "_error")

    def __init__(self):
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    def wait(self) -> float:
        """Block until durable; returns seconds spent blocked."""
        t0 = time.perf_counter()
        self._done.wait()
        waited = time.perf_counter() - t0
        if self._error is not None:
            raise self._error
        return waited


class SpillWriter:
    """One background writer thread (lazily started) with a bounded
    pending queue.  Thread-safe: submits may come from any thread; the
    writes themselves are serialized in submit order."""

    def __init__(self, max_pending: int = 2, path: str = "spill"):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, max_pending))
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._path = path
        self._closed = False

    def submit(self, fn: Callable[[], None]) -> Pending:
        """Enqueue ``fn`` (the write closure); blocks when max_pending
        writes are already queued (backpressure — counted as foreground
        wait, it IS time the sorter spent stalled on the writer).
        Returns the :class:`Pending` barrier handle."""
        if self._closed:
            raise RuntimeError("SpillWriter is closed")
        pending = Pending()
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"mrtpu-{self._path}-writer")
                self._thread.start()
        # trace-context handoff (obs/context.py): the writer thread is
        # long-lived and SHARED across requests, so the submitting
        # request's context rides each queue item — the write's span
        # and wsize counter bump charge the request that spilled, not
        # whichever request happened to submit last
        from ..obs import context as _obs_ctx
        req_ctx = _obs_ctx.capture()
        t0 = time.perf_counter()
        self._q.put((fn, pending, req_ctx))
        blocked = time.perf_counter() - t0
        if blocked > 1e-4:
            from . import note_overlap
            note_overlap(self._path, wait_s=blocked)
        return pending

    def _run(self) -> None:
        from ..obs import context as _obs_ctx
        from ..obs import get_tracer
        from . import note_overlap
        tracer = get_tracer()
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, pending, req_ctx = item
            t0 = time.perf_counter()
            try:
                with _obs_ctx.use(req_ctx), \
                        tracer.span("exec.spill_write", cat="exec",
                                    path=self._path):
                    fn()
            except BaseException as e:
                pending._error = e
            finally:
                pending._done.set()
                note_overlap(self._path,
                             busy_s=time.perf_counter() - t0, items=1)

    def close(self) -> None:
        """Drain every queued write and join the thread (idempotent).
        The drain wall counts as foreground wait — without it a run
        whose writes outlast its sorts would still read as "fully
        overlapped" (the close blocks exactly as long as the writer is
        behind).  Errors stay parked on their Pending handles — close
        never raises; the reader's barrier is where failures surface."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            t = self._thread
        if t is not None:
            self._q.put(None)
            t0 = time.perf_counter()
            t.join(timeout=60.0)
            blocked = time.perf_counter() - t0
            if blocked > 1e-4:
                from . import note_overlap
                note_overlap(self._path, wait_s=blocked)
