"""Bounded background prefetch over an iterator.

The ingest pipeline's producer/consumer split: a daemon thread pulls
items from the source iterator (file reads + tokenizing callbacks — work
that releases the GIL) up to ``depth`` items ahead of the consumer, so
chunk N+1 is being read while chunk N's frames assemble/intern.  The
queue gives backpressure both ways: the producer blocks when the
consumer falls behind (peak residency ≈ depth+1 items, preserving the
host path's lazy-window property), the consumer blocks only when the
producer is genuinely slower.

Order is the source order (single FIFO queue), so output is
bit-identical to the unprefetched loop — the golden contract
``tests/test_exec.py`` pins.  Producer exceptions re-raise in the
consumer with their original traceback; an early consumer exit (break,
exception) stops the producer promptly via a stop event.  The ft/
retry policy composes cleanly: retries happen INSIDE the producer's
task slots (``ft.retry.ingest_task`` under ``run_sinks``), so a
recovered fault never reorders the stream — only an EXHAUSTED budget
surfaces here, as the producer error the consumer re-raises.

Telemetry: one ``exec.prefetch`` span per stream (emitted from the
producer thread: items, busy seconds), a cumulative
:func:`..exec.note_overlap` record driving ``mrtpu_overlap_ratio{path}``,
and two direct metrics the stream plane attributes lag with
(doc/streaming.md#lag-attribution): ``mrtpu_prefetch_depth{path}``
(look-ahead actually banked — producer ahead of consumer) and
``mrtpu_prefetch_wait_seconds_total{path}`` (consumer blocked on the
producer — ingest-bound time).

Tail/follow mode: :func:`tail_chunks` reads whatever an append-only
file grew past an offset cursor — newline-aligned so a torn mid-line
append is never split across micro-batches — and returns the advanced
cursor with the chunk.  The stream/ tailers poll it; exactly-once
comes from committing the returned cursor atomically with the batch
that consumed it (stream/engine.py).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Iterable, Iterator, List, Optional, Tuple

_END = "end"
_ITEM = "item"
_ERR = "err"


def _prefetch_metrics(path: str):
    """(depth_gauge_setter, wait_counter_adder) for one stream label —
    resolved once per prefetch stream, no-ops when the registry is
    unavailable."""
    try:
        from ..obs.metrics import get_registry
        reg = get_registry()
        depth = reg.gauge(
            "mrtpu_prefetch_depth",
            "items the prefetch producer holds ahead of the consumer",
            ("path",))
        wait = reg.counter(
            "mrtpu_prefetch_wait_seconds_total",
            "seconds the consumer spent blocked on the prefetch "
            "producer (ingest-bound time)", ("path",))
        return (lambda n: depth.set(n, path=path),
                lambda s: wait.inc(s, path=path))
    except Exception:
        return (lambda n: None), (lambda s: None)


def tail_chunks(path: str, offset: int = 0,
                max_bytes: Optional[int] = None,
                final: bool = False) -> Tuple[List[bytes], int]:
    """One follow-mode poll of an append-only file: the bytes ``path``
    grew past ``offset``, newline-aligned, as ``(chunks, new_offset)``.

    Only whole lines are consumed — a producer caught mid-``write()``
    leaves a torn tail that stays pending until its newline lands, so
    a record never splits across two micro-batches.  ``final=True``
    (stream close/drain) consumes the unterminated tail too.
    ``max_bytes`` bounds one poll (backpressure: the rest stays
    pending for the next cut).  A file shorter than ``offset``
    (truncated — NOT append-only) raises ``OSError`` so the caller can
    surface a real error instead of silently re-reading."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return [], offset               # not born yet: nothing pending
    if size < offset:
        raise OSError(f"{path!r} shrank below cursor {offset} "
                      f"(size {size}): tailed sources must be "
                      f"append-only")
    if size == offset:
        return [], offset
    want = size - offset
    if max_bytes is not None:
        want = min(want, max_bytes)
    with open(path, "rb") as f:
        f.seek(offset)
        buf = f.read(want)
    if not buf:
        return [], offset
    cut = len(buf)
    if not final:
        nl = buf.rfind(b"\n")
        if nl < 0:
            return [], offset           # torn line: wait for its \n
        cut = nl + 1
    return [buf[:cut]], offset + cut


def prefetch_iter(src: Iterable, depth: Optional[int] = None,
                  path: str = "ingest") -> Iterator:
    """Iterate ``src`` through a background producer thread with a
    bounded look-ahead of ``depth`` items (default: the MRTPU_PREFETCH
    knob).  ``depth <= 0`` yields from ``src`` directly — the eager
    golden path, zero threads."""
    if depth is None:
        from . import prefetch_depth
        depth = prefetch_depth()
    if depth <= 0:
        yield from src
        return

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    state = {"busy": 0.0, "items": 0, "inflight_max": 0}
    set_depth, add_wait = _prefetch_metrics(path)
    # trace-context handoff (obs/context.py): the producer thread runs
    # the CONSUMER's request — its exec.prefetch span and any counters
    # the source iterator bumps must charge the submitting request, not
    # fall into the anonymous process bucket
    from ..obs import context as _obs_ctx
    req_ctx = _obs_ctx.capture()

    def _put(msg) -> None:
        # bounded put that gives up when the consumer is gone
        while not stop.is_set():
            try:
                q.put(msg, timeout=0.1)
                return
            except queue.Full:
                continue

    def producer() -> None:
        err = None
        try:
            from ..obs import get_tracer
            it = iter(src)
            with _obs_ctx.use(req_ctx), \
                    get_tracer().span("exec.prefetch", cat="exec",
                                      path=path, depth=depth) as sp:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    except BaseException as e:   # callback/read failure
                        err = e
                        break
                    state["busy"] += time.perf_counter() - t0
                    state["items"] += 1
                    state["inflight_max"] = max(state["inflight_max"],
                                                q.qsize() + 1)
                    set_depth(q.qsize() + 1)
                    _put((_ITEM, item))
                sp.set(items=state["items"],
                       busy_s=round(state["busy"], 6),
                       error=type(err).__name__ if err is not None
                       else "")
        except BaseException as e:   # anything else: never strand the
            err = err or e           # consumer without a sentinel
        finally:
            _put((_ERR, err) if err is not None else (_END, None))

    t = threading.Thread(target=producer, daemon=True,
                         name=f"mrtpu-prefetch-{path}")
    t.start()
    wait = 0.0
    try:
        while True:
            t0 = time.perf_counter()
            kind, payload = q.get()
            wait += time.perf_counter() - t0
            set_depth(q.qsize())
            if kind == _END:
                break
            if kind == _ERR:
                raise payload
            yield payload
    finally:
        stop.set()
        # unblock a producer stuck on a full queue, then reap it
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=10.0)
        set_depth(0)
        add_wait(wait)
        from . import note_overlap
        note_overlap(path, busy_s=state["busy"], wait_s=wait,
                     items=state["items"])
