"""Bounded background prefetch over an iterator.

The ingest pipeline's producer/consumer split: a daemon thread pulls
items from the source iterator (file reads + tokenizing callbacks — work
that releases the GIL) up to ``depth`` items ahead of the consumer, so
chunk N+1 is being read while chunk N's frames assemble/intern.  The
queue gives backpressure both ways: the producer blocks when the
consumer falls behind (peak residency ≈ depth+1 items, preserving the
host path's lazy-window property), the consumer blocks only when the
producer is genuinely slower.

Order is the source order (single FIFO queue), so output is
bit-identical to the unprefetched loop — the golden contract
``tests/test_exec.py`` pins.  Producer exceptions re-raise in the
consumer with their original traceback; an early consumer exit (break,
exception) stops the producer promptly via a stop event.  The ft/
retry policy composes cleanly: retries happen INSIDE the producer's
task slots (``ft.retry.ingest_task`` under ``run_sinks``), so a
recovered fault never reorders the stream — only an EXHAUSTED budget
surfaces here, as the producer error the consumer re-raises.

Telemetry: one ``exec.prefetch`` span per stream (emitted from the
producer thread: items, busy seconds) and a cumulative
:func:`..exec.note_overlap` record driving ``mrtpu_overlap_ratio{path}``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator, Optional

_END = "end"
_ITEM = "item"
_ERR = "err"


def prefetch_iter(src: Iterable, depth: Optional[int] = None,
                  path: str = "ingest") -> Iterator:
    """Iterate ``src`` through a background producer thread with a
    bounded look-ahead of ``depth`` items (default: the MRTPU_PREFETCH
    knob).  ``depth <= 0`` yields from ``src`` directly — the eager
    golden path, zero threads."""
    if depth is None:
        from . import prefetch_depth
        depth = prefetch_depth()
    if depth <= 0:
        yield from src
        return

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    state = {"busy": 0.0, "items": 0, "inflight_max": 0}
    # trace-context handoff (obs/context.py): the producer thread runs
    # the CONSUMER's request — its exec.prefetch span and any counters
    # the source iterator bumps must charge the submitting request, not
    # fall into the anonymous process bucket
    from ..obs import context as _obs_ctx
    req_ctx = _obs_ctx.capture()

    def _put(msg) -> None:
        # bounded put that gives up when the consumer is gone
        while not stop.is_set():
            try:
                q.put(msg, timeout=0.1)
                return
            except queue.Full:
                continue

    def producer() -> None:
        err = None
        try:
            from ..obs import get_tracer
            it = iter(src)
            with _obs_ctx.use(req_ctx), \
                    get_tracer().span("exec.prefetch", cat="exec",
                                      path=path, depth=depth) as sp:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    except BaseException as e:   # callback/read failure
                        err = e
                        break
                    state["busy"] += time.perf_counter() - t0
                    state["items"] += 1
                    state["inflight_max"] = max(state["inflight_max"],
                                                q.qsize() + 1)
                    _put((_ITEM, item))
                sp.set(items=state["items"],
                       busy_s=round(state["busy"], 6),
                       error=type(err).__name__ if err is not None
                       else "")
        except BaseException as e:   # anything else: never strand the
            err = err or e           # consumer without a sentinel
        finally:
            _put((_ERR, err) if err is not None else (_END, None))

    t = threading.Thread(target=producer, daemon=True,
                         name=f"mrtpu-prefetch-{path}")
    t.start()
    wait = 0.0
    try:
        while True:
            t0 = time.perf_counter()
            kind, payload = q.get()
            wait += time.perf_counter() - t0
            if kind == _END:
                break
            if kind == _ERR:
                raise payload
            yield payload
    finally:
        stop.set()
        # unblock a producer stuck on a full queue, then reap it
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=10.0)
        from . import note_overlap
        note_overlap(path, busy_s=state["busy"], wait_s=wait,
                     items=state["items"])
