"""gpu_mapreduce_tpu — a TPU-native MapReduce + graph-analytics framework.

A from-scratch re-design (not a port) of baoxuezhao/GPU-mapreduce —
Sandia's MapReduce-MPI library + OINK scripting + CUDA InvertedIndex —
built on JAX/XLA/Pallas: columnar sharded arrays instead of byte-packed
pages, mesh collectives instead of MPI, sort+segment ops instead of hash
tables, Pallas kernels instead of CUDA.  See SURVEY.md at the repo root for
the full reference analysis and design mapping.

Quick start (the reference's hello world, examples/wordfreq.cpp)::

    from gpu_mapreduce_tpu import MapReduce

    mr = MapReduce()
    mr.map_files(files, read_words_callback)
    mr.collate()
    mr.reduce(sum_counts_callback)
"""

import jax as _jax

# The reference is built around 64-bit keys/counters (MRMPI_BIGINT,
# src/mrtype.h:24; VERTEX=uint64, oink/typedefs.h:22).  JAX defaults to
# 32-bit; enable x64 so u64 graph keys survive device round-trips.  Hot
# kernels cast to u32 lanes internally where it matters.
_jax.config.update("jax_enable_x64", True)

# jax < 0.5 ships shard_map only under jax.experimental (and spells
# check_vma as check_rep); every mesh module calls the stable
# jax.shard_map spelling — alias it so the package runs on both
if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_old(f, **kw)

    _jax.shard_map = _shard_map

from .core.mapreduce import MapReduce, SerialBackend
from .core.dataset import KeyValue, KeyMultiValue
from .core.frame import (BlockedMultivalue, KMVFrame, KVFrame,
                         iter_blocks)
from .core.column import BytesColumn, DenseColumn, as_column
from .core.runtime import MRError, Settings, global_counters
from . import ft                      # fault tolerance (ft.schedule,
#                                       ft.resume — doc/reliability.md)

__version__ = "0.1.0"

__all__ = [
    "BlockedMultivalue", "iter_blocks",
    "MapReduce", "SerialBackend", "KeyValue", "KeyMultiValue",
    "KVFrame", "KMVFrame", "BytesColumn", "DenseColumn", "as_column",
    "MRError", "Settings", "global_counters", "ft",
]
