"""stream/ — the standing-query micro-batch engine (doc/streaming.md).

Three surfaces over one engine:

* programmatic — ``mr.stream(sources, dir=...)`` (core/mapreduce.py)
  or :func:`open_stream` here;
* the serve plane — ``POST /v1/streams`` (serve/daemon.py +
  serve/streams.py): open/feed/status/close with tenant budgets,
  deadlines, the ``/events`` chunked watcher, and fleet takeover of a
  dead replica's streams;
* OINK — the ``stream`` command family (oink/commands/stream.py).

The model: tail append-only sources with offset cursors, cut
micro-batches by rows/bytes/time, run the recorded map/reduce chain on
each delta, merge into the resident dataset with the reduce's
accumulator kernel.  Exactly-once via the ft/ journal — cursors commit
atomically with each batch's merge record.
"""

from .engine import ACCUMULATORS, PARSERS, Stream
from .scheduler import BatchCutter
from .tailer import Tailer

__all__ = ["Stream", "Tailer", "BatchCutter", "PARSERS",
           "ACCUMULATORS", "open_stream"]


def open_stream(dir, sources, **kw) -> Stream:
    """Open (or resume) a standing query — see :class:`Stream`."""
    return Stream(dir, sources, **kw)
