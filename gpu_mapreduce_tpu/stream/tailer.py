"""Source tailers: follow append-only files/dirs with offset cursors.

One :class:`Tailer` owns the cursor map of a stream — ``{abspath:
byte_offset}`` — and each :meth:`poll` asks the exec/ prefetch layer's
tail mode (:func:`..exec.prefetch.tail_chunks`) what every source grew
since its cursor.  Directory sources re-scan for NEW files on every
poll (a log-rotation layout: the producer opens ``dir/part-0001`` and
keeps appending), so a file that appears after the stream opened is
picked up at offset 0.

The cursor map is the stream's exactly-once anchor: the engine commits
it atomically with the batch that consumed the bytes (one journal
record carries both — stream/engine.py), so a kill -9 between a read
and its commit re-reads the same bytes from the same cursors on
resume, and a kill after the commit never re-reads them.

Watermark evidence rides each poll: the max source mtime of the data
actually consumed, feeding ``Stream.status()['watermark']`` and the
lag gauges (doc/streaming.md#watermarks-and-lag).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple


class Tailer:
    """Cursor-tracking follower of a fixed set of file/dir sources."""

    def __init__(self, sources: List[str],
                 cursors: Optional[Dict[str, int]] = None):
        self.sources = [os.path.abspath(s) for s in sources]
        self.cursors: Dict[str, int] = dict(cursors or {})

    # -- discovery ---------------------------------------------------------
    def files(self) -> List[str]:
        """Every tailed file right now (sorted: deterministic batch
        assembly order).  A directory source contributes its current
        regular files; a missing source is simply not born yet."""
        out = set()
        for src in self.sources:
            if os.path.isdir(src):
                try:
                    names = sorted(os.listdir(src))
                except OSError:
                    continue
                for n in names:
                    p = os.path.join(src, n)
                    if os.path.isfile(p):
                        out.add(p)
            elif os.path.isfile(src):
                out.add(src)
        return sorted(out)

    # -- polling -----------------------------------------------------------
    def poll(self, max_bytes: Optional[int] = None,
             final: bool = False) -> Tuple[List[bytes], float]:
        """One follow pass over every source: ``(chunks, watermark)``
        where watermark is the max mtime among files that produced
        data (0.0 when nothing moved).  Advances ``self.cursors`` —
        the caller owns committing them."""
        from ..exec.prefetch import tail_chunks
        chunks: List[bytes] = []
        watermark = 0.0
        budget = max_bytes
        for path in self.files():
            if budget is not None and budget <= 0:
                break
            off = self.cursors.get(path, 0)
            got, new_off = tail_chunks(path, off, max_bytes=budget,
                                       final=final)
            if new_off == off:
                continue
            self.cursors[path] = new_off
            chunks.extend(got)
            if budget is not None:
                budget -= sum(len(c) for c in got)
            try:
                watermark = max(watermark, os.path.getmtime(path))
            except OSError:
                pass
        return chunks, watermark

    def pending_bytes(self) -> int:
        """Bytes appended past the committed cursors but not yet
        consumed — the ingest half of the stream's lag."""
        n = 0
        for path in self.files():
            try:
                n += max(0, os.path.getsize(path)
                         - self.cursors.get(path, 0))
            except OSError:
                continue
        return n
