"""The standing-query micro-batch engine (doc/streaming.md).

One :class:`Stream` turns the batch MapReduce chain into a standing
query: tailers (stream/tailer.py) follow append-only sources through
the exec/ prefetch producer, a :class:`~.scheduler.BatchCutter` cuts
micro-batches by rows/bytes/time, and each batch runs the SAME
registered map/reduce chain a one-shot job would — on the delta only —
then merges into the resident dataset with the accumulator kernel of
the recorded reduce (count partials merge with ``sum``: the resident
already holds counts, and counting the partials would count records).

Exactly-once is one journal record: the batch's source cursors commit
ATOMICALLY with its merge (``stream_batch`` carries both, appended only
after the post-merge checkpoint is durably renamed into place — records
never lead their facts, ft/journal discipline).  A kill -9 anywhere
resumes from the last committed record: cursors and resident state can
never disagree, so the recovered stream re-reads exactly the bytes
whose merge never committed and the final state is byte-identical to an
uninterrupted run (tests/test_stream.py pins this, fuse={0,1}).

Sliding windows are bucketed retire-and-merge: ``window=N`` keeps the
last N batch deltas as reduced buckets; the resident view is their
merge, and retiring a bucket rebuilds the view from the survivors —
no subtraction kernel needed (min/max have none).

Because every batch replays one recorded chain over same-shaped
deltas, the plan cache (PR 12/17) makes steady state recompile-free:
warm micro-batches reuse the cached fused program
(``mr.stats()["plan"]`` — the acceptance assertion).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.runtime import MRError
from ..utils.env import env_knob
from .scheduler import BatchCutter
from .tailer import Tailer

# delta-reduce kernel → the accumulator that merges its partials into
# the resident dataset.  count's partials are already counts — merging
# them with count would count KV records, not occurrences.
ACCUMULATORS = {"count": "sum", "sum": "sum", "min": "min",
                "max": "max"}

_OPEN, _CLOSED, _FAILED = "open", "closed", "failed"


def _parse_words(chunk: bytes, kv) -> int:
    words = chunk.split()
    if words:
        kv.add_batch(words, np.ones(len(words), np.int64))
    return len(words)


def _parse_lines(chunk: bytes, kv) -> int:
    lines = chunk.splitlines()
    if lines:
        kv.add_batch(lines, np.ones(len(lines), np.int64))
    return len(lines)


def _parse_kv(chunk: bytes, kv) -> int:
    keys: List[bytes] = []
    vals: List[int] = []
    for line in chunk.splitlines():
        parts = line.split()
        if len(parts) >= 2:
            try:
                vals.append(int(parts[1]))
            except ValueError:
                continue
            keys.append(parts[0])
    if keys:
        kv.add_batch(keys, np.asarray(vals, np.int64))
    return len(keys)


PARSERS: Dict[str, Callable] = {"words": _parse_words,
                                "lines": _parse_lines,
                                "kv": _parse_kv}


def ckpt_keep_default() -> int:
    return max(1, env_knob("MRTPU_STREAM_KEEP", int, 2))


class Stream:
    """One standing query over append-only sources.

    ``dir`` is the stream's durable home (its ft/ journal + committed
    checkpoints); ``sources`` are files or directories to tail;
    ``parser``/``reduce`` name the recorded chain (PARSERS and the
    oink/ REDUCE_KERNELS registry); ``window`` > 0 keeps only the last
    N micro-batches resident (bucketed retire-and-merge).  ``resident``
    optionally binds the resident dataset to a caller-owned MapReduce
    (the ``mr.stream()`` surface) — merges land in that object.

    Construction RESUMES when the directory already holds committed
    batches: cursors, seq, and the resident dataset restore from the
    last committed record (integrity-verified; an unloadable generation
    falls back to the previous one, ft/ discipline)."""

    def __init__(self, dir: str, sources: List[str],
                 parser: str = "words", reduce: str = "count",
                 window: int = 0, comm=None,
                 settings: Optional[dict] = None,
                 rows: Optional[int] = None,
                 nbytes: Optional[int] = None,
                 wait_s: Optional[float] = None,
                 name: Optional[str] = None,
                 resident=None, keep: Optional[int] = None):
        from ..oink.kernels import REDUCE_KERNELS
        if parser not in PARSERS:
            raise MRError(f"unknown stream parser {parser!r} "
                          f"(have {sorted(PARSERS)})")
        if reduce not in ACCUMULATORS:
            raise MRError(f"unknown stream reduce {reduce!r} "
                          f"(have {sorted(ACCUMULATORS)})")
        self.dir = os.path.abspath(dir)
        self.name = name or os.path.basename(self.dir.rstrip("/")) \
            or "stream"
        self.parser = parser
        self.reduce = reduce
        self.window = max(0, int(window))
        self.comm = comm
        self.settings = dict(settings or {})
        self.keep = keep if keep is not None else ckpt_keep_default()
        self._parse = PARSERS[parser]
        self._reduce_fn = REDUCE_KERNELS[reduce]
        self._accum_fn = REDUCE_KERNELS[ACCUMULATORS[reduce]]
        self.tailer = Tailer(sources)
        self.cutter = BatchCutter(rows=rows, nbytes=nbytes,
                                  wait_s=wait_s)
        self.state = _OPEN
        self.error: Optional[str] = None
        self.seq = 0                    # committed batches
        self.rows_total = 0
        self.bytes_total = 0
        self.watermark = 0.0            # max source mtime committed
        self.resumes = 0
        self._lock = threading.Lock()
        self._external = resident is not None
        self.resident = resident if resident is not None \
            else self._new_mr()
        self._buckets: List = []        # window mode: last N deltas
        os.makedirs(self.dir, exist_ok=True)
        self._restore()
        from ..ft.journal import Journal
        self._journal = Journal(self.dir, script_mode=True)
        if self.seq == 0:
            self._journal.append({
                "kind": "stream_open", "name": self.name,
                "parser": parser, "reduce": reduce,
                "window": self.window,
                "sources": list(self.tailer.sources)})

    # -- construction helpers ----------------------------------------------
    def _new_mr(self):
        from ..core.mapreduce import MapReduce
        return MapReduce(self.comm, **self.settings)

    def _ckpt_dir(self, tag: str) -> str:
        return os.path.join(self.dir, "ckpt", tag)

    def _restore(self) -> None:
        """Resume from the last committed ``stream_batch`` record whose
        checkpoint still loads (generation fallback: a torn or
        bit-flipped newest checkpoint falls back to the one before it —
        its record's cursors come along, so the re-read covers exactly
        the gap)."""
        from ..ft.journal import read_journal
        try:
            recs = read_journal(self.dir)
        except MRError:
            return
        batches = [r for r in recs if r.get("kind") == "stream_batch"]
        # a ``stream_rehome`` record marks a directory move (fleet
        # takeover copies the stream dir — serve/streams.adopt): the
        # journaled cursors still name paths under the OLD home, so
        # every restored cursor key gets the prefix maps applied in
        # record order.  Without this the moved feed file reads from
        # offset 0 and every committed batch double-counts
        remaps = [r.get("map") or {} for r in recs
                  if r.get("kind") == "stream_rehome"]

        def rehome(path: str) -> str:
            for m in remaps:
                for old, new in m.items():
                    if path == old or path.startswith(
                            old.rstrip(os.sep) + os.sep):
                        path = new + path[len(old):]
                        break
            return path
        if any(r.get("kind") == "stream_close" for r in recs):
            # a cleanly closed stream re-opens for MORE data; its
            # committed state still restores below
            pass
        from ..core import checkpoint as ckpt_mod
        for rec in reversed(batches):
            tag = rec.get("ckpt", "")
            path = os.path.join(self._ckpt_dir(tag), "resident")
            try:
                resident = self._new_mr()
                ckpt_mod.load(resident, path)
                buckets = []
                for i in range(int(rec.get("buckets", 0))):
                    b = self._new_mr()
                    ckpt_mod.load(b, os.path.join(
                        self._ckpt_dir(tag), f"b{i}"))
                    buckets.append(b)
            except Exception:
                continue                 # fall back a generation
            self._set_resident(resident)
            self._buckets = buckets
            self.tailer.cursors = {
                rehome(str(k)): int(v)
                for k, v in (rec.get("cursors") or {}).items()}
            with self._lock:
                self.seq = int(rec.get("seq", 0))
                self.rows_total = int(rec.get("rows_cum", 0))
                self.bytes_total = int(rec.get("bytes_cum", 0))
                self.watermark = float(rec.get("wm", 0.0))
                self.resumes = 1
            self._metric("mrtpu_stream_resumes_total",
                         "streams resumed from a committed journal "
                         "record", 1)
            return

    def _set_resident(self, mr) -> None:
        """Install ``mr`` as the resident dataset.  An external
        resident (``mr.stream()``) keeps the CALLER's object identity:
        its dataset is replaced in place through public ops (a fresh
        0-task map resets the KV, then one add pulls the new state
        in)."""
        if not self._external:
            self.resident = mr
            return
        if mr is self.resident:
            return
        self.resident.map(0, lambda i, kv, p: None)
        self.resident.add(mr)

    # -- ingest ------------------------------------------------------------
    def _collect(self, max_bytes: Optional[int],
                 final: bool) -> tuple:
        """Pull pending chunks through the exec/ prefetch producer —
        the reads overlap the batch's compute, and the stream's lag
        attribution metrics (``mrtpu_prefetch_*{path="stream/<name>"}``)
        are fed here."""
        from ..exec.prefetch import prefetch_iter
        state = {"wm": 0.0}

        def tail_iter():
            chunks, wm = self.tailer.poll(max_bytes=max_bytes,
                                          final=final)
            state["wm"] = wm
            for c in chunks:
                yield c

        out = list(prefetch_iter(tail_iter(),
                                 path=f"stream/{self.name}"))
        return out, state["wm"]

    # -- the micro-batch ---------------------------------------------------
    def poll_once(self, force: bool = False,
                  final: bool = False) -> int:
        """One scheduler pass: cut and process at most one micro-batch;
        returns rows processed (0 = nothing cut).  ``force`` cuts any
        pending data regardless of thresholds (drain / close);
        ``final`` also consumes an unterminated trailing line."""
        if self.state != _OPEN:
            return 0
        pending = self.tailer.pending_bytes()
        if pending <= 0 and not final:
            self._update_gauges(0)
            return 0
        if not (force or final):
            # rows trigger rides the observed bytes/row of committed
            # batches (no pre-read row count exists for free)
            est_rows = 0
            if self.rows_total and self.bytes_total:
                est_rows = int(pending * self.rows_total
                               / self.bytes_total)
            if not self.cutter.should_cut(pending, est_rows):
                self._update_gauges(pending)
                return 0
        cursors_before = dict(self.tailer.cursors)
        try:
            chunks, wm = self._collect(
                None if final else max(pending, self.cutter.nbytes),
                final)
            if not chunks:
                self._update_gauges(self.tailer.pending_bytes())
                return 0
            rows = self._process(chunks, wm)
        except Exception:
            # the cursors advanced but the batch never committed:
            # rewind so a retry (or the resumed stream) re-reads the
            # exact same bytes — exactly-once, not at-most-once
            self.tailer.cursors = cursors_before
            raise
        self.cutter.cut_done()
        self._update_gauges(self.tailer.pending_bytes())
        return rows

    def drain(self, final: bool = False) -> int:
        """Process everything pending right now (deterministic tests,
        OINK ``stream poll``, close).  Returns total rows."""
        total = 0
        while True:
            n = self.poll_once(force=True, final=final)
            if n <= 0 and self.tailer.pending_bytes() <= 0:
                return total
            if n <= 0:
                return total            # torn tail only (not final)
            total += n

    def _process(self, chunks: List[bytes], wm: float) -> int:
        """The incremental chain + atomic commit for one batch."""
        from ..obs import get_tracer
        nbytes = sum(len(c) for c in chunks)
        with get_tracer().span("stream.batch", cat="stream",
                               stream=self.name, seq=self.seq + 1,
                               bytes=nbytes) as sp:
            delta = self._new_mr()

            def mapper(itask, kv, ptr):
                self._parse(ptr[itask], kv)

            delta.map(len(chunks), mapper, ptr=chunks)
            delta.collate()
            delta.reduce(self._reduce_fn, batch=True)
            rows = sum(c.count(b"\n") for c in chunks)
            if chunks and not chunks[-1].endswith(b"\n"):
                rows += 1               # final-drain unterminated tail
            if self.window > 0:
                self._buckets.append(delta)
                while len(self._buckets) > self.window:
                    self._buckets.pop(0)    # retire the aged bucket
                view = self._new_mr()
                for b in self._buckets:
                    view.add(b)
                view.collate()
                view.reduce(self._accum_fn, batch=True)
                self._set_resident(view)
            else:
                self.resident.add(delta)
                self.resident.collate()
                self.resident.reduce(self._accum_fn, batch=True)
            self._commit(rows, nbytes, wm)
            sp.set(rows=rows, seq=self.seq)
        return rows

    def _commit(self, rows: int, nbytes: int, wm: float) -> None:
        """Checkpoint, THEN the record — the exactly-once edge.  Every
        save is atomic (tmp sibling + rename, core/checkpoint.py), and
        the ``stream_batch`` record carrying the advanced cursors is
        appended only after all of them: a kill -9 before the append
        leaves the PREVIOUS record authoritative, and its cursors
        re-read exactly the bytes whose merge was lost."""
        from ..core import checkpoint as ckpt_mod
        seq = self.seq + 1
        tag = f"g{seq:06d}"
        ckpt_mod.save(self.resident,
                      os.path.join(self._ckpt_dir(tag), "resident"))
        for i, b in enumerate(self._buckets):
            ckpt_mod.save(b, os.path.join(self._ckpt_dir(tag),
                                          f"b{i}"))
        with self._lock:
            self.seq = seq
            self.rows_total += rows
            self.bytes_total += nbytes
            if wm > 0:
                self.watermark = max(self.watermark, wm)
            cursors = dict(self.tailer.cursors)
        self._journal.append({
            "kind": "stream_batch", "seq": seq, "ckpt": tag,
            "cursors": cursors, "rows": rows, "bytes": nbytes,
            "rows_cum": self.rows_total, "bytes_cum": self.bytes_total,
            "buckets": len(self._buckets), "wm": self.watermark})
        self._gc_ckpts(seq)
        self._metric("mrtpu_stream_batches_total",
                     "micro-batches committed per stream", 1)
        self._metric("mrtpu_stream_rows_total",
                     "records committed per stream", rows)

    def _gc_ckpts(self, seq: int) -> None:
        """Drop committed checkpoint generations past ``keep`` (the
        newest is always load-bearing; older ones are the generation
        fallback)."""
        root = os.path.join(self.dir, "ckpt")
        try:
            tags = sorted(n for n in os.listdir(root)
                          if n.startswith("g") and ".tmp" not in n)
        except OSError:
            return
        live = {f"g{s:06d}" for s in
                range(max(1, seq - self.keep + 1), seq + 1)}
        for t in tags:
            if t not in live and t <= f"g{seq:06d}":
                shutil.rmtree(os.path.join(root, t),
                              ignore_errors=True)

    # -- observation -------------------------------------------------------
    def _metric(self, name: str, help: str, amount) -> None:
        try:
            from ..obs.metrics import get_registry
            get_registry().counter(name, help, ("stream",)).inc(
                amount, stream=self.name)
        except Exception:
            pass

    def _update_gauges(self, pending: int) -> None:
        try:
            from ..obs.metrics import get_registry
            reg = get_registry()
            reg.gauge("mrtpu_stream_pending_bytes",
                      "bytes appended past the committed cursors but "
                      "not yet consumed", ("stream",)).set(
                          pending, stream=self.name)
            reg.gauge("mrtpu_stream_lag_seconds",
                      "event-time lag of the stream (0 when caught "
                      "up)", ("stream",)).set(self.lag_s(pending),
                                              stream=self.name)
        except Exception:
            pass

    def lag_s(self, pending: Optional[int] = None) -> float:
        """Event-time lag: 0 when caught up, else now minus the
        watermark (the newest source mtime already committed — the
        uncommitted tail is AT LEAST that old)."""
        if pending is None:
            pending = self.tailer.pending_bytes()
        if pending <= 0 or self.watermark <= 0:
            return 0.0
        return max(0.0, time.time() - self.watermark)

    def _ingest_stats(self) -> dict:
        """The lag-attribution half: what the exec/ prefetch producer
        reports for THIS stream's path label — wait says ingest-bound,
        depth says the producer is ahead (compute-bound)."""
        out = {"prefetch_depth": 0, "prefetch_wait_s": 0.0}
        try:
            from ..obs.metrics import get_registry
            reg = get_registry()
            label = f"stream/{self.name}"
            d = reg.gauge(
                "mrtpu_prefetch_depth",
                "items the prefetch producer holds ahead of the "
                "consumer", ("path",)).value(path=label)
            w = reg.counter(
                "mrtpu_prefetch_wait_seconds_total",
                "seconds the consumer spent blocked on the prefetch "
                "producer (ingest-bound time)",
                ("path",)).value(path=label)
            out["prefetch_depth"] = int(d or 0)
            out["prefetch_wait_s"] = round(float(w or 0.0), 6)
        except Exception:
            pass
        return out

    def status(self) -> dict:
        pending = self.tailer.pending_bytes()
        with self._lock:
            out = {
                "name": self.name, "state": self.state,
                "error": self.error,
                "parser": self.parser, "reduce": self.reduce,
                "window": self.window,
                "buckets": len(self._buckets),
                "batches": self.seq, "rows": self.rows_total,
                "bytes": self.bytes_total,
                "pending_bytes": pending,
                "watermark": round(self.watermark, 6),
                "lag_s": round(self.lag_s(pending), 6),
                "resumed": bool(self.resumes),
                "cursors": dict(self.tailer.cursors),
            }
        out["ingest"] = self._ingest_stats()
        return out

    def snapshot(self) -> str:
        """Canonical text of the resident dataset — gathered, key-
        sorted, one ``key value`` line per pair.  THE byte-identity
        surface: incremental-vs-batch and kill-9-resume goldens
        compare exactly this string."""
        mr = self.resident.copy()
        mr.gather(1)
        mr.sort_keys(1)
        lines: List[str] = []

        def emit(k, v, _ptr):
            key = k.decode("utf-8", "replace") if isinstance(
                k, (bytes, bytearray)) else str(k)
            lines.append(f"{key} {int(v)}\n")

        mr.scan_kv(emit)
        return "".join(lines)

    # -- lifecycle ---------------------------------------------------------
    def suspend(self) -> None:
        """Release this HANDLE without closing the QUERY: the journal
        handle closes, no ``stream_close`` record lands — a later
        ``Stream(dir, ...)`` over the same directory resumes from the
        last committed batch.  The OINK command surface (one process
        per invocation) and daemon shutdown both detach this way."""
        if self.state == _OPEN:
            self.state = "suspended"
        try:
            self._journal.close()
        except Exception:
            pass

    def close(self, drain: bool = True) -> dict:
        """Final drain (unterminated tail included), the
        ``stream_close`` record, and the journal handle.  Returns the
        final status.  Idempotent."""
        if self.state == _OPEN:
            if drain:
                try:
                    self.drain(final=True)
                except Exception as e:
                    self.error = f"{type(e).__name__}: {e}"
                    self.state = _FAILED
            if self.state == _OPEN:
                self.state = _CLOSED
            try:
                self._journal.append({"kind": "stream_close",
                                      "state": self.state})
            except (ValueError, OSError):
                pass
        try:
            self._journal.close()
        except Exception:
            pass
        return self.status()
