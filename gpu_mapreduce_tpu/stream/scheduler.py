"""Micro-batch cut policy: rows / bytes / time.

One :class:`BatchCutter` decides when the pending delta is worth a
micro-batch.  Three triggers, any of which cuts (doc/streaming.md):

* ``rows``  — pending newline-terminated records ≥ ``MRTPU_STREAM_ROWS``
* ``bytes`` — pending bytes ≥ ``MRTPU_STREAM_BYTES``
* ``time``  — ANY pending data older than ``MRTPU_STREAM_WAIT_MS``
  (latency floor: a trickle must not wait forever for a full batch)

The cutter never cuts an EMPTY batch: an idle stream writes no
journal records, takes no checkpoints, and recompiles nothing.
"""

from __future__ import annotations

import time
from typing import Optional

from ..utils.env import env_knob


def cut_rows_default() -> int:
    return max(1, env_knob("MRTPU_STREAM_ROWS", int, 4096))


def cut_bytes_default() -> int:
    return max(1, env_knob("MRTPU_STREAM_BYTES", int, 1 << 20))


def cut_wait_default() -> float:
    return max(0.0, env_knob("MRTPU_STREAM_WAIT_MS", int, 200) / 1000.0)


class BatchCutter:
    """Accumulates pending-delta evidence and answers "cut now?"."""

    def __init__(self, rows: Optional[int] = None,
                 nbytes: Optional[int] = None,
                 wait_s: Optional[float] = None):
        self.rows = rows if rows is not None else cut_rows_default()
        self.nbytes = nbytes if nbytes is not None \
            else cut_bytes_default()
        self.wait_s = wait_s if wait_s is not None \
            else cut_wait_default()
        self._first_pending: Optional[float] = None

    def note_pending(self, nbytes: int, rows: int,
                     now: Optional[float] = None) -> None:
        """Record the current pending census (from the tailer)."""
        if nbytes <= 0 and rows <= 0:
            self._first_pending = None
            return
        if self._first_pending is None:
            self._first_pending = time.monotonic() if now is None \
                else now

    def should_cut(self, nbytes: int, rows: int,
                   now: Optional[float] = None) -> bool:
        """True when the pending delta crosses any trigger."""
        if nbytes <= 0 and rows <= 0:
            self._first_pending = None
            return False
        self.note_pending(nbytes, rows, now=now)
        if rows >= self.rows or nbytes >= self.nbytes:
            return True
        now = time.monotonic() if now is None else now
        return self._first_pending is not None and \
            now - self._first_pending >= self.wait_s

    def cut_done(self) -> None:
        self._first_pending = None
