"""trace-purity: no host effects inside traced program bodies.

The paper's contract is "user code supplies serial callbacks; the
library does all parallelism" — inside a ``jit``/``shard_map``/
``pallas_call`` body that means NO host work: a ``print`` traces once
and never again, ``time``/``random``/``os.environ`` reads bake one
ambient value into a cached executable, a lock acquisition runs at
trace time only (and orders against nothing at run time), and
``.item()``/``float()``-style coercions force a device sync or crash
under tracing outright.

Entry points (the traced set's roots):

* functions decorated ``@jax.jit`` / ``@jit`` /
  ``@functools.partial(jax.jit, ...)``;
* the callable passed to ``jax.shard_map`` / ``shard_map`` /
  ``pallas_call`` / ``pl.pallas_call`` / ``jax.jit(...)`` /
  ``donated_jit(...)`` (the repo's one donation-wrapping rule,
  ``exec/__init__.py``) — including one wrapped as
  ``functools.partial(kernel, static_args...)``, the ops/pallas
  call-site idiom for baking static kernel parameters.

Everything reachable from an entry through the project callgraph is
treated as traced.  Reachability is best-effort (unresolvable calls
drop), so this rule under-approximates — it exists to catch the
recurring review classes, not to prove purity.

Rules emitted:

* ``purity-host-call`` — print/open/time/random/os.environ/env_knob
  reads in traced code;
* ``purity-lock`` — lock acquisition (``with <lock>`` / ``.acquire()``)
  in traced code;
* ``purity-coerce`` — ``.item()`` anywhere, or ``float()/int()/bool()``
  and ``np.asarray/np.array`` applied to a value data-flowed from a
  traced entry's parameters (one-level positional taint propagation).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ENV_HELPERS as _ENV_HELPERS
from .callgraph import (CallGraph, FuncInfo, env_reads, get_graph,
                        name_chain)
from .driver import Finding, Project, register

_TRACE_WRAPPERS = {
    ("jax", "shard_map"): 0, ("shard_map",): 0,
    ("jax", "experimental", "shard_map", "shard_map"): 0,
    ("pallas_call",): 0, ("pl", "pallas_call"): 0,
    ("jax", "jit"): 0, ("jit",): 0, ("donated_jit",): 0,
}

_TIME_FNS = {"time", "perf_counter", "monotonic", "sleep",
             "process_time", "time_ns", "perf_counter_ns"}


def _is_jit_decorator(dec: ast.AST) -> bool:
    chain = name_chain(dec)
    if chain and chain[-1] == "jit":
        return True
    if isinstance(dec, ast.Call):
        chain = name_chain(dec.func)
        if chain and chain[-1] == "jit":
            return True
        # functools.partial(jax.jit, ...)
        if chain and chain[-1] == "partial" and dec.args:
            inner = name_chain(dec.args[0])
            if inner and inner[-1] == "jit":
                return True
    return False


def _entries(graph: CallGraph) -> List[FuncInfo]:
    roots: List[FuncInfo] = []
    seen: Set[str] = set()

    def add(info: Optional[FuncInfo]) -> None:
        if info is not None and info.key not in seen:
            seen.add(info.key)
            roots.append(info)

    for info in graph.funcs.values():
        node = info.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                add(info)
    for mod in graph.project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if chain is None:
                continue
            argpos = None
            for pat, pos in _TRACE_WRAPPERS.items():
                if chain[-len(pat):] == pat:
                    argpos = pos
                    break
            if argpos is None or len(node.args) <= argpos:
                continue
            arg = node.args[argpos]
            scope = graph.enclosing(mod, node)
            if isinstance(arg, ast.Lambda):
                add(graph.funcs.get(
                    f"{mod.relpath}::"
                    + (f"{scope.qual}.<lambda:{arg.lineno}>" if scope
                       else f"<lambda:{arg.lineno}>")))
                # fall through to name-chain lookup below for non-lambda
                continue
            if isinstance(arg, ast.Call):
                # functools.partial(kernel, static_args...) — the
                # ops/pallas call-site idiom: the traced body is the
                # partial's FIRST argument.  Without this unwrap every
                # partial-wrapped pallas_call kernel body went unwalked.
                fchain = name_chain(arg.func)
                if fchain and fchain[-1] == "partial" and arg.args:
                    achain = name_chain(arg.args[0])
                    if achain:
                        add(graph.resolve(mod, scope, achain))
                continue
            achain = name_chain(arg)
            if achain:
                add(graph.resolve(mod, scope, achain))
    return roots


def _taint(graph: CallGraph, traced: List[FuncInfo],
           entries: List[FuncInfo]) -> Dict[str, Set[str]]:
    """function key -> set of local names carrying traced values."""
    traced_keys = {f.key for f in traced}
    taint: Dict[str, Set[str]] = {f.key: set(f.params) for f in entries}
    by_key = {f.key: f for f in traced}
    for _round in range(5):
        changed = False
        for info in traced:
            names = taint.get(info.key, set())
            # closure flow: a nested def sees its ancestors' taints
            prefix = info.qual.rsplit(".", 1)[0] if "." in info.qual else ""
            while prefix:
                parent = taint.get(f"{info.module.relpath}::{prefix}")
                if parent:
                    names = names | parent
                prefix = prefix.rsplit(".", 1)[0] if "." in prefix else ""
            if names != taint.get(info.key, set()):
                taint[info.key] = set(names)
                changed = True
            if not names:
                continue
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                chain = name_chain(node.func)
                if not chain:
                    continue
                callee = graph.resolve(info.module, info, chain)
                if callee is None or callee.key not in traced_keys:
                    continue
                tgt = taint.setdefault(callee.key, set())
                for pos, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) and arg.id in names \
                            and pos < len(callee.params):
                        if callee.params[pos] not in tgt:
                            tgt.add(callee.params[pos])
                            changed = True
        if not changed:
            break
    for key in list(taint):
        if key in by_key:
            # assignments from tainted expressions taint their targets
            info = by_key[key]
            names = taint[key]
            for node in ast.walk(info.node):
                if isinstance(node, ast.Assign):
                    used = {n.id for n in ast.walk(node.value)
                            if isinstance(n, ast.Name)}
                    if used & names:
                        for t in node.targets:
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name):
                                    names.add(n.id)
    return taint


def _lockish(expr: ast.AST) -> Optional[str]:
    chain = name_chain(expr)
    if isinstance(expr, ast.Call):
        chain = name_chain(expr.func)
    if not chain:
        return None
    last = chain[-1].lower()
    if "lock" in last or last in ("condition", "cv", "mutex"):
        return ".".join(chain)
    return None


def check(project: Project) -> List[Finding]:
    graph = get_graph(project)
    entries = _entries(graph)
    traced = graph.reachable(entries)
    taint = _taint(graph, traced, entries)
    out: List[Finding] = []

    for info in traced:
        mod = info.module
        names = taint.get(info.key, set())
        body = info.node
        nested_spans = [
            (f.node.lineno, f.node.end_lineno or f.node.lineno)
            for f in traced
            if f.module is mod and f.key != info.key
            and f.qual.startswith(info.qual + ".")]

        def owned(node) -> bool:
            # skip nodes belonging to a nested traced def (they report
            # under their own FuncInfo, once)
            ln = getattr(node, "lineno", None)
            if ln is None:
                return False
            return not any(a <= ln <= b for a, b in nested_spans)

        def emit(rule, node, msg):
            out.append(Finding(rule, mod.relpath, node.lineno, msg,
                               symbol=info.qual))

        for knob, node in env_reads(body):
            # skip the registry helpers' own non-literal reads: if a
            # traced body calls env_knob("MRTPU_X", ...), the call site
            # reports with the real knob name; the helper body's
            # os.environ.get(name) would only add an unactionable "?"
            if info.qual in _ENV_HELPERS:
                continue
            if owned(node):
                emit("purity-host-call", node,
                     f"env read {knob!r} inside traced code bakes an "
                     f"ambient value into a cached executable")
        for node in ast.walk(body):
            if not owned(node):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lk = _lockish(item.context_expr)
                    if lk:
                        emit("purity-lock", node,
                             f"lock {lk!r} acquired inside traced code "
                             f"(held at trace time only)")
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func) or ()
            if chain == ("print",):
                emit("purity-host-call", node,
                     "print() inside traced code runs once at trace "
                     "time, then never again")
            elif chain == ("open",):
                emit("purity-host-call", node,
                     "open() inside traced code is a host file effect")
            elif len(chain) == 2 and chain[0] == "time" \
                    and chain[1] in _TIME_FNS:
                emit("purity-host-call", node,
                     f"time.{chain[1]}() inside traced code freezes one "
                     f"trace-time value into the executable")
            elif chain[:1] == ("random",) and len(chain) == 2:
                emit("purity-host-call", node,
                     f"random.{chain[1]}() inside traced code — use "
                     f"jax.random with an explicit key")
            elif len(chain) >= 3 and chain[0] in ("np", "numpy") \
                    and chain[1] == "random":
                emit("purity-host-call", node,
                     "np.random inside traced code — use jax.random")
            elif chain[-1:] == ("acquire",) and len(chain) >= 2 \
                    and "lock" in chain[-2].lower():
                emit("purity-lock", node,
                     f"{'.'.join(chain)} inside traced code")
            elif chain[-1:] == ("item",) and not node.args:
                emit("purity-coerce", node,
                     ".item() inside traced code forces a host sync "
                     "(fails under tracing)")
            elif chain in (("float",), ("int",), ("bool",)) and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id in names:
                    emit("purity-coerce", node,
                         f"{chain[0]}({arg.id}) coerces a traced value "
                         f"on the host (fails under tracing)")
            elif len(chain) == 2 and chain[0] in ("np", "numpy") \
                    and chain[1] in ("asarray", "array", "save", "load") \
                    and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id in names:
                    emit("purity-coerce", node,
                         f"np.{chain[1]}({arg.id}) pulls a traced value "
                         f"to the host")
    return out


register(
    "trace-purity", check,
    "host effects (print/time/random/env/lock/.item()/coercions) in "
    "functions reachable from jit/shard_map/pallas_call bodies")
