"""Scope and callgraph builder for mrlint.

Best-effort static resolution, tuned for this repo's idioms rather
than full Python semantics:

* functions are indexed by qualname within their module (nested defs
  and lambdas included — jit bodies are nested defs by construction);
* calls resolve through (a) enclosing-scope defs, (b) module-level
  defs/classes, (c) ``self.``/``cls.`` methods, (d) package-relative
  imports (module-level OR function-local — the repo late-imports
  heavily to keep import time down);
* reachability is a bounded BFS over resolved calls plus bare-``Name``
  references to project functions (so ``cache.get_or_build(key, build)``
  reaches ``build``).

Unresolvable calls (jnp.*, dict methods, externals) drop silently —
checkers built on this must treat reachability as an under-approximation
and say so in their rule docs.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .driver import Module, Project


@dataclass
class FuncInfo:
    qual: str                    # "Class.method" / "outer.<locals>.inner"
    module: Module
    node: ast.AST                # FunctionDef | AsyncFunctionDef | Lambda
    class_name: str = ""
    params: Tuple[str, ...] = ()

    @property
    def key(self) -> str:
        return f"{self.module.relpath}::{self.qual}"

    @property
    def line(self) -> int:
        return self.node.lineno


def _params(node) -> Tuple[str, ...]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args]
    return tuple(names)


def name_chain(node) -> Optional[Tuple[str, ...]]:
    """``a.b.c`` -> ("a","b","c"); None for anything not a pure
    Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        self.funcs: Dict[str, FuncInfo] = {}
        # module relpath -> {alias: dotted target or (dotted, attr)}
        self.imports: Dict[str, Dict[str, object]] = {}
        # module relpath -> {name: FuncInfo} at module level
        self.top: Dict[str, Dict[str, FuncInfo]] = {}
        # module relpath -> {Class: {method: FuncInfo}}
        self.methods: Dict[str, Dict[str, Dict[str, FuncInfo]]] = {}
        for mod in project.modules.values():
            self._index_module(mod)

    # -- indexing ----------------------------------------------------------

    def _index_module(self, mod: Module) -> None:
        imports: Dict[str, object] = {}
        self.imports[mod.relpath] = imports
        self.top.setdefault(mod.relpath, {})
        self.methods.setdefault(mod.relpath, {})
        pkg_parts = mod.dotted.split(".")
        is_pkg = mod.relpath.endswith("__init__.py")

        def resolve_relative(level: int, target: str) -> str:
            # inside package __init__, "from . import x" is level-1 off
            # the package itself
            base = pkg_parts if is_pkg else pkg_parts[:-1]
            if level > 0:
                base = base[:len(base) - (level - 1)]
                return ".".join(base + ([target] if target else []))
            return target

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom):
                src = resolve_relative(node.level, node.module or "")
                for alias in node.names:
                    imports[alias.asname or alias.name] = (src, alias.name)

        stack: List[str] = []
        class_stack: List[str] = []
        graph = self

        class V(ast.NodeVisitor):
            def _add(self, node, name: str):
                qual = ".".join(stack + [name]) if stack else name
                info = FuncInfo(qual, mod, node,
                                class_stack[-1] if class_stack else "",
                                _params(node))
                graph.funcs[info.key] = info
                if not stack:
                    graph.top[mod.relpath][name] = info
                if class_stack and stack and stack[-1] == class_stack[-1]:
                    graph.methods[mod.relpath].setdefault(
                        class_stack[-1], {})[name] = info
                return info

            def visit_FunctionDef(self, node):
                self._add(node, node.name)
                stack.append(node.name)
                self.generic_visit(node)
                stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                self._add(node, f"<lambda:{node.lineno}>")
                self.generic_visit(node)

            def visit_ClassDef(self, node):
                stack.append(node.name)
                class_stack.append(node.name)
                self.generic_visit(node)
                class_stack.pop()
                stack.pop()

        V().visit(mod.tree)

    # -- resolution --------------------------------------------------------

    def _module_by_dotted(self, dotted: str) -> Optional[Module]:
        return self.project.by_dotted.get(dotted)

    def resolve(self, mod: Module, scope: Optional[FuncInfo],
                chain: Tuple[str, ...]) -> Optional[FuncInfo]:
        """Resolve a name chain at a call/reference site to a project
        function, or None (external / unknown)."""
        if not chain:
            return None
        head = chain[0]
        # self.meth / cls.meth inside a class
        if head in ("self", "cls") and len(chain) == 2 and scope is not None \
                and scope.class_name:
            return self.methods.get(mod.relpath, {}).get(
                scope.class_name, {}).get(chain[1])
        if len(chain) == 1:
            # nested def in enclosing scopes, innermost first
            if scope is not None:
                parts = scope.qual.split(".")
                for i in range(len(parts), 0, -1):
                    key = f"{mod.relpath}::{'.'.join(parts[:i])}.{head}"
                    if key in self.funcs:
                        return self.funcs[key]
            hit = self.top.get(mod.relpath, {}).get(head)
            if hit is not None:
                return hit
            return self._resolve_import(mod, head, ())
        # Class.method in same module
        cls_methods = self.methods.get(mod.relpath, {}).get(head)
        if cls_methods is not None and len(chain) == 2:
            return cls_methods.get(chain[1])
        return self._resolve_import(mod, head, chain[1:])

    def _resolve_import(self, mod: Module, head: str,
                        rest: Tuple[str, ...]) -> Optional[FuncInfo]:
        target = self.imports.get(mod.relpath, {}).get(head)
        if target is None:
            return None
        if isinstance(target, tuple):              # from X import y
            src, attr = target
            child = self._module_by_dotted(f"{src}.{attr}")
            if child is not None and rest:
                # "from . import shuffle" then shuffle.f(...)
                return self.top.get(child.relpath, {}).get(rest[0])
            src_mod = self._module_by_dotted(src)
            if src_mod is None:
                return None
            if not rest:
                return self.top.get(src_mod.relpath, {}).get(attr)
            # from X import Class; Class.method(...)
            return self.methods.get(src_mod.relpath, {}).get(
                attr, {}).get(rest[0])
        src_mod = self._module_by_dotted(str(target))
        if src_mod is None or not rest:
            return None
        if len(rest) == 1:
            return self.top.get(src_mod.relpath, {}).get(rest[0])
        return self.methods.get(src_mod.relpath, {}).get(
            rest[0], {}).get(rest[1])

    def enclosing(self, mod: Module, node: ast.AST) -> Optional[FuncInfo]:
        """Innermost FuncInfo whose span contains node (by lineno)."""
        best = None
        for info in self.funcs.values():
            if info.module is not mod:
                continue
            n = info.node
            if n.lineno <= node.lineno <= (n.end_lineno or n.lineno):
                if best is None or n.lineno > best.node.lineno:
                    best = info
        return best

    # -- reachability ------------------------------------------------------

    def callees(self, info: FuncInfo) -> List[FuncInfo]:
        """Functions called OR referenced by bare name inside info's
        body (nested defs included — they execute under the same entry
        for our purposes).  Memoized per function: the checkers walk
        the graph O(rounds x functions) times over immutable bodies."""
        memo = getattr(self, "_callees_memo", None)
        if memo is None:
            memo = self._callees_memo = {}
        hit = memo.get(info.key)
        if hit is not None:
            return hit
        out: List[FuncInfo] = []
        seen: Set[str] = set()
        for node in ast.walk(info.node):
            chain = None
            if isinstance(node, ast.Call):
                chain = name_chain(node.func)
            elif isinstance(node, ast.Name) and isinstance(
                    getattr(node, "ctx", None), ast.Load):
                chain = (node.id,)
            if not chain:
                continue
            hit = self.resolve(info.module, info, chain)
            if hit is not None and hit.key != info.key \
                    and hit.key not in seen:
                seen.add(hit.key)
                out.append(hit)
        memo[info.key] = out
        return out

    def reachable(self, roots: List[FuncInfo],
                  max_depth: int = 8,
                  max_funcs: int = 400) -> List[FuncInfo]:
        seen: Dict[str, FuncInfo] = {}
        frontier = list(roots)
        for r in roots:
            seen[r.key] = r
        depth = 0
        while frontier and depth < max_depth and len(seen) < max_funcs:
            nxt: List[FuncInfo] = []
            for info in frontier:
                for callee in self.callees(info):
                    if callee.key not in seen:
                        seen[callee.key] = callee
                        nxt.append(callee)
            frontier = nxt
            depth += 1
        return list(seen.values())


def get_graph(project: Project) -> CallGraph:
    """The project's CallGraph, built once and cached on the Project —
    three checkers need it and indexing 100+ modules three times over
    would dominate the whole run."""
    graph = getattr(project, "_callgraph", None)
    if graph is None or graph.project is not project:
        graph = CallGraph(project)
        project._callgraph = graph
    return graph


# ---------------------------------------------------------------------------
# env-knob read detection (shared by purity / cache-key / knob checkers)
# ---------------------------------------------------------------------------

ENV_HELPERS = ("env_knob", "env_str", "env_flag")


def env_reads(root: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(knob_name, node) for every env read under root: os.environ.get /
    os.environ[...] / os.getenv / the utils.env helpers.  Name "?" when
    the knob name is not a string literal."""
    out: List[Tuple[str, ast.AST]] = []

    def lit(node) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return "?"

    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            chain = name_chain(node.func) or ()
            if chain[-2:] == ("environ", "get") or \
                    chain[-1:] == ("getenv",):
                if node.args:
                    out.append((lit(node.args[0]), node))
            elif chain and chain[-1] in ENV_HELPERS:
                if node.args:
                    out.append((lit(node.args[0]), node))
        elif isinstance(node, ast.Subscript) and isinstance(
                getattr(node, "ctx", None), ast.Load):
            # stores (os.environ["X"] = ...) are knob WRITES — the A/B
            # harness save/restore pattern, not consumption
            chain = name_chain(node.value) or ()
            if chain[-1:] == ("environ",):
                out.append((lit(node.slice), node))
    return out


def is_env_helper_call(node: ast.Call) -> bool:
    chain = name_chain(node.func) or ()
    return bool(chain) and chain[-1] in ENV_HELPERS
