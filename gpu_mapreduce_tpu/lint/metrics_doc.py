"""metric-catalog: code and doc/observability.md must agree (the
former scripts/check_metrics_doc.py, re-homed as an mrlint checker —
the script remains as a thin shim).

Every metric name registered in the package (any lowercase ``mrtpu_*``
string literal — the reserved namespace for metric names) must appear
in doc/observability.md's catalog, and every name the catalog documents
must still exist in code.  Regex over source text on purpose: metric
specs ride tuples (the ft collector), so matching only
counter()/gauge()/histogram() call sites would miss them, and
non-metric identifiers use dashes or uppercase (thread names
"mrtpu-...", env vars "MRTPU_...") which the pattern excludes.

Rules: ``metric-undocumented``, ``metric-stale``.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from .driver import Finding, Project, register

_REG_CALL = re.compile(r"[\"'](mrtpu_[a-z0-9_]+)[\"']")
_DOC_NAME = re.compile(r"mrtpu_[a-z0-9_]+")

# histogram exposition suffixes the doc may quote verbatim
_SUFFIXES = ("_bucket", "_sum", "_count")

DOC_NAME = "observability.md"


def code_metrics(project: Project) -> Dict[str, Tuple[str, int]]:
    """metric -> (relpath, line) of its first registration."""
    out: Dict[str, Tuple[str, int]] = {}
    for mod in project.all_modules():
        for i, text in enumerate(mod.lines, 1):
            for name in _REG_CALL.findall(text):
                out.setdefault(name, (mod.relpath, i))
    return out


def doc_metrics(doc: str) -> set:
    raw = set(_DOC_NAME.findall(doc))
    out = set()
    for name in raw:
        for suf in _SUFFIXES:
            if name.endswith(suf) and name[:-len(suf)] in raw:
                break
        else:
            out.add(name)
    return out


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    in_code = code_metrics(project)
    doc = project.doc(DOC_NAME)
    if doc is None:
        return out
    in_doc = doc_metrics(doc)
    doc_lines = doc.splitlines()
    for name in sorted(set(in_code) - in_doc):
        rel, line = in_code[name]
        out.append(Finding(
            "metric-undocumented", rel, line,
            f"metric {name} is registered here but missing from "
            f"doc/{DOC_NAME}'s catalog — invisible to operators",
            symbol=name))
    for name in sorted(in_doc - set(in_code)):
        line = next((i for i, t in enumerate(doc_lines, 1)
                     if name in t), 1)
        out.append(Finding(
            "metric-stale", f"doc/{DOC_NAME}", line,
            f"metric {name} is documented but registered nowhere — "
            f"operators will grep for a series that never appears",
            symbol=name))
    return out


register(
    "metric-catalog", check,
    "mrtpu_* metric names in code and doc/observability.md must agree "
    "both ways",
    global_findings=("metric-undocumented", "metric-stale"))
