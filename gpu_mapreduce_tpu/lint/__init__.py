"""mrlint — domain-aware static analysis for this repo's recurring
review-fix classes.

Six checkers over a shared AST driver (``driver.py``) and best-effort
callgraph (``callgraph.py``):

* ``trace-purity`` — host effects inside jit/shard_map/pallas_call
  bodies (purity.py);
* ``lock-discipline`` — acquisition-order cycles + guarded/unguarded
  mutation splits (locks.py);
* ``cache-key`` — knob reads reachable from cached builders must key
  the cache (cachekey.py);
* ``knob-registry`` — MRTPU_*/SOAK_* knobs route through utils/env.py
  and match doc/settings.md (knobs.py);
* ``metric-catalog`` — mrtpu_* metrics match doc/observability.md
  (metrics_doc.py, formerly scripts/check_metrics_doc.py);
* ``net-timeout`` — outbound network calls in serve/router/client code
  must carry an explicit timeout (nettimeout.py).

CLI: ``scripts/mrlint.py`` (which loads this package standalone so jax
stays cold).  Policy, rule catalog and pragma etiquette: doc/lint.md.

IMPORTANT: nothing in this package may import from the parent package —
the analyzer must run with no side effects in milliseconds.
"""

from .driver import (Finding, Project, RULES, RULE_DOC, load_baseline,
                     run, summary, write_baseline)

# importing the checker modules registers their rules
from . import (cachekey, knobs, locks, metrics_doc,  # noqa: F401,E402
               nettimeout, purity)

__all__ = ["Finding", "Project", "RULES", "RULE_DOC", "run", "summary",
           "load_baseline", "write_baseline"]
