"""mrlint driver: module loading, findings, pragmas, baselines, rule
registry.

The analyzer is PURE AST — it must never import the package it lints
(importing pulls in jax and the import-time metrics/env hooks; a lint
gate has to run in seconds with no side effects).  For the same
reason this package never imports from its parent: ``scripts/mrlint.py``
loads it standalone via importlib so ``gpu_mapreduce_tpu/__init__``
(and jax behind it) stays cold.

Vocabulary shared by every checker:

* :class:`Module` — one parsed source file (relpath, dotted name, AST,
  source lines).
* :class:`Project` — the loaded tree: package modules (analyzed by all
  checkers) plus ``extra`` modules (harness scripts such as soak.py
  that only opted-in checkers scan).
* :class:`Finding` — (rule, path, line, message, symbol), with a
  line-independent fingerprint so baselines survive unrelated edits.
* pragmas — ``# mrlint: disable=rule1,rule2`` (or bare ``disable`` for
  all rules) suppresses findings on its own line, on the whole function
  or class when placed on the ``def``/``class`` line, or on the whole
  file when it appears before the first statement.  Suppressed findings
  are still counted (``--json`` reports them) so a silently growing
  pragma pile stays visible.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

_PRAGMA = re.compile(r"#\s*mrlint:\s*disable(?:=([A-Za-z0-9_,\-]+))?")


@dataclass
class Finding:
    rule: str
    path: str          # project-relative, forward slashes
    line: int
    msg: str
    symbol: str = ""   # enclosing function/class qualname when known
    suppressed: bool = False
    # occurrence index among same-(rule,path,symbol,msg) findings, in
    # file order — assigned by run().  Line numbers would break the
    # baseline on every unrelated edit; with no discriminator at all,
    # one baselined raw read of a knob would suppress every FUTURE raw
    # read of that knob in the same file forever.
    seq: int = 0

    @property
    def fingerprint(self) -> str:
        digest = hashlib.sha1(self.msg.encode()).hexdigest()[:8]
        return f"{self.rule}:{self.path}:{self.symbol}:{digest}:{self.seq}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "msg": self.msg, "symbol": self.symbol,
                "suppressed": self.suppressed,
                "fingerprint": self.fingerprint}

    def __str__(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.msg}{sym}"


@dataclass
class Module:
    relpath: str               # "gpu_mapreduce_tpu/parallel/shuffle.py"
    dotted: str                # "gpu_mapreduce_tpu.parallel.shuffle"
    tree: ast.Module
    lines: List[str]
    # lineno -> set of disabled rules ({"*"} = all)
    pragmas: Dict[int, set] = field(default_factory=dict)
    # all comment-only lines: a pragma on one covers the statement
    # below the comment block (the disable-next-line idiom for
    # statements too long to annotate inline)
    comment_only: set = field(default_factory=set)
    module_pragma: set = field(default_factory=set)
    # (first_line, end_line, def_line) spans of every function/class
    scopes: List[Tuple[int, int, int]] = field(default_factory=list)

    @property
    def source(self) -> str:
        return "\n".join(self.lines)


def _dotted_name(relpath: str) -> str:
    parts = relpath[:-3].split("/")          # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<root>"


def _parse_pragmas(mod: Module) -> None:
    # the module-pragma header extends past a leading docstring: nearly
    # every module here opens with one, and a file-wide pragma reads
    # most naturally right under it
    body = mod.tree.body
    idx = 0
    if body and isinstance(body[0], ast.Expr) \
            and isinstance(body[0].value, ast.Constant) \
            and isinstance(body[0].value.value, str):
        idx = 1
    first_stmt_line = (body[idx].lineno if len(body) > idx
                       else len(mod.lines) + 1)
    for i, text in enumerate(mod.lines, start=1):
        if text.lstrip().startswith("#"):
            mod.comment_only.add(i)
        m = _PRAGMA.search(text)
        if not m:
            continue
        rules = set((m.group(1) or "*").split(","))
        mod.pragmas[i] = rules
        # a pragma above any code (header comment) covers the file; a
        # file-level docstring does not push it out of the header
        if i <= first_stmt_line and text.lstrip().startswith("#"):
            mod.module_pragma |= rules
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            first = min([node.lineno]
                        + [d.lineno for d in node.decorator_list])
            mod.scopes.append((first, node.end_lineno or node.lineno,
                               node.lineno))


def load_module(root: str, relpath: str) -> Optional[Module]:
    path = os.path.join(root, relpath)
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=relpath)
    except (OSError, SyntaxError):
        return None
    mod = Module(relpath.replace(os.sep, "/"), _dotted_name(relpath),
                 tree, src.splitlines())
    _parse_pragmas(mod)
    return mod


class Project:
    """The loaded analysis universe: ``modules`` is the package every
    checker sees; ``extra`` holds harness scripts only opted-in checkers
    (knob registry) scan; ``doc(name)`` reads a doc/ file for the
    code<->doc reconciliation rules."""

    def __init__(self, root: str, package: str = "gpu_mapreduce_tpu",
                 extra_files: Tuple[str, ...] = ()):
        self.root = root
        self.package = package
        self.modules: Dict[str, Module] = {}
        self.extra: Dict[str, Module] = {}
        pkg_dir = os.path.join(root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fname), root)
                mod = load_module(root, rel)
                if mod is not None:
                    self.modules[mod.relpath] = mod
        for rel in extra_files:
            if os.path.exists(os.path.join(root, rel)):
                mod = load_module(root, rel)
                if mod is not None:
                    self.extra[mod.relpath] = mod
        self.by_dotted: Dict[str, Module] = {
            m.dotted: m for m in self.modules.values()}

    def doc(self, name: str) -> Optional[str]:
        path = os.path.join(self.root, "doc", name)
        try:
            with open(path, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def all_modules(self, include_extra: bool = False) -> List[Module]:
        out = list(self.modules.values())
        if include_extra:
            out += list(self.extra.values())
        return out


def _suppressed(mod: Module, finding: Finding) -> bool:
    def covers(rules: set) -> bool:
        return "*" in rules or finding.rule in rules
    if covers(mod.module_pragma):
        return True
    rules = mod.pragmas.get(finding.line)
    if rules and covers(rules):
        return True
    # a comment-only pragma covers the line below it; chains of
    # comment-only lines extend upward (a two-line justification above
    # the flagged statement still counts)
    above = finding.line - 1
    while above in mod.comment_only:
        rules = mod.pragmas.get(above)
        if rules and covers(rules):
            return True
        above -= 1
    # a pragma on (or on a decorator line of, or in the comment block
    # directly above) an enclosing def/class suppresses the whole scope
    for first, end, def_line in mod.scopes:
        if first <= finding.line <= end:
            for line in range(first, def_line + 1):
                rules = mod.pragmas.get(line)
                if rules and covers(rules):
                    return True
            above = first - 1
            while above in mod.comment_only:
                rules = mod.pragmas.get(above)
                if rules and covers(rules):
                    return True
                above -= 1
    return False


# ---------------------------------------------------------------------------
# rule registry + run loop
# ---------------------------------------------------------------------------

# rule name -> checker callable(project) -> List[Finding]; populated by
# register() calls at the bottom of each checker module
RULES: Dict[str, Callable] = {}

# checker docstrings for --list-rules
RULE_DOC: Dict[str, str] = {}

# finding-rule names whose findings are whole-tree invariants (code<->
# doc reconciliation): they must survive the quick gate's changed-file
# report scope — the violation's ATTRIBUTED file is often an unchanged
# code file even when the doc edit caused it (and vice versa)
GLOBAL_FINDINGS: set = set()


def register(name: str, fn: Callable, doc: str = "",
             global_findings: Tuple[str, ...] = ()) -> None:
    RULES[name] = fn
    RULE_DOC[name] = doc
    GLOBAL_FINDINGS.update(global_findings)


def run(project: Project, rules: Optional[List[str]] = None,
        baseline: Optional[set] = None,
        only_paths: Optional[set] = None) -> List[Finding]:
    """Run the selected rules (default: all registered) and apply
    pragma + baseline suppression.  ``only_paths`` restricts REPORTING
    to those relpaths — analysis always sees the whole project, so
    cross-module rules (lock graph, doc reconciliation) stay sound
    under ci.sh quick's changed-file scope."""
    findings: List[Finding] = []
    for name in (rules if rules is not None else sorted(RULES)):
        if name not in RULES:
            raise KeyError(f"unknown rule {name!r} "
                           f"(known: {', '.join(sorted(RULES))})")
        findings.extend(RULES[name](project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    occurrence: Dict[Tuple, int] = {}
    for f in findings:
        key = (f.rule, f.path, f.symbol, f.msg)
        f.seq = occurrence.get(key, 0)
        occurrence[key] = f.seq + 1
    out = []
    for f in findings:
        mod = (project.modules.get(f.path) or project.extra.get(f.path))
        if mod is not None and _suppressed(mod, f):
            f.suppressed = True
        if baseline and f.fingerprint in baseline:
            f.suppressed = True
        if only_paths is not None and f.path not in only_paths \
                and not f.path.startswith("doc/") \
                and f.rule not in GLOBAL_FINDINGS:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def load_baseline(path: str) -> set:
    with open(path) as f:
        data = json.load(f)
    return set(data.get("fingerprints", data) if isinstance(data, dict)
               else data)


def write_baseline(path: str, findings: List[Finding]) -> None:
    fps = sorted({f.fingerprint for f in findings if not f.suppressed})
    with open(path, "w") as f:
        json.dump({"fingerprints": fps}, f, indent=2)
        f.write("\n")


def summary(findings: List[Finding]) -> dict:
    """The --json payload: per-rule counts of live and suppressed
    findings (what ci.sh publishes so counts are trackable across PRs)."""
    by_rule: Dict[str, int] = {}
    nsupp = 0
    for f in findings:
        if f.suppressed:
            nsupp += 1
        else:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {"findings": [f.to_dict() for f in findings],
            "counts": dict(sorted(by_rule.items())),
            "total": sum(by_rule.values()),
            "suppressed": nsupp}
