"""net-timeout: every outbound network call must carry a timeout.

The fleet tier grew a lot of plain-stdlib networking — router proxying,
client retries, replica probes, health checks.  ``urllib`` and
``socket`` default to NO timeout: one unresponsive peer (SYN-blackholed
port, half-dead NAT entry, a daemon wedged mid-accept) turns the
calling thread into a permanent hostage, and in the serve tier that
thread is a worker or the router's proxy path — a daemon-wide stall
with no error, the same failure shape the parallel/dist.py collective
watchdog exists to kill on the data plane.  The rule makes the control
plane hold the same line statically.

Flagged callables (kwarg or the known positional slot both count as
"has a timeout"):

* ``urllib.request.urlopen(url, data=None, timeout=...)`` — pos 3;
* ``socket.create_connection(addr, timeout=...)`` — pos 2;
* ``http.client.HTTPConnection/HTTPSConnection(host, port,
  timeout=...)`` — pos 3;
* ``socket.socket(...).connect`` is NOT flagged (no timeout param —
  the discipline there is ``settimeout`` first, which this rule cannot
  see soundly; ``create_connection`` is the preferred spelling and IS
  covered).

Scope: the serve tier (``gpu_mapreduce_tpu/serve/``), the obs HTTP
daemon, and the opted-in harness scripts (``mrctl.py`` rides along as
the client) — the modules whose threads are daemon-critical.  Library
code elsewhere that grows a socket should move behind one of these or
get the rule extended.

Pragma: ``# mrlint: disable=net-timeout`` on the call line, for the
rare site where blocking forever is the intent (none today).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .callgraph import name_chain
from .driver import Finding, Project, register

# (dotted-suffix chain, human name, 1-based positional slot of timeout)
_CALLS: Tuple[Tuple[Tuple[str, ...], str, int], ...] = (
    (("urllib", "request", "urlopen"), "urllib.request.urlopen", 3),
    (("request", "urlopen"), "urllib.request.urlopen", 3),
    (("urlopen",), "urlopen", 3),
    (("socket", "create_connection"), "socket.create_connection", 2),
    (("create_connection",), "socket.create_connection", 2),
    (("http", "client", "HTTPConnection"), "http.client.HTTPConnection",
     3),
    (("client", "HTTPConnection"), "http.client.HTTPConnection", 3),
    (("HTTPConnection",), "HTTPConnection", 3),
    (("http", "client", "HTTPSConnection"),
     "http.client.HTTPSConnection", 3),
    (("client", "HTTPSConnection"), "http.client.HTTPSConnection", 3),
    (("HTTPSConnection",), "HTTPSConnection", 3),
)


def _match(chain) -> Optional[Tuple[str, int]]:
    if not chain:
        return None
    for suffix, name, pos in _CALLS:
        if tuple(chain[-len(suffix):]) == suffix:
            return name, pos
    return None


def _in_scope(relpath: str) -> bool:
    return ("/serve/" in relpath
            or relpath.endswith("obs/httpd.py"))


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    mods = [m for m in project.all_modules() if _in_scope(m.relpath)]
    mods += list(project.extra.values())
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _match(name_chain(node.func))
            if hit is None:
                continue
            name, pos = hit
            has_kw = any(kw.arg == "timeout" for kw in node.keywords)
            # a **kwargs splat may carry it — trust the splat (the
            # forwarding wrappers in router.py build their kw dicts
            # from sites this rule already checks)
            has_splat = any(kw.arg is None for kw in node.keywords)
            has_pos = len(node.args) >= pos
            if not (has_kw or has_pos or has_splat):
                out.append(Finding(
                    "net-timeout", mod.relpath, node.lineno,
                    f"{name} without an explicit timeout — one "
                    f"unresponsive peer stalls this thread forever "
                    f"(pass timeout=, doc/lint.md#net-timeout)"))
    return out


register(
    "net-timeout", check,
    "outbound network calls reachable from serve/router/client code "
    "must carry an explicit timeout")
