"""knob-registry: every ``MRTPU_*``/``SOAK_*`` knob routes through
``utils/env.py`` and has a row in ``doc/settings.md``.

``utils/env.py`` is the one place knob parsing is allowed to live: the
crash-proof warn-and-fall-back contract (a malformed observability
knob must degrade, never crash the run it was meant to observe) cannot
drift between sites when every read goes through ``env_knob`` /
``env_str`` / ``env_flag``.  A raw ``os.environ.get("MRTPU_...")``
bypasses that contract; an undocumented knob is invisible to operators;
a documented-but-removed knob sends them setting a variable nothing
reads.

Scope: the package plus the harness scripts (soak.py, bench.py,
weakscale.py — Project ``extra`` modules).  Only the reserved
``MRTPU_``/``SOAK_`` namespaces are enforced; legacy ``MR_*``/
``GPUMR_*`` app knobs predate the registry and stay out of it until
renamed.

Rules:

* ``knob-bypass`` — a reserved-namespace knob read via raw
  ``os.environ``/``os.getenv`` outside utils/env.py;
* ``knob-undocumented`` — a knob read anywhere but absent from
  doc/settings.md;
* ``knob-stale`` — a knob documented in doc/settings.md but read
  nowhere in code.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from .callgraph import env_reads, is_env_helper_call
from .driver import Finding, Project, register

_KNOB = re.compile(r"^(MRTPU|SOAK)_[A-Z0-9_]+$")
_DOC_KNOB = re.compile(r"\b(?:MRTPU|SOAK)_[A-Z0-9_]+\b")


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    read_knobs: Dict[str, Tuple[str, int]] = {}

    for mod in project.all_modules(include_extra=True):
        in_registry = mod.relpath.endswith("utils/env.py")
        for knob, node in env_reads(mod.tree):
            if not _KNOB.match(knob):
                continue
            read_knobs.setdefault(knob, (mod.relpath, node.lineno))
            raw = not (isinstance(node, ast.Call)
                       and is_env_helper_call(node))
            if raw and not in_registry:
                out.append(Finding(
                    "knob-bypass", mod.relpath, node.lineno,
                    f"{knob} read via raw os.environ — route through "
                    f"utils/env.py (env_knob/env_str/env_flag) so the "
                    f"warn-and-fall-back contract can't drift"))

    doc = project.doc("settings.md") or ""
    doc_knobs = set(_DOC_KNOB.findall(doc))

    for knob, (rel, line) in sorted(read_knobs.items()):
        if knob not in doc_knobs:
            out.append(Finding(
                "knob-undocumented", rel, line,
                f"{knob} is read here but has no row in "
                f"doc/settings.md — operators can't discover it",
                symbol=knob))

    doc_lines = doc.splitlines()
    for knob in sorted(doc_knobs - set(read_knobs)):
        line = next((i for i, t in enumerate(doc_lines, 1) if knob in t),
                    1)
        out.append(Finding(
            "knob-stale", "doc/settings.md", line,
            f"{knob} is documented but read nowhere in code — setting "
            f"it does nothing", symbol=knob))
    return out


register(
    "knob-registry", check,
    "MRTPU_*/SOAK_* knobs must route through utils/env.py and have a "
    "doc/settings.md row (and doc rows must match live knobs)",
    global_findings=("knob-undocumented", "knob-stale"))
