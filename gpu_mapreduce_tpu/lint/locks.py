"""lock-discipline: acquisition-order cycles + guarded/unguarded
mutation splits.

Two recurring review-fix classes (PRs 6-8 each burned rounds on them):

* **order cycles** — thread A takes L1 then L2, thread B takes L2 then
  L1.  The static lock graph has an edge L1->L2 for every acquisition
  of L2 while L1 is (syntactically or via a resolved call, bounded
  depth) held; any cycle among strongly-identified locks is reported.
* **unguarded mutations** (the PR 6 ``rejects``-counter class) — a
  counter/dict that is mutated under a lock at >=1 site but bare at
  another is a torn-read/lost-update bug by construction.  Grouping is
  per (class, attribute) for ``self.X`` mutations and per (module,
  global) for module globals; ``__init__``/``__new__`` and module
  top-level are construction-time and exempt.

Lock identity:

* module-level ``NAME = threading.Lock()`` (Lock/RLock/Condition/
  Semaphore) -> strong id ``module::NAME``;
* ``self.NAME = threading.Lock()`` anywhere in a class -> strong id
  ``module::Class.NAME``;
* anything else lock-shaped (``with other._lock``) guards mutations in
  its block but does NOT enter the order graph — weak identities across
  classes would fabricate cycles.

Rules: ``lock-order-cycle``, ``lock-unguarded-mutation``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo, get_graph, name_chain
from .driver import Finding, Project, register

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {"append", "extend", "add", "update", "pop", "popitem",
             "remove", "discard", "clear", "setdefault", "insert",
             "move_to_end", "appendleft"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = name_chain(node.func) or ()
    return bool(chain) and chain[-1] in _LOCK_CTORS


class _ModuleLocks:
    """Strong lock identities declared in one module."""

    def __init__(self, mod):
        self.mod = mod
        self.module_locks: Set[str] = set()
        self.class_locks: Dict[str, Set[str]] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attrs = self.class_locks.setdefault(cls.name, set())
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) \
                        and _is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            attrs.add(t.attr)

    def identify(self, expr: ast.AST,
                 scope: Optional[FuncInfo]) -> Optional[Tuple[str, bool]]:
        """(lock_id, strong) for a with-item expr, or None if not
        lock-shaped at all."""
        chain = name_chain(expr)
        if not chain:
            return None
        rel = self.mod.relpath
        if len(chain) == 1:
            if chain[0] in self.module_locks:
                return f"{rel}::{chain[0]}", True
        if chain[0] == "self" and len(chain) == 2 and scope is not None \
                and scope.class_name:
            if chain[1] in self.class_locks.get(scope.class_name, set()):
                return f"{rel}::{scope.class_name}.{chain[1]}", True
        last = chain[-1].lower()
        if "lock" in last or "cv" == last or "cond" in last \
                or "mutex" in last:
            return ".".join(chain), False
        return None


def _mutation_targets(node: ast.AST) -> List[Tuple[str, str, ast.AST]]:
    """(kind, name, node): kind "attr" for self.X, "global" for NAME.
    Covers Assign/AugAssign, subscript stores, and mutating method
    calls."""
    out = []
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            base = t
            if isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                out.append(("attr", base.attr, node))
            elif isinstance(base, ast.Name):
                out.append(("global", base.id, node))
    elif isinstance(node, ast.Call):
        chain = name_chain(node.func)
        if chain and chain[-1] in _MUTATORS:
            if len(chain) == 3 and chain[0] == "self":
                out.append(("attr", chain[1], node))
            elif len(chain) == 2:
                out.append(("global", chain[0], node))
    return out


class _FuncScan:
    """Per-function: direct acquisitions, nested (held -> acquired)
    pairs, calls made while holding locks, and mutation sites."""

    def __init__(self, locks: _ModuleLocks, info: FuncInfo):
        self.acquired: Set[str] = set()          # strong ids
        self.nested: List[Tuple[str, str, ast.AST]] = []
        self.calls_held: List[Tuple[str, Tuple[str, ...], ast.AST]] = []
        # (kind, name, guarded, node)
        self.mutations: List[Tuple[str, str, bool, ast.AST]] = []
        self._locks = locks
        self._info = info
        self._walk(info.node, [])

    def _walk(self, node: ast.AST, held: List[Tuple[str, bool]]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and child is not \
                    self._info.node:
                continue        # nested defs scanned as their own funcs
            if isinstance(child, (ast.With, ast.AsyncWith)):
                ids = []
                for item in child.items:
                    ident = self._locks.identify(item.context_expr,
                                                 self._info)
                    if ident is not None:
                        ids.append(ident)
                for lock_id, strong in ids:
                    if strong:
                        self.acquired.add(lock_id)
                        for held_id, held_strong in held:
                            if held_strong and held_id != lock_id:
                                self.nested.append(
                                    (held_id, lock_id, child))
                self._walk(child, held + ids)
                continue
            if isinstance(child, ast.Call):
                chain = name_chain(child.func)
                if chain and held:
                    for held_id, strong in held:
                        if strong:
                            self.calls_held.append(
                                (held_id, chain, child))
            for kind, nm, mnode in _mutation_targets(child):
                self.mutations.append((kind, nm, bool(held), mnode))
            self._walk(child, held)


def check(project: Project) -> List[Finding]:
    graph = get_graph(project)
    mod_locks = {m.relpath: _ModuleLocks(m)
                 for m in project.modules.values()}
    scans: Dict[str, _FuncScan] = {}
    for info in graph.funcs.values():
        if isinstance(info.node, ast.Lambda):
            continue
        scans[info.key] = _FuncScan(mod_locks[info.module.relpath], info)

    # transitive acquires, bounded: what may be taken inside a call
    trans: Dict[str, Set[str]] = {k: set(s.acquired)
                                  for k, s in scans.items()}
    for _ in range(4):
        changed = False
        for key, scan in scans.items():
            info = graph.funcs[key]
            for callee in graph.callees(info):
                extra = trans.get(callee.key, set()) - trans[key]
                if extra:
                    trans[key] |= extra
                    changed = True
        if not changed:
            break

    # lock graph edges
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}

    def add_edge(a: str, b: str, mod_rel: str, line: int) -> None:
        edges.setdefault(a, {}).setdefault(b, (mod_rel, line))

    for key, scan in scans.items():
        info = graph.funcs[key]
        for a, b, node in scan.nested:
            add_edge(a, b, info.module.relpath, node.lineno)
        for held_id, chain, node in scan.calls_held:
            callee = graph.resolve(info.module, info, chain)
            if callee is None:
                continue
            for b in trans.get(callee.key, ()):
                if b != held_id:
                    add_edge(held_id, b, info.module.relpath, node.lineno)

    out: List[Finding] = []

    # cycle detection (DFS with colors); report each cycle once
    seen_cycles: Set[frozenset] = set()
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(n: str) -> None:
        color[n] = 1
        stack.append(n)
        for m in edges.get(n, {}):
            if color.get(m, 0) == 0:
                dfs(m)
            elif color.get(m) == 1:
                cyc = stack[stack.index(m):] + [m]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    mod_rel, line = edges[n][m]
                    out.append(Finding(
                        "lock-order-cycle", mod_rel, line,
                        "lock acquisition-order cycle: "
                        + " -> ".join(c.split("::")[-1] for c in cyc)
                        + " (full ids: " + " -> ".join(cyc) + ")"))
        stack.pop()
        color[n] = 2

    for n in list(edges):
        if color.get(n, 0) == 0:
            dfs(n)

    # guarded/unguarded mutation splits
    sites: Dict[Tuple, List[Tuple[bool, FuncInfo, ast.AST]]] = {}
    for key, scan in scans.items():
        info = graph.funcs[key]
        fname = info.qual.split(".")[-1]
        if fname in ("__init__", "__new__"):
            continue
        mlocks = mod_locks[info.module.relpath]
        for kind, nm, guarded, node in scan.mutations:
            if kind == "attr":
                if not info.class_name:
                    continue
                # only attributes of classes that own a lock matter
                gkey = ("attr", info.module.relpath, info.class_name, nm)
            else:
                # only module globals assigned at top level qualify
                # (a bare local assignment is not a global mutation)
                if not _is_module_global(info.module, nm):
                    continue
                gkey = ("global", info.module.relpath, nm)
            sites.setdefault(gkey, []).append((guarded, info, node))
    for gkey, entries in sites.items():
        guarded_n = sum(1 for g, _i, _n in entries if g)
        if guarded_n == 0:
            continue
        for g, info, node in entries:
            if g:
                continue
            nm = gkey[-1]
            scope = (f"{gkey[2]}.{nm}" if gkey[0] == "attr"
                     else nm)
            out.append(Finding(
                "lock-unguarded-mutation", info.module.relpath,
                node.lineno,
                f"{scope!r} is mutated under a lock at {guarded_n} "
                f"site(s) but bare here — torn reads / lost updates",
                symbol=info.qual))
    return out


def _is_module_global(mod, name: str) -> bool:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                return True
    return False


register(
    "lock-discipline", check,
    "lock acquisition-order cycles and mutations guarded at one site "
    "but bare at another")
