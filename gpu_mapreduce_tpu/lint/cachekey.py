"""cache-key-completeness: every knob a cached builder reads must key
its cache.

Key discipline (plan/cache.py docstring): every knob that changes a
compiled program's BYTES must be in its cache key — PR 9 threaded
``MRTPU_WIRE`` into all five executable caches BY HAND, which is
exactly the review class this rule automates.  A knob read reachable
from a builder that is NOT derivable from the key means flipping that
knob silently replays a stale executable.

Covered cache shapes (the repo's two idioms):

* ``SOMECACHE.get_or_build(KEY, BUILD)`` (plan/cache.LRUCache) — the
  knob set reachable from ``BUILD`` (lambda or function reference,
  project callgraph, bounded depth) must be a subset of the knob set
  derivable from ``KEY``: env reads syntactically inside the key
  expression, inside local assignments feeding it, or inside functions
  the key expression calls (``wire_enabled()`` in the plan key is the
  canonical example).
* ``@functools.lru_cache`` / ``@lru_cache(...)`` / ``@functools.cache``
  builders — the arguments ARE the key, so ANY env read reachable from
  the body is a finding (read the knob in the caller and pass it in,
  the ``apps/invertedindex._env_knobs`` pattern).
* content-address key builders (``*_key`` / ``*_digest`` functions
  that hash — ``serve/memo.memo_key``, ``plan/cache.
  stable_plan_digest``): their digests name entries in the SHARED
  on-disk store (utils/cas.py), so an env knob that can influence the
  bytes but is not derivable from a returned key expression poisons
  every replica's cache at once, across restarts.

Module-top-level env reads (cache *sizing*, e.g. ``MRTPU_JIT_CACHE``)
never execute inside a builder and are not findings.

Rule: ``cache-key-missing-knob``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .callgraph import (CallGraph, ENV_HELPERS, FuncInfo, env_reads,
                        get_graph, name_chain)
from .driver import Finding, Project, register


def _is_lru_decorator(dec: ast.AST) -> bool:
    chain = name_chain(dec)
    if isinstance(dec, ast.Call):
        chain = name_chain(dec.func)
    return bool(chain) and chain[-1] in ("lru_cache", "cache")


def _reachable_env_reads(graph: CallGraph, roots: List[FuncInfo]
                         ) -> List[Tuple[str, FuncInfo, ast.AST]]:
    out = []
    for info in graph.reachable(roots, max_depth=6):
        if info.qual in ENV_HELPERS:
            # the registry helpers' own os.environ.get(name) reads a
            # NON-LITERAL name ("?"): the actionable finding is at the
            # env_knob("MRTPU_X", ...) call site, which already reports
            continue
        for knob, node in env_reads(info.node):
            out.append((knob, info, node))
    return out


def _roots_of_expr(graph: CallGraph, mod, scope: Optional[FuncInfo],
                   expr: ast.AST) -> List[FuncInfo]:
    roots = []
    if isinstance(expr, ast.Lambda):
        qual = (f"{scope.qual}.<lambda:{expr.lineno}>" if scope
                else f"<lambda:{expr.lineno}>")
        hit = graph.funcs.get(f"{mod.relpath}::{qual}")
        if hit is not None:
            roots.append(hit)
        return roots
    for node in [expr] + list(ast.walk(expr)):
        chain = None
        if isinstance(node, ast.Call):
            chain = name_chain(node.func)
        elif isinstance(node, ast.Name):
            chain = (node.id,)
        if chain:
            hit = graph.resolve(mod, scope, chain)
            if hit is not None and hit not in roots:
                roots.append(hit)
    return roots


def _key_knobs(graph: CallGraph, mod, scope: Optional[FuncInfo],
               key_expr: ast.AST) -> Set[str]:
    """Knob names derivable from the key expression: read directly in
    it, read in local assignments that feed it (3 dataflow rounds), or
    read in functions it calls."""
    knobs: Set[str] = set()
    exprs: List[ast.AST] = [key_expr]
    seen_names: Set[str] = set()
    fn_node = scope.node if scope is not None else mod.tree
    for _ in range(3):
        new_names: Set[str] = set()
        for e in exprs:
            for knob, _node in env_reads(e):
                knobs.add(knob)
            for r in _roots_of_expr(graph, mod, scope, e):
                for knob, _i, _n in _reachable_env_reads(graph, [r]):
                    knobs.add(knob)
            for n in ast.walk(e):
                if isinstance(n, ast.Name) and n.id not in seen_names:
                    new_names.add(n.id)
        if not new_names:
            break
        seen_names |= new_names
        exprs = []
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign):
                hits = any(isinstance(t, ast.Name) and t.id in new_names
                           for t in node.targets)
                if hits:
                    exprs.append(node.value)
        # function parameters named in the key are the CALLER's
        # responsibility — a knob passed in as an argument is keyed by
        # construction, nothing further to derive here
        if not exprs:
            break
    return knobs


def check(project: Project) -> List[Finding]:
    graph = get_graph(project)
    out: List[Finding] = []

    # idiom 1: CACHE.get_or_build(KEY, BUILD)
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if not chain or chain[-1] != "get_or_build" \
                    or len(node.args) < 2:
                continue
            scope = graph.enclosing(mod, node)
            key_expr, build_expr = node.args[0], node.args[1]
            build_roots = _roots_of_expr(graph, mod, scope, build_expr)
            if not build_roots:
                continue
            keyed = _key_knobs(graph, mod, scope, key_expr)
            for knob, info, read in _reachable_env_reads(
                    graph, build_roots):
                if knob in keyed:
                    continue
                out.append(Finding(
                    "cache-key-missing-knob", info.module.relpath,
                    read.lineno,
                    f"env knob {knob!r} is read in code reachable from "
                    f"the builder cached at "
                    f"{mod.relpath}:{node.lineno} but does not appear "
                    f"in its cache key — flipping it replays a stale "
                    f"executable",
                    symbol=info.qual))

    # idiom 2: functools.lru_cache builders (the args ARE the key)
    for info in graph.funcs.values():
        node = info.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not any(_is_lru_decorator(d) for d in node.decorator_list):
            continue
        for knob, rinfo, read in _reachable_env_reads(graph, [info]):
            out.append(Finding(
                "cache-key-missing-knob", rinfo.module.relpath,
                read.lineno,
                f"env knob {knob!r} is read inside (or reachable from) "
                f"lru_cache'd builder {info.qual!r} "
                f"({info.module.relpath}:{node.lineno}) whose arguments "
                f"are its cache key — read it in the caller and pass it "
                f"in",
                symbol=rinfo.qual))

    # idiom 3: content-address key builders.  A *_key / *_digest
    # function that hashes builds a CONTENT ADDRESS shared fleet-wide
    # through the CAS store — a knob it (or anything it calls) reads
    # must be derivable from a return expression, else flipping the
    # knob serves stale store entries on every replica at once.
    hashers = ("sha256", "sha256_bytes", "sha256_file", "md5",
               "blake2b", "crc32")
    for info in graph.funcs.values():
        node = info.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not (node.name.endswith("_key")
                or node.name.endswith("_digest")):
            continue
        if not any(isinstance(n, ast.Call) and
                   (name_chain(n.func) or ("",))[-1] in hashers
                   for n in ast.walk(node)):
            continue
        keyed: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Return) and n.value is not None:
                keyed |= _key_knobs(graph, info.module, info, n.value)
        for knob, rinfo, read in _reachable_env_reads(graph, [info]):
            if knob in keyed:
                continue
            out.append(Finding(
                "cache-key-missing-knob", rinfo.module.relpath,
                read.lineno,
                f"env knob {knob!r} is readable from content-address "
                f"key builder {info.qual!r} "
                f"({info.module.relpath}:{node.lineno}) but is not "
                f"derivable from its returned key expression — "
                f"replicas sharing the store would keep serving "
                f"entries the knob should have invalidated",
                symbol=rinfo.qual))
    return out


register(
    "cache-key", check,
    "env knobs readable from a cached builder must appear in (or be "
    "derivable from) its cache key")
