"""Native C++ host runtime — ctypes loader and numpy-facing wrappers.

The reference's host hot paths are C++ (hashing ``src/hash.cpp``, file
parsing ``oink/map_read_*.cpp``, the InvertedIndex FSM
``cpu/InvertedIndex.cpp``); ours live in ``mrnative.cpp`` next to this
file, compiled lazily with the baked-in ``g++`` the first time the
package is imported (no pybind11 in the image — plain ``extern "C"`` +
ctypes, see environment notes).  Every wrapper has a pure-Python/numpy
fallback, so the framework works identically when no compiler exists —
``available()`` tells which path is live, and callers (ops/hash.py,
oink/kernels.py, apps/invertedindex.py) branch on it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "mrnative.cpp")
_SO = os.path.join(_DIR, f"mrnative-{sys.implementation.cache_tag}.so")

_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> Optional[str]:
    """Compile mrnative.cpp → .so; returns an error string or None."""
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"{cxx}: {e}"
    if proc.returncode != 0:
        return proc.stderr.strip() or f"{cxx} failed"
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _build_error
    have_src = os.path.exists(_SRC)
    stale = (have_src and os.path.exists(_SO)
             and os.path.getmtime(_SO) < os.path.getmtime(_SRC))
    if not os.path.exists(_SO) or stale:
        if not have_src:  # .so absent and nothing to build from
            _build_error = f"{_SRC} missing"
            return None
        _build_error = _build()
        if _build_error is not None:
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:  # pragma: no cover
        _build_error = str(e)
        return None
    i64, u32, u64 = ctypes.c_int64, ctypes.c_uint32, ctypes.c_uint64
    p = ctypes.POINTER
    u8p = p(ctypes.c_uint8)
    lib.mr_hashlittle.restype = u32
    lib.mr_hashlittle.argtypes = [u8p, i64, u32]
    lib.mr_hashlittle_batch.restype = None
    lib.mr_hashlittle_batch.argtypes = [u8p, p(i64), i64, u32, p(u32)]
    lib.mr_intern64_batch.restype = None
    lib.mr_intern64_batch.argtypes = [u8p, p(i64), i64, p(u64)]
    lib.mr_intern_ranges.argtypes = [u8p, p(i64), p(i64), i64, u32, u32,
                                     p(u64)]
    lib.mr_intern_ranges.restype = None
    lib.mr_intern_ranges2.argtypes = [u8p, p(i64), p(i64), i64, u32, u32,
                                      u32, u32, p(u64), p(u64)]
    lib.mr_intern_ranges2.restype = None
    lib.mr_parse_table.restype = i64
    lib.mr_parse_table.argtypes = [u8p, i64, i64, p(ctypes.c_int32),
                                   p(ctypes.c_void_p), i64]
    lib.mr_find_hrefs.restype = i64
    lib.mr_find_hrefs.argtypes = [u8p, i64, p(i64), p(i64), i64]
    lib.mr_tokenize.restype = i64
    lib.mr_tokenize.argtypes = [u8p, i64, p(i64), p(i64), i64]
    return lib


def available() -> bool:
    return _lib is not None


def build_error() -> Optional[str]:
    return _build_error


def _u8(buf: bytes):
    return ctypes.cast(ctypes.c_char_p(buf), ctypes.POINTER(ctypes.c_uint8))


def _arr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# wrappers (callers must check available() first)
# ---------------------------------------------------------------------------

def hashlittle(data: bytes, initval: int = 0) -> int:
    return int(_lib.mr_hashlittle(_u8(data), len(data), initval))


def hashlittle_batch(buf: bytes, offsets: np.ndarray,
                     initval: int = 0) -> np.ndarray:
    """Hash n packed byte strings; offsets is int64[n+1]."""
    n = len(offsets) - 1
    offsets = np.ascontiguousarray(offsets, np.int64)
    out = np.empty(n, np.uint32)
    _lib.mr_hashlittle_batch(_u8(buf), _arr(offsets, ctypes.c_int64), n,
                             initval, _arr(out, ctypes.c_uint32))
    return out


def intern_ranges(buf: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                  seed_hi: int = 0, seed_lo: int = 0xDEADBEEF) -> np.ndarray:
    """u64 ids over (start, len) ranges of ``buf`` — zero-copy interning
    straight out of a file buffer (default seeds = the intern family of
    hash_bytes64; alternate seeds = an independent check family)."""
    n = len(starts)
    starts = np.ascontiguousarray(starts, np.int64)
    lens = np.ascontiguousarray(lens, np.int64)
    out = np.empty(n, np.uint64)
    if isinstance(buf, np.ndarray):
        ptr = _arr(np.ascontiguousarray(buf, np.uint8), ctypes.c_uint8)
    else:
        ptr = _u8(buf)
    _lib.mr_intern_ranges(ptr, _arr(starts, ctypes.c_int64),
                          _arr(lens, ctypes.c_int64), n, seed_hi, seed_lo,
                          _arr(out, ctypes.c_uint64))
    return out


def intern_ranges2(buf: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                   alt_hi: int, alt_lo: int) -> Tuple[np.ndarray, np.ndarray]:
    """Both u64 id families over (start, len) ranges in one pass over
    ``buf``: (intern ids, alt-family check ids).  Equivalent to two
    :func:`intern_ranges` calls but reads each URL byte once."""
    n = len(starts)
    starts = np.ascontiguousarray(starts, np.int64)
    lens = np.ascontiguousarray(lens, np.int64)
    out0 = np.empty(n, np.uint64)
    out1 = np.empty(n, np.uint64)
    if isinstance(buf, np.ndarray):
        ptr = _arr(np.ascontiguousarray(buf, np.uint8), ctypes.c_uint8)
    else:
        ptr = _u8(buf)
    _lib.mr_intern_ranges2(ptr, _arr(starts, ctypes.c_int64),
                           _arr(lens, ctypes.c_int64), n, 0, 0xDEADBEEF,
                           alt_hi, alt_lo, _arr(out0, ctypes.c_uint64),
                           _arr(out1, ctypes.c_uint64))
    return out0, out1


def intern64_batch(buf: bytes, offsets: np.ndarray) -> np.ndarray:
    """String → u64 intern ids (ops/hash.py hash_bytes64 semantics)."""
    n = len(offsets) - 1
    offsets = np.ascontiguousarray(offsets, np.int64)
    out = np.empty(n, np.uint64)
    _lib.mr_intern64_batch(_u8(buf), _arr(offsets, ctypes.c_int64), n,
                           _arr(out, ctypes.c_uint64))
    return out


def parse_table(buf: bytes, dtypes) -> List[np.ndarray]:
    """Parse a whitespace table of len(dtypes) columns; dtype entries are
    np.uint64 or np.float64.  Returns one array per column; raises
    ValueError on malformed input (same contract as kernels._parse_cols)."""
    ncols = len(dtypes)
    spec = np.array([0 if dt == np.uint64 else 1 for dt in dtypes],
                    np.int32)
    cap = max(16, len(buf) // (2 * ncols))
    while True:
        cols = [np.empty(cap, dt) for dt in dtypes]
        ptrs = (ctypes.c_void_p * ncols)(
            *[c.ctypes.data_as(ctypes.c_void_p) for c in cols])
        n = _lib.mr_parse_table(_u8(buf), len(buf), ncols,
                                _arr(spec, ctypes.c_int32), ptrs, cap)
        if n == -1:
            raise ValueError("malformed numeric table")
        if n >= 0:
            return [c[:n] for c in cols]
        cap = -n


def find_hrefs(buf) -> Tuple[np.ndarray, np.ndarray]:
    """URL (starts, lens) of every `<a href="..."` match — the host
    equivalent of the Pallas mark/extract pipeline.  ``buf``: bytes or a
    uint8 ndarray (passed zero-copy)."""
    if isinstance(buf, np.ndarray):
        ptr = _arr(np.ascontiguousarray(buf, np.uint8), ctypes.c_uint8)
    else:
        ptr = _u8(buf)
    cap = max(16, len(buf) // 64)
    while True:
        starts = np.empty(cap, np.int64)
        lens = np.empty(cap, np.int64)
        n = _lib.mr_find_hrefs(ptr, len(buf),
                               _arr(starts, ctypes.c_int64),
                               _arr(lens, ctypes.c_int64), cap)
        if n >= 0:
            return starts[:n], lens[:n]
        cap = -n


def tokenize(buf) -> Tuple[np.ndarray, np.ndarray]:
    """(starts, lens) of every whitespace-separated token — the host
    tokenizer behind wordfreq/read_words ingestion (pairs with
    intern_ranges for zero-per-token-Python word ids)."""
    if isinstance(buf, np.ndarray):
        ptr = _arr(np.ascontiguousarray(buf, np.uint8), ctypes.c_uint8)
    else:
        ptr = _u8(buf)
    cap = max(16, len(buf) // 4)
    while True:
        starts = np.empty(cap, np.int64)
        lens = np.empty(cap, np.int64)
        n = _lib.mr_tokenize(ptr, len(buf),
                             _arr(starts, ctypes.c_int64),
                             _arr(lens, ctypes.c_int64), cap)
        if n >= 0:
            return starts[:n], lens[:n]
        cap = -n


_lib = _load()
