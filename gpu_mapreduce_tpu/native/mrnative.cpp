// mrnative — host-side C++ runtime for the TPU MapReduce framework.
//
// The reference keeps its host hot paths in C++: lookup3 hashing
// (src/hash.cpp), byte-packed KV ingestion (src/keyvalue.cpp), file/word
// parsing in map callbacks (oink/map_read_*.cpp), and the CPU
// InvertedIndex href FSM (cpu/InvertedIndex.cpp:144-265).  This library is
// their TPU-framework equivalent: the device work is JAX/Pallas, and the
// host-side ingestion/hashing that feeds it runs here instead of in
// Python loops.  Python binds via ctypes (gpu_mapreduce_tpu/native/
// __init__.py); every entry point is extern "C" with flat buffers.
//
// Build: g++ -O3 -shared -fPIC mrnative.cpp -o mrnative.so  (done lazily
// by the loader; no external dependencies).

#include <cstdint>
#include <cstring>
#include <cstdlib>

namespace {

// ---------------------------------------------------------------------------
// lookup3 hashlittle (Bob Jenkins, public domain algorithm; reference
// src/hash.cpp:104-228).  Byte-at-a-time formulation — bit-identical to
// the aligned-read C original on little-endian hosts and to the Python
// port in ops/hash.py.
// ---------------------------------------------------------------------------

inline uint32_t rot(uint32_t x, int k) { return (x << k) | (x >> (32 - k)); }

inline void mix(uint32_t &a, uint32_t &b, uint32_t &c) {
  a -= c; a ^= rot(c, 4);  c += b;
  b -= a; b ^= rot(a, 6);  a += c;
  c -= b; c ^= rot(b, 8);  b += a;
  a -= c; a ^= rot(c, 16); c += b;
  b -= a; b ^= rot(a, 19); a += c;
  c -= b; c ^= rot(b, 4);  b += a;
}

inline void final_mix(uint32_t &a, uint32_t &b, uint32_t &c) {
  c ^= b; c -= rot(b, 14);
  a ^= c; a -= rot(c, 11);
  b ^= a; b -= rot(a, 25);
  c ^= b; c -= rot(b, 16);
  a ^= c; a -= rot(c, 4);
  b ^= a; b -= rot(a, 14);
  c ^= b; c -= rot(b, 24);
}

inline uint32_t load_le32(const uint8_t *p, int64_t avail) {
  uint32_t v = 0;
  for (int i = 0; i < 4 && i < avail; i++) v |= uint32_t(p[i]) << (8 * i);
  return v;
}

uint32_t hashlittle(const uint8_t *key, int64_t length, uint32_t initval) {
  uint32_t a, b, c;
  a = b = c = 0xDEADBEEFu + uint32_t(length) + initval;
  const uint8_t *k = key;
  while (length > 12) {
    a += load_le32(k, 4);
    b += load_le32(k + 4, 4);
    c += load_le32(k + 8, 4);
    mix(a, b, c);
    k += 12;
    length -= 12;
  }
  if (length == 0) return c;
  a += load_le32(k, length);
  b += load_le32(k + 4, length - 4);
  c += load_le32(k + 8, length - 8);
  final_mix(a, b, c);
  return c;
}

inline bool is_space(uint8_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == '\v';
}

}  // namespace

extern "C" {

// single hash (parity with ops/hash.py hashlittle)
uint32_t mr_hashlittle(const uint8_t *key, int64_t len, uint32_t initval) {
  return hashlittle(key, len, initval);
}

// hash n byte strings packed in `buf` at `offsets` (n+1 entries) → u32
void mr_hashlittle_batch(const uint8_t *buf, const int64_t *offsets,
                         int64_t n, uint32_t initval, uint32_t *out) {
  for (int64_t i = 0; i < n; i++)
    out[i] = hashlittle(buf + offsets[i], offsets[i + 1] - offsets[i],
                        initval);
}

// 64-bit intern ids: (hashlittle(s,0) << 32) | hashlittle(s,0xDEADBEEF)
// (ops/hash.py hash_bytes64 — string→u64 interning for the device path)
void mr_intern64_batch(const uint8_t *buf, const int64_t *offsets,
                       int64_t n, uint64_t *out) {
  for (int64_t i = 0; i < n; i++) {
    const uint8_t *p = buf + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    uint64_t hi = hashlittle(p, len, 0);
    uint64_t lo = hashlittle(p, len, 0xDEADBEEFu);
    out[i] = (hi << 32) | lo;
  }
}

// 64-bit ids over (start, len) ranges of one buffer — the zero-copy
// variant of mr_intern64_batch: the InvertedIndex native tier hashes
// URLs straight out of the file buffer, no per-URL Python slicing or
// repacking (the reference's map callback likewise works in place on
// its chunk buffer, cpu/InvertedIndex.cpp:144-265).  Seeds select the
// id family: (0, 0xDEADBEEF) is the intern family shared with the
// device tier; alternate seeds give the independent collision-check
// family (apps/invertedindex.py).
void mr_intern_ranges(const uint8_t *buf, const int64_t *starts,
                      const int64_t *lens, int64_t n, uint32_t seed_hi,
                      uint32_t seed_lo, uint64_t *out) {
  for (int64_t i = 0; i < n; i++) {
    const uint8_t *p = buf + starts[i];
    uint64_t hi = hashlittle(p, lens[i], seed_hi);
    uint64_t lo = hashlittle(p, lens[i], seed_lo);
    out[i] = (hi << 32) | lo;
  }
}

// both 64-bit id families over (start, len) ranges in ONE pass over the
// bytes: the intern family (seed0_hi/lo) and the independent collision-
// check family (seed1_hi/lo) run four interleaved lookup3 states off
// shared word loads — the InvertedIndex native tier at URL_DICT_MAX
// scale needs both ids per URL, and two mr_intern_ranges calls read
// every URL byte twice (VERDICT r3 weak #1: the doubled map-stage hash
// cost sat inside the timed host_add group).
void mr_intern_ranges2(const uint8_t *buf, const int64_t *starts,
                       const int64_t *lens, int64_t n,
                       uint32_t seed0_hi, uint32_t seed0_lo,
                       uint32_t seed1_hi, uint32_t seed1_lo,
                       uint64_t *out0, uint64_t *out1) {
  const uint32_t seeds[4] = {seed0_hi, seed0_lo, seed1_hi, seed1_lo};
  for (int64_t i = 0; i < n; i++) {
    const uint8_t *k = buf + starts[i];
    int64_t length = lens[i];
    uint32_t A[4], B[4], C[4];
    for (int j = 0; j < 4; j++)
      A[j] = B[j] = C[j] = 0xDEADBEEFu + uint32_t(length) + seeds[j];
    while (length > 12) {
      uint32_t w0 = load_le32(k, 4);
      uint32_t w1 = load_le32(k + 4, 4);
      uint32_t w2 = load_le32(k + 8, 4);
      for (int j = 0; j < 4; j++) {
        A[j] += w0; B[j] += w1; C[j] += w2;
        mix(A[j], B[j], C[j]);
      }
      k += 12;
      length -= 12;
    }
    if (length != 0) {
      uint32_t w0 = load_le32(k, length);
      uint32_t w1 = load_le32(k + 4, length - 4);
      uint32_t w2 = load_le32(k + 8, length - 8);
      for (int j = 0; j < 4; j++) {
        A[j] += w0; B[j] += w1; C[j] += w2;
        final_mix(A[j], B[j], C[j]);
      }
    }  // length == 0: lookup3 returns c un-finalised, same as hashlittle
    out0[i] = (uint64_t(C[0]) << 32) | C[1];
    out1[i] = (uint64_t(C[2]) << 32) | C[3];
  }
}

// numeric table parser (read_edge / read_edge_weight ingestion):
// whitespace-separated tokens parsed round-robin per column; colspec[j]:
// 0 = u64 (exact integer parse), 1 = f64 (strtod).  cols[j] points at a
// u64- or f64-sized output array with capacity maxrows.  Returns row
// count, -1 on malformed input (bad char / token count not divisible),
// or -needed when maxrows is too small.
int64_t mr_parse_table(const uint8_t *buf, int64_t len, int64_t ncols,
                       const int32_t *colspec, void **cols,
                       int64_t maxrows) {
  int64_t ntok = 0, i = 0;
  while (i < len) {
    while (i < len && is_space(buf[i])) i++;
    if (i >= len) break;
    int64_t s = i;
    while (i < len && !is_space(buf[i])) i++;
    int64_t col = ntok % ncols, row = ntok / ncols;
    if (row < maxrows) {
      if (colspec[col] == 0) {
        int64_t p = s;
        if (p < i && buf[p] == '+') p++;          // fallback accepts '+5'
        while (p < i - 1 && buf[p] == '0') p++;   // and zero-padding
        if (p >= i || i - p > 20) return -1;      // u64 max is 20 digits
        uint64_t v = 0;
        for (; p < i; p++) {
          uint8_t c = buf[p];
          if (c < '0' || c > '9') return -1;
          uint64_t next = v * 10u + (c - '0');
          if (next / 10u != v) return -1;         // overflow: error, never
          v = next;                               // wrap (fallback raises)
        }
        ((uint64_t *)cols[col])[row] = v;
      } else {
        char tmp[64];
        if (i - s == 0 || i - s >= 63) return -1;  // no f64 literal needs more
        int64_t tl = i - s;
        // decimal literals plus inf/nan (which the numpy fallback also
        // accepts) — but not strtod's hex or partial-token forms
        int64_t body = (buf[s] == '+' || buf[s] == '-') ? 1 : 0;
        int is_special = 0;
        if (tl - body == 3 &&
            (memcmp(buf + s + body, "inf", 3) == 0 ||
             memcmp(buf + s + body, "nan", 3) == 0))
          is_special = 1;
        if (tl - body == 8 && memcmp(buf + s + body, "infinity", 8) == 0)
          is_special = 1;
        if (!is_special)
          for (int64_t p = 0; p < tl; p++) {
            char c = buf[s + p];
            if (!((c >= '0' && c <= '9') || c == '.' || c == '+' ||
                  c == '-' || c == 'e' || c == 'E'))
              return -1;
          }
        memcpy(tmp, buf + s, tl);
        tmp[tl] = '\0';
        char *endp = nullptr;
        double v = strtod(tmp, &endp);
        // full-token consumption: '1.5abc' is malformed like the fallback
        if (endp != tmp + tl) return -1;
        ((double *)cols[col])[row] = v;
      }
    }
    ntok++;
  }
  if (ntok % ncols) return -1;
  int64_t rows = ntok / ncols;
  return rows <= maxrows ? rows : -rows;
}

// whitespace tokenizer — (start, len) of every token, the host hot path
// of the wordfreq/read_words ingestion (oink/map_read_words.cpp splits
// per word in its callback; doing it here removes the per-token Python
// object churn when paired with mr_intern_ranges).  Same whitespace set
// as is_space/bytes.split.  Returns count or -needed.
int64_t mr_tokenize(const uint8_t *buf, int64_t len, int64_t *starts,
                    int64_t *lens, int64_t max) {
  int64_t n = 0, i = 0;
  while (i < len) {
    while (i < len && is_space(buf[i])) i++;
    if (i >= len) break;
    int64_t s = i;
    while (i < len && !is_space(buf[i])) i++;
    if (n < max) { starts[n] = s; lens[n] = i - s; }
    n++;
  }
  return n <= max ? n : -n;
}

// href-URL extraction — the host equivalent of the CUDA mark /
// compute_url_length kernels (cuda/InvertedIndex.cu:79-135) and the CPU
// FSM parser (cpu/InvertedIndex.cpp:144-265): find every `<a href="`,
// record the URL [start,len) up to the closing quote.  Returns count or
// -needed.
int64_t mr_find_hrefs(const uint8_t *buf, int64_t len, int64_t *starts,
                      int64_t *lens, int64_t max) {
  static const char pat[] = "<a href=\"";
  const int64_t plen = 9;
  int64_t n = 0;
  // memchr-driven: jump '<' to '<' (SIMD in libc) instead of a
  // memcmp at every byte — the scan runs at memory bandwidth on
  // tag-sparse text and still wins on dense HTML
  for (int64_t i = 0; i + plen <= len; ) {
    const void *hit = memchr(buf + i, '<', len - plen - i + 1);
    if (hit == nullptr) break;
    i = (const uint8_t *)hit - buf;
    if (i + plen > len) break;
    if (memcmp(buf + i, pat, plen) == 0) {
      int64_t s = i + plen;
      const void *q = memchr(buf + s, '"', len - s);
      if (q == nullptr) break;
      int64_t e = (const uint8_t *)q - buf;
      if (n < max) { starts[n] = s; lens[n] = e - s; }
      n++;
    }
    // advance one byte only: the device mark kernel flags every pattern
    // position, and a match can legally start inside a prior URL span
    i++;
  }
  return n <= max ? n : -n;
}

}  // extern "C"
