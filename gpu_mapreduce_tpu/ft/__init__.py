"""Fault tolerance — injection, retry policy, journaled resume.

The reference MR-MPI has NO fault tolerance: page files are scratch
state and the only recovery is a full re-run (SURVEY.md §5,
``core/checkpoint.py:3-5``).  This package is the policy layer that
turns the existing durability building blocks (atomic checkpoints,
``exec/spill.atomic_save``, the obs flight recorder) into survivable
failures, in three pillars:

* **deterministic fault injection** (:mod:`.inject`) — named fault
  points at the real failure sites (``ingest.read``,
  ``ingest.tokenize``, ``spill.write``, ``spill.read``,
  ``shuffle.exchange``, ``checkpoint.save``), armed by a seeded
  schedule (``MRTPU_FAULTS`` or :func:`schedule`), one bool check when
  disarmed;
* **retry / backoff policy** (:mod:`.retry`) — per-site transient-vs-
  fatal classification, bounded retries with exponential backoff +
  jitter (``MRTPU_RETRY``), the ``onfault`` dataset setting
  (``fail`` | ``retry`` | ``skip``-with-quarantine), ``MRError`` +
  flight-recorder dump on exhaustion;
* **journaled auto-checkpoint + resume** (:mod:`.journal`) — an
  append-only fsync'd op journal (``MRTPU_JOURNAL=dir``), automatic
  checkpoints every ``MRTPU_CKPT_EVERY`` ops, and :func:`resume` /
  OINK ``resume <dir>`` replaying an interrupted script from the last
  durable checkpoint.

The golden contract mirrors exec/: any fault schedule that the retry
budget absorbs must leave output BYTE-IDENTICAL to the fault-free run
(``tests/test_ft.py``), and with everything disarmed the whole package
costs one bool check per site probe.

Observability: ``ft.retry`` / ``ft.inject`` spans,
``mrtpu_retries_total{site,outcome}`` /
``mrtpu_faults_injected_total{site}`` /
``mrtpu_quarantined_total{site}`` counters (obs/metrics.py collector),
and the ``mr.stats()["ft"]`` section (:func:`ft_stats`).  Knob table
and runbook: ``doc/reliability.md``.
"""

from __future__ import annotations

from .inject import (SITES, FaultSpec, InjectedFault, InjectedFatal,
                     clear_faults, counts as fault_counts, fault_point,
                     parse_faults, schedule)
from .retry import (budget, classify, ingest_task, parse_retry,
                    quarantine_snapshot, retries_snapshot, retry_call,
                    set_budget)
from .journal import Journal, latest_checkpoint, read_journal, resume

__all__ = [
    "SITES", "FaultSpec", "InjectedFault", "InjectedFatal",
    "schedule", "clear_faults", "fault_point", "parse_faults",
    "fault_counts",
    "retry_call", "set_budget", "budget", "classify", "parse_retry",
    "ingest_task", "retries_snapshot", "quarantine_snapshot",
    "Journal", "resume", "read_journal", "latest_checkpoint",
    "configure_from_env", "ft_stats", "counters_snapshot", "reset",
]


def configure_from_env() -> None:
    """Apply ``MRTPU_FAULTS`` / ``MRTPU_RETRY`` / ``MRTPU_JOURNAL``
    when they changed — called from every ``MapReduce()`` construction
    (three getenv+compare when nothing changed)."""
    from . import inject as _inject, journal as _journal, retry as _retry
    _inject.configure_from_env()
    _retry.configure_from_env()
    _journal.configure_from_env()


def counters_snapshot() -> dict:
    """The raw cumulative counters (the obs/metrics collector's pull
    source): retries by (site, outcome), faults and quarantines by
    site."""
    from . import inject as _inject, retry as _retry
    q = _retry.quarantine_snapshot()
    return {"retries": _retry.retries_snapshot(),
            "faults": _inject.counts(),
            "quarantined": q["by_site"]}


def ft_stats() -> dict:
    """The ``mr.stats()["ft"]`` section: retry outcomes per site, faults
    injected per site, quarantine accounting, journal progress."""
    from . import inject as _inject, journal as _journal, retry as _retry
    retries: dict = {}
    for (site, outcome), n in _retry.retries_snapshot().items():
        retries.setdefault(site, {})[outcome] = n
    j = _journal.active()
    return {"retries": retries,
            "faults_injected": _inject.counts(),
            "quarantined": _retry.quarantine_snapshot(),
            "budgets": {s: _retry.budget(s) for s in SITES
                        if _retry.budget(s)},
            "journal": j.stats() if j is not None else None}


def reset() -> None:
    """Test isolation: disarm injection, drop budgets/counters/
    quarantine, close the active journal."""
    from . import inject as _inject, journal as _journal, retry as _retry
    _inject.clear_faults()
    _retry.reset()
    _journal.reset()
