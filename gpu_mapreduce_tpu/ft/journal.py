"""Journaled auto-checkpoint + resume.

The reference's only recovery story is re-running the job from scratch
(``core/checkpoint.py:3-5``).  With atomic dataset checkpoints
(``core/checkpoint.py``) already in place, this module adds the policy
layer that makes a crash at ANY point survivable:

* an **append-only JSONL op journal** under ``MRTPU_JOURNAL=dir``
  (``journal.jsonl``): a ``begin`` record capturing the script's lines,
  one ``cmd`` record per completed script command, one ``op`` record
  per completed MapReduce barrier op (forensics), and a ``ckpt`` record
  per durable checkpoint set.  Every append is flushed + fsync'd BEFORE
  the run proceeds, and every record is written only AFTER the thing it
  describes completed — so the journal never claims work that did not
  durably happen.
* **auto-checkpointing** every ``MRTPU_CKPT_EVERY`` completed commands
  (default 5): every named MR saves through ``core/checkpoint.py``'s
  atomic directory swap into ``dir/ckpt-<seq>/<name>``; the ``ckpt``
  record lands only after ALL saves succeeded, so a crash mid-
  checkpoint leaves the previous record as the durable truth.  Non-
  script (programmatic) runs auto-checkpoint the reporting MapReduce
  into the single ``dir/auto`` slot every N ops instead.
* **resume** — ``ft.resume(dir)`` in code or the OINK builtin
  ``resume <dir>``: re-runs the recorded script lines, SKIPPING the
  first K command executions (K = the last checkpoint's sequence
  number; builtins like ``variable``/``set``/``mr``/``jump`` re-execute
  so loop variables and control flow reproduce exactly), restores every
  named MR from the checkpoint at the skip boundary, then continues
  live — journaling into the same directory, so a resumed run is
  itself resumable.

Everything here is plain files: resume needs no state from the crashed
process, which is what "kill -9 at any point" safety means.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

from ..core.runtime import MRError

_FILE = "journal.jsonl"

_LOCK = threading.Lock()
_ACTIVE: Optional["Journal"] = None


def _rec_crc(body: str) -> str:
    """crc of a record's serialized payload (its ``"c"`` field)."""
    import zlib
    return f"{zlib.crc32(body.encode()) & 0xFFFFFFFF:08x}"


def _rec_valid(rec: dict) -> bool:
    """Verify a parsed record against its own ``"c"`` stamp; records
    written before the integrity layer (no ``"c"``) pass — absence is
    back-compat, mismatch is corruption."""
    c = rec.get("c")
    if c is None:
        return True
    body = json.dumps({k: v for k, v in rec.items() if k != "c"},
                      default=str)
    return _rec_crc(body) == c


class Journal:
    """One append-only journal + its checkpoint directory."""

    def __init__(self, dir: str, script_mode: bool = False,
                 every: Optional[int] = None):
        from ..utils.env import env_knob
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.path = os.path.join(dir, _FILE)
        # seal a torn tail from a previous crash BEFORE appending: a
        # partial final line with no newline (kill -9 mid-append) would
        # otherwise MERGE with our first record into one unparseable
        # line, losing that record to every future read
        sealed = True
        try:
            with open(self.path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                sealed = f.read(1) == b"\n"
        except (OSError, ValueError):
            pass        # missing or empty file — nothing to seal
        created = not os.path.exists(self.path)
        self._f = open(self.path, "a")
        if created:
            # make the journal FILE's directory entry durable at birth:
            # its first ckpt record is worthless if a crash can lose
            # the file name itself (utils/fsio rename-durability rule)
            from ..utils.fsio import fsync_dir
            fsync_dir(dir)
        if not sealed:
            self._f.write("\n")
            self._f.flush()
        self.script_mode = script_mode
        self.every = max(1, every if every is not None
                         else env_knob("MRTPU_CKPT_EVERY", int, 5))
        self.cmd_seq = 0          # completed script-command executions
        self.op_seq = 0           # completed MR barrier ops
        self.nckpt = 0
        self._since = 0           # cmds (or ops) since last checkpoint
        self._wlock = threading.Lock()

    # -- append -------------------------------------------------------------
    def append(self, rec: dict, sync: bool = True) -> None:
        """Durable append: the record is on disk when this returns (the
        whole design rests on records never leading their facts).
        ``sync=False`` skips the fsync — for FORENSIC records nothing
        replays from (op records), so an iterative workload doesn't
        serialize on one disk flush per barrier op.

        Every record carries a ``"c"`` crc of its own serialized
        payload (utils/integrity.py): a bit-flipped or half-torn line
        is QUARANTINED by :func:`read_journal` instead of replayed —
        the journal never claims work a corrupt record describes.

        Records also carry the active request's ``"trace"`` id
        (obs/context.py) unless the caller already stamped one — the
        link that lets ``trace_view --trace`` and a post-mortem connect
        a journal line back to the request (and its spans/flight dump)
        that wrote it."""
        if "trace" not in rec:
            try:
                from ..obs.context import current_trace_id
                tid = current_trace_id()
            except Exception:
                tid = None
            if tid is not None:
                rec = {**rec, "trace": tid}
        body = json.dumps(rec, default=str)
        line = json.dumps({**json.loads(body), "c": _rec_crc(body)},
                          default=str)
        with self._wlock:
            self._f.write(line + "\n")
            self._f.flush()
            if sync:
                os.fsync(self._f.fileno())

    def begin(self, lines: List[str], name: str) -> None:
        # command numbering is PER SCRIPT: resume applies a ckpt's seq
        # as a skip count within the last begin's lines, so a second
        # run_string on the same interpreter must restart the count or
        # its checkpoints would over-skip the replay
        self.cmd_seq = 0
        self._since = 0
        self.append({"kind": "begin", "name": name, "lines": list(lines),
                     "pid": os.getpid(),
                     "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())})

    def cmd_done(self, command: str) -> None:
        self.cmd_seq += 1
        self.append({"kind": "cmd", "seq": self.cmd_seq, "cmd": command})

    def note_op(self, op: str, **extra) -> None:
        # forensics only — resume replays cmd/ckpt records, never ops,
        # so these flush without the per-record fsync
        self.op_seq += 1
        self.append({"kind": "op", "op_seq": self.op_seq, "op": op,
                     **extra}, sync=False)

    # -- checkpointing ------------------------------------------------------
    def maybe_checkpoint(self, obj) -> None:
        """Script-mode trigger: checkpoint all named MRs every
        ``every`` completed commands."""
        self._since += 1
        if self._since >= self.every:
            self.checkpoint(obj)

    def checkpoint(self, obj) -> bool:
        """Save every named MR (atomic per-MR via checkpoint.save's
        directory swap); the ``ckpt`` record is appended only after ALL
        saves succeeded.  An MR in the open() cross-add state cannot
        checkpoint — the whole round is skipped and retried after the
        next command.  Returns whether a checkpoint landed."""
        import dataclasses
        from ..core.checkpoint import save as _cksave
        from .retry import retry_call
        seq = self.cmd_seq
        reldir = f"ckpt-{seq:05d}"
        cdir = os.path.join(self.dir, reldir)
        mrs: Dict[str, dict] = {}
        nprocs = 1
        try:
            for name in sorted(obj.named):
                mr = obj.named[name]
                path = os.path.join(cdir, name)
                retry_call("checkpoint.save",
                           lambda m=mr, p=path: _cksave(m, p),
                           detail=path)
                nprocs = max(nprocs, int(mr.backend.nprocs))
                mrs[name] = {"path": f"{reldir}/{name}",
                             "settings": dataclasses.asdict(mr.settings)}
        except Exception:
            # un-checkpointable right now (open() state, exhausted save
            # retries, disk error or injected fault of ANY kind with no
            # budget armed): drop the partial set and try again next
            # trigger — a failed OPTIONAL checkpoint must never kill
            # the run it protects (KeyboardInterrupt/SystemExit pass)
            shutil.rmtree(cdir, ignore_errors=True)
            return False
        # the writer's mesh width rides the record: a resume onto a
        # DIFFERENT width restores fine (checkpoints are host frames)
        # but must surface the fact (serve/'s meta.resharded)
        self.append({"kind": "ckpt", "seq": seq, "mrs": mrs,
                     "nprocs": nprocs})
        self.nckpt += 1
        self._since = 0
        self._gc(keep=2)
        return True

    def auto_checkpoint(self, mr) -> None:
        """Programmatic-run trigger (no script): every ``every`` ops,
        checkpoint the reporting MR into the single ``auto`` slot."""
        self._since += 1
        if self._since < self.every:
            return
        from ..core.checkpoint import save as _cksave
        from .retry import retry_call
        path = os.path.join(self.dir, "auto")
        try:
            retry_call("checkpoint.save", lambda: _cksave(mr, path),
                       detail=path)
        except Exception:
            return      # open()-state MR / disk / injection: next time
        self.append({"kind": "auto_ckpt", "op_seq": self.op_seq,
                     "path": "auto", "nprocs": int(mr.backend.nprocs)})
        self.nckpt += 1
        self._since = 0

    def _gc(self, keep: int) -> None:
        """Bound disk: drop all but the ``keep`` NEWEST ckpt dirs — by
        mtime, not name: begin() restarts the seq numbering per script,
        so a re-run in the same journal dir writes low-numbered dirs
        that must outlive a previous run's stale high-numbered ones."""
        try:
            dirs = sorted((d for d in os.listdir(self.dir)
                           if d.startswith("ckpt-")),
                          key=lambda d: os.path.getmtime(
                              os.path.join(self.dir, d)))
            for d in dirs[:-keep]:
                shutil.rmtree(os.path.join(self.dir, d),
                              ignore_errors=True)
        except OSError:
            pass

    def stats(self) -> dict:
        return {"dir": self.dir, "cmds": self.cmd_seq, "ops": self.op_seq,
                "ckpts": self.nckpt, "every": self.every}

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# process-global arming (the MapReduce._op_stats hook reads this)
# ---------------------------------------------------------------------------

def active() -> Optional[Journal]:
    return _ACTIVE


def activate(journal: Optional[Journal]) -> Optional[Journal]:
    """Install ``journal`` as the process-global op-record sink;
    returns the previous one (callers restore it)."""
    global _ACTIVE
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, journal
    return prev


def from_env(script_mode: bool = False) -> Optional[Journal]:
    """A Journal for ``MRTPU_JOURNAL`` (activated), or None.  Each call
    makes a FRESH Journal — arming is per run, not cached, so two
    scripts in one process each journal their own lines.  A previous
    PROGRAMMATIC journal (the one the env auto-armed, held by nobody)
    is closed; a script's journal is left open — that script still
    appends through its own handle (concurrent scripts sharing one
    journal dir are unsupported for resume either way — journal per
    world, doc/reliability.md)."""
    from ..utils.env import env_str
    dir = env_str("MRTPU_JOURNAL", "")
    if not dir:
        return None
    j = Journal(dir, script_mode=script_mode)
    prev = activate(j)
    if prev is not None and prev is not j and not prev.script_mode:
        prev.close()
    return j


_ENV_APPLIED: Optional[str] = None


def configure_from_env() -> None:
    """Auto-arm the PROGRAMMATIC journal from ``MRTPU_JOURNAL`` (called
    via ``ft.configure_from_env`` on every MapReduce construction) —
    the settings.md contract that the env var alone arms journaling
    must hold for non-script runs too.  Script runs arm their own
    journal in ``OinkScript.__init__`` (before any MR exists), which
    this never replaces."""
    global _ENV_APPLIED
    from ..utils.env import env_str
    raw = env_str("MRTPU_JOURNAL", "")
    # check-and-set under _LOCK: two concurrent MapReduce constructions
    # racing the compare outside the lock could both see "unapplied" and
    # double-arm (the PR 6 counter-outside-lock class, caught by mrlint)
    with _LOCK:
        if raw == (_ENV_APPLIED or ""):
            return
        _ENV_APPLIED = raw
        active_now = _ACTIVE
    if raw and active_now is None:
        try:
            from_env(script_mode=False)
        except OSError as e:
            # unusable journal dir: warn-and-disarm like every other
            # ft env knob — never crash the MapReduce constructor
            import sys
            print(f"MRTPU_JOURNAL ignored: {e!r}", file=sys.stderr)
    elif not raw and active_now is not None and not active_now.script_mode:
        reset()     # env cleared: disarm the env-armed programmatic one


def note_op(mr, op: str, n=None) -> None:
    """Called from ``MapReduce._op_stats`` after every completed barrier
    op — one dict check when no journal is armed."""
    j = _ACTIVE
    if j is None:
        return
    try:
        j.note_op(op, **({"n": int(n)} if isinstance(n, (int, float))
                         else {}))
        if not j.script_mode:
            j.auto_checkpoint(mr)
    except ValueError:
        # the journal closed between the _ACTIVE read and the append
        # (a resume_into finishing on another thread, ft.reset, an
        # env-cleared disarm — serve/ worker pools run concurrently).
        # Op records and OPTIONAL checkpoints are best-effort; a lost
        # one must never fail the op that reported it
        return


# ---------------------------------------------------------------------------
# reading + resume
# ---------------------------------------------------------------------------

def read_journal(dir: str) -> List[dict]:
    path = os.path.join(dir, _FILE)
    try:
        with open(path) as f:
            out = []
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except ValueError:
                    # torn line from a crash mid-append.  SKIP, don't
                    # stop: a journal reopened after a kill -9 keeps
                    # appending past its sealed torn tail (Journal
                    # init), so records AFTER the tear are valid and
                    # replay depends on them; the torn record itself
                    # was never durable, so treating it as absent is
                    # the records-follow-facts contract
                    continue
                if isinstance(rec, dict) and not _rec_valid(rec):
                    # parses as JSON but fails its own crc: a bit flip
                    # inside the line.  Quarantine it (skip + count) —
                    # replaying a corrupt record is how a resume turns
                    # one flipped bit into wrong output
                    from ..utils.integrity import record_integrity_failure
                    record_integrity_failure("journal")
                    continue
                out.append(rec)
            return out
    except FileNotFoundError:
        raise MRError(f"no journal under {dir!r}")


def _ckpt_usable(dir: str, ckpt: dict) -> bool:
    """Pre-restore probe of one ``ckpt`` record's generation: every
    named MR's checkpoint directory must validate (manifest + frame
    files + digests under MRTPU_VERIFY — ``core/checkpoint.validate``).
    The probe runs BEFORE replay commits to a skip count, which is what
    lets a damaged newest generation fall back to the previous kept one
    instead of raising mid-restore."""
    from ..core.checkpoint import validate
    try:
        mrs = ckpt.get("mrs", {})
        return all(validate(os.path.join(dir, meta["path"]))
                   for meta in mrs.values())
    except Exception:
        return False


def plan_resume(dir: str) -> dict:
    """Read the journal and compute the replay plan: the recorded
    script lines, the number of command executions to skip, and the
    checkpoint record to restore at the skip boundary.

    Generation fallback: the newest ``ckpt`` record whose directories
    actually VALIDATE wins (missing frame files, a bit-flipped array —
    keep-2 GC guarantees the previous generation still exists).  A run
    whose every recorded generation is damaged resumes from scratch
    (skip 0) — slower, never wrong."""
    recs = read_journal(dir)
    begin_i = max((i for i, r in enumerate(recs)
                   if r.get("kind") == "begin"), default=None)
    if begin_i is None:
        raise MRError(f"journal under {dir!r} has no begin record "
                      f"(nothing to resume)")
    begin = recs[begin_i]
    tail = recs[begin_i:]
    ckpts = [r for r in tail if r.get("kind") == "ckpt"]
    done = max((int(r.get("seq", 0)) for r in tail
                if r.get("kind") == "cmd"), default=0)
    ckpt = None
    fell_back = 0
    for cand in reversed(ckpts):
        if _ckpt_usable(dir, cand):
            ckpt = cand
            break
        fell_back += 1
        import sys
        print(f"ft.resume: checkpoint generation seq={cand.get('seq')} "
              f"under {dir!r} is damaged or incomplete; falling back",
              file=sys.stderr)
    return {"lines": begin["lines"], "name": begin.get("name", "<resume>"),
            "skip": int(ckpt["seq"]) if ckpt else 0, "ckpt": ckpt,
            "cmds_done": done, "generations_skipped": fell_back}


def restore_mrs(obj, ckpt: dict, dir: str) -> None:
    """Rebuild every named MR of a ``ckpt`` record into ``obj``:
    settings reapplied, dataset loaded from the checkpoint directory."""
    for name, meta in ckpt.get("mrs", {}).items():
        mr = obj.named.get(name)
        if mr is None:
            mr = obj.create_mr()
            obj.name_mr(name, mr)
        settings = dict(meta.get("settings", {}))
        if settings:
            mr.set(**settings)
        mr.load(os.path.join(dir, meta["path"]))


def resume_into(script, dir: str) -> None:
    """Drive an (ideally fresh) OinkScript through the resume plan:
    skip the already-checkpointed command executions, restore the MRs,
    continue live with journaling re-armed into the same directory.

    Topology-portable: the checkpoint's frames are host-side, so the
    replay restores onto WHATEVER mesh the interpreter carries — a
    4-shard checkpoint resumes on a 1-, 2- or 8-shard mesh.  When the
    widths differ, ``script._ft_resharded`` is set so callers (the
    serve/ daemon's degraded mode) can surface ``meta.resharded``."""
    plan = plan_resume(dir)
    if getattr(script, "_ft_journal", None) is not None:
        script._ft_journal.close()   # replace an env-armed journal
    j = Journal(dir, script_mode=True)
    activate(j)
    j.cmd_seq = plan["skip"]      # seq continues from the restore point
    ckpt_np = int((plan["ckpt"] or {}).get("nprocs") or 0)
    here_np = int(script._nprocs()) if hasattr(script, "_nprocs") else 1
    script._ft_resharded = bool(ckpt_np and ckpt_np != here_np)
    j.append({"kind": "resume", "from_seq": plan["skip"],
              "cmds_done_before_crash": plan["cmds_done"],
              "nprocs": here_np, "ckpt_nprocs": ckpt_np or None,
              "generations_skipped": plan.get("generations_skipped", 0),
              "pid": os.getpid()})
    script._ft_journal = j
    script._ft_pending_begin = None   # never shadow the real begin
    script._ft_skip = plan["skip"]
    script._ft_restore = (plan["ckpt"], dir) if plan["ckpt"] else None
    script._ft_resuming = True
    try:
        script._run_lines(plan["lines"], plan["name"])
    finally:
        script._ft_resuming = False
    # the replay completed: disarm.  Commands an ENCLOSING script might
    # run after its `resume <dir>` line are not part of the recorded
    # begin, so journaling them would corrupt the seq numbering a later
    # resume skips by — resume is a whole-script operation
    # (doc/reliability.md); a crash DURING the replay leaves the
    # journal armed and resumable, which is the state that matters
    j.close()
    script._ft_journal = None
    if active() is j:
        activate(None)


def resume(dir: str, comm=None, screen=False, logfile: Optional[str] = None,
           mesh=None):
    """``ft.resume(dir)``: build a fresh interpreter and replay the
    journal's script from its last durable (and VALID — generation
    fallback) checkpoint.  Returns the finished OinkScript (named MRs
    inspectable by the caller).

    ``mesh`` (alias of ``comm``): the target mesh for the replay — it
    need NOT match the mesh that wrote the checkpoint.  A checkpoint
    taken on a 4-shard mesh resumes onto 1, 2 or 8 shards; the restored
    frames are host-side and re-shard on the replay's own collectives
    (doc/reliability.md#elastic-recovery)."""
    if mesh is not None:
        if comm is not None and comm is not mesh:
            raise MRError("resume: pass comm OR mesh, not both")
        comm = mesh
    from ..oink.script import OinkScript
    s = OinkScript(comm=comm, screen=screen, logfile=logfile)
    resume_into(s, dir)
    return s


def latest_checkpoint(dir: str) -> Optional[str]:
    """Path of the newest USABLE durable checkpoint under a journal
    dir: the programmatic ``auto`` slot, or the last script ``ckpt``
    set's directory that still validates (damaged generations skip to
    the previous kept one, like resume).  None when no checkpoint
    record exists."""
    from ..core.checkpoint import validate
    recs = read_journal(dir)
    for r in reversed(recs):
        if r.get("kind") == "auto_ckpt":
            path = os.path.join(dir, r.get("path", "auto"))
            if validate(path):
                return path
            # the single auto slot is damaged: keep scanning — an
            # older script ckpt generation may still be restorable
        if r.get("kind") == "ckpt" and _ckpt_usable(dir, r):
            return os.path.join(dir, f"ckpt-{int(r['seq']):05d}")
    return None


def reset() -> None:
    """Test isolation: close + drop the active journal and the env
    cache (the next configure_from_env re-reads from scratch)."""
    global _ACTIVE, _ENV_APPLIED
    with _LOCK:
        if _ACTIVE is not None:
            _ACTIVE.close()
        _ACTIVE = None
        _ENV_APPLIED = None
