"""Deterministic fault injection at named sites.

The reference has no fault story at all — a failed read aborts the job
(SURVEY.md §5).  Before a retry/resume layer can be trusted it must be
*provoked* on demand: this module registers the real failure sites
(:data:`SITES`) and arms them from a seeded schedule so a chaos run is
reproducible bit-for-bit.

Arming:

* env — ``MRTPU_FAULTS="seed=7;site=ingest.read;rate=0.05;kind=oserror"``
  (several specs separated by ``|``; ``site=*`` hits every registered
  site, ``n=K`` caps a spec at K injected faults and ``after=K`` skips
  a site's first K probes — both PER SITE, so wildcard specs stay
  deterministic under thread interleaving);
* code — :func:`schedule` with the same fields.

Each spec owns a ``random.Random`` seeded from ``(seed, site)`` (via
crc32, not the salted ``hash()``), so the k-th *probe* of a site draws
the same verdict in every process — which probe faults does not depend
on thread scheduling, only on how many times the site was reached.

Disarmed cost: :func:`fault_point` is one module-bool check and
returns — the acceptance criterion is "no measurable overhead with
``MRTPU_FAULTS`` unset".

Injected exceptions carry ``.ft_site`` (so the retry engine labels
metrics by the true site even through wrapper frames) and subclass
:class:`InjectedFault`, which the classifier treats as transient —
except ``kind=fatal``, the kill switch the resume tests use.
"""

from __future__ import annotations

import random
import threading
import zlib
from typing import Dict, List, Optional

# the registered fault points — every name appears at exactly one real
# failure site (see doc/reliability.md for the wiring table).  The
# dist.* sites are the collective watchdog's armed sync points
# (parallel/dist.guard): the ONLY sites where the process-level kinds
# (peer_kill / peer_hang) make sense, since they simulate a peer dying
# at — not near — a collective.
SITES = ("ingest.read", "ingest.tokenize", "spill.write", "spill.read",
         "shuffle.exchange", "checkpoint.save",
         "dist.count_sync", "dist.exchange", "dist.reshard",
         "dist.ckpt_barrier")


class InjectedFault:
    """Marker mixin: this exception was injected by ft/, not real."""


class InjectedOSError(InjectedFault, OSError):
    pass


class InjectedTimeout(InjectedFault, TimeoutError):
    pass


class InjectedRuntimeError(InjectedFault, RuntimeError):
    pass


class InjectedFatal(InjectedFault, RuntimeError):
    """kind=fatal: classified NON-retryable — kills the run through any
    retry budget (the mid-run "crash" the journal/resume tests stage)."""


_KINDS = {"oserror": InjectedOSError, "ioerror": InjectedOSError,
          "timeout": InjectedTimeout, "runtime": InjectedRuntimeError,
          "fatal": InjectedFatal}

# process-level kinds: no exception to classify — the PROCESS is the
# fault.  peer_kill SIGKILLs self at the drawn probe (the k-th sync of
# a chaos golden, deterministic via after=/n=); peer_hang sleeps past
# every watchdog deadline (MRTPU_DIST_HANG_S) so survivors must trip on
# the sync timeout, not a lease expiry; delay sleeps MRTPU_DIST_DELAY_S
# and then PROCEEDS into the collective — a slow host, not a dead one,
# which is what the straggler-attribution goldens stage.  Restricted to
# dist.* sites — killing the process at spill.write would just be a
# worse `fatal`.
_PROC_KINDS = ("peer_kill", "peer_hang", "delay")


class FaultSpec:
    """One armed schedule entry: which site(s), how often, what to raise."""

    __slots__ = ("site", "rate", "kind", "seed", "max_faults", "after",
                 "rank", "_rngs", "injected", "_probes",
                 "_injected_by_site", "_from_env")

    def __init__(self, site: str = "*", rate: float = 1.0,
                 kind: str = "oserror", seed: int = 0,
                 max_faults: Optional[int] = None, after: int = 0,
                 rank: Optional[int] = None):
        if kind not in _KINDS and kind not in _PROC_KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(one of {sorted(_KINDS) + list(_PROC_KINDS)})")
        if site != "*" and site not in SITES:
            raise ValueError(f"unknown fault site {site!r} "
                             f"(registered: {SITES})")
        if kind in _PROC_KINDS and not site.startswith("dist."):
            raise ValueError(f"kind={kind} only arms at an explicit "
                             f"dist.* site (got {site!r}) — SIGKILLing "
                             f"at spill.write would just be a worse "
                             f"'fatal'")
        self.rank = None if rank is None else int(rank)
        self.site = site
        self.rate = float(rate)
        self.kind = kind
        self.seed = int(seed)
        self.max_faults = max_faults
        self.after = int(after)      # skip the first `after` probes —
        #                              places a fault mid-run on purpose
        self._rngs: Dict[str, random.Random] = {}
        self.injected = 0
        # per-SITE probe/fault counters: a site="*" spec must stay
        # deterministic per site — one shared counter would let thread
        # interleaving (mapstyle-2 ingest vs the spill writer) move the
        # fault between sites across runs, breaking the reproducibility
        # contract; `after` and `n` therefore apply per site
        self._probes: Dict[str, int] = {}
        self._injected_by_site: Dict[str, int] = {}
        self._from_env = False   # env respec replaces only env specs

    def matches(self, site: str) -> bool:
        if self.site not in ("*", site):
            return False
        # rank selector: a chaos golden kills ONE chosen rank — every
        # process runs the same MRTPU_FAULTS string, so the spec itself
        # must know which rank it is for
        return self.rank is None or self.rank == _self_rank()

    def draw(self, site: str) -> bool:
        """Deterministic verdict for the next probe of ``site``."""
        probes = self._probes.get(site, 0) + 1
        self._probes[site] = probes
        if probes <= self.after:
            return False
        if self.max_faults is not None and \
                self._injected_by_site.get(site, 0) >= self.max_faults:
            return False
        rng = self._rngs.get(site)
        if rng is None:
            # crc32, not hash(): hash() of str is salted per process and
            # would break cross-run determinism
            rng = self._rngs[site] = random.Random(
                (self.seed << 32) ^ zlib.crc32(site.encode()))
        if rng.random() < self.rate:
            self._injected_by_site[site] = \
                self._injected_by_site.get(site, 0) + 1
            return True
        return False


def _self_rank() -> int:
    """This process's data-plane rank (0 in single-process runs) —
    read once from the launcher-set env, not from parallel/dist (the
    fault layer must stay importable with jax cold)."""
    global _RANK
    if _RANK is None:
        from ..utils.env import env_knob
        _RANK = env_knob("MRTPU_DIST_RANK", int, 0)
    return _RANK


_RANK: Optional[int] = None

_LOCK = threading.Lock()
_SPECS: List[FaultSpec] = []
_ARMED = False           # the fault_point fast-path check
_ENV_APPLIED: Optional[str] = None   # last MRTPU_FAULTS string applied
_COUNTS: Dict[str, int] = {}         # site → faults injected


def schedule(site: str = "*", rate: float = 1.0, kind: str = "oserror",
             seed: int = 0, max_faults: Optional[int] = None,
             after: int = 0, rank: Optional[int] = None) -> FaultSpec:
    """Arm one fault spec programmatically; returns it (its ``injected``
    count is live).  ``ft.clear_faults()`` disarms everything."""
    global _ARMED
    spec = FaultSpec(site, rate, kind, seed, max_faults, after, rank)
    with _LOCK:
        _SPECS.append(spec)
        _ARMED = True
    return spec


def clear_faults() -> None:
    """Disarm every spec (programmatic and env-sourced) and drop the
    injection counts; the next :func:`configure_from_env` re-reads the
    environment from scratch."""
    global _ARMED, _ENV_APPLIED
    with _LOCK:
        _SPECS.clear()
        _COUNTS.clear()
        _ARMED = False
        _ENV_APPLIED = None


def armed() -> bool:
    return _ARMED


def armed_for(site: str) -> bool:
    """Whether any armed spec can hit ``site`` — callers that pay a
    structural cost to be injectable (the ingest paths' buffered
    attempts, materialized chunk lists) check per site, so arming
    spill-only chaos never changes ingest behavior."""
    if not _ARMED:
        return False
    with _LOCK:
        return any(s.matches(site) for s in _SPECS)


def parse_faults(text: str) -> List[FaultSpec]:
    """``"seed=7;site=ingest.read;rate=0.05;kind=oserror"`` → specs.
    ``|`` separates independent specs; ``site`` may list several sites
    comma-separated (one spec each, sharing the other fields)."""
    specs: List[FaultSpec] = []
    for part in text.split("|"):
        part = part.strip()
        if not part:
            continue
        fields = {}
        for kv in part.split(";"):
            kv = kv.strip()
            if not kv:
                continue
            if "=" not in kv:
                raise ValueError(f"malformed MRTPU_FAULTS field {kv!r}")
            k, v = kv.split("=", 1)
            fields[k.strip()] = v.strip()
        sites = fields.pop("site", "*").split(",")
        kw = {"rate": float(fields.pop("rate", 1.0)),
              "kind": fields.pop("kind", "oserror"),
              "seed": int(fields.pop("seed", 0)),
              "after": int(fields.pop("after", 0))}
        if "n" in fields:
            kw["max_faults"] = int(fields.pop("n"))
        if "rank" in fields:
            kw["rank"] = int(fields.pop("rank"))
        if fields:
            raise ValueError(f"unknown MRTPU_FAULTS fields "
                             f"{sorted(fields)}")
        for s in sites:
            specs.append(FaultSpec(site=s.strip(), **kw))
    return specs


def configure_from_env() -> None:
    """Apply ``MRTPU_FAULTS`` if it changed since last look (called from
    every ``MapReduce()`` construction — cheap: one getenv + compare).
    A malformed value warns and stays disarmed, never crashes the run
    (the utils.env contract)."""
    global _ARMED, _ENV_APPLIED
    import sys

    from ..utils.env import env_str
    raw = env_str("MRTPU_FAULTS", "")
    if raw == (_ENV_APPLIED or ""):
        return
    try:
        specs = parse_faults(raw) if raw else []
    except (ValueError, TypeError) as e:
        print(f"MRTPU_FAULTS ignored: {e!r}", file=sys.stderr)
        specs = []
    with _LOCK:
        # env respec replaces only env-sourced arming; programmatic
        # specs are the caller's to clear
        _SPECS[:] = [s for s in _SPECS if not s._from_env]
        for s in specs:
            s._from_env = True
            _SPECS.append(s)
        _ARMED = bool(_SPECS)
        _ENV_APPLIED = raw


def fault_point(site: str, **detail) -> None:
    """Probe a registered site: raise the scheduled fault or return.
    THE hot-path entry — one bool check when disarmed."""
    if not _ARMED:
        return
    with _LOCK:
        for spec in _SPECS:
            if spec.matches(site) and spec.draw(site):
                spec.injected += 1
                _COUNTS[site] = _COUNTS.get(site, 0) + 1
                kind = spec.kind
                exc_cls = _KINDS.get(kind)
                break
        else:
            return
    if exc_cls is None:            # peer_kill / peer_hang
        _proc_fault(kind, site)
        return
    exc = exc_cls(f"injected {kind} fault at {site}"
                  + (f" ({detail})" if detail else ""))
    exc.ft_site = site
    from ..obs import get_tracer
    with get_tracer().span("ft.inject", cat="ft", site=site, kind=kind):
        raise exc


def _proc_fault(kind: str, site: str) -> None:
    """Execute a process-level fault: the chaos goldens' deterministic
    stand-ins for a rank SIGKILLed (OOM-killer, preemption) or wedged
    (NIC death, livelock) exactly AT a collective sync point."""
    import sys
    import time as _time
    print(f"ft.inject: {kind} at {site} (rank {_self_rank()}, "
          f"pid {__import__('os').getpid()})", file=sys.stderr, flush=True)
    if kind == "peer_kill":
        import os as _os
        import signal as _signal
        _os.kill(_os.getpid(), _signal.SIGKILL)
        return                      # unreachable
    if kind == "delay":
        # a slow host, not a dead one: stall short of the watchdog
        # deadline, then ENTER the collective — every survivor completes
        # the sync late and the straggler attribution must name us
        from ..utils.env import env_knob
        _time.sleep(env_knob("MRTPU_DIST_DELAY_S", float, 2.0))
        return
    # peer_hang: sleep past every watchdog deadline so survivors must
    # trip on the sync timeout; the sleep happens ON the sync path (the
    # main thread), so our own heartbeat thread keeps beating — the
    # hardest detection case, by design
    from ..utils.env import env_knob
    _time.sleep(env_knob("MRTPU_DIST_HANG_S", float, 3600.0))


def counts() -> Dict[str, int]:
    """{site: faults injected so far} (process-cumulative)."""
    with _LOCK:
        return dict(_COUNTS)
