"""Retry / backoff policy engine for the registered fault sites.

One transient ``OSError`` from a single input file, a torn spill read
or a flaky device transfer used to abort the whole pipeline.  This
module gives every registered site (:data:`..ft.inject.SITES`) a
bounded-retry policy:

* **budgets** — ``MRTPU_RETRY="ingest.read=3,spill.read=2"`` (or a bare
  ``MRTPU_RETRY=3`` for every site), or :func:`set_budget`.  Budget 0
  (the default) means the call runs bare — no wrapper frames, no
  behavior change.
* **classification** — transient (worth retrying: OS/timeout/connection
  errors, injected faults) vs fatal (semantic errors, ``MRError``,
  ``FileNotFoundError`` — a missing file will still be missing on the
  4th attempt, and ``kind=fatal`` injections).
* **backoff** — exponential with jitter: ``base * 2^k``, capped, scaled
  by a seeded jitter in [0.5, 1.0) (``MRTPU_RETRY_BACKOFF`` base
  seconds, ``MRTPU_RETRY_BACKOFF_MAX`` cap; tests monkeypatch
  :data:`_sleep`).
* **exhaustion** — raises ``MRError`` naming the site, attempt count
  and last error, chained to the original.  The failing attempt chain
  is one ``ft.retry`` obs span (site / attempts / outcome), so the
  flight recorder's dump shows exactly which site gave up.

Retries count into ``mrtpu_retries_total{site,outcome}`` (outcome:
``retry`` per re-attempt, ``recovered`` on late success, ``exhausted``
/ ``fatal`` on the final disposition) via the obs/metrics collector.

The ingest task wrapper (:func:`ingest_task`) additionally implements
the ``onfault`` dataset setting: attempts buffer into a private
``_TaskSink`` (a retry can therefore never duplicate or reorder the
pairs a partial attempt already emitted), raw ``OSError`` from a map
callback wraps into an ``MRError`` naming the file/shard/task, and
``onfault="skip"`` quarantines the poisoned input instead of failing
the run.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.runtime import MRError
from . import inject

_sleep = time.sleep          # monkeypatch hook for backoff-timing tests

_LOCK = threading.Lock()
_BUDGETS: Dict[str, int] = {}        # site → max retries (not attempts)
_DEFAULT_BUDGET = 0                  # applies to sites not listed
_ENV_APPLIED: Optional[str] = None
_ENV_SITES: set = set()              # budget keys set by MRTPU_RETRY —
_ENV_DEFAULT = False                 # an env respec replaces only these
#                                      (programmatic set_budget state
#                                      survives, mirroring inject specs)
# (site, outcome) → count; outcomes: retry / recovered / exhausted / fatal
_RETRIES: Dict[tuple, int] = {}
_QUARANTINE: List[dict] = []         # skip-with-record entries
_QUARANTINE_KEEP = 64                # records kept for stats (count is exact)
_NQUAR: Dict[str, int] = {}          # site → total quarantined
_JITTER = random.Random(0xF7A11)     # seeded: backoff is reproducible


def set_budget(site: str, retries: int) -> None:
    """Programmatic twin of ``MRTPU_RETRY``: allow ``retries``
    re-attempts at ``site`` (``"*"`` sets the default for every site).
    Survives later MRTPU_RETRY changes (those replace only env-sourced
    budgets)."""
    global _DEFAULT_BUDGET, _ENV_DEFAULT
    if site != "*" and site not in inject.SITES:
        # same loud contract as parse_faults: a typo'd site silently
        # disarming the protection the operator thinks is on would be
        # the worst possible failure mode for this knob
        raise ValueError(f"unknown retry site {site!r} "
                         f"(registered: {inject.SITES})")
    with _LOCK:
        if site == "*":
            _DEFAULT_BUDGET = int(retries)
            _ENV_DEFAULT = False
        else:
            _BUDGETS[site] = int(retries)
            _ENV_SITES.discard(site)


def budget(site: str) -> int:
    with _LOCK:
        return _BUDGETS.get(site, _DEFAULT_BUDGET)


def parse_retry(text: str) -> Dict[str, int]:
    """``"ingest.read=3,spill.read=2"`` (or bare ``"3"``) → budgets.
    Unknown sites raise (→ one stderr warning via configure_from_env),
    like parse_faults — never a silently-inert typo."""
    out: Dict[str, int] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            site, n = part.split("=", 1)
            site = site.strip()
            if site != "*" and site not in inject.SITES:
                raise ValueError(f"unknown retry site {site!r} "
                                 f"(registered: {inject.SITES})")
            out[site] = int(n)
        else:
            out["*"] = int(part)
    return out


def configure_from_env() -> None:
    """Apply ``MRTPU_RETRY`` when it changed (one getenv + compare per
    MapReduce construction); malformed values warn and disarm.  A
    respec replaces only ENV-sourced budgets — programmatic
    ``set_budget`` state survives (same contract as inject specs)."""
    global _ENV_APPLIED, _DEFAULT_BUDGET, _ENV_DEFAULT
    import sys

    from ..utils.env import env_str
    raw = env_str("MRTPU_RETRY", "")
    if raw == (_ENV_APPLIED or ""):
        return
    try:
        budgets = parse_retry(raw) if raw else {}
    except (ValueError, TypeError) as e:
        print(f"MRTPU_RETRY ignored: {e!r}", file=sys.stderr)
        budgets = {}
    with _LOCK:
        for site in _ENV_SITES:
            _BUDGETS.pop(site, None)
        _ENV_SITES.clear()
        if _ENV_DEFAULT:
            _DEFAULT_BUDGET = 0
            _ENV_DEFAULT = False
        if "*" in budgets:
            _DEFAULT_BUDGET = budgets.pop("*")
            _ENV_DEFAULT = True
        _BUDGETS.update(budgets)
        _ENV_SITES.update(budgets)
        _ENV_APPLIED = raw


def _backoff(attempt: int) -> float:
    """Delay before retry ``attempt`` (0-based): exponential, capped,
    jittered into [0.5, 1.0)× so retry storms decorrelate."""
    from ..utils.env import env_knob
    base = env_knob("MRTPU_RETRY_BACKOFF", float, 0.05)
    cap = env_knob("MRTPU_RETRY_BACKOFF_MAX", float, 2.0)
    return min(cap, base * (2.0 ** attempt)) * (0.5 + 0.5 * _JITTER.random())


def classify(site: str, exc: BaseException) -> str:
    """``"transient"`` (retry may help) or ``"fatal"`` (it will not)."""
    if isinstance(exc, inject.InjectedFatal):
        return "fatal"
    if isinstance(exc, inject.InjectedFault):
        return "transient"
    if isinstance(exc, MRError):
        return "fatal"
    if isinstance(exc, (FileNotFoundError, IsADirectoryError,
                        NotADirectoryError)):
        # deterministically absent input: retrying burns the budget on
        # an error the satellite contract wraps as MRError instead
        return "fatal"
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return "transient"
    return "fatal"


def _count(site: str, outcome: str) -> None:
    with _LOCK:
        _RETRIES[(site, outcome)] = _RETRIES.get((site, outcome), 0) + 1
    # per-request attribution (obs/context.py): the same outcome lands
    # on the active request account, so a session's cost profile shows
    # ITS retries, not the process total
    try:
        from ..obs.context import note_retry
        note_retry(site, outcome)
    except Exception:
        pass


def retry_call(site: str, fn: Callable, *, detail: str = "",
               retryable: Optional[Callable[[BaseException], bool]] = None,
               budget_override: Optional[int] = None):
    """Run ``fn()`` under ``site``'s retry policy.  Budget 0 (the
    disarmed default) calls straight through — no wrapper frames, no
    behavior delta.  ``retryable``: extra per-call veto (e.g. "the
    exchange's donated buffers are already consumed").
    ``budget_override``: a caller-computed budget (the ingest paths'
    onfault-derived default) instead of the site's configured one."""
    b = budget(site) if budget_override is None else budget_override
    if b <= 0:
        return fn()
    try:
        return fn()
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as first:
        return _retry_tail(site, fn, first, b, detail, retryable)


def _retry_tail(site: str, fn: Callable, first: BaseException, b: int,
                detail: str, retryable) -> object:
    """The slow path after a first failure: classification + bounded
    backoff retries, all under ONE ``ft.retry`` span."""
    from ..obs import get_tracer
    with get_tracer().span("ft.retry", cat="ft", site=site,
                           detail=detail) as sp:
        e = first
        attempt = 0
        while True:
            s = getattr(e, "ft_site", site)   # injected faults know theirs
            if classify(s, e) == "fatal" or \
                    (retryable is not None and not retryable(e)):
                _count(s, "fatal")
                sp.set(site=s, outcome="fatal", attempts=attempt)
                raise e
            if attempt >= b:
                _count(s, "exhausted")
                sp.set(site=s, outcome="exhausted", attempts=attempt,
                       last_error=type(e).__name__)
                err = MRError(
                    f"ft: {s} retry budget exhausted after "
                    f"{attempt + 1} attempts"
                    + (f" ({detail})" if detail else "")
                    + f": {e!r}")
                err.ft_site = s    # downstream quarantine keeps the site
                raise err from e
            _sleep(_backoff(attempt))
            _count(s, "retry")
            attempt += 1
            try:
                out = fn()
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e2:
                e = e2
                continue
            _count(s, "recovered")
            sp.set(site=s, outcome="recovered", attempts=attempt)
            return out


# ---------------------------------------------------------------------------
# the ingest task wrapper: onfault policy + MRError wrapping + quarantine
# ---------------------------------------------------------------------------

def quarantine(site: str, **record) -> None:
    """Record one skipped (poisoned) input; counted exactly, last
    :data:`_QUARANTINE_KEEP` records kept for ``mr.stats()["ft"]``.
    The record carries the active request's trace id (obs/context.py)
    so a multi-tenant daemon can say WHOSE input was quarantined."""
    try:
        from ..obs.context import current_trace_id
        tid = current_trace_id()
    except Exception:
        tid = None
    if tid is not None:
        record.setdefault("trace", tid)
    with _LOCK:
        _NQUAR[site] = _NQUAR.get(site, 0) + 1
        _QUARANTINE.append({"site": site, **record})
        del _QUARANTINE[:-_QUARANTINE_KEEP]
    from ..obs import get_tracer
    get_tracer().annotate(ft_quarantined=record.get("task"))


def ingest_active(onfault: str = "fail") -> bool:
    """Whether the ingest paths need the buffered-attempt wrapper
    (injection armed FOR an ingest site, any ingest retry budget, or a
    non-default ``onfault`` policy) — False is the zero-delta fast
    path.  Per-site arming matters: spill-only chaos must not cost the
    chunk readers their lazy-window memory property."""
    return (onfault != "fail"
            or inject.armed_for("ingest.read")
            or inject.armed_for("ingest.tokenize")
            or budget("ingest.read") > 0 or budget("ingest.tokenize") > 0)


def _ingest_budget(onfault: str) -> int:
    b = max(budget("ingest.read"), budget("ingest.tokenize"))
    if b == 0 and onfault == "retry":
        b = 2       # onfault=retry without an explicit budget: default 2
    return b


def input_unreadable(e: OSError, file=None) -> "MRError":
    """THE discovery-failure wrapper, one copy (map_files/_map_chunks
    findfiles + the mesh paths' balance_by_bytes): an OSError from
    input discovery becomes an MRError naming the file — worded by
    what actually happened, not assumed to be 'not found'."""
    name = file if file is not None else getattr(e, "filename", None)
    if name is None and e.args and isinstance(e.args[0], str):
        name = e.args[0]      # findfiles raises FileNotFoundError(path)
    err = MRError(f"map input file {name!r} unreadable: {e!r}")
    err.ft_site = "ingest.read"
    return err


def quarantine_or_raise(e: OSError, file, onfault: str,
                        shard=None) -> bool:
    """Discovery-stage disposition (findfiles / balance_by_bytes): the
    same policy a task-time failure gets — quarantine under
    ``onfault="skip"`` (returns True: caller drops the file), else
    raise the wrapping MRError.  Which stage notices a bad input must
    not decide whether the run survives it."""
    if onfault == "skip" and _skippable(e):
        quarantine("ingest.read", file=file, shard=shard,
                   error=repr(e)[:200])
        return True
    raise input_unreadable(e, file) from e


def _skippable(e: BaseException) -> bool:
    """What onfault='skip' may quarantine: per-input failures (I/O
    errors, poisoned-input semantic errors, exhausted budgets) — NOT
    the injected kill switch (InjectedFatal exists to kill the run
    through any policy; the resume runbook depends on it) and not
    resource exhaustion."""
    return not isinstance(e, (inject.InjectedFatal, MemoryError))


def _where(itask, fname, shard) -> str:
    out = f"task {itask}"
    if shard is not None:
        out += f", shard {shard}"
    if fname is not None:
        out += f", file {fname!r}"
    return out


def ingest_task(call: Callable, itask: int, payload, out, *,
                onfault: str = "fail", shard: Optional[int] = None,
                private_sink: bool = True):
    """Run one map task (``call(itask, payload, sink)``) under the
    ingest fault policy.

    ``out`` is the task's own ``_TaskSink`` (``private_sink=True`` —
    the run_sinks / mapstyle-2 paths) or the live ``KeyValue``
    (``private_sink=False`` — the serial ``_run_tasks`` path).  Either
    way every ATTEMPT buffers into a fresh private sink that is only
    published on success, so a retried task can never duplicate pairs a
    failed attempt already emitted, and task-order (hence output
    byte-identity) is untouched.

    A raw ``OSError`` escaping the callback wraps into an ``MRError``
    naming the file, shard and task id (the "missing input file
    surfaces as a raw OSError from deep inside the pipeline" fix);
    ``onfault="skip"`` quarantines the input instead and the task
    contributes nothing."""
    fname = payload if isinstance(payload, str) else None
    if not ingest_active(onfault):
        try:
            return call(itask, payload, out)
        except OSError as e:
            if fname is None:
                raise   # not a file task: the callback's own OSError
                #         (ENOSPC writing ITS output…) keeps its type
            raise MRError(f"map input {_where(itask, fname, shard)} "
                          f"failed: {e}") from e
    from ..core.mapreduce import _TaskSink
    where = _where(itask, fname, shard)

    def attempt():
        inject.fault_point("ingest.read", task=itask)
        tmp = _TaskSink()
        call(itask, payload, tmp)
        inject.fault_point("ingest.tokenize", task=itask)
        return tmp

    b = _ingest_budget(onfault)
    try:
        try:
            tmp = attempt()
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as first:
            if b <= 0:
                # no retry policy configured: the original error
                # propagates untouched — never reported as an
                # "exhausted budget" that was never armed
                raise
            tmp = _retry_tail("ingest.read", attempt, first, b,
                              where, None)
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as e:
        if onfault == "skip" and _skippable(e):
            quarantine(getattr(e, "ft_site", "ingest.read"), task=itask,
                       shard=shard, file=fname, error=repr(e)[:200])
            return None
        if isinstance(e, OSError) and fname is not None:
            raise MRError(f"map input {where} failed: {e}") from e
        raise
    if private_sink:
        out._calls[:] = tmp._calls
    else:
        tmp.replay(out)
    return None


def ingest_read(fn: Callable, *, file: Optional[str] = None,
                onfault: str = "fail", shard: Optional[int] = None):
    """Wrap a host-side input READ that runs outside a task callback
    (the chunked readers' ``file_chunks`` materialization): same
    policy as :func:`ingest_task` — retry budget, MRError naming the
    file, quarantine under ``onfault="skip"`` (returns None)."""
    def attempt():
        inject.fault_point("ingest.read", file=file)
        return fn()

    try:
        b = _ingest_budget(onfault) if ingest_active(onfault) else 0
        if b <= 0:
            return attempt() if inject.armed_for("ingest.read") else fn()
        return retry_call("ingest.read", attempt,
                          detail=str(file or ""), budget_override=b)
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as e:
        if onfault == "skip" and _skippable(e):
            quarantine(getattr(e, "ft_site", "ingest.read"), shard=shard,
                       file=file, error=repr(e)[:200])
            return None
        if isinstance(e, OSError):
            raise input_unreadable(e, file) from e
        raise


# ---------------------------------------------------------------------------
# stats / isolation
# ---------------------------------------------------------------------------

def retries_snapshot() -> Dict[tuple, int]:
    with _LOCK:
        return dict(_RETRIES)


def quarantine_snapshot() -> dict:
    with _LOCK:
        return {"count": sum(_NQUAR.values()), "by_site": dict(_NQUAR),
                "records": list(_QUARANTINE)}


def reset() -> None:
    """Test isolation: budgets, counters, quarantine, env cache."""
    global _DEFAULT_BUDGET, _ENV_APPLIED, _ENV_DEFAULT
    with _LOCK:
        _BUDGETS.clear()
        _RETRIES.clear()
        _QUARANTINE.clear()
        _NQUAR.clear()
        _ENV_SITES.clear()
        _DEFAULT_BUDGET = 0
        _ENV_APPLIED = None
        _ENV_DEFAULT = False
