/* coink — drive the OINK script interpreter from C (the counterpart of
 * the reference's oink/library.h mrmpi_open/_file/_command/_close).
 *
 * Usage: coink script.oink [logfile]
 */

#include <stdio.h>

#include "../cmapreduce.h"

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s script.oink [logfile]\n", argv[0]);
    return 1;
  }
  if (MR_init() != 0) {
    fprintf(stderr, "MR_init failed: %s\n", MR_last_error());
    return 1;
  }
  void *oink = OINK_open(argc > 2 ? argv[2] : NULL);
  int rc = OINK_file(oink, argv[1]);
  if (rc != 0) fprintf(stderr, "script error: %s\n", MR_last_error());
  OINK_close(oink);
  MR_finalize();
  return rc == 0 ? 0 : 1;
}
