/* cwordfreq — word frequency via the C ABI, the counterpart of the
 * reference's examples/cwordfreq.c: map files → collate → reduce(sum) →
 * gather → sort by count → print the top words.
 *
 * Usage: cwordfreq file1 [file2 ...]
 * Prints "<nwords> total words, <nunique> unique words" then the top-5
 * "<count> <word>" lines (descending), like examples/wordfreq.cpp:119-130.
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <ctype.h>

#include "../cmapreduce.h"

/* map: read one file, emit (word, NULL) per whitespace token */
static void fileread(int itask, char *fname, void *kv, void *ptr) {
  FILE *fp = fopen(fname, "r");
  if (fp == NULL) return;
  char word[256];
  while (fscanf(fp, "%255s", word) == 1)
    MR_kv_add(kv, word, (int)strlen(word), NULL, 0);
  fclose(fp);
}

/* reduce: emit (word, count) with count as zero-padded ascii so the
 * byte-wise value sort orders numerically (typed columns would use the
 * int comparator; byte values compare lexicographically) */
static void count_words(char *key, int keybytes, char *multivalue,
                        int nvalues, int *valuebytes, void *kv, void *ptr) {
  long *total = (long *)ptr;
  *total += nvalues;
  char buf[32];
  int n = snprintf(buf, sizeof buf, "%08d", nvalues);
  MR_kv_add(kv, key, keybytes, buf, n);
}

/* scan: print "<count> <word>" for the first `limit` pairs */
struct topctx { int seen, limit; };

static void print_top(char *key, int keybytes, char *value, int valuebytes,
                      void *ptr) {
  struct topctx *c = (struct topctx *)ptr;
  if (c->seen++ >= c->limit) return;
  char num[32];
  int n = valuebytes < 31 ? valuebytes : 31;
  memcpy(num, value, n);
  num[n] = '\0';
  printf("%d %.*s\n", atoi(num), keybytes, key);
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s file1 [file2 ...]\n", argv[0]);
    return 1;
  }
  if (MR_init() != 0) {
    fprintf(stderr, "MR_init failed: %s\n", MR_last_error());
    return 1;
  }

  void *mr = MR_create();
  MR_map_file_list(mr, argc - 1, &argv[1], fileread, NULL);
  uint64_t nwords = MR_kv_stats(mr);
  MR_collate(mr);
  long total = 0;
  uint64_t nunique = MR_reduce(mr, count_words, &total);
  printf("%lu total words, %lu unique words\n",
         (unsigned long)nwords, (unsigned long)nunique);

  /* top-5: zero-padded ascii counts — flag -5 = string descending */
  MR_gather(mr, 1);
  MR_sort_values_flag(mr, -5);
  struct topctx ctx = {0, 5};
  MR_scan_kv(mr, print_top, &ctx);

  MR_destroy(mr);
  MR_finalize();
  return 0;
}
