/* crmat — RMAT matrix generation via the C ABI, the counterpart of the
 * reference's examples/crmat.c: generate-until-unique loop (map_add →
 * collate → cull), then the nonzero/degree/histo pipeline finishing
 * with a descending degree sort and an MR_map_mr stats pass.
 *
 * Usage: crmat N Nz a b c d frac seed [outfile]
 * Prints "<order> rows in matrix", "<ntotal> nonzeroes in matrix",
 * the "<degree> <count>" histogram, and "<n> rows with 0 nonzeroes".
 * With [outfile], writes "vi vj" edge lines to <outfile>.0.
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <stdint.h>

#include "../cmapreduce.h"

typedef struct {
  int nlevels, order, nnonzero, ngenerate;
  double a, b, c, d, fraction;
  FILE *fp;
} RMAT;

typedef struct {
  uint64_t vi, vj;
} EDGE;

/* map: emit ngenerate random RMAT edges (key = EDGE struct, value = NULL) */
static void generate(int itask, void *kv, void *ptr) {
  RMAT *r = (RMAT *)ptr;
  for (int m = 0; m < r->ngenerate; m++) {
    uint64_t i = 0, j = 0;
    int delta = r->order >> 1;
    double a1 = r->a, b1 = r->b, c1 = r->c, d1 = r->d;
    for (int lev = 0; lev < r->nlevels; lev++) {
      double rn = drand48();
      if (rn < a1) {
      } else if (rn < a1 + b1) {
        j += delta;
      } else if (rn < a1 + b1 + c1) {
        i += delta;
      } else {
        i += delta;
        j += delta;
      }
      delta >>= 1;
      if (r->fraction > 0.0) {
        a1 += a1 * r->fraction * (drand48() - 0.5);
        b1 += b1 * r->fraction * (drand48() - 0.5);
        c1 += c1 * r->fraction * (drand48() - 0.5);
        d1 += d1 * r->fraction * (drand48() - 0.5);
        double total = a1 + b1 + c1 + d1;
        a1 /= total; b1 /= total; c1 /= total; d1 /= total;
      }
    }
    EDGE e = {i, j};
    MR_kv_add(kv, (char *)&e, (int)sizeof(EDGE), NULL, 0);
  }
}

/* reduce: keep one copy of each edge */
static void cull(char *key, int keybytes, char *mv, int nvalues,
                 int *valuebytes, void *kv, void *ptr) {
  MR_kv_add(kv, key, keybytes, NULL, 0);
}

/* reduce: write "vi vj" per unique edge, keep the edge */
static void output(char *key, int keybytes, char *mv, int nvalues,
                   int *valuebytes, void *kv, void *ptr) {
  RMAT *r = (RMAT *)ptr;
  EDGE e;
  memcpy(&e, key, sizeof(EDGE));
  fprintf(r->fp, "%llu %llu\n", (unsigned long long)e.vi,
          (unsigned long long)e.vj);
  MR_kv_add(kv, key, keybytes, NULL, 0);
}

/* reduce: edge → (row vi, NULL) */
static void nonzero(char *key, int keybytes, char *mv, int nvalues,
                    int *valuebytes, void *kv, void *ptr) {
  EDGE e;
  memcpy(&e, key, sizeof(EDGE));
  MR_kv_add(kv, (char *)&e.vi, (int)sizeof(uint64_t), NULL, 0);
}

/* reduce: row → (degree, NULL) */
static void degree(char *key, int keybytes, char *mv, int nvalues,
                   int *valuebytes, void *kv, void *ptr) {
  uint64_t deg = (uint64_t)nvalues;
  MR_kv_add(kv, (char *)&deg, (int)sizeof(uint64_t), NULL, 0);
}

/* reduce: degree → (degree, count of rows with it) */
static void histo(char *key, int keybytes, char *mv, int nvalues,
                  int *valuebytes, void *kv, void *ptr) {
  uint64_t cnt = (uint64_t)nvalues;
  MR_kv_add(kv, key, keybytes, (char *)&cnt, (int)sizeof(uint64_t));
}

/* descending numeric order on u64 degree keys */
static int ncompare(char *a, int na, char *b, int nb) {
  uint64_t x, y;
  memcpy(&x, a, sizeof(uint64_t));
  memcpy(&y, b, sizeof(uint64_t));
  if (x > y) return -1;
  if (x < y) return 1;
  return 0;
}

/* map over the sorted histogram: print rows, total the row count */
static void stats(uint64_t itask, char *key, int keybytes, char *value,
                  int valuebytes, void *kv, void *ptr) {
  uint64_t deg, cnt;
  memcpy(&deg, key, sizeof(uint64_t));
  memcpy(&cnt, value, sizeof(uint64_t));
  *(uint64_t *)ptr += cnt;
  printf("%llu %llu\n", (unsigned long long)deg, (unsigned long long)cnt);
}

int main(int argc, char **argv) {
  if (argc != 9 && argc != 10) {
    fprintf(stderr,
            "usage: %s N Nz a b c d frac seed [outfile]\n", argv[0]);
    return 1;
  }
  RMAT rmat;
  rmat.nlevels = atoi(argv[1]);
  rmat.nnonzero = atoi(argv[2]);
  rmat.a = atof(argv[3]);
  rmat.b = atof(argv[4]);
  rmat.c = atof(argv[5]);
  rmat.d = atof(argv[6]);
  rmat.fraction = atof(argv[7]);
  int seed = atoi(argv[8]);
  const char *outfile = argc == 10 ? argv[9] : NULL;

  if (rmat.a + rmat.b + rmat.c + rmat.d != 1.0) {
    fprintf(stderr, "ERROR: a,b,c,d must sum to 1\n");
    return 1;
  }
  if (rmat.fraction >= 1.0) {
    fprintf(stderr, "ERROR: fraction must be < 1\n");
    return 1;
  }
  srand48(seed);
  rmat.order = 1 << rmat.nlevels;

  if (MR_init() != 0) {
    fprintf(stderr, "MR_init failed: %s\n", MR_last_error());
    return 1;
  }
  void *mr = MR_create();

  /* generate until all ntotal edges are unique (reference crmat.c loop) */
  int niterate = 0;
  uint64_t ntotal = (uint64_t)rmat.order * rmat.nnonzero;
  uint64_t nremain = ntotal;
  while (nremain) {
    niterate++;
    rmat.ngenerate = (int)nremain;
    MR_map_add(mr, 1, generate, &rmat, 1);
    uint64_t nunique = MR_collate(mr);
    MR_reduce(mr, cull, &rmat);
    if (nunique == ntotal) break;
    nremain = ntotal - nunique;
  }

  if (outfile) {
    char fname[512];
    snprintf(fname, sizeof fname, "%s.0", outfile);
    rmat.fp = fopen(fname, "w");
    if (rmat.fp == NULL) {
      fprintf(stderr, "ERROR: could not open %s\n", fname);
      return 1;
    }
    void *mr2 = MR_copy(mr);
    MR_collate(mr2);
    MR_reduce(mr2, output, &rmat);
    fclose(rmat.fp);
    MR_destroy(mr2);
  }

  printf("%d rows in matrix\n", rmat.order);
  printf("%llu nonzeroes in matrix\n", (unsigned long long)ntotal);

  /* nonzeroes per row → degree histogram, printed descending */
  MR_collate(mr);
  MR_reduce(mr, nonzero, NULL);
  MR_collate(mr);
  MR_reduce(mr, degree, NULL);
  MR_collate(mr);
  MR_reduce(mr, histo, NULL);
  MR_gather(mr, 1);
  MR_sort_keys(mr, ncompare);
  uint64_t total = 0;
  MR_map_mr(mr, mr, stats, &total);
  printf("%llu rows with 0 nonzeroes\n",
         (unsigned long long)(rmat.order - total));
  printf("generated in %d iterations\n", niterate);

  MR_destroy(mr);
  MR_finalize();
  return 0;
}
