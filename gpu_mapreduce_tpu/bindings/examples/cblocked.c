/* C ABI tail exercise: open/close cross-MR adds, multi-pair adds
 * (static + dynamic widths), a blocked multivalue reduce via
 * MR_multivalue_blocks/_block, scrunch, screen print and cumulative
 * stats — the reference surface of src/cmapreduce.h:24-148 beyond the
 * wordfreq basics (see cwordfreq.c).
 *
 * Expected stdout (checked by tests/test_bindings.py):
 *   pairs 36
 *   scrunch groups 1
 *   groups 6 blocked 3 values 36
 *   k0 8 ... (sorted key/count lines)
 */

#include <stdint.h>
#include <stdio.h>
#include <string.h>

#include "../cmapreduce.h"

static void *g_mr;

static void mymap(int itask, void *kv, void *ptr) {
  char keys[12];
  int32_t vals[6];
  for (int i = 0; i < 6; i++) {
    keys[2 * i] = 'k';
    keys[2 * i + 1] = (char)('0' + (i % 3));
    vals[i] = itask;
  }
  MR_kv_add_multi_static(kv, 6, keys, 2, (char *)vals, 4);

  const char *dk = "aabbbcccc"; /* "aa" "bbb" "cccc" */
  int ks[3] = {2, 3, 4};
  const char *dv = "xyyzzz"; /* "x" "yy" "zzz" */
  int vs[3] = {1, 2, 3};
  MR_kv_add_multi_dynamic(kv, 3, dk, ks, dv, vs);
  (void)ptr;
}

static long blocked_groups = 0, plain_groups = 0, total_vals = 0;

static void myreduce(char *key, int keybytes, char *multivalue, int nvalues,
                     int *valuebytes, void *kv, void *ptr) {
  uint32_t count = 0;
  if (multivalue == NULL && nvalues == 0) {
    blocked_groups++;
    uint64_t nb = MR_multivalue_blocks(g_mr);
    for (int b = 0; b < (int)nb; b++) {
      char *bm;
      int *bs;
      int n = MR_multivalue_block(g_mr, b, &bm, &bs);
      if (n < 0) {
        fprintf(stderr, "block error: %s\n", MR_last_error());
        return;
      }
      /* touch the buffers like a real consumer would */
      long bytes = 0;
      for (int i = 0; i < n; i++) bytes += bs[i];
      (void)bm;
      (void)bytes;
      count += (uint32_t)n;
    }
  } else {
    plain_groups++;
    count = (uint32_t)nvalues;
    (void)valuebytes;
  }
  total_vals += count;
  MR_kv_add(kv, key, keybytes, (char *)&count, 4);
  (void)ptr;
}

static void myscan(char *key, int keybytes, char *value, int valuebytes,
                   void *ptr) {
  uint32_t count;
  memcpy(&count, value, 4);
  printf("%.*s %u\n", keybytes, key, count);
  (void)valuebytes;
  (void)ptr;
}

int main(void) {
  setvbuf(stdout, NULL, _IONBF, 0); /* diagnosable output under a crash */
  if (MR_init() != 0) {
    fprintf(stderr, "init failed: %s\n", MR_last_error());
    return 1;
  }
  void *mr = MR_create();
  g_mr = mr;

  /* open/close: two map rounds add into ONE KV */
  MR_open(mr);
  MR_map_add(mr, 2, mymap, NULL, 1);
  MR_map_add(mr, 2, mymap, NULL, 1);
  uint64_t npairs = MR_close(mr);
  printf("pairs %llu\n", (unsigned long long)npairs);

  /* scrunch a copy into a single collapsed group */
  void *cp = MR_copy(mr);
  MR_scrunch(cp, 1, "ALL", 3);
  uint64_t ngroups = MR_kmv_stats(cp);
  printf("scrunch groups %llu\n", (unsigned long long)ngroups);
  MR_destroy(cp);

  /* blocked reduce: groups > 5 values arrive as nvalues==0 blocks */
  MR_set(mr, "c_block_rows", "5");
  MR_convert(mr);
  MR_reduce(mr, myreduce, NULL);
  printf("groups %ld blocked %ld values %ld\n",
         blocked_groups + plain_groups, blocked_groups, total_vals);

  MR_sort_keys_flag(mr, 5);
  MR_scan_kv(mr, myscan, NULL);

  MR_print(mr, 1, 5, 1);       /* screen print (stderr-irrelevant) */
  MR_cummulative_stats(mr, 1, 0);
  if (MR_last_error() != NULL) {
    fprintf(stderr, "error: %s\n", MR_last_error());
    return 1;
  }
  MR_destroy(mr);
  MR_finalize();
  return 0;
}
