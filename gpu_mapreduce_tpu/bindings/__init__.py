"""Bindings layer — the C ABI over the framework.

* ``cmapreduce.h`` / ``cmapreduce.c`` — flat ``MR_*`` C interface with C
  function-pointer callbacks (reference ``src/cmapreduce.{h,cpp}``) plus
  the ``OINK_*`` script driver (reference ``oink/library.{h,cpp}``); the
  shim embeds CPython and forwards to :mod:`.cbridge`.
* ``examples/cwordfreq.c`` — the reference's ``examples/cwordfreq.c``
  workload through this API.

The Python API needs no binding: the framework *is* Python-first (the
reference's ``python/mrmpi.py`` ctypes+pickle wrapper is this package's
moral ancestor, inverted).
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
from typing import List, Optional

_DIR = os.path.dirname(os.path.abspath(__file__))


def embed_flags() -> List[str]:
    """Compiler/linker flags to embed this CPython (what
    ``python3-config --includes --ldflags --embed`` prints)."""
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    libpl = sysconfig.get_config_var("LIBPL")  # static builds keep
    ver = sysconfig.get_config_var("LDVERSION")  # libpython here
    flags = [f"-I{inc}", f"-L{libdir}"]
    if libpl:
        flags.append(f"-L{libpl}")
    flags += [f"-lpython{ver}", "-ldl", "-lm", f"-Wl,-rpath,{libdir}"]
    return flags


def build_example(name: str, out: Optional[str] = None,
                  cc: Optional[str] = None) -> str:
    """Compile bindings/examples/<name>.c + cmapreduce.c into an
    executable; returns its path.  Raises RuntimeError with the compiler
    output on failure."""
    cc = cc or os.environ.get("CC", "gcc")
    src = os.path.join(_DIR, "examples", f"{name}.c")
    shim = os.path.join(_DIR, "cmapreduce.c")
    out = out or os.path.join(_DIR, "examples", name)
    cmd = [cc, "-O2", src, shim] + embed_flags() + ["-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=180)
    if proc.returncode != 0:
        raise RuntimeError(f"{' '.join(cmd)}\n{proc.stderr}")
    return out
