"""Python side of the C ABI — handle table + C-callback trampolines.

The reference's C interface (``src/cmapreduce.{h,cpp}``) wraps the C++
MapReduce class in flat ``MR_*`` functions over ``void*`` handles, with
user callbacks as C function pointers.  Our engine is Python, so the
shim inverts: ``bindings/cmapreduce.c`` embeds CPython and forwards every
call here; C callback pointers arrive as integers and are invoked back
through ``ctypes.CFUNCTYPE`` with the reference's byte-oriented
signatures (map ``(itask, kv, ptr)`` / file map ``(itask, fname, kv,
ptr)`` / reduce ``(key, keybytes, multivalue, nvalues, valuebytes, kv,
ptr)`` / scan ``(key, keybytes, value, valuebytes, ptr)`` —
``src/cmapreduce.h:24-148``).

Keys/values cross the boundary as raw bytes, exactly like the
reference's byte-packed pages: C-added pairs become BytesColumn rows;
typed columns flatten to their little-endian bytes on the way out (a C
struct view, ``oink/typedefs.h`` style).
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

import numpy as np

from ..core.mapreduce import MapReduce
from ..oink.script import OinkScript

_handles: Dict[int, object] = {}
_next_id = [1]
# mr handle → active BlockedMultivalue during a nvalues==0 reduce call
_blockmeta: Dict[int, object] = {}
# mr handle → block_rows threshold for the C reduce tier (the ONEMAX
# stress hook, src/keymultivalue.cpp:43-45; set via
# MR_set(mr, "c_block_rows", ...))
_c_block_rows: Dict[int, int] = {}

MAPTASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                              ctypes.c_void_p)
MAPFILE_FN = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_void_p, ctypes.c_void_p)
REDUCE_FN = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_int,
                             ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                             ctypes.POINTER(ctypes.c_int), ctypes.c_void_p,
                             ctypes.c_void_p)
SCAN_FN = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_int,
                           ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                           ctypes.c_void_p)
MAPCHUNK_FN = ctypes.CFUNCTYPE(None, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                               ctypes.c_void_p, ctypes.c_void_p)
MAPMR_FN = ctypes.CFUNCTYPE(None, ctypes.c_uint64,
                            ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                            ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                            ctypes.c_void_p, ctypes.c_void_p)
HASH_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int)
CMP_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.POINTER(ctypes.c_char),
                          ctypes.c_int, ctypes.POINTER(ctypes.c_char),
                          ctypes.c_int)
SCANKMV_FN = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_int,
                              ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                              ctypes.POINTER(ctypes.c_int), ctypes.c_void_p)


# created at import: the lazy create was itself racy — two mapstyle-2
# workers making the FIRST concurrent _register could each see None and
# build different Lock objects, un-serializing the very RMW the lock
# guards (ADVICE r5)
import threading as _threading

_reg_lock = _threading.Lock()


def _register(obj) -> int:
    # locked: mapstyle-2 worker threads register per-task accumulators
    # concurrently, and `_next_id[0] += 1` is a read-modify-write — two
    # tasks sharing one handle would cross-route their kv_adds (r5
    # review)
    with _reg_lock:
        h = _next_id[0]
        _next_id[0] += 1
        _handles[h] = obj
    return h


def _unregister(h: int):
    """Locked twin of :func:`_register`: every ``_handles`` mutation
    goes through ``_reg_lock`` (mapstyle-2 workers pop per-task
    accumulators concurrently with registrations — mrlint
    lock-discipline)."""
    with _reg_lock:
        return _handles.pop(h, None)


def _get(h: int):
    return _handles[h]


def _to_bytes(x) -> bytes:
    """Any key/value → its raw bytes (C struct view of typed rows)."""
    if isinstance(x, bytes):
        return x
    if isinstance(x, str):
        return x.encode()
    if isinstance(x, tuple):
        return b"".join(_to_bytes(e) for e in x)
    return np.asarray(x).tobytes()


class _KVAccum:
    """Batches per-pair C adds into one columnar add at flush (the
    reference appends to a page; we append to a BytesColumn batch)."""

    def __init__(self, kv):
        self.kv = kv
        self.keys: List[bytes] = []
        self.values: List[bytes] = []

    def add(self, key: bytes, value: bytes):
        self.keys.append(key)
        self.values.append(value)

    def flush(self):
        if self.keys:
            self.kv.add_batch(self.keys, self.values)
            self.keys, self.values = [], []


# ---------------------------------------------------------------------------
# entry points called from cmapreduce.c
# ---------------------------------------------------------------------------

def mr_create() -> int:
    return _register(MapReduce())


def mr_destroy(h: int):
    _unregister(h)
    _blockmeta.pop(h, None)
    _c_block_rows.pop(h, None)


def mr_copy(h: int) -> int:
    h2 = _register(_get(h).copy())
    if h in _c_block_rows:      # MR_copy carries every setting over
        _c_block_rows[h2] = _c_block_rows[h]
    return h2


def mr_set(h: int, name: str, value: str) -> int:
    if name == "c_block_rows":
        _c_block_rows[h] = int(value)
        return 0
    mr = _get(h)
    mr.set(**{name: value if name == "fpath" else int(value)})
    return 0


def kv_add(kvh: int, key: bytes, value: bytes):
    _get(kvh).add(key, value)


def mr_map(h: int, nmap: int, fnptr: int, appptr: int, addflag: int) -> int:
    fn = MAPTASK_FN(fnptr)
    mr = _get(h)

    def wrapper(itask, kv, ptr):
        acc = _KVAccum(kv)
        kvh = _register(acc)
        try:
            fn(itask, kvh, appptr)
            acc.flush()
        finally:
            _unregister(kvh)

    return mr.map(nmap, wrapper, addflag=addflag)


def mr_map_file_list(h: int, paths: List[bytes], fnptr: int, appptr: int,
                     addflag: int) -> int:
    fn = MAPFILE_FN(fnptr)
    mr = _get(h)

    def wrapper(itask, fname, kv, ptr):
        acc = _KVAccum(kv)
        kvh = _register(acc)
        try:
            fn(itask, fname.encode() if isinstance(fname, str) else fname,
               kvh, appptr)
            acc.flush()
        finally:
            _unregister(kvh)

    return mr.map_files([p.decode() for p in paths], wrapper,
                        addflag=addflag)


def mr_map_file_chunks(h: int, which: str, nmap: int, paths: List[bytes],
                       sep: bytes, delta: int, fnptr: int,
                       appptr: int) -> int:
    """Chunked file maps (reference MR_map_file_char/str): the C callback
    receives each chunk's raw bytes."""
    fn = MAPCHUNK_FN(fnptr)
    mr = _get(h)

    def wrapper(itask, chunk, kv, ptr):
        acc = _KVAccum(kv)
        kvh = _register(acc)
        try:
            buf = ctypes.create_string_buffer(bytes(chunk), len(chunk))
            fn(itask, buf, len(chunk), kvh, appptr)
            acc.flush()
        finally:
            _unregister(kvh)

    files = [p.decode() for p in paths]
    if which == "char":
        return mr.map_file_char(nmap, files, 0, 0, sep, delta, wrapper)
    return mr.map_file_str(nmap, files, 0, 0, sep, delta, wrapper)


def mr_map_mr(h: int, h2: int, fnptr: int, appptr: int) -> int:
    """MR_map_mr: per-pair map over an existing MR's KV (reference
    map(mr,func,...) via C, src/cmapreduce.cpp; self-map h2 == h works
    through map_mr's snapshot).  The callback sees the raw key/value
    bytes exactly as the reference's byte-packed pages would.

    Unlike the task-scoped wrappers, this one registers the target kv
    ONCE and lets KeyValue.add's own 1M-row scalar buffer do the
    batching — a per-pair _KVAccum would build one single-row frame per
    pair (r5 review)."""
    fn = MAPMR_FN(fnptr)
    mr, src = _get(h), _get(h2)
    reg: dict = {}

    def wrapper(itask, k, v, kv, ptr):
        kvh = reg.get(id(kv))
        if kvh is None:
            kvh = _register(kv)
            reg[id(kv)] = kvh
        kb, vb = _to_bytes(k), _to_bytes(v)
        fn(itask,
           ctypes.create_string_buffer(kb, len(kb)), len(kb),
           ctypes.create_string_buffer(vb, len(vb)), len(vb),
           kvh, appptr)

    try:
        return mr.map_mr(src, wrapper)
    finally:
        for kvh in reg.values():
            _unregister(kvh)


def mr_aggregate_hash(h: int, fnptr: int) -> int:
    """MR_aggregate with a user C hash: proc = myhash(key, keybytes) %
    nprocs, evaluated on the host per key (the reference calls it per
    pair too, src/mapreduce.cpp:469-471)."""
    fn = HASH_FN(fnptr)

    def host_hash(key_bytes_list):
        return np.asarray([fn(b, len(b)) for b in key_bytes_list],
                          np.int64)

    host_hash.host_hash = True
    return _get(h).aggregate(host_hash)


def _bytes_cmp(fnptr: int):
    fn = CMP_FN(fnptr)

    def cmp(a, b):
        ab, bb = _to_bytes(a), _to_bytes(b)
        return fn(ctypes.create_string_buffer(ab, len(ab)), len(ab),
                  ctypes.create_string_buffer(bb, len(bb)), len(bb))

    return cmp


def mr_sort_cmp(h: int, which: str, fnptr: int) -> int:
    mr = _get(h)
    cmp = _bytes_cmp(fnptr)
    if which == "keys":
        return mr.sort_keys(cmp)
    if which == "values":
        return mr.sort_values(cmp)
    return mr.sort_multivalues(cmp)


def mr_scan_kmv(h: int, fnptr: int, appptr: int) -> int:
    fn = SCANKMV_FN(fnptr)

    def wrapper(k, vals, ptr):
        kb = _to_bytes(k)
        bvals = [_to_bytes(v) for v in vals]
        mv = b"".join(bvals)
        sizes = (ctypes.c_int * len(bvals))(*[len(b) for b in bvals])
        buf = ctypes.create_string_buffer(mv, len(mv))
        fn(kb, len(kb), buf, len(bvals), sizes, appptr)

    return _get(h).scan_kmv(wrapper)


def _call_reduce(fn, appptr, key, vals, kv, mrh=None):
    from ..core.frame import BlockedMultivalue
    kb = _to_bytes(key)
    acc = _KVAccum(kv)
    kvh = _register(acc)
    try:
        if isinstance(vals, BlockedMultivalue):
            # the reference's multi-page signal: NULL multivalue +
            # nvalues==0; the callback pulls blocks through
            # MR_multivalue_blocks/_block (src/mapreduce.cpp:1874-1925)
            _blockmeta[mrh] = vals
            try:
                fn(kb, len(kb), None, 0, None, kvh, appptr)
            finally:
                _blockmeta.pop(mrh, None)
        else:
            bvals = [_to_bytes(v) for v in vals]
            mv = b"".join(bvals)
            sizes = (ctypes.c_int * len(bvals))(*[len(b) for b in bvals])
            buf = ctypes.create_string_buffer(mv, len(mv))
            fn(kb, len(kb), buf, len(bvals), sizes, kvh, appptr)
        acc.flush()
    finally:
        _unregister(kvh)


def mr_reduce(h: int, fnptr: int, appptr: int) -> int:
    fn = REDUCE_FN(fnptr)
    mr = _get(h)
    return mr.reduce(lambda k, vals, kv, ptr:
                     _call_reduce(fn, appptr, k, vals, kv, mrh=h),
                     block_rows=_c_block_rows.get(h))


def mr_compress(h: int, fnptr: int, appptr: int) -> int:
    fn = REDUCE_FN(fnptr)
    mr = _get(h)
    return mr.compress(lambda k, vals, kv, ptr:
                       _call_reduce(fn, appptr, k, vals, kv, mrh=h),
                       block_rows=_c_block_rows.get(h))


def mr_multivalue_blocks(h: int) -> int:
    """#blocks of the active nvalues==0 group (0 outside one)."""
    bmv = _blockmeta.get(h)
    if bmv is None:
        return 0
    return -(-bmv.nvalues_total // bmv.block_rows)


def mr_multivalue_block(h: int, iblock: int):
    """→ (nvalues, multivalue bytes, int32 LE valuesizes bytes) for block
    ``iblock`` of the active group; the C shim pins the buffers until the
    next block request (reference page-buffer lifetime)."""
    bmv = _blockmeta.get(h)
    if bmv is None:
        raise RuntimeError("MR_multivalue_block outside a blocked reduce")
    fr, i, br = bmv._frame, bmv._i, bmv.block_rows
    start = int(fr.offsets[i]) + iblock * br
    stop = min(start + br, int(fr.offsets[i + 1]))
    if iblock < 0 or start >= int(fr.offsets[i + 1]):
        raise IndexError(
            f"block {iblock} out of range "
            f"(group has {mr_multivalue_blocks(h)} blocks)")
    col = fr.values.slice(start, stop)
    bvals = [_to_bytes(v) for v in col.tolist()]
    sizes = np.asarray([len(b) for b in bvals], np.int32)
    return len(bvals), b"".join(bvals), sizes.tobytes()


def mr_scan_kv(h: int, fnptr: int, appptr: int) -> int:
    fn = SCAN_FN(fnptr)

    def wrapper(k, v, ptr):
        kb, vb = _to_bytes(k), _to_bytes(v)
        buf = ctypes.create_string_buffer(vb, len(vb))
        fn(kb, len(kb), buf, len(vb), appptr)

    return _get(h).scan_kv(wrapper)


def mr_method_u64(h: int, name: str, *args) -> int:
    """Run a no-callback MapReduce method returning a count: aggregate,
    convert, collate, clone, collapse, close, open, gather, broadcast,
    add, sort_keys, sort_values, sort_multivalues."""
    mr = _get(h)
    if name == "aggregate":
        return mr.aggregate(None)
    if name == "collate":
        return mr.collate(None)
    if name == "collapse":
        return mr.collapse(args[0])
    if name == "add":
        return mr.add(_get(args[0]))
    if name == "open":
        mr.open(*args)
        return 0
    return getattr(mr, name)(*args)


def mr_stats(h: int, which: str) -> int:
    mr = _get(h)
    if which == "kv":
        return mr.kv_stats(0)[0] if mr.kv is not None else 0
    return mr.kmv_stats(0)[0] if mr.kmv is not None else 0


def mr_print_file(h: int, path: str, kflag: int, vflag: int) -> int:
    return _get(h).print(kflag=kflag, vflag=vflag, file=path)


def mr_print(h: int, nstride: int, kflag: int, vflag: int) -> int:
    """Screen print (reference MR_print, src/cmapreduce.h)."""
    return _get(h).print(nstride=nstride, kflag=kflag, vflag=vflag)


def mr_cummulative_stats(h: int, level: int, reset: int) -> int:
    _get(h).cummulative_stats(level, reset)
    return 0


def kv_add_multi_static(kvh: int, n: int, keyblob: bytes, keybytes: int,
                        valblob: bytes, valuebytes: int):
    """n pairs of FIXED-width keys/values packed back to back (reference
    MR_kv_add_multi_static)."""
    acc = _get(kvh)
    for i in range(n):
        acc.add(keyblob[i * keybytes:(i + 1) * keybytes],
                valblob[i * valuebytes:(i + 1) * valuebytes])


def kv_add_multi_dynamic(kvh: int, n: int, keyblob: bytes,
                         keysizes: bytes, valblob: bytes,
                         valsizes: bytes):
    """n pairs of VARIABLE-width keys/values; per-pair byte counts arrive
    as int32 arrays (reference MR_kv_add_multi_dynamic)."""
    acc = _get(kvh)
    ks = np.frombuffer(keysizes, np.int32, n)
    vs = np.frombuffer(valsizes, np.int32, n)
    ko = np.concatenate([[0], np.cumsum(ks)])
    vo = np.concatenate([[0], np.cumsum(vs)])
    for i in range(n):
        acc.add(keyblob[ko[i]:ko[i + 1]], valblob[vo[i]:vo[i + 1]])


# -- OINK script driver (reference oink/library.h mrmpi_open/...) ----------

def oink_open(logfile: Optional[str]) -> int:
    return _register(OinkScript(screen=None, logfile=logfile or None))


def oink_file(h: int, path: str):
    _get(h).run_file(path)


def oink_command(h: int, line: str) -> Optional[str]:
    return _get(h).one(line)


def oink_close(h: int):
    interp = _unregister(h)
    if interp is not None:
        interp.close()
