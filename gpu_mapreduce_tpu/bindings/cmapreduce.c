/* C ABI shim — embeds CPython and forwards every MR_* call to
 * gpu_mapreduce_tpu.bindings.cbridge (the reference implements
 * src/cmapreduce.cpp as a thin forwarding layer over the C++ class; this
 * is the same layer over the Python engine).
 *
 * Handles: cbridge keeps an int→object table; the void* handles here are
 * those ints cast to pointers.  C callback pointers travel to Python as
 * integers and are re-entered through ctypes (cbridge.*_FN).
 *
 * Build (see bindings/__init__.py build_clib()):
 *   gcc -shared -fPIC cmapreduce.c $(python3-config --includes) \
 *       $(python3-config --ldflags --embed) -o libcmapreduce.so
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#include "cmapreduce.h"

static PyObject *bridge = NULL;
static char errbuf[4096];
static int have_error = 0;

static void capture_error(void) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  errbuf[0] = '\0';
  if (value != NULL) {
    PyObject *s = PyObject_Str(value);
    if (s != NULL) {
      const char *msg = PyUnicode_AsUTF8(s);
      if (msg != NULL) strncpy(errbuf, msg, sizeof(errbuf) - 1);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  have_error = 1;
}

/* call bridge.<method>(args...) → new ref or NULL (error captured) */
static PyObject *bridge_call(const char *method, const char *fmt, ...) {
  if (bridge == NULL) {
    strncpy(errbuf, "MR_init() not called", sizeof(errbuf) - 1);
    have_error = 1;
    return NULL;
  }
  have_error = 0;
  PyGILState_STATE g = PyGILState_Ensure();
  va_list ap;
  va_start(ap, fmt);
  PyObject *args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject *result = NULL;
  if (args != NULL) {
    PyObject *fn = PyObject_GetAttrString(bridge, method);
    if (fn != NULL) {
      result = PyObject_CallObject(fn, args);
      Py_DECREF(fn);
    }
    Py_DECREF(args);
  }
  if (result == NULL) capture_error();
  PyGILState_Release(g);
  return result;
}

/* Every Python-object touch needs the GIL: MR_* functions are legal
 * INSIDE map/reduce callbacks (MR_kv_add, MR_multivalue_blocks...),
 * where ctypes released the GIL before entering the C callback — a
 * GIL-less PyErr_Occurred there dereferences a NULL thread state. */
static uint64_t as_u64(PyObject *r) {
  if (r == NULL) return 0;
  PyGILState_STATE g = PyGILState_Ensure();
  uint64_t v = 0;
  if (r != Py_None) v = (uint64_t)PyLong_AsUnsignedLongLong(r);
  if (PyErr_Occurred()) {
    capture_error();
    v = 0;
  }
  Py_DECREF(r);
  PyGILState_Release(g);
  return v;
}

static void drop(PyObject *r) {
  if (r == NULL) return;
  PyGILState_STATE g = PyGILState_Ensure();
  Py_DECREF(r);
  PyGILState_Release(g);
}

/* ------------------------------------------------------------------ */

int MR_init(void) {
  if (bridge != NULL) return 0;
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  PyGILState_STATE g = PyGILState_Ensure();
  bridge = PyImport_ImportModule("gpu_mapreduce_tpu.bindings.cbridge");
  if (bridge == NULL) capture_error();
  PyGILState_Release(g);
  return bridge == NULL ? -1 : 0;
}

void MR_finalize(void) {
  Py_XDECREF(bridge);
  bridge = NULL;
  if (Py_IsInitialized()) Py_FinalizeEx();
}

const char *MR_last_error(void) { return have_error ? errbuf : NULL; }

void *MR_create(void) {
  return (void *)(intptr_t)as_u64(bridge_call("mr_create", "()"));
}

void MR_destroy(void *mr) {
  drop(bridge_call("mr_destroy", "(n)", (Py_ssize_t)mr));
}

void *MR_copy(void *mr) {
  return (void *)(intptr_t)as_u64(
      bridge_call("mr_copy", "(n)", (Py_ssize_t)mr));
}

int MR_set(void *mr, const char *name, const char *value) {
  PyObject *r = bridge_call("mr_set", "(nss)", (Py_ssize_t)mr, name, value);
  if (r == NULL) return -1;
  Py_DECREF(r);
  return 0;
}

void MR_kv_add(void *kv, const char *key, int keybytes, const char *value,
               int valuebytes) {
  drop(bridge_call("kv_add", "(ny#y#)", (Py_ssize_t)kv, key,
                         (Py_ssize_t)keybytes, value,
                         (Py_ssize_t)valuebytes));
}

uint64_t MR_map_add(void *mr, int nmap, void (*mymap)(int, void *, void *),
                    void *ptr, int addflag) {
  return as_u64(bridge_call("mr_map", "(ninni)", (Py_ssize_t)mr, nmap,
                            (Py_ssize_t)(intptr_t)mymap,
                            (Py_ssize_t)(intptr_t)ptr, addflag));
}

uint64_t MR_map(void *mr, int nmap, void (*mymap)(int, void *, void *),
                void *ptr) {
  return MR_map_add(mr, nmap, mymap, ptr, 0);
}

static PyObject *path_list(int nstr, char **paths) {
  /* GIL-safe: map-from-a-callback is legal (the doc promises it) */
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject *list = PyList_New(nstr);
  if (list != NULL)
    for (int i = 0; i < nstr; i++)
      PyList_SET_ITEM(list, i, PyBytes_FromString(paths[i]));
  PyGILState_Release(g);
  return list;
}

uint64_t MR_map_file_list(void *mr, int nstr, char **paths,
                          void (*mymap)(int, char *, void *, void *),
                          void *ptr) {
  PyObject *list = path_list(nstr, paths);
  if (list == NULL) return 0;
  uint64_t n = as_u64(bridge_call("mr_map_file_list", "(nOnni)",
                                  (Py_ssize_t)mr, list,
                                  (Py_ssize_t)(intptr_t)mymap,
                                  (Py_ssize_t)(intptr_t)ptr, 0));
  drop(list);
  return n;
}

static uint64_t map_chunks(void *mr, const char *which, int nmap, int nstr,
                           char **paths, const char *sep, int seplen,
                           int delta, void (*fn)(int, char *, int, void *,
                                                 void *),
                           void *ptr) {
  PyObject *list = path_list(nstr, paths);
  if (list == NULL) return 0;
  uint64_t n = as_u64(bridge_call("mr_map_file_chunks", "(nsiOy#inn)",
                                  (Py_ssize_t)mr, which, nmap, list, sep,
                                  (Py_ssize_t)seplen, delta,
                                  (Py_ssize_t)(intptr_t)fn,
                                  (Py_ssize_t)(intptr_t)ptr));
  drop(list);
  return n;
}

uint64_t MR_map_file_char(void *mr, int nmap, int nstr, char **paths,
                          char sepchar, int delta,
                          void (*fn)(int, char *, int, void *, void *),
                          void *ptr) {
  return map_chunks(mr, "char", nmap, nstr, paths, &sepchar, 1, delta, fn,
                    ptr);
}

uint64_t MR_map_file_str(void *mr, int nmap, int nstr, char **paths,
                         const char *sepstr, int delta,
                         void (*fn)(int, char *, int, void *, void *),
                         void *ptr) {
  return map_chunks(mr, "str", nmap, nstr, paths, sepstr,
                    (int)strlen(sepstr), delta, fn, ptr);
}

uint64_t MR_map_mr(void *mr, void *mr2,
                   void (*fn)(uint64_t, char *, int, char *, int, void *,
                              void *),
                   void *ptr) {
  return as_u64(bridge_call("mr_map_mr", "(nnnn)", (Py_ssize_t)mr,
                            (Py_ssize_t)mr2, (Py_ssize_t)(intptr_t)fn,
                            (Py_ssize_t)(intptr_t)ptr));
}

uint64_t MR_aggregate_hash(void *mr, int (*myhash)(char *, int)) {
  return as_u64(bridge_call("mr_aggregate_hash", "(nn)", (Py_ssize_t)mr,
                            (Py_ssize_t)(intptr_t)myhash));
}

uint64_t MR_reduce(void *mr,
                   void (*fn)(char *, int, char *, int, int *, void *,
                              void *),
                   void *ptr) {
  return as_u64(bridge_call("mr_reduce", "(nnn)", (Py_ssize_t)mr,
                            (Py_ssize_t)(intptr_t)fn,
                            (Py_ssize_t)(intptr_t)ptr));
}

uint64_t MR_compress(void *mr,
                     void (*fn)(char *, int, char *, int, int *, void *,
                                void *),
                     void *ptr) {
  return as_u64(bridge_call("mr_compress", "(nnn)", (Py_ssize_t)mr,
                            (Py_ssize_t)(intptr_t)fn,
                            (Py_ssize_t)(intptr_t)ptr));
}

uint64_t MR_scan_kv(void *mr,
                    void (*fn)(char *, int, char *, int, void *),
                    void *ptr) {
  return as_u64(bridge_call("mr_scan_kv", "(nnn)", (Py_ssize_t)mr,
                            (Py_ssize_t)(intptr_t)fn,
                            (Py_ssize_t)(intptr_t)ptr));
}

static uint64_t method0(void *mr, const char *name) {
  return as_u64(bridge_call("mr_method_u64", "(ns)", (Py_ssize_t)mr, name));
}

uint64_t MR_aggregate(void *mr) { return method0(mr, "aggregate"); }
uint64_t MR_convert(void *mr) { return method0(mr, "convert"); }
uint64_t MR_collate(void *mr) { return method0(mr, "collate"); }
uint64_t MR_clone(void *mr) { return method0(mr, "clone"); }

uint64_t MR_collapse(void *mr, const char *key, int keybytes) {
  return as_u64(bridge_call("mr_method_u64", "(nsy#)", (Py_ssize_t)mr,
                            "collapse", key, (Py_ssize_t)keybytes));
}

uint64_t MR_gather(void *mr, int nprocs) {
  return as_u64(bridge_call("mr_method_u64", "(nsi)", (Py_ssize_t)mr,
                            "gather", nprocs));
}

uint64_t MR_broadcast(void *mr, int root) {
  return as_u64(bridge_call("mr_method_u64", "(nsi)", (Py_ssize_t)mr,
                            "broadcast", root));
}

uint64_t MR_add(void *mr, void *mr2) {
  return as_u64(bridge_call("mr_method_u64", "(nsn)", (Py_ssize_t)mr,
                            "add", (Py_ssize_t)mr2));
}

uint64_t MR_scrunch(void *mr, int nprocs, const char *key, int keybytes) {
  return as_u64(bridge_call("mr_method_u64", "(nsiy#)", (Py_ssize_t)mr,
                            "scrunch", nprocs, key, (Py_ssize_t)keybytes));
}

void MR_open(void *mr) {
  drop(bridge_call("mr_method_u64", "(ns)", (Py_ssize_t)mr, "open"));
}

uint64_t MR_close(void *mr) { return method0(mr, "close"); }

uint64_t MR_sort_keys_flag(void *mr, int flag) {
  return as_u64(bridge_call("mr_method_u64", "(nsi)", (Py_ssize_t)mr,
                            "sort_keys", flag));
}

uint64_t MR_sort_values_flag(void *mr, int flag) {
  return as_u64(bridge_call("mr_method_u64", "(nsi)", (Py_ssize_t)mr,
                            "sort_values", flag));
}

uint64_t MR_sort_multivalues_flag(void *mr, int flag) {
  return as_u64(bridge_call("mr_method_u64", "(nsi)", (Py_ssize_t)mr,
                            "sort_multivalues", flag));
}

static uint64_t sort_cmp(void *mr, const char *which,
                         int (*cmp)(char *, int, char *, int)) {
  return as_u64(bridge_call("mr_sort_cmp", "(nsn)", (Py_ssize_t)mr, which,
                            (Py_ssize_t)(intptr_t)cmp));
}

uint64_t MR_sort_keys(void *mr, int (*cmp)(char *, int, char *, int)) {
  return sort_cmp(mr, "keys", cmp);
}

uint64_t MR_sort_values(void *mr, int (*cmp)(char *, int, char *, int)) {
  return sort_cmp(mr, "values", cmp);
}

uint64_t MR_sort_multivalues(void *mr,
                             int (*cmp)(char *, int, char *, int)) {
  return sort_cmp(mr, "multivalues", cmp);
}

uint64_t MR_scan_kmv(void *mr,
                     void (*fn)(char *, int, char *, int, int *, void *),
                     void *ptr) {
  return as_u64(bridge_call("mr_scan_kmv", "(nnn)", (Py_ssize_t)mr,
                            (Py_ssize_t)(intptr_t)fn,
                            (Py_ssize_t)(intptr_t)ptr));
}

uint64_t MR_kv_stats(void *mr) {
  return as_u64(bridge_call("mr_stats", "(ns)", (Py_ssize_t)mr, "kv"));
}

uint64_t MR_kmv_stats(void *mr) {
  return as_u64(bridge_call("mr_stats", "(ns)", (Py_ssize_t)mr, "kmv"));
}

int MR_print_file(void *mr, const char *path, int kflag, int vflag) {
  PyObject *r = bridge_call("mr_print_file", "(nsii)", (Py_ssize_t)mr, path,
                            kflag, vflag);
  if (r == NULL) return -1;
  Py_DECREF(r);
  return 0;
}

uint64_t MR_print(void *mr, int nstride, int kflag, int vflag) {
  return as_u64(bridge_call("mr_print", "(niii)", (Py_ssize_t)mr, nstride,
                            kflag, vflag));
}

void MR_cummulative_stats(void *mr, int level, int reset) {
  drop(bridge_call("mr_cummulative_stats", "(nii)", (Py_ssize_t)mr,
                         level, reset));
}

void MR_kv_add_multi_static(void *kv, int n, const char *key, int keybytes,
                            const char *value, int valuebytes) {
  drop(bridge_call(
      "kv_add_multi_static", "(niy#iy#i)", (Py_ssize_t)kv, n, key,
      (Py_ssize_t)((Py_ssize_t)n * keybytes), keybytes, value,
      (Py_ssize_t)((Py_ssize_t)n * valuebytes), valuebytes));
}

void MR_kv_add_multi_dynamic(void *kv, int n, const char *key,
                             const int *keybytes, const char *value,
                             const int *valuebytes) {
  Py_ssize_t tk = 0, tv = 0;
  for (int i = 0; i < n; i++) {
    tk += keybytes[i];
    tv += valuebytes[i];
  }
  drop(bridge_call(
      "kv_add_multi_dynamic", "(niy#y#y#y#)", (Py_ssize_t)kv, n, key, tk,
      (const char *)keybytes, (Py_ssize_t)(n * (Py_ssize_t)sizeof(int)),
      value, tv, (const char *)valuebytes,
      (Py_ssize_t)(n * (Py_ssize_t)sizeof(int))));
}

/* multi-block multivalue API: the bridge returns (nval, mv, sizes); the
 * buffers stay pinned here until the next block request (reference
 * page-buffer lifetime, src/mapreduce.cpp:1874-1925) */
static PyObject *blk_hold = NULL;

uint64_t MR_multivalue_blocks(void *mr) {
  return as_u64(
      bridge_call("mr_multivalue_blocks", "(n)", (Py_ssize_t)mr));
}

int MR_multivalue_block(void *mr, int iblock, char **ptr_multivalue,
                        int **ptr_valuesizes) {
  PyObject *r =
      bridge_call("mr_multivalue_block", "(ni)", (Py_ssize_t)mr, iblock);
  if (r == NULL) return -1;
  PyGILState_STATE g = PyGILState_Ensure();
  Py_XDECREF(blk_hold);
  blk_hold = r; /* keeps mv + sizes bytes alive */
  long nval = PyLong_AsLong(PyTuple_GetItem(r, 0));
  *ptr_multivalue = PyBytes_AsString(PyTuple_GetItem(r, 1));
  *ptr_valuesizes = (int *)PyBytes_AsString(PyTuple_GetItem(r, 2));
  PyGILState_Release(g);
  return (int)nval;
}

void MR_multivalue_block_select(void *mr, int which) {
  (void)mr;
  (void)which; /* reference 2-page scratch selector; no-op here */
}

/* -- OINK script driver -------------------------------------------- */

void *OINK_open(const char *logfile) {
  PyObject *r;
  if (logfile != NULL)
    r = bridge_call("oink_open", "(s)", logfile);
  else
    r = bridge_call("oink_open", "(O)", Py_None);
  return (void *)(intptr_t)as_u64(r);
}

int OINK_file(void *oink, const char *path) {
  PyObject *r = bridge_call("oink_file", "(ns)", (Py_ssize_t)oink, path);
  if (r == NULL) return -1;
  Py_DECREF(r);
  return 0;
}

int OINK_command(void *oink, const char *line) {
  PyObject *r = bridge_call("oink_command", "(ns)", (Py_ssize_t)oink, line);
  if (r == NULL) return -1;
  Py_DECREF(r);
  return 0;
}

void OINK_close(void *oink) {
  drop(bridge_call("oink_close", "(n)", (Py_ssize_t)oink));
}
