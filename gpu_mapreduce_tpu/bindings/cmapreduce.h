/* C interface to the TPU MapReduce framework.
 *
 * The counterpart of the reference's src/cmapreduce.h: flat MR_*
 * functions over opaque handles, with user callbacks as C function
 * pointers carrying the same byte-oriented signatures.  The engine is
 * the Python/JAX framework, embedded via CPython (cmapreduce.c); call
 * MR_init() once before anything else and MR_finalize() at exit.
 *
 * Handles are returned by MR_create(); KV handles only exist inside
 * callbacks (MR_kv_add them there, like the reference's KVptr).
 */

#ifndef GPUMR_CMAPREDUCE_H
#define GPUMR_CMAPREDUCE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* runtime */
int MR_init(void);                      /* 0 on success */
void MR_finalize(void);
const char *MR_last_error(void);        /* NULL if the last call succeeded */

/* lifecycle */
void *MR_create(void);
void MR_destroy(void *mr);
void *MR_copy(void *mr);
int MR_set(void *mr, const char *name, const char *value);

/* pair adds — valid only on the KV handle passed into a callback */
void MR_kv_add(void *kv, const char *key, int keybytes,
               const char *value, int valuebytes);
/* n fixed-width pairs packed back to back (reference
 * MR_kv_add_multi_static) */
void MR_kv_add_multi_static(void *kv, int n, const char *key, int keybytes,
                            const char *value, int valuebytes);
/* n variable-width pairs; keybytes/valuebytes are per-pair size arrays
 * (reference MR_kv_add_multi_dynamic) */
void MR_kv_add_multi_dynamic(void *kv, int n, const char *key,
                             const int *keybytes, const char *value,
                             const int *valuebytes);

/* map */
uint64_t MR_map(void *mr, int nmap,
                void (*mymap)(int itask, void *kv, void *ptr), void *ptr);
uint64_t MR_map_add(void *mr, int nmap,
                    void (*mymap)(int, void *, void *), void *ptr,
                    int addflag);
uint64_t MR_map_file_list(void *mr, int nstr, char **paths,
                          void (*mymap)(int itask, char *fname, void *kv,
                                        void *ptr),
                          void *ptr);

/* chunked file maps (reference map_file_char/str variants,
 * src/cmapreduce.h — callback receives one chunk of bytes ending on the
 * separator, with `delta` lookahead trimmed) */
uint64_t MR_map_file_char(void *mr, int nmap, int nstr, char **paths,
                          char sepchar, int delta,
                          void (*mymap)(int itask, char *bytes, int nbytes,
                                        void *kv, void *ptr),
                          void *ptr);
uint64_t MR_map_file_str(void *mr, int nmap, int nstr, char **paths,
                         const char *sepstr, int delta,
                         void (*mymap)(int itask, char *bytes, int nbytes,
                                       void *kv, void *ptr),
                         void *ptr);
/* map over an existing MR's KV pairs, incl. self-map mr2 == mr
 * (reference MR_map_mr, src/cmapreduce.cpp): mymap(itask, key,
 * keybytes, value, valuebytes, KVptr, APPptr) */
uint64_t MR_map_mr(void *mr, void *mr2,
                   void (*mymap)(uint64_t itask, char *key, int keybytes,
                                 char *value, int valuebytes,
                                 void *kv, void *ptr),
                   void *ptr);

/* shuffle / grouping / reduce */
uint64_t MR_aggregate(void *mr);
/* user hash: key → int; proc = hash % nprocs (reference MR_aggregate's
 * myhash).  The callback runs on the host per key. */
uint64_t MR_aggregate_hash(void *mr,
                           int (*myhash)(char *key, int keybytes));
uint64_t MR_convert(void *mr);
uint64_t MR_collate(void *mr);
uint64_t MR_clone(void *mr);
uint64_t MR_collapse(void *mr, const char *key, int keybytes);
uint64_t MR_gather(void *mr, int nprocs);
uint64_t MR_broadcast(void *mr, int root);
uint64_t MR_add(void *mr, void *mr2);
/* gather to nprocs + collapse under one key (reference MR_scrunch) */
uint64_t MR_scrunch(void *mr, int nprocs, const char *key, int keybytes);
/* cross-MR add state: open() lets later maps/reduces add into this MR's
 * KV; close() completes it (reference MR_open/MR_close) */
void MR_open(void *mr);
uint64_t MR_close(void *mr);
uint64_t MR_reduce(void *mr,
                   void (*myreduce)(char *key, int keybytes,
                                    char *multivalue, int nvalues,
                                    int *valuebytes, void *kv, void *ptr),
                   void *ptr);
uint64_t MR_compress(void *mr,
                     void (*myreduce)(char *, int, char *, int, int *,
                                      void *, void *),
                     void *ptr);

/* sorts (flag semantics of the reference: ±1..6; _cmp variants take the
 * reference's appcompare over raw bytes) */
uint64_t MR_sort_keys_flag(void *mr, int flag);
uint64_t MR_sort_values_flag(void *mr, int flag);
uint64_t MR_sort_multivalues_flag(void *mr, int flag);
uint64_t MR_sort_keys(void *mr,
                      int (*mycompare)(char *, int, char *, int));
uint64_t MR_sort_values(void *mr,
                        int (*mycompare)(char *, int, char *, int));
uint64_t MR_sort_multivalues(void *mr,
                             int (*mycompare)(char *, int, char *, int));

/* read-only */
uint64_t MR_scan_kv(void *mr,
                    void (*myscan)(char *key, int keybytes, char *value,
                                   int valuebytes, void *ptr),
                    void *ptr);
uint64_t MR_scan_kmv(void *mr,
                     void (*myscan)(char *key, int keybytes,
                                    char *multivalue, int nvalues,
                                    int *valuebytes, void *ptr),
                     void *ptr);
uint64_t MR_kv_stats(void *mr);
uint64_t MR_kmv_stats(void *mr);
void MR_cummulative_stats(void *mr, int level, int reset);
int MR_print_file(void *mr, const char *path, int kflag, int vflag);
uint64_t MR_print(void *mr, int nstride, int kflag, int vflag);

/* multi-block ("extended") multivalues: a reduce callback that receives
 * multivalue==NULL and nvalues==0 iterates the group in blocks —
 * MR_multivalue_blocks() gives the block count, MR_multivalue_block()
 * loads block iblock and returns its value count (buffers stay valid
 * until the next block request); _block_select is accepted for
 * reference parity and is a no-op (no 2-page scratch here).  Enable
 * blocking with MR_set(mr, "c_block_rows", "<rows>") — groups larger
 * than that arrive blocked (the reference blocks when a group outgrows
 * a page; src/mapreduce.cpp:1874-1925). */
uint64_t MR_multivalue_blocks(void *mr);
int MR_multivalue_block(void *mr, int iblock, char **ptr_multivalue,
                        int **ptr_valuesizes);
void MR_multivalue_block_select(void *mr, int which);

/* OINK script driver (reference oink/library.h mrmpi_open/file/command/
 * close) */
void *OINK_open(const char *logfile);   /* logfile NULL → no log */
int OINK_file(void *oink, const char *path);
int OINK_command(void *oink, const char *line);
void OINK_close(void *oink);

#ifdef __cplusplus
}
#endif

#endif /* GPUMR_CMAPREDUCE_H */
