"""The recorder: defer op calls into PlanStage nodes.

Entered two ways:

* explicitly — ``with mr.pipeline(): ...`` records every deferrable op
  in the block and fuses+executes at exit (or earlier, at any barrier);
* implicitly — ``Settings.fuse=1`` (or ``MRTPU_FUSE=1``): the first
  deferrable op auto-opens a recorder; any barrier (map, gather, scan,
  print, stats, save, user-callback ops, direct ``mr.kv``/``mr.kmv``
  reads, ...) flushes it.  Only side-effect-free ops defer at all —
  see ``core.mapreduce._defer_ok``.

Deferred ops can't return their real global pair counts (nothing ran
yet), so they return a :class:`PendingCount` — an int-like proxy that
flushes the plan the moment the number is actually *looked at* (int(),
comparison, arithmetic, str).  Code that ignores the return value — the
normal pipeline shape — pays nothing.
"""

from __future__ import annotations

from typing import List

from .ir import Plan, PlanStage, snapshot_settings


class PendingCount:
    """Lazy stand-in for a deferred op's global pair/group count.
    Coercing it (int/float/index/comparison/arithmetic/str) flushes the
    owning plan and yields the real count."""

    __slots__ = ("_mr", "_stage")

    def __init__(self, mr, stage: PlanStage):
        self._mr = mr
        self._stage = stage

    def _resolve(self) -> int:
        self._mr._flush_plan()
        r = self._stage.result
        if r is None:
            # the stage never executed — its pipeline() block aborted
            # and discarded it; a silent 0 would look like a real count
            self._mr.error.all(
                f"deferred {self._stage.op} was discarded before "
                "executing (its pipeline aborted)")
        return int(r)

    def __int__(self):
        return self._resolve()

    __index__ = __int__

    def __float__(self):
        return float(self._resolve())

    def __bool__(self):
        return bool(self._resolve())

    def __eq__(self, other):
        return self._resolve() == other

    def __ne__(self, other):
        return self._resolve() != other

    def __lt__(self, other):
        return self._resolve() < other

    def __le__(self, other):
        return self._resolve() <= other

    def __gt__(self, other):
        return self._resolve() > other

    def __ge__(self, other):
        return self._resolve() >= other

    def __hash__(self):
        return hash(self._resolve())

    def __add__(self, other):
        return self._resolve() + other

    __radd__ = __add__

    def __sub__(self, other):
        return self._resolve() - other

    def __rsub__(self, other):
        return other - self._resolve()

    def __mul__(self, other):
        return self._resolve() * other

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._resolve() / other

    def __rtruediv__(self, other):
        return other / self._resolve()

    def __floordiv__(self, other):
        return self._resolve() // other

    def __rfloordiv__(self, other):
        return other // self._resolve()

    def __mod__(self, other):
        return self._resolve() % other

    def __rmod__(self, other):
        return other % self._resolve()

    def __divmod__(self, other):
        return divmod(self._resolve(), other)

    def __rdivmod__(self, other):
        return divmod(other, self._resolve())

    def __neg__(self):
        return -self._resolve()

    def __pos__(self):
        return self._resolve()

    def __abs__(self):
        return abs(self._resolve())

    def __str__(self):
        return str(self._resolve())

    def __repr__(self):
        return repr(self._resolve())

    def __format__(self, spec):
        return format(self._resolve(), spec)


class PlanRecorder:
    """Collects deferred stages for one MapReduce object.  ``auto``
    recorders (Settings.fuse) uninstall themselves at flush; explicit
    ``mr.pipeline()`` recorders stay installed so ops after a
    mid-pipeline barrier keep recording."""

    def __init__(self, mr, auto: bool = False):
        self.mr = mr
        self.auto = auto
        self.stages: List[PlanStage] = []

    def record(self, op: str, args: tuple, kw: dict) -> PendingCount:
        stage = PlanStage(op=op, args=tuple(args), kw=dict(kw),
                          settings=snapshot_settings(self.mr.settings))
        self.stages.append(stage)
        return PendingCount(self.mr, stage)

    def flush(self) -> None:
        """Fuse + execute everything recorded so far.  Re-entrant: the
        stage list is swapped out first, so replayed ops that hit a
        barrier (and call _flush_plan again) see an empty recorder."""
        stages, self.stages = self.stages, []
        if not stages:
            return
        from .fuser import execute_plan
        execute_plan(self.mr, Plan(stages))
