"""Plan IR: the deferred-op record a pipeline compiles from.

A :class:`PlanStage` is one deferred MapReduce op call — op name,
positional/keyword args (callbacks included), and the settings snapshot
taken at record time (replay runs under the settings the user had when
they issued the call, even if they ``mr.set(...)`` afterwards).  A
:class:`Plan` is the ordered stage chain plus a structural fingerprint
used as the first component of the plan-cache key.

The IR stays deliberately tiny: fusibility is NOT decided here — the
fuser classifies stages against the *live* dataset/backend state at
execution time (a chain is device-fusible or not depending on what the
preceding stages produced), so a stage only carries what the user said,
never a guessed tier.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass
class PlanStage:
    op: str                      # MapReduce method name (aggregate, ...)
    args: tuple = ()
    kw: dict = field(default_factory=dict)
    settings: object = None      # Settings snapshot at record time
    result: Optional[int] = None  # global pair/group count, set at execution

    def signature(self) -> tuple:
        """Hashable structural identity for cache keys: op name plus the
        identity of any callback/flag arguments.  Callbacks hash by
        function object — the same registered kernel recurs across runs,
        a fresh lambda per run correctly misses."""
        def _sig(x):
            if callable(x):
                return ("fn", x)
            if isinstance(x, (int, float, str, bytes, bool, type(None))):
                return x
            return ("repr", repr(x))
        return (self.op,
                tuple(_sig(a) for a in self.args),
                tuple(sorted((k, _sig(v)) for k, v in self.kw.items())))

    def describe(self) -> str:
        parts = [repr(a) if not callable(a)
                 else getattr(a, "__name__", repr(a)) for a in self.args]
        parts += [f"{k}={getattr(v, '__name__', None) or v!r}"
                  for k, v in self.kw.items()]
        return f"{self.op}({', '.join(parts)})"


class Plan:
    """One recorded stage chain, in issue order."""

    def __init__(self, stages: Tuple[PlanStage, ...]):
        self.stages = tuple(stages)

    def fingerprint(self) -> tuple:
        return tuple(s.signature() for s in self.stages)

    def describe(self) -> list:
        return [s.describe() for s in self.stages]

    def __len__(self) -> int:
        return len(self.stages)

    def __repr__(self):
        return f"Plan([{', '.join(self.describe())}])"


def snapshot_settings(settings):
    return copy.deepcopy(settings)


def frame_signature(frame) -> tuple:
    """Shape/dtype identity of the dataset the plan will run over — the
    second component of the plan-cache key.  Host columnar frames key on
    column kind + dtype; sharded frames on the padded device shapes."""
    import numpy as np
    kind = type(frame).__name__
    sig = [kind]
    for name in ("key", "value"):
        col = getattr(frame, name, None)
        if col is None:
            continue
        data = getattr(col, "data", col)
        try:
            arr = np.asarray(data) if not hasattr(data, "shape") else data
            sig.append((name, tuple(arr.shape), str(arr.dtype)))
        except Exception:
            sig.append((name, "object"))
    return tuple(sig)
