"""The fuser: compile a recorded stage chain into fused device programs.

Walks the plan front-to-back against the LIVE dataset/backend state and
greedily groups maximal fusible runs:

* ``[aggregate, convert, reduce(kernel, batch)]`` on a multi-shard mesh
  → TWO compiled programs: the shuffle's jitted phase 1 (hash + sort by
  dest + counts), then ONE ``jit``/``shard_map`` program that composes
  the phase-2 exchange (``shuffle.phase2_shard_body``), the local
  convert (sort + boundary detection, the ``parallel/group`` bodies)
  and the segment reduce — where the eager path dispatches ~5 programs
  with a host sync between every op.
* ``[aggregate, convert]`` (collate feeding a host-callback reduce)
  → the same two programs, producing a grouped ShardedKMV.
* ``[convert, reduce(kernel, batch)]`` on an already-sharded KV
  → ONE fused local program (no exchange).

Everything else — host-callback tiers, serial backend, spill/out-of-core
datasets, over-HBM-budget datasets, comparator sorts — **breaks fusion**:
those stages replay through the ordinary eager methods, so every
pipeline still runs, fused or not.

Compiled plans live in the plan cache (``plan.cache``) keyed on
(stage-chain fingerprint, frame shapes/dtypes, mesh, transport); a hit
reuses the previous run's exchange caps (validated against the fresh
count matrix, like the shuffle's speculative-cap cache) so repeated
pipelines reuse compiled programs instead of re-deriving shapes.
Telemetry: ``plan.execute`` / ``plan.group`` obs spans with
``cache_hit``/``fused`` attrs, plan-cache hit/miss/eviction counters in
``MapReduce.stats()["plan"]``, and every program launch counted in
``Counters.ndispatch``.
"""

from __future__ import annotations

from dataclasses import dataclass, field as _field
from typing import Optional

import numpy as np

from ..utils.env import env_knob
from .cache import LRUCache, plan_cache, record_history
from .ir import Plan, PlanStage, frame_signature

# bounded builder cache for the fused jitted programs (same policy as
# the shuffle's phase caches)
FUSED_CACHE = LRUCache(env_knob("MRTPU_JIT_CACHE", int, 64),
                       name="plan.fused")


@dataclass
class CompiledPlan:
    """Cached executable state of one (fingerprint, shapes) plan: the
    group structure last used plus per-group exchange caps for reuse."""
    groups: list = _field(default_factory=list)   # descriptions (history)
    caps: dict = _field(default_factory=dict)     # group idx → (B, R, cap)
    runs: int = 0


# ---------------------------------------------------------------------------
# stage classification helpers
# ---------------------------------------------------------------------------

def _kernel_op(fn) -> Optional[str]:
    """Registered kernel reduce → segment-op name (None = host tier)."""
    from ..ops import reduces
    table = {reduces.count: "count", reduces.sum_values: "sum",
             reduces.max_values: "max", reduces.min_values: "min",
             reduces.cull: "first"}
    return table.get(fn)


def _reduce_stage_op(st: PlanStage) -> Optional[str]:
    """Fusible reduce stage → segment-op name, else None."""
    if st.op != "reduce" or not st.args:
        return None
    if not (st.kw.get("batch") or (len(st.args) > 2 and st.args[2])):
        return None
    if st.kw.get("block_rows") is not None:
        return None
    return _kernel_op(st.args[0])


def _agg_hash(st: PlanStage):
    """(ok, hash_fn) for an aggregate stage: host-evaluated hashes break
    fusion (they need per-key python on the controller)."""
    fn = st.args[0] if st.args else st.kw.get("hash_fn")
    if fn is not None and getattr(fn, "host_hash", False):
        return False, fn
    return True, fn


def _device_state(mr):
    """The live frame a fused group would consume, or None when the
    current state is not device-fusible (spill, budget, serial, host
    tiers) — the fusion-break rules of doc/plan.md."""
    from ..parallel.backend import MeshBackend
    if not isinstance(mr.backend, MeshBackend):
        return None
    kv = mr.kv
    if kv is None or not kv.complete_done or mr._open:
        return None
    if mr.settings.outofcore == 1:          # spill boundary
        return None
    if not kv.is_host_dataset() and mr._mesh_over_budget(kv):
        return None                          # HBM budget → external path
    frame = kv.one_frame()
    if len(frame) == 0:
        return None                          # eager handles empties
    return frame


def _match_group(mr, stages, i):
    """(n_stages, kind, reduce_op, frame) of the fused group starting at
    stage i against the live state, or (1, None, None, None) → eager
    replay.  The materialized frame rides along so the exec functions
    don't pay ``one_frame()`` (a device concat on multi-frame datasets)
    a second time."""
    from ..core.frame import KVFrame
    from ..parallel.sharded import ShardedKV
    st = stages[i]
    n = len(stages)
    if st.op == "aggregate":
        ok, _fn = _agg_hash(st)
        frame = _device_state(mr) if ok else None
        if (frame is not None and mr.backend.nprocs > 1
                and i + 1 < n and stages[i + 1].op == "convert"
                and (isinstance(frame, ShardedKV)
                     or (isinstance(frame, KVFrame) and frame.is_dense())
                     or _internable(frame))):
            rop = _reduce_stage_op(stages[i + 2]) if i + 2 < n else None
            if rop is not None and not _reduce_value_ok(frame, rop):
                rop = None
            if rop is not None:
                return 3, "exchange", rop, frame
            return 2, "exchange", None, frame
        return 1, None, None, None
    if st.op == "convert":
        frame = _device_state(mr)
        if isinstance(frame, ShardedKV) and i + 1 < n:
            rop = _reduce_stage_op(stages[i + 1])
            if rop is not None and _reduce_value_ok(frame, rop):
                return 2, "local", rop, frame
        return 1, None, None, None
    return 1, None, None, None


def _internable(frame) -> bool:
    from ..core.column import BytesColumn, DenseColumn, ObjectColumn
    return all(isinstance(c, (BytesColumn, DenseColumn, ObjectColumn))
               for c in (frame.key, frame.value))


def _reduce_value_ok(frame, rop: str) -> bool:
    """Arithmetic on interned byte/object VALUE ids is meaningless —
    eager reduce_sharded raises for it; fall back so the same error
    surfaces from the same code path."""
    if rop in ("count", "first"):
        return True
    from ..core.column import BytesColumn, ObjectColumn
    if getattr(frame, "value_decode", None) is not None:
        return False
    value = getattr(frame, "value", None)
    return not isinstance(value, (BytesColumn, ObjectColumn))


# ---------------------------------------------------------------------------
# fused program bodies (composable, shard-local)
# ---------------------------------------------------------------------------

def _group_reduce_body(k, v, nrecv, gcap: int, out_kind: str,
                       reduce_op: Optional[str]):
    """Shard-local convert(+reduce) over packed valid rows: sort by key,
    boundary-detect groups, then either emit the grouped layout
    (out_kind='kmv') or segment-reduce to one pair per group
    (out_kind='kv').  Composes the SAME shard-local bodies the eager
    tier jits — `parallel/group`'s `_local_sort`/`_boundary`/
    `grouped_layout`/`segment_reduce_rows` — so fused output is
    byte-identical to the eager path by construction."""
    import jax.numpy as jnp
    from ..parallel.group import (_boundary, _local_sort, grouped_layout,
                                  segment_reduce_rows)

    sk, sv, valid = _local_sort(k, v, nrecv)
    mask = _boundary(sk, valid)
    ukey, sizes, voff, seg, g = grouped_layout(sk, mask, nrecv, gcap)
    meta = jnp.stack([g, nrecv.astype(jnp.int32)])
    if out_kind == "kmv":
        return ukey, sizes, voff, sv, meta
    if reduce_op == "count":
        return ukey, sizes.astype(jnp.int64), meta
    if reduce_op == "first":
        uval = jnp.zeros((gcap,) + sv.shape[1:], sv.dtype).at[
            jnp.where(mask, seg, gcap)].set(sv, mode="drop")
        return ukey, uval, meta
    return ukey, segment_reduce_rows(sv, seg, valid, gcap, reduce_op), meta


def _donate_argnums(donate: bool, aliasable_dim0: bool, out_kind: str,
                    reduce_op, svalue) -> tuple:
    """Which of (skey, svalue) to donate: only buffers whose donation
    can actually alias an output of the same byte size (anything else
    would be a warned no-op).  The key side always has a same-dtype
    same-trailing-dims output; the value side does too EXCEPT for a
    count reduce, whose output is 1-D int64 regardless of the value's
    shape."""
    if not (donate and aliasable_dim0):
        return ()
    if (out_kind == "kmv" or reduce_op != "count"
            or (svalue.ndim == 1 and svalue.dtype.itemsize == 8)):
        return (0, 1)
    return (0,)


def _fused_exchange_jit(mesh, transport: int, plan, out_kind: str,
                        reduce_op: Optional[str], donate_argnums=()):
    """``plan`` is the tagged exchange plan (parallel/wire.py): raw
    plans compose the original phase-2 body, wire plans the codec body —
    either way every static knob of the plan keys the executable cache."""
    key = ("exchange", mesh, transport, plan, out_kind,
           reduce_op, tuple(donate_argnums))
    return FUSED_CACHE.get_or_build(
        key, lambda: _fused_exchange_build(mesh, transport, plan,
                                           out_kind, reduce_op,
                                           donate_argnums))


def _fused_exchange_build(mesh, transport, plan, out_kind,
                          reduce_op, donate_argnums=()):
    import jax
    from ..exec import donated_jit
    from ..parallel.mesh import mesh_axis_size, row_spec
    from ..parallel.shuffle import phase2_shard_body
    from ..parallel.wire import phase2_wire_shard_body, plan_cap_out
    nprocs = mesh_axis_size(mesh)
    spec = row_spec(mesh)
    nouts = 5 if out_kind == "kmv" else 3
    cap_out = plan_cap_out(plan)

    if plan[0] == "wire":
        _tag, tiers, _cap, kpack, vpack = plan

        def run(skey, svalue, counts_local, stats_local):
            def body(k, v, cl, st):
                out_k, out_v, nrecv = phase2_wire_shard_body(
                    nprocs, transport, mesh, tiers, cap_out, kpack,
                    vpack, k, v, cl, st)
                return _group_reduce_body(out_k, out_v, nrecv, cap_out,
                                          out_kind, reduce_op)
            return jax.shard_map(
                body, mesh=mesh, in_specs=(spec,) * 4,
                out_specs=(spec,) * nouts)(skey, svalue, counts_local,
                                           stats_local)
    else:
        _tag, B, nrounds, _cap = plan

        def run(skey, svalue, counts_local):
            def body(k, v, cl):
                out_k, out_v, nrecv = phase2_shard_body(
                    nprocs, transport, mesh, B, nrounds, cap_out, k, v,
                    cl)
                return _group_reduce_body(out_k, out_v, nrecv, cap_out,
                                          out_kind, reduce_op)
            return jax.shard_map(
                body, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=(spec,) * nouts)(skey, svalue, counts_local)

    # exec/: the dest-sorted phase-1 intermediates are dead after the
    # fused program — donate the aliasable ones (MRTPU_DONATE)
    return donated_jit(run, donate_argnums)


def _compact_jit(mesh, n: int, narrs: int):
    """Per-shard leading-rows slice: shrink a fused group's [cap_out]
    outputs to the eager tier's round_cap(max groups) residency.  One
    cheap extra dispatch, paid only when it shrinks ≥4× (see
    _maybe_compact) — without it duplicate-heavy keys leave the resident
    dataset (and every downstream compile) sized at row capacity."""
    key = ("compact", mesh, n, narrs)

    def build():
        import jax
        from ..parallel.mesh import row_spec
        spec = row_spec(mesh)

        @jax.jit
        def run(*arrs):
            body = lambda *xs: tuple(x[:n] for x in xs)
            return jax.shard_map(body, mesh=mesh, in_specs=(spec,) * narrs,
                                 out_specs=(spec,) * narrs)(*arrs)
        return run
    return FUSED_CACHE.get_or_build(key, build)


def _maybe_compact(mesh, gcap: int, gcounts, *arrs):
    """Slice group-indexed outputs down to round_cap(max group count)
    when that shrinks ≥4×; otherwise return them unchanged (the extra
    dispatch isn't worth single-digit savings)."""
    from ..core.runtime import bump_dispatch
    from ..parallel.sharded import round_cap
    new_gcap = round_cap(max(int(gcounts.max()), 1))
    if new_gcap * 4 > gcap:
        return arrs
    bump_dispatch()
    return _compact_jit(mesh, new_gcap, len(arrs))(*arrs)


def _fused_local_jit(mesh, out_kind: str, reduce_op: Optional[str],
                     donate_argnums=()):
    key = ("local", mesh, out_kind, reduce_op, tuple(donate_argnums))
    return FUSED_CACHE.get_or_build(
        key, lambda: _fused_local_build(mesh, out_kind, reduce_op,
                                        donate_argnums))


def _fused_local_build(mesh, out_kind, reduce_op, donate_argnums=()):
    import jax
    from ..exec import donated_jit
    from ..parallel.mesh import row_spec
    spec = row_spec(mesh)
    nouts = 5 if out_kind == "kmv" else 3

    def run(key, value, counts):
        def body(k, v, c):
            return _group_reduce_body(k, v, c[0], k.shape[0], out_kind,
                                      reduce_op)
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(spec,) * nouts)(key, value, counts)

    # exec/: the consumed KV is replaced by the grouped output right
    # after (_install_kv) — donating lets the group layout reuse its
    # buffers (ukey is same-size as key here: gcap == cap)
    return donated_jit(run, donate_argnums)


# ---------------------------------------------------------------------------
# fused group execution
# ---------------------------------------------------------------------------

def _as_sharded(mr, frame):
    """Host frame → ShardedKV (intern byte/object columns + block-shard),
    exactly the eager aggregate's preparation (shuffle.aggregate_kv)."""
    from ..core.frame import KVFrame
    from ..parallel.sharded import shard_frame
    from ..parallel.shuffle import _intern_frame
    if not isinstance(frame, KVFrame):
        return frame
    frame, ktable, vtable = _intern_frame(frame, mr.backend.nprocs)
    skv = shard_frame(frame, mr.backend.mesh)
    skv.key_decode = ktable
    skv.value_decode = vtable
    return skv


def _install_kv(mr, skv):
    """Replace mr's dataset with a fused group's ShardedKV output."""
    if mr.kmv is not None:
        mr.kmv.free()
        mr.kmv = None
    old = mr.kv
    newkv = mr._new_kv()
    newkv.add_frame(skv)
    newkv.complete()
    if old is not None:
        old.free()
    mr.kv = newkv


def _install_kmv(mr, skmv):
    if mr.kv is not None:
        mr.kv.free()
        mr.kv = None
    mr.kmv = mr._new_kmv()
    mr.kmv.push(skmv)
    mr.kmv.complete()


def _exec_exchange_group(mr, stages, reduce_op, compiled: CompiledPlan,
                         gidx: int, sp, frame):
    """Run [aggregate, convert(, reduce)] as phase1 + ONE fused program.
    Under ``MRTPU_WIRE`` the fused program is the wire-codec variant
    (parallel/wire.py): the rows cross the interconnect delta-packed
    with tiered caps and decode inside the same program, so the grouped
    output stays byte-identical to the eager tiers."""
    import jax
    from ..core.runtime import Timer, bump_dispatch
    from ..parallel import wire as _wire
    from ..parallel.mesh import mesh_axis_size, row_sharding
    from ..parallel.sharded import ShardedKMV, ShardedKV, SyncStats
    from ..parallel.shuffle import (ExchangeCallStats, ExchangeStats,
                                    _phase1_jit)

    mesh = mr.backend.mesh
    nprocs = mesh_axis_size(mesh)
    transport = mr.settings.all2all
    out_kind = "kv" if reduce_op is not None else "kmv"
    _ok, hash_fn = _agg_hash(stages[0])
    dest = ("hash", hash_fn)

    skv = _as_sharded(mr, frame)
    from ..exec import can_donate
    donate = can_donate(skv)
    wire_on = _wire.wire_enabled()
    elig = _wire.columns_eligible(skv.key, skv.value) if wire_on else None
    counts_dev = jax.device_put(skv.counts.astype(np.int32),
                                row_sharding(mesh))
    t = Timer()
    bump_dispatch()
    stats_local = None
    if wire_on:
        skey, svalue, counts_local, stats_local = _phase1_jit(
            mesh, dest, donate, wire=elig)(skv.key, skv.value, counts_dev)
    else:
        skey, svalue, counts_local = _phase1_jit(mesh, dest, donate)(
            skv.key, skv.value, counts_dev)
    SyncStats.bump()   # the op's ONE round-trip: the count matrix
    counts_mat = np.asarray(counts_local).reshape(nprocs, nprocs)
    stats_mat = (np.asarray(stats_local).reshape(nprocs, nprocs, 4)
                 if stats_local is not None else None)
    # ONE planning step shared with the eager exchange (wire.plan_from_
    # pull): plan choice and telemetry must never diverge between tiers
    plan, kvrange, bmax_raw, nmax_out, _new_counts = _wire.plan_from_pull(
        skv.key, skv.value, counts_mat, stats_mat, wire_on, elig)
    cached = compiled.caps.get(gidx)
    if cached is not None and cached[0] == plan[0] \
            and _wire.plan_holds(cached, bmax_raw, nmax_out, kvrange) \
            and not _wire.plan_oversized(cached, bmax_raw, nmax_out):
        # the cached plan still holds every row exactly and isn't
        # grossly oversized: reuse the compiled program
        plan = cached
    else:
        # too small OR ≥4× too large (skewed first run followed by
        # uniform data would pay the padded transfer forever, like the
        # eager speculative cache's right-sizing): recompile at the
        # fresh plan
        compiled.caps[gidx] = plan
    cap_out = _wire.plan_cap_out(plan)
    bump_dispatch()
    argnums = _donate_argnums(
        donate, cap_out == skey.shape[0] // max(nprocs, 1), out_kind,
        reduce_op, svalue)
    fused = _fused_exchange_jit(mesh, transport, plan, out_kind,
                                reduce_op, donate_argnums=argnums)
    if plan[0] == "wire":
        out = fused(skey, svalue, counts_local, stats_local)
    else:
        out = fused(skey, svalue, counts_local)
    meta = np.asarray(out[-1]).reshape(nprocs, 2)
    gcounts = meta[:, 0].astype(np.int32)
    vcounts = meta[:, 1].astype(np.int32)
    mr.counters.add(commtime=t.elapsed())
    nrows = int(counts_mat.sum())
    ngroups = int(gcounts.sum())
    # exchange byte accounting + per-call stats, like the eager exchange
    B_eff, nrounds_eff = _wire.plan_rounds(plan)
    stats = ExchangeCallStats(nrounds=nrounds_eff, bucket=B_eff,
                              cap_out=cap_out, rows=nrows,
                              speculative=False)
    _account_exchange(mr, skv, counts_mat, plan, nprocs, stats)
    ExchangeStats.last = (nrounds_eff, B_eff)   # deprecated shim
    mr.last_exchange = stats
    sp.set(bucket=B_eff, nrounds=nrounds_eff, cap_out=cap_out,
           rows=nrows, groups=ngroups, wire_bytes=stats.wire_bytes,
           wire_ratio=stats.wire_ratio)
    stages[0].result = nrows
    stages[1].result = ngroups
    if out_kind == "kv":
        ukey, uval, _meta = out
        ukey, uval = _maybe_compact(mesh, cap_out, gcounts, ukey, uval)
        skv_out = ShardedKV(mesh, ukey, uval, gcounts,
                            key_decode=skv.key_decode)
        if reduce_op == "first":
            skv_out.value_decode = skv.value_decode
        _install_kv(mr, skv_out)
        stages[2].result = ngroups
    else:
        # values/voff stay row-capacity-sized (voff indexes value rows,
        # exactly like the eager ShardedKMV); only group-indexed arrays
        # compact
        ukey, sizes, voff, values, _meta = out
        ukey, sizes, voff = _maybe_compact(mesh, cap_out, gcounts,
                                           ukey, sizes, voff)
        skmv = ShardedKMV(mesh, ukey, sizes, voff, values, gcounts,
                          vcounts, key_decode=skv.key_decode,
                          value_decode=skv.value_decode)
        _install_kmv(mr, skmv)


def _account_exchange(mr, skv, counts_mat, plan, nprocs, stats):
    from ..obs.metrics import record_exchange
    from ..parallel.shuffle import exchange_volume
    from ..parallel.wire import plan_slots, wire_ratio, wire_volume
    moved, pad, _rowbytes = exchange_volume(skv, counts_mat,
                                            plan_slots(plan), nprocs)
    mr.counters.add(cssize=moved, crsize=moved, cspad=pad)
    stats.sent_bytes, stats.pad_bytes = moved, pad
    if plan[0] == "wire":
        stats.wire_bytes = wire_volume(skv, counts_mat, plan)
        stats.wire_ratio = wire_ratio(moved, pad, stats.wire_bytes)
    # the fused tier's twin of the eager _exchange_impl feed: without it
    # a MRTPU_FUSE=1 run reads "no exchange traffic" on /metrics
    record_exchange(stats)


def _exec_local_group(mr, stages, reduce_op, sp, frame):
    """Run [convert, reduce(kernel)] on a ShardedKV as ONE program."""
    import jax
    from ..core.runtime import bump_dispatch
    from ..parallel.mesh import mesh_axis_size, row_sharding
    from ..parallel.sharded import ShardedKV, SyncStats

    skv = frame
    mesh = skv.mesh
    nprocs = mesh_axis_size(mesh)
    from ..exec import can_donate
    donate = can_donate(skv)
    cap = skv.key.shape[0] // nprocs   # before donation deletes the data
    counts_dev = jax.device_put(skv.counts.astype(np.int32),
                                row_sharding(mesh))
    bump_dispatch()
    argnums = _donate_argnums(donate, True, "kv", reduce_op, skv.value)
    ukey, uval, meta = _fused_local_jit(mesh, "kv", reduce_op,
                                        donate_argnums=argnums)(
        skv.key, skv.value, counts_dev)
    SyncStats.bump()
    gcounts = np.asarray(meta).reshape(nprocs, 2)[:, 0].astype(np.int32)
    ngroups = int(gcounts.sum())
    ukey, uval = _maybe_compact(mesh, cap, gcounts, ukey, uval)
    skv_out = ShardedKV(mesh, ukey, uval, gcounts,
                        key_decode=skv.key_decode)
    if reduce_op == "first":
        skv_out.value_decode = skv.value_decode
    _install_kv(mr, skv_out)
    sp.set(groups=ngroups)
    stages[0].result = ngroups
    stages[1].result = ngroups


def _replay(mr, stage: PlanStage):
    """Eager fallback: run one recorded stage through the ordinary op
    method (tracing, stats, tier notes all behave as if never deferred),
    under the settings snapshot taken at record time."""
    saved = mr.settings
    if stage.settings is not None:
        mr.settings = stage.settings
    mr._plan_replaying = True
    try:
        stage.result = getattr(mr, stage.op)(*stage.args, **stage.kw)
    finally:
        mr._plan_replaying = False
        mr.settings = saved


# ---------------------------------------------------------------------------
# plan execution
# ---------------------------------------------------------------------------

def execute_plan(mr, plan: Plan) -> None:
    """Fuse + run a recorded plan against mr's current dataset."""
    tracer = mr.tracer
    key = None
    frame = None
    kv = mr.kv
    if kv is not None and kv.complete_done and kv._frames:
        frame = kv._frames[0]
    try:
        # MRTPU_WIRE is part of the key: a cached wire plan's caps are
        # tier/pack tuples a raw run can't validate against (and vice
        # versa), so the two knob states never share an entry
        from ..parallel.wire import wire_enabled
        key = (plan.fingerprint(), frame_signature(frame),
               _backend_signature(mr), mr.settings.all2all,
               mr.settings.outofcore, wire_enabled())
        compiled = plan_cache().get(key)
    except TypeError:       # unhashable stage arg: run uncached
        key = None
        compiled = None
    cache_hit = compiled is not None
    if compiled is None:
        compiled = CompiledPlan()
        if key is not None:
            plan_cache().put(key, compiled)
    compiled.runs += 1
    groups_desc = []
    with tracer.span("plan.execute", cat="plan", nstages=len(plan),
                     cache_hit=cache_hit) as psp:
        stages = list(plan.stages)
        i = 0
        gidx = 0
        while i < len(stages):
            n, kind, rop, frame = _match_group(mr, stages, i)
            run = stages[i:i + n]
            desc = {"stages": [s.describe() for s in run],
                    "fused": kind is not None, "kind": kind or "eager",
                    "reduce_op": rop}
            groups_desc.append(desc)
            if kind is None:
                _replay(mr, run[0])
            else:
                with tracer.span("plan.group", cat="plan", kind=kind,
                                 fused=True, nstages=n,
                                 reduce_op=rop or "") as sp:
                    try:
                        if kind == "exchange":
                            _exec_exchange_group(mr, run, rop, compiled,
                                                 gidx, sp, frame)
                        else:
                            _exec_local_group(mr, run, rop, sp, frame)
                    except BaseException:
                        # same contract as the eager exchange callers:
                        # a failure after a donated dispatch must leave
                        # a clean empty dataset (MRError on next op),
                        # never frames holding deleted buffers
                        from ..parallel.shuffle import free_if_donated
                        kv = mr._kv_data
                        if kv is not None:
                            free_if_donated(kv, frame)
                        raise
            i += n
            gidx += 1
        psp.set(ngroups=gidx,
                nfused=sum(1 for d in groups_desc if d["fused"]))
    compiled.groups = groups_desc
    record_history({"stages": plan.describe(), "groups": groups_desc,
                    "cache_hit": cache_hit,
                    "cache_key": _key_brief(key)})


def _backend_signature(mr):
    from ..parallel.backend import MeshBackend
    if isinstance(mr.backend, MeshBackend):
        return ("mesh", mr.backend.mesh)
    return ("serial",)


def _key_brief(key) -> Optional[str]:
    if key is None:
        return None
    fp, frame_sig, backend, transport, ooc, wire = key
    ops = "→".join(s[0] for s in fp)
    return (f"ops[{ops}] frame{frame_sig!r} backend={backend[0]} "
            f"all2all={transport} outofcore={ooc} wire={int(wire)}")
