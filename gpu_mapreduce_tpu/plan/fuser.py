"""The fuser: compile a recorded stage chain into fused device programs.

Walks the plan front-to-back against the LIVE dataset/backend state and
greedily groups maximal fusible runs:

* ``[aggregate, convert, reduce(kernel, batch)]`` on a multi-shard mesh
  → TWO compiled programs: the shuffle's jitted phase 1 (hash + sort by
  dest + counts), then ONE ``jit``/``shard_map`` program that composes
  the phase-2 exchange (``shuffle.phase2_shard_body``), the local
  convert (sort + boundary detection, the ``parallel/group`` bodies)
  and the segment reduce — where the eager path dispatches ~5 programs
  with a host sync between every op.
* ``[aggregate, convert]`` (collate feeding a host-callback reduce)
  → the same two programs, producing a grouped ShardedKMV.
* ``[convert, reduce(kernel, batch)]`` on an already-sharded KV
  → ONE fused local program (no exchange).

**Megafusion** (``MRTPU_MEGAFUSE``, default on — fusion v2,
doc/plan.md): on a *warm* group (the CompiledPlan carries the previous
run's exchange plan and group capacity) the remaining fusion boundary —
the host count/stats sync between phase 1 and the fused program — moves
OFF the dispatch path: ONE jit/``shard_map`` program composes phase-1
dest-sort + wire-encode + exchange + wire-decode + group/segment-reduce
and *additionally emits* the count/stats/meta matrices, which the host
pulls AFTER the single dispatch as a speculation check (``plan_holds``
+ group-capacity coverage + kernel-overflow count).  A failed check
discards the result and re-runs the two-dispatch v1 path on the same
inputs (megafused programs never donate, precisely so this replay and
the chaos retry stay possible).  Steady state: **1 dispatch per plan
group** (``Counters.ndispatch``, the bench ``detail.plan_ab`` target).
Inside the megafused program, supported group chains (kv out, count/sum
reduce, ≤8-byte integer columns) replace the per-shard ``lexsort``
grouping with the paged Pallas table kernels of ``ops/pallas/group.py``
(``MRTPU_PALLAS_GROUP``); unsupported chains warn once and keep the
sort path — still fused, still byte-identical.

Everything else — host-callback tiers, serial backend, spill/out-of-core
datasets, over-HBM-budget datasets, comparator sorts — **breaks fusion**:
those stages replay through the ordinary eager methods, so every
pipeline still runs, fused or not.

Compiled plans live in the plan cache (``plan.cache``) keyed on
(stage-chain fingerprint, frame shapes/dtypes, mesh, transport); a hit
reuses the previous run's exchange caps (validated against the fresh
count matrix, like the shuffle's speculative-cap cache) so repeated
pipelines reuse compiled programs instead of re-deriving shapes.
Telemetry: ``plan.execute`` / ``plan.group`` obs spans with
``cache_hit``/``fused`` attrs, plan-cache hit/miss/eviction counters in
``MapReduce.stats()["plan"]``, and every program launch counted in
``Counters.ndispatch``.
"""

from __future__ import annotations

from dataclasses import dataclass, field as _field
from typing import Optional

import numpy as np

from ..utils.env import env_flag, env_knob
from .cache import (LRUCache, note_fusion, persistent_cache, plan_cache,
                    record_history, stable_plan_digest)
from .ir import Plan, PlanStage, frame_signature

# bounded builder cache for the fused jitted programs (same policy as
# the shuffle's phase caches)
FUSED_CACHE = LRUCache(env_knob("MRTPU_JIT_CACHE", int, 64),
                       name="plan.fused")


def megafuse_enabled() -> bool:
    """``MRTPU_MEGAFUSE`` (default on): single-dispatch warm groups —
    fusion v2.  ``0`` restores the v1 two-dispatch fuser everywhere
    (the auto-fallback target; the A/B knob of bench ``--fuse ab``)."""
    return env_flag("MRTPU_MEGAFUSE", True)


# eager-tier compiled-program launches per op (shuffle phase 1+2,
# convert phase 1+2, one segment-reduce program) — the baseline the
# fusion-savings telemetry in mr.stats()["plan"]["fusion"] compares
# actual group dispatches against (doc/plan.md "reading the counters")
_EAGER_DISPATCHES = {"aggregate": 2, "convert": 2, "reduce": 1}


@dataclass
class CompiledPlan:
    """Cached executable state of one (fingerprint, shapes) plan: the
    group structure last used plus per-group exchange caps for reuse."""
    groups: list = _field(default_factory=list)   # descriptions (history)
    caps: dict = _field(default_factory=dict)     # group idx → (B, R, cap)
    # fusion v2: per-group megafuse speculation state, recorded by a
    # successful v1 run and validated after every single-dispatch run —
    # gidx → ("x", exchange_plan, gcap) | ("l", gcap)
    mega: dict = _field(default_factory=dict)
    runs: int = 0


# ---------------------------------------------------------------------------
# stage classification helpers
# ---------------------------------------------------------------------------

def _kernel_op(fn) -> Optional[str]:
    """Registered kernel reduce → segment-op name (None = host tier)."""
    from ..ops import reduces
    table = {reduces.count: "count", reduces.sum_values: "sum",
             reduces.max_values: "max", reduces.min_values: "min",
             reduces.cull: "first"}
    return table.get(fn)


def _reduce_stage_op(st: PlanStage) -> Optional[str]:
    """Fusible reduce stage → segment-op name, else None."""
    if st.op != "reduce" or not st.args:
        return None
    if not (st.kw.get("batch") or (len(st.args) > 2 and st.args[2])):
        return None
    if st.kw.get("block_rows") is not None:
        return None
    return _kernel_op(st.args[0])


def _agg_hash(st: PlanStage):
    """(ok, hash_fn) for an aggregate stage: host-evaluated hashes break
    fusion (they need per-key python on the controller)."""
    fn = st.args[0] if st.args else st.kw.get("hash_fn")
    if fn is not None and getattr(fn, "host_hash", False):
        return False, fn
    return True, fn


def _device_state(mr):
    """The live frame a fused group would consume, or None when the
    current state is not device-fusible (spill, budget, serial, host
    tiers) — the fusion-break rules of doc/plan.md."""
    from ..parallel.backend import MeshBackend
    if not isinstance(mr.backend, MeshBackend):
        return None
    kv = mr.kv
    if kv is None or not kv.complete_done or mr._open:
        return None
    if mr.settings.outofcore == 1:          # spill boundary
        return None
    if not kv.is_host_dataset() and mr._mesh_over_budget(kv):
        return None                          # HBM budget → external path
    frame = kv.one_frame()
    if len(frame) == 0:
        return None                          # eager handles empties
    return frame


def _match_group(mr, stages, i):
    """(n_stages, kind, reduce_op, frame) of the fused group starting at
    stage i against the live state, or (1, None, None, None) → eager
    replay.  The materialized frame rides along so the exec functions
    don't pay ``one_frame()`` (a device concat on multi-frame datasets)
    a second time."""
    from ..core.frame import KVFrame
    from ..parallel.sharded import ShardedKV
    st = stages[i]
    n = len(stages)
    if st.op == "aggregate":
        ok, _fn = _agg_hash(st)
        frame = _device_state(mr) if ok else None
        if (frame is not None and mr.backend.nprocs > 1
                and i + 1 < n and stages[i + 1].op == "convert"
                and (isinstance(frame, ShardedKV)
                     or (isinstance(frame, KVFrame) and frame.is_dense())
                     or _internable(frame))):
            rop = _reduce_stage_op(stages[i + 2]) if i + 2 < n else None
            if rop is not None and not _reduce_value_ok(frame, rop):
                rop = None
            if rop is not None:
                return 3, "exchange", rop, frame
            return 2, "exchange", None, frame
        return 1, None, None, None
    if st.op == "convert":
        frame = _device_state(mr)
        if isinstance(frame, ShardedKV) and i + 1 < n:
            rop = _reduce_stage_op(stages[i + 1])
            if rop is not None and _reduce_value_ok(frame, rop):
                return 2, "local", rop, frame
        return 1, None, None, None
    return 1, None, None, None


def _internable(frame) -> bool:
    from ..core.column import BytesColumn, DenseColumn, ObjectColumn
    return all(isinstance(c, (BytesColumn, DenseColumn, ObjectColumn))
               for c in (frame.key, frame.value))


def _reduce_value_ok(frame, rop: str) -> bool:
    """Arithmetic on interned byte/object VALUE ids is meaningless —
    eager reduce_sharded raises for it; fall back so the same error
    surfaces from the same code path."""
    if rop in ("count", "first"):
        return True
    from ..core.column import BytesColumn, ObjectColumn
    if getattr(frame, "value_decode", None) is not None:
        return False
    value = getattr(frame, "value", None)
    return not isinstance(value, (BytesColumn, ObjectColumn))


# ---------------------------------------------------------------------------
# fused program bodies (composable, shard-local)
# ---------------------------------------------------------------------------
# The convert(+reduce) shard body itself lives with its eager siblings
# in ``parallel/group.fused_group_body`` (sort path + the Pallas table
# path); the builders here only choose its static knobs and compose it
# with the exchange bodies.


def _pallas_cfg_for(mr, skv, cap: int, out_kind: str, reduce_op,
                    gcap: int):
    """The hashable kernel config threaded into the builder cache keys,
    or None → sort path.  None when the knob is off or the chain is
    unsupported (``ops/pallas/group.group_supported`` — warn once)."""
    from ..ops.pallas import group as pgroup
    if not pgroup.pallas_group_enabled():
        return None
    ok, reason = pgroup.group_supported(skv.key, skv.value, out_kind,
                                        reduce_op)
    if not ok:
        pgroup.warn_fallback(reason)
        return None
    import jax
    return ("tbl", pgroup.table_slots(gcap),
            pgroup.page_rows_for(cap, mr.settings.memsize),
            jax.default_backend() != "tpu")


def _gcap_for(gcounts, cap_out: int) -> int:
    """The group capacity a warm megafused run compiles at: the eager
    tier's pow2 residency bound (``round_cap`` of the observed max),
    clamped to the exchange output capacity."""
    from ..parallel.sharded import round_cap
    return min(round_cap(max(int(gcounts.max()), 1)), cap_out)


def _donate_argnums(donate: bool, aliasable_dim0: bool, out_kind: str,
                    reduce_op, svalue) -> tuple:
    """Which of (skey, svalue) to donate: only buffers whose donation
    can actually alias an output of the same byte size (anything else
    would be a warned no-op).  The key side always has a same-dtype
    same-trailing-dims output; the value side does too EXCEPT for a
    count reduce, whose output is 1-D int64 regardless of the value's
    shape."""
    if not (donate and aliasable_dim0):
        return ()
    if (out_kind == "kmv" or reduce_op != "count"
            or (svalue.ndim == 1 and svalue.dtype.itemsize == 8)):
        return (0, 1)
    return (0,)


def _fused_exchange_jit(mesh, transport: int, plan, out_kind: str,
                        reduce_op: Optional[str], donate_argnums=()):
    """``plan`` is the tagged exchange plan (parallel/wire.py): raw
    plans compose the original phase-2 body, wire plans the codec body —
    either way every static knob of the plan keys the executable cache."""
    key = ("exchange", mesh, transport, plan, out_kind,
           reduce_op, tuple(donate_argnums))
    return FUSED_CACHE.get_or_build(
        key, lambda: _fused_exchange_build(mesh, transport, plan,
                                           out_kind, reduce_op,
                                           donate_argnums))


def _fused_exchange_build(mesh, transport, plan, out_kind,
                          reduce_op, donate_argnums=()):
    import jax
    from ..exec import donated_jit
    from ..parallel.group import fused_group_body
    from ..parallel.mesh import mesh_axis_size, row_spec
    from ..parallel.shuffle import phase2_shard_body
    from ..parallel.wire import phase2_wire_shard_body, plan_cap_out
    nprocs = mesh_axis_size(mesh)
    spec = row_spec(mesh)
    nouts = 5 if out_kind == "kmv" else 3
    cap_out = plan_cap_out(plan)

    if plan[0] == "wire":
        _tag, tiers, _cap, kpack, vpack = plan

        def run(skey, svalue, counts_local, stats_local):
            def body(k, v, cl, st):
                out_k, out_v, nrecv = phase2_wire_shard_body(
                    nprocs, transport, mesh, tiers, cap_out, kpack,
                    vpack, k, v, cl, st)
                return fused_group_body(out_k, out_v, nrecv, cap_out,
                                        out_kind, reduce_op)
            return jax.shard_map(
                body, mesh=mesh, in_specs=(spec,) * 4,
                out_specs=(spec,) * nouts)(skey, svalue, counts_local,
                                           stats_local)
    else:
        _tag, B, nrounds, _cap = plan

        def run(skey, svalue, counts_local):
            def body(k, v, cl):
                out_k, out_v, nrecv = phase2_shard_body(
                    nprocs, transport, mesh, B, nrounds, cap_out, k, v,
                    cl)
                return fused_group_body(out_k, out_v, nrecv, cap_out,
                                        out_kind, reduce_op)
            return jax.shard_map(
                body, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=(spec,) * nouts)(skey, svalue, counts_local)

    # exec/: the dest-sorted phase-1 intermediates are dead after the
    # fused program — donate the aliasable ones (MRTPU_DONATE)
    return donated_jit(run, donate_argnums)


def _mega_jit(mesh, transport: int, dest, plan, gcap: int,
              out_kind: str, reduce_op, elig, pallas_cfg):
    """The fusion-v2 single-dispatch program: phase-1 dest-sort (+wire
    stats) + exchange (+wire encode/decode) + group/segment-reduce in
    ONE jit/shard_map, with the count/stats/meta matrices as extra
    outputs the host pulls AFTER dispatch (the speculation check).
    Every static knob — the exchange plan, the group capacity, the
    kernel config — keys the executable cache."""
    key = ("mega", mesh, transport, dest, plan, gcap, out_kind,
           reduce_op, elig, pallas_cfg)
    return FUSED_CACHE.get_or_build(
        key, lambda: _mega_build(mesh, transport, dest, plan, gcap,
                                 out_kind, reduce_op, elig, pallas_cfg))


def _mega_build(mesh, transport, dest, plan, gcap, out_kind, reduce_op,
                elig, pallas_cfg):
    import jax
    from ..parallel.group import fused_group_body
    from ..parallel.mesh import (mesh_axis_size, row_spec,
                                 shard_map_kernels)
    from ..parallel.shuffle import (_dest_fn, phase1_shard_body,
                                    phase2_shard_body)
    from ..parallel.wire import phase2_wire_shard_body, plan_cap_out
    nprocs = mesh_axis_size(mesh)
    spec = row_spec(mesh)
    dest_of = _dest_fn(dest, nprocs, mesh)
    cap_out = plan_cap_out(plan)
    ngout = 5 if out_kind == "kmv" else 3
    nouts = ngout + 1 + (1 if elig is not None else 0)

    def body(k, v, c):
        sk, sv, cl, st = phase1_shard_body(nprocs, dest_of, elig, k, v, c)
        if plan[0] == "wire":
            _tag, tiers, _cap, kpack, vpack = plan
            out_k, out_v, nrecv = phase2_wire_shard_body(
                nprocs, transport, mesh, tiers, cap_out, kpack, vpack,
                sk, sv, cl, st)
        else:
            _tag, B, nrounds, _cap = plan
            out_k, out_v, nrecv = phase2_shard_body(
                nprocs, transport, mesh, B, nrounds, cap_out, sk, sv, cl)
        gouts = fused_group_body(out_k, out_v, nrecv, gcap, out_kind,
                                 reduce_op, pallas_cfg)
        return (*gouts, cl) if st is None else (*gouts, cl, st)

    def run(key, value, count):
        if pallas_cfg is not None:
            sm = shard_map_kernels(body, mesh, (spec,) * 3,
                                   (spec,) * nouts)
        else:
            sm = jax.shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                               out_specs=(spec,) * nouts)
        return sm(key, value, count)

    # NEVER donated: a failed speculation check (or a chaos retry)
    # re-runs on the same inputs, which donation would have deleted
    return jax.jit(run)


def _compact_jit(mesh, n: int, narrs: int):
    """Per-shard leading-rows slice: shrink a fused group's [cap_out]
    outputs to the eager tier's round_cap(max groups) residency.  One
    cheap extra dispatch, paid only when it shrinks ≥4× (see
    _maybe_compact) — without it duplicate-heavy keys leave the resident
    dataset (and every downstream compile) sized at row capacity."""
    key = ("compact", mesh, n, narrs)

    def build():
        import jax
        from ..parallel.mesh import row_spec
        spec = row_spec(mesh)

        @jax.jit
        def run(*arrs):
            body = lambda *xs: tuple(x[:n] for x in xs)
            return jax.shard_map(body, mesh=mesh, in_specs=(spec,) * narrs,
                                 out_specs=(spec,) * narrs)(*arrs)
        return run
    return FUSED_CACHE.get_or_build(key, build)


def _maybe_compact(mesh, gcap: int, gcounts, *arrs):
    """Slice group-indexed outputs down to round_cap(max group count)
    when that shrinks ≥4×; otherwise return them unchanged (the extra
    dispatch isn't worth single-digit savings)."""
    from ..core.runtime import bump_dispatch
    from ..parallel.sharded import round_cap
    new_gcap = round_cap(max(int(gcounts.max()), 1))
    if new_gcap * 4 > gcap:
        return arrs
    bump_dispatch()
    return _compact_jit(mesh, new_gcap, len(arrs))(*arrs)


def _fused_local_jit(mesh, out_kind: str, reduce_op: Optional[str],
                     gcap: Optional[int] = None, pallas_cfg=None,
                     donate_argnums=()):
    key = ("local", mesh, out_kind, reduce_op, gcap, pallas_cfg,
           tuple(donate_argnums))
    return FUSED_CACHE.get_or_build(
        key, lambda: _fused_local_build(mesh, out_kind, reduce_op,
                                        gcap, pallas_cfg,
                                        donate_argnums))


def _fused_local_build(mesh, out_kind, reduce_op, gcap=None,
                       pallas_cfg=None, donate_argnums=()):
    import jax
    from ..exec import donated_jit
    from ..parallel.group import fused_group_body
    from ..parallel.mesh import row_spec, shard_map_kernels
    spec = row_spec(mesh)
    nouts = 5 if out_kind == "kmv" else 3

    def run(key, value, counts):
        def body(k, v, c):
            # gcap=None → full row capacity (the cold run); a warm run
            # compiles at the cached compact capacity (fusion v2)
            return fused_group_body(k, v, c[0],
                                    k.shape[0] if gcap is None else gcap,
                                    out_kind, reduce_op, pallas_cfg)
        if pallas_cfg is not None:
            sm = shard_map_kernels(body, mesh, (spec, spec, spec),
                                   (spec,) * nouts)
        else:
            sm = jax.shard_map(
                body, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=(spec,) * nouts)
        return sm(key, value, counts)

    # exec/: the consumed KV is replaced by the grouped output right
    # after (_install_kv) — donating lets the group layout reuse its
    # buffers (ukey is same-size as key here: gcap == cap)
    return donated_jit(run, donate_argnums)


# ---------------------------------------------------------------------------
# fused group execution
# ---------------------------------------------------------------------------

def _as_sharded(mr, frame):
    """Host frame → ShardedKV (intern byte/object columns + block-shard),
    exactly the eager aggregate's preparation (shuffle.aggregate_kv)."""
    from ..core.frame import KVFrame
    from ..parallel.sharded import shard_frame
    from ..parallel.shuffle import _intern_frame
    if not isinstance(frame, KVFrame):
        return frame
    frame, ktable, vtable = _intern_frame(frame, mr.backend.nprocs)
    skv = shard_frame(frame, mr.backend.mesh)
    skv.key_decode = ktable
    skv.value_decode = vtable
    return skv


def _install_kv(mr, skv):
    """Replace mr's dataset with a fused group's ShardedKV output."""
    if mr.kmv is not None:
        mr.kmv.free()
        mr.kmv = None
    old = mr.kv
    newkv = mr._new_kv()
    newkv.add_frame(skv)
    newkv.complete()
    if old is not None:
        old.free()
    mr.kv = newkv


def _install_kmv(mr, skmv):
    if mr.kv is not None:
        mr.kv.free()
        mr.kv = None
    mr.kmv = mr._new_kmv()
    mr.kmv.push(skmv)
    mr.kmv.complete()


def _exec_exchange_group(mr, stages, reduce_op, compiled: CompiledPlan,
                         gidx: int, sp, frame) -> tuple:
    """Run [aggregate, convert(, reduce)] as a fused exchange group.
    Warm + ``MRTPU_MEGAFUSE``: ONE megafused program (see module doc);
    cold or speculation-failed: phase 1 + ONE fused program (v1).
    Under ``MRTPU_WIRE`` both compose the wire-codec bodies
    (parallel/wire.py): the rows cross the interconnect delta-packed
    with tiered caps and decode inside the same program, so the grouped
    output stays byte-identical to the eager tiers.

    Runs under the ft/ ``shuffle.exchange`` fault site + retry policy
    like the eager exchange: the fault point sits before any dispatch,
    and a failure after the v1 path's donated phase-1 dispatch is
    vetoed as non-retryable (the megafused program never donates, so
    its retries are always safe).  Returns ``(mode, pallas)`` for the
    fusion telemetry."""
    from ..ft.inject import fault_point
    from ..ft.retry import retry_call
    from ..parallel.mesh import mesh_axis_size

    skv = _as_sharded(mr, frame)

    def _once():
        fault_point("shuffle.exchange")
        return _exchange_group_impl(mr, stages, reduce_op, compiled,
                                    gidx, sp, skv)

    def _retryable(e):
        try:
            return not skv.key.is_deleted()
        except Exception:
            return False

    return retry_call(
        "shuffle.exchange", _once,
        detail=f"P={mesh_axis_size(mr.backend.mesh)} fused",
        retryable=_retryable)


def _exchange_group_impl(mr, stages, reduce_op, compiled, gidx, sp,
                         skv) -> tuple:
    import jax
    from ..core.runtime import Timer, bump_dispatch
    from ..parallel import wire as _wire
    from ..parallel.mesh import mesh_axis_size, row_sharding
    from ..parallel.sharded import SyncStats
    from ..parallel.shuffle import _phase1_jit

    mesh = mr.backend.mesh
    nprocs = mesh_axis_size(mesh)
    transport = mr.settings.all2all
    out_kind = "kv" if reduce_op is not None else "kmv"
    _ok, hash_fn = _agg_hash(stages[0])
    dest = ("hash", hash_fn)

    from ..exec import can_donate
    donate = can_donate(skv)
    wire_on = _wire.wire_enabled()
    elig = _wire.columns_eligible(skv.key, skv.value) if wire_on else None
    counts_dev = jax.device_put(skv.counts.astype(np.int32),
                                row_sharding(mesh))
    t = Timer()

    entry = compiled.mega.get(gidx) if megafuse_enabled() else None
    if entry is not None and entry[0] == "x":
        pallas = _exec_mega_exchange(mr, stages, reduce_op, compiled,
                                     gidx, sp, skv, dest, out_kind,
                                     entry, wire_on, elig, counts_dev, t)
        if pallas is not None:
            return "mega", pallas
        # speculation failed — discard and fall through to v1 on the
        # SAME (never-donated) inputs; the commtime Timer keeps running
        # so the failed attempt's wall is charged honestly

    bump_dispatch()
    stats_local = None
    if wire_on:
        skey, svalue, counts_local, stats_local = _phase1_jit(
            mesh, dest, donate, wire=elig)(skv.key, skv.value, counts_dev)
    else:
        skey, svalue, counts_local = _phase1_jit(mesh, dest, donate)(
            skv.key, skv.value, counts_dev)
    SyncStats.bump()   # the op's ONE round-trip: the count matrix
    counts_mat = np.asarray(counts_local).reshape(nprocs, nprocs)
    stats_mat = (np.asarray(stats_local).reshape(nprocs, nprocs, 4)
                 if stats_local is not None else None)
    # ONE planning step shared with the eager exchange (wire.plan_from_
    # pull): plan choice and telemetry must never diverge between tiers
    plan, kvrange, bmax_raw, nmax_out, _new_counts = _wire.plan_from_pull(
        skv.key, skv.value, counts_mat, stats_mat, wire_on, elig)
    cached = compiled.caps.get(gidx)
    if cached is not None and cached[0] == plan[0] \
            and _wire.plan_holds(cached, bmax_raw, nmax_out, kvrange) \
            and not _wire.plan_oversized(cached, bmax_raw, nmax_out):
        # the cached plan still holds every row exactly and isn't
        # grossly oversized: reuse the compiled program
        plan = cached
    else:
        # too small OR ≥4× too large (skewed first run followed by
        # uniform data would pay the padded transfer forever, like the
        # eager speculative cache's right-sizing): recompile at the
        # fresh plan
        compiled.caps[gidx] = plan
    cap_out = _wire.plan_cap_out(plan)
    bump_dispatch()
    argnums = _donate_argnums(
        donate, cap_out == skey.shape[0] // max(nprocs, 1), out_kind,
        reduce_op, svalue)
    fused = _fused_exchange_jit(mesh, transport, plan, out_kind,
                                reduce_op, donate_argnums=argnums)
    if plan[0] == "wire":
        out = fused(skey, svalue, counts_local, stats_local)
    else:
        out = fused(skey, svalue, counts_local)
    meta = np.asarray(out[-1]).reshape(nprocs, 3)
    gcounts = meta[:, 0].astype(np.int32)
    vcounts = meta[:, 1].astype(np.int32)
    _finish_exchange_group(mr, stages, sp, skv, out_kind, reduce_op,
                           mesh, nprocs, plan, counts_mat, gcounts,
                           vcounts, out, t, compact_from=cap_out)
    # arm the NEXT run's single-dispatch speculation with what this run
    # measured: the plan that ran and the compact group capacity
    if megafuse_enabled():
        compiled.mega[gidx] = ("x", plan, _gcap_for(gcounts, cap_out))
    return "v1", False


def _exec_mega_exchange(mr, stages, reduce_op, compiled, gidx, sp, skv,
                        dest, out_kind, entry, wire_on, elig,
                        counts_dev, t):
    """One megafused attempt.  Returns the pallas flag on success, or
    None when the post-dispatch speculation check failed (the caller
    re-runs v1 on the same inputs — nothing was donated)."""
    from ..core.runtime import bump_dispatch
    from ..parallel import wire as _wire
    from ..parallel.mesh import mesh_axis_size
    from ..parallel.sharded import SyncStats

    mesh = mr.backend.mesh
    nprocs = mesh_axis_size(mesh)
    transport = mr.settings.all2all
    _tag, plan, gcap = entry
    pallas_cfg = _pallas_cfg_for(mr, skv, _wire.plan_cap_out(plan),
                                 out_kind, reduce_op, gcap)
    bump_dispatch()   # THE one dispatch of the warm group
    prog = _mega_jit(mesh, transport, dest, plan, gcap, out_kind,
                     reduce_op, elig, pallas_cfg)
    out = prog(skv.key, skv.value, counts_dev)
    SyncStats.bump()   # still ONE host round-trip — now after dispatch
    ngout = 5 if out_kind == "kmv" else 3
    gouts = out[:ngout]
    counts_mat = np.asarray(out[ngout]).reshape(nprocs, nprocs)
    stats_mat = (np.asarray(out[ngout + 1]).reshape(nprocs, nprocs, 4)
                 if elig is not None else None)
    meta = np.asarray(gouts[-1]).reshape(nprocs, 3)
    gcounts = meta[:, 0].astype(np.int32)
    vcounts = meta[:, 1].astype(np.int32)
    overflow = int(meta[:, 2].sum())
    # the speculation check: would the compiled shapes have dropped any
    # row (exchange plan) or group (gcap / kernel table overflow)?
    fresh, kvrange, bmax_raw, nmax_out, _nc = _wire.plan_from_pull(
        skv.key, skv.value, counts_mat, stats_mat, wire_on, elig)
    max_g = int(gcounts.max()) if gcounts.size else 0
    if (overflow or max_g > gcap
            or not _wire.plan_holds(plan, bmax_raw, nmax_out, kvrange)):
        compiled.mega.pop(gidx, None)
        sp.set(mega_miss=True)
        return None
    # right-size a grossly oversized or tag-shifted entry for NEXT time
    # (this run's result is exact and kept)
    if (plan[0] != fresh[0]
            or _wire.plan_oversized(plan, bmax_raw, nmax_out)
            or gcap > 4 * _gcap_for(gcounts, _wire.plan_cap_out(plan))):
        compiled.mega[gidx] = (
            "x", fresh, _gcap_for(gcounts, _wire.plan_cap_out(fresh)))
    _finish_exchange_group(mr, stages, sp, skv, out_kind, reduce_op,
                           mesh, nprocs, plan, counts_mat, gcounts,
                           vcounts, gouts, t, compact_from=None,
                           mega=True, pallas=pallas_cfg is not None)
    return pallas_cfg is not None


def _finish_exchange_group(mr, stages, sp, skv, out_kind, reduce_op,
                           mesh, nprocs, plan, counts_mat, gcounts,
                           vcounts, out, t, compact_from=None,
                           mega=False, pallas=False):
    """Shared tail of the v1 and megafused exchange groups: byte/stat
    accounting, span attrs, stage results and dataset installation —
    ONE copy so the two tiers' telemetry can never diverge."""
    from ..parallel import wire as _wire
    from ..parallel.sharded import ShardedKMV, ShardedKV
    from ..parallel.shuffle import ExchangeCallStats, ExchangeStats

    mr.counters.add(commtime=t.elapsed())
    nrows = int(counts_mat.sum())
    ngroups = int(gcounts.sum())
    cap_out = _wire.plan_cap_out(plan)
    B_eff, nrounds_eff = _wire.plan_rounds(plan)
    stats = ExchangeCallStats(nrounds=nrounds_eff, bucket=B_eff,
                              cap_out=cap_out, rows=nrows,
                              speculative=mega)
    _account_exchange(mr, skv, counts_mat, plan, nprocs, stats)
    ExchangeStats.last = (nrounds_eff, B_eff)   # deprecated shim
    mr.last_exchange = stats
    sp.set(bucket=B_eff, nrounds=nrounds_eff, cap_out=cap_out,
           rows=nrows, groups=ngroups, wire_bytes=stats.wire_bytes,
           wire_ratio=stats.wire_ratio, mega=mega, pallas=pallas)
    stages[0].result = nrows
    stages[1].result = ngroups
    if out_kind == "kv":
        ukey, uval = out[0], out[1]
        if compact_from is not None:
            ukey, uval = _maybe_compact(mesh, compact_from, gcounts,
                                        ukey, uval)
        skv_out = ShardedKV(mesh, ukey, uval, gcounts,
                            key_decode=skv.key_decode)
        if reduce_op == "first":
            skv_out.value_decode = skv.value_decode
        _install_kv(mr, skv_out)
        stages[2].result = ngroups
    else:
        # values/voff stay row-capacity-sized (voff indexes value rows,
        # exactly like the eager ShardedKMV); only group-indexed arrays
        # compact (already compiled compact in the megafused program)
        ukey, sizes, voff, values = out[0], out[1], out[2], out[3]
        if compact_from is not None:
            ukey, sizes, voff = _maybe_compact(mesh, compact_from,
                                               gcounts, ukey, sizes,
                                               voff)
        skmv = ShardedKMV(mesh, ukey, sizes, voff, values, gcounts,
                          vcounts, key_decode=skv.key_decode,
                          value_decode=skv.value_decode)
        _install_kmv(mr, skmv)


def _account_exchange(mr, skv, counts_mat, plan, nprocs, stats):
    from ..obs.metrics import record_exchange
    from ..parallel.shuffle import exchange_volume
    from ..parallel.wire import plan_slots, wire_ratio, wire_volume
    moved, pad, _rowbytes = exchange_volume(skv, counts_mat,
                                            plan_slots(plan), nprocs)
    mr.counters.add(cssize=moved, crsize=moved, cspad=pad)
    stats.sent_bytes, stats.pad_bytes = moved, pad
    if plan[0] == "wire":
        stats.wire_bytes = wire_volume(skv, counts_mat, plan)
        stats.wire_ratio = wire_ratio(moved, pad, stats.wire_bytes)
    # the fused tier's twin of the eager _exchange_impl feed: without it
    # a MRTPU_FUSE=1 run reads "no exchange traffic" on /metrics
    record_exchange(stats)


def _exec_local_group(mr, stages, reduce_op, compiled: CompiledPlan,
                      gidx: int, sp, frame) -> tuple:
    """Run [convert, reduce(kernel)] on a ShardedKV as ONE program.
    Fusion v2: a warm group compiles at the cached compact group
    capacity (skipping the separate compact dispatch) and may take the
    Pallas table path; the post-dispatch meta pull validates the
    capacity and re-runs at full capacity when it no longer covers.
    Returns ``(mode, pallas)`` for the fusion telemetry."""
    import jax
    from ..core.runtime import bump_dispatch
    from ..parallel.mesh import mesh_axis_size, row_sharding
    from ..parallel.sharded import ShardedKV, SyncStats

    skv = frame
    mesh = skv.mesh
    nprocs = mesh_axis_size(mesh)
    from ..exec import can_donate
    donate = can_donate(skv)
    cap = skv.key.shape[0] // nprocs   # before donation deletes the data
    counts_dev = jax.device_put(skv.counts.astype(np.int32),
                                row_sharding(mesh))
    entry = compiled.mega.get(gidx) if megafuse_enabled() else None
    gcap = entry[1] if entry is not None and entry[0] == "l" else None
    pallas_cfg = None
    if gcap is not None:
        pallas_cfg = _pallas_cfg_for(mr, skv, cap, "kv", reduce_op,
                                     gcap)
    mode = "local1" if gcap is not None else "local"
    bump_dispatch()
    # donation only when the group outputs alias the inputs byte for
    # byte — a compact (gcap < cap) warm program's outputs are smaller,
    # and its speculative re-run needs the inputs alive anyway
    argnums = _donate_argnums(donate and gcap is None, True, "kv",
                              reduce_op, skv.value)
    ukey, uval, meta = _fused_local_jit(mesh, "kv", reduce_op,
                                        gcap=gcap,
                                        pallas_cfg=pallas_cfg,
                                        donate_argnums=argnums)(
        skv.key, skv.value, counts_dev)
    SyncStats.bump()
    m = np.asarray(meta).reshape(nprocs, 3)
    gcounts = m[:, 0].astype(np.int32)
    overflow = int(m[:, 2].sum())
    if gcap is not None and (overflow or int(gcounts.max()) > gcap):
        # the cached capacity no longer covers: discard and re-run at
        # full row capacity (nothing was donated on the compact path)
        compiled.mega.pop(gidx, None)
        sp.set(mega_miss=True)
        bump_dispatch()
        ukey, uval, meta = _fused_local_jit(
            mesh, "kv", reduce_op, donate_argnums=())(
            skv.key, skv.value, counts_dev)
        SyncStats.bump()   # the re-run's meta pull is a second sync
        m = np.asarray(meta).reshape(nprocs, 3)
        gcounts = m[:, 0].astype(np.int32)
        gcap = None
        mode = "local"
        pallas_cfg = None
    ngroups = int(gcounts.sum())
    if gcap is None:
        ukey, uval = _maybe_compact(mesh, cap, gcounts, ukey, uval)
        if megafuse_enabled():
            compiled.mega[gidx] = ("l", _gcap_for(gcounts, cap))
    skv_out = ShardedKV(mesh, ukey, uval, gcounts,
                        key_decode=skv.key_decode)
    if reduce_op == "first":
        skv_out.value_decode = skv.value_decode
    _install_kv(mr, skv_out)
    sp.set(groups=ngroups, mega=gcap is not None,
           pallas=pallas_cfg is not None)
    stages[0].result = ngroups
    stages[1].result = ngroups
    return mode, pallas_cfg is not None


def _replay(mr, stage: PlanStage):
    """Eager fallback: run one recorded stage through the ordinary op
    method (tracing, stats, tier notes all behave as if never deferred),
    under the settings snapshot taken at record time."""
    saved = mr.settings
    if stage.settings is not None:
        mr.settings = stage.settings
    mr._plan_replaying = True
    try:
        stage.result = getattr(mr, stage.op)(*stage.args, **stage.kw)
    finally:
        mr._plan_replaying = False
        mr.settings = saved


# ---------------------------------------------------------------------------
# plan execution
# ---------------------------------------------------------------------------

def execute_plan(mr, plan: Plan) -> None:
    """Fuse + run a recorded plan against mr's current dataset."""
    tracer = mr.tracer
    key = None
    frame = None
    kv = mr.kv
    if kv is not None and kv.complete_done and kv._frames:
        frame = kv._frames[0]
    try:
        # MRTPU_WIRE is part of the key: a cached wire plan's caps are
        # tier/pack tuples a raw run can't validate against (and vice
        # versa), so the two knob states never share an entry
        from ..parallel.wire import wire_enabled
        key = (plan.fingerprint(), frame_signature(frame),
               _backend_signature(mr), mr.settings.all2all,
               mr.settings.outofcore, wire_enabled())
        compiled = plan_cache().get(key)
    except TypeError:       # unhashable stage arg: run uncached
        key = None
        compiled = None
    cache_hit = compiled is not None
    # the persistent tier (plan/cache.py): an in-memory miss consults
    # the on-disk plan store before compiling cold — a restarted
    # replica re-enters warm speculation state (caps + megafuse plans)
    # and, with the XLA executable cache armed next door, recompiles
    # nothing
    pkey = stable_plan_digest(key) if key is not None \
        and persistent_cache() is not None else None
    if compiled is None and pkey is not None:
        payload = persistent_cache().load(pkey)
        if payload is not None:
            compiled = _plan_from_payload(payload)
            plan_cache().put(key, compiled)
            cache_hit = True
    if compiled is None:
        compiled = CompiledPlan()
        if key is not None:
            plan_cache().put(key, compiled)
    compiled.runs += 1
    groups_desc = []
    with tracer.span("plan.execute", cat="plan", nstages=len(plan),
                     cache_hit=cache_hit) as psp:
        from ..core.runtime import thread_dispatches
        stages = list(plan.stages)
        i = 0
        gidx = 0
        while i < len(stages):
            n, kind, rop, frame = _match_group(mr, stages, i)
            run = stages[i:i + n]
            desc = {"stages": [s.describe() for s in run],
                    "fused": kind is not None, "kind": kind or "eager",
                    "reduce_op": rop}
            groups_desc.append(desc)
            # per-THREAD meter: concurrent serve workers' dispatches
            # must not contaminate this group's count (review fix)
            d0 = thread_dispatches()
            mode, pallas = "eager", False
            if kind is None:
                _replay(mr, run[0])
            else:
                with tracer.span("plan.group", cat="plan", kind=kind,
                                 fused=True, nstages=n,
                                 reduce_op=rop or "") as sp:
                    try:
                        if kind == "exchange":
                            mode, pallas = _exec_exchange_group(
                                mr, run, rop, compiled, gidx, sp, frame)
                        else:
                            mode, pallas = _exec_local_group(
                                mr, run, rop, compiled, gidx, sp, frame)
                    except BaseException:
                        # same contract as the eager exchange callers:
                        # a failure after a donated dispatch must leave
                        # a clean empty dataset (MRError on next op),
                        # never frames holding deleted buffers
                        from ..parallel.shuffle import free_if_donated
                        kv = mr._kv_data
                        if kv is not None:
                            free_if_donated(kv, frame)
                        raise
            # fusion effectiveness telemetry (mr.stats()["plan"]
            # ["fusion"] + the per-request profile): actual dispatches
            # of this group vs the eager tier's known per-op counts
            note_fusion(
                kind or "eager", mode, thread_dispatches() - d0,
                sum(_EAGER_DISPATCHES.get(s.op, 1) for s in run),
                pallas=pallas)
            desc["mode"] = mode
            i += n
            gidx += 1
        psp.set(ngroups=gidx,
                nfused=sum(1 for d in groups_desc if d["fused"]))
    compiled.groups = groups_desc
    if pkey is not None:
        # persist what this run learned (no-op when unchanged); an
        # empty speculation state still marks the digest as seen, so a
        # restarted replica re-enters the warm path; an unserializable
        # plan component just stays process-local
        payload = _plan_payload(compiled)
        if payload is not None:
            pp = persistent_cache()
            if pp is not None:
                pp.store(pkey, payload)
    record_history({"stages": plan.describe(), "groups": groups_desc,
                    "cache_hit": cache_hit,
                    "cache_key": _key_brief(key)})


def _plan_payload(compiled: CompiledPlan) -> Optional[dict]:
    """CompiledPlan speculation state → JSON-safe payload (None when a
    component has no stable serialization)."""
    from .cache import to_jsonable
    try:
        # runs is deliberately NOT persisted: it changes every
        # execution, which would defeat the store's unchanged-bytes
        # no-op and rewrite the entry per run
        return {"caps": {str(k): to_jsonable(v)
                         for k, v in compiled.caps.items()},
                "mega": {str(k): to_jsonable(v)
                         for k, v in compiled.mega.items()}}
    except TypeError:
        return None


def _plan_from_payload(payload: dict) -> CompiledPlan:
    """Inverse of :func:`_plan_payload`: group indices back to ints,
    lists back to tuples (wire plans are hashed into FUSED_CACHE keys,
    so tuple-ness matters)."""
    from .cache import from_jsonable
    cp = CompiledPlan()
    try:
        cp.caps = {int(k): from_jsonable(v)
                   for k, v in dict(payload.get("caps") or {}).items()}
        cp.mega = {int(k): from_jsonable(v)
                   for k, v in dict(payload.get("mega") or {}).items()}
        cp.runs = int(payload.get("runs", 0))
    except (TypeError, ValueError):
        return CompiledPlan()
    return cp


def _backend_signature(mr):
    from ..parallel.backend import MeshBackend
    if isinstance(mr.backend, MeshBackend):
        return ("mesh", mr.backend.mesh)
    return ("serial",)


def _key_brief(key) -> Optional[str]:
    if key is None:
        return None
    fp, frame_sig, backend, transport, ooc, wire = key
    ops = "→".join(s[0] for s in fp)
    return (f"ops[{ops}] frame{frame_sig!r} backend={backend[0]} "
            f"all2all={transport} outofcore={ooc} wire={int(wire)}")
