"""plan/ — lazy pipeline planner with fused compiled execution.

Every MapReduce op is eager by default: ``map``, ``aggregate``,
``convert``, ``reduce`` each dispatch their own jitted program(s),
materialize an intermediate dataset and sync with the host between ops.
This subsystem defers op chains into a small IR (:mod:`.ir`), fuses
maximal device-tier runs into single ``jit``/``shard_map`` programs
(:mod:`.fuser`) and caches compiled plans across runs (:mod:`.cache`):

    with mr.pipeline():          # or MapReduce(fuse=1) / MRTPU_FUSE=1
        mr.aggregate()
        mr.convert()
        mr.reduce(count, batch=True)
    # ← one phase-1 dispatch + ONE fused exchange/group/reduce program

Host-callback stages, spill boundaries, serial backends and
gather/print-style barriers break fusion — those segments run the
ordinary eager path, so every pipeline still runs, fused or not.  See
``doc/plan.md`` for the fusion-break rules and the cache key.
"""

from .cache import (LRUCache, cache_stats, clear_history, plan_cache,
                    plan_history)
from .ir import Plan, PlanStage
from .recorder import PendingCount, PlanRecorder

__all__ = [
    "Plan", "PlanStage", "PlanRecorder", "PendingCount",
    "LRUCache", "plan_cache", "cache_stats", "plan_history",
    "clear_history",
]
