"""Bounded caches for compiled artifacts + the plan cache.

Two problems, one mechanism:

* the shuffle/convert jit builders were ``functools.lru_cache(None)`` —
  unbounded, so long soak runs over many meshes/dest functions pin every
  executable forever (ISSUE 2 satellite);
* the plan fuser compiles whole pipelines and must reuse them across
  runs, with visible hit/miss/eviction telemetry (the production
  inference-stack shape: a compiled-plan cache keyed on program
  fingerprint + shapes).

:class:`LRUCache` is the shared policy: thread-safe (``-partition``
worlds record/execute plans from interpreter threads), move-to-back on
hit, evict-front past ``maxsize``, with cumulative hit/miss/eviction
counters that ``MapReduce.stats()`` and the obs spans report.

Key discipline: every knob that changes a compiled program's BYTES must
be in its cache key — the plan cache keys (fingerprint, frame
signature, backend, transport, outofcore, ``MRTPU_WIRE``), and the
shuffle/fused executable caches additionally key the wire codec's full
plan tuple (tier ladder + pack dtypes; ``parallel/wire.py``), so
flipping a knob can never replay a stale executable.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Optional

from ..utils.env import env_knob


class LRUCache:
    """Thread-safe LRU with telemetry.  ``get_or_build(key, build)`` is
    the only way entries appear; ``build()`` runs OUTSIDE the lock (it
    may trace/compile for seconds) — a racing builder for the same key
    wastes one build but never deadlocks or tears the dict."""

    def __init__(self, maxsize: int, name: str = "cache"):
        self.name = name
        self.maxsize = max(1, int(maxsize))
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                hit = self._d[key]
            else:
                self.misses += 1
                hit = None
        # per-request attribution (obs/context.py): the same hit/miss
        # lands on the active request account, so a serve session's
        # "did this recompile?" is ITS delta even with concurrent
        # neighbors warming the same process-global cache
        try:
            from ..obs.context import note_plan
            note_plan(self.name, hit is not None)
        except Exception:
            pass
        return hit

    def put(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)
                self.evictions += 1

    def get_or_build(self, key, build: Callable):
        hit = self.get(key)
        if hit is not None:
            return hit
        value = build()
        self.put(key, value)
        return value

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def resize(self, maxsize: int) -> None:
        with self._lock:
            self.maxsize = max(1, int(maxsize))
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)
                self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"size": len(self._d), "maxsize": self.maxsize,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


# ---------------------------------------------------------------------------
# the plan cache: (stage-chain fingerprint, frame shapes/dtypes, mesh,
# transport) → executable plan (see fuser.CompiledPlan)
# ---------------------------------------------------------------------------

_PLAN_CACHE: Optional[LRUCache] = None
_PLAN_LOCK = threading.Lock()


def plan_cache() -> LRUCache:
    global _PLAN_CACHE
    if _PLAN_CACHE is None:
        with _PLAN_LOCK:
            if _PLAN_CACHE is None:
                _PLAN_CACHE = LRUCache(
                    env_knob("MRTPU_PLAN_CACHE", int, 32),
                    name="plan")
    return _PLAN_CACHE


def cache_stats() -> dict:
    """Structured snapshot of every bounded compile cache — the plan
    cache plus the shuffle's phase1/phase2 jit caches — and the
    cumulative fusion-effectiveness counters (what
    ``MapReduce.stats()['plan']`` reports)."""
    out = {"plan": plan_cache().stats()}
    from ..parallel import shuffle
    out["shuffle_phase1"] = shuffle.PHASE1_CACHE.stats()
    out["shuffle_phase2"] = shuffle.PHASE2_CACHE.stats()
    out["fusion"] = fusion_stats()
    return out


# ---------------------------------------------------------------------------
# fusion effectiveness: per-group fused program counts + dispatch
# savings (fusion v2, plan/fuser.py) — the "did megafusion actually
# shrink dispatches" half of mr.stats()["plan"], next to the cache
# hit/miss half above.  Also fed per-request into the active
# RequestAccount so GET /v1/jobs/<id>/profile shows it per job.
# ---------------------------------------------------------------------------

_FUSION_LOCK = threading.Lock()
_FUSION = {"groups": 0, "fused_groups": 0, "eager_groups": 0,
           "mega_groups": 0, "pallas_groups": 0, "dispatches": 0,
           "eager_dispatch_estimate": 0, "dispatches_saved": 0}


def note_fusion(kind: str, mode: str, dispatches: int, eager_est: int,
                pallas: bool = False) -> None:
    """One executed plan group: its fusion kind ("exchange"/"local"/
    "eager"), execution mode ("mega"/"local1" = single-dispatch warm,
    "v1"/"local" = cold or fallback, "eager" = replay), the compiled-
    program launches it actually made, and the eager tier's per-op
    baseline for the same stages."""
    # classify ONCE; the per-request twin (obs/context) receives the
    # derived booleans so the mode-string sets can never drift
    fused = kind != "eager"
    mega = fused and mode in ("mega", "local1")
    saved = max(0, int(eager_est) - int(dispatches)) if fused else 0
    with _FUSION_LOCK:
        _FUSION["groups"] += 1
        if not fused:
            _FUSION["eager_groups"] += 1
        else:
            _FUSION["fused_groups"] += 1
            if mega:
                _FUSION["mega_groups"] += 1
            if pallas:
                _FUSION["pallas_groups"] += 1
        _FUSION["dispatches"] += int(dispatches)
        _FUSION["eager_dispatch_estimate"] += int(eager_est)
        _FUSION["dispatches_saved"] += saved
    try:
        from ..obs.context import note_fusion as _ctx_note
        _ctx_note(fused, mega, int(dispatches), saved, pallas)
    except Exception:
        pass


def fusion_stats() -> dict:
    with _FUSION_LOCK:
        return dict(_FUSION)


def reset_fusion_stats() -> None:
    """Test/bench isolation: zero the cumulative fusion counters."""
    with _FUSION_LOCK:
        for k in _FUSION:
            _FUSION[k] = 0


def stats_delta(before: dict, after: Optional[dict] = None) -> dict:
    """Per-request compile-cache deltas: ``{cache: {hits, misses,
    evictions}}`` between two :func:`cache_stats` snapshots (``after``
    defaults to a fresh snapshot).  The caches are process-global —
    PR 2's LRU is a fleet-wide warm cache under the serve/ daemon — so
    a single request's "did this recompile?" question is only
    answerable as a delta: the serve/ session runner stamps one into
    every result (``misses == 0`` on a warm identical request is the
    no-recompile assertion bench's ``detail.serve_ab`` and the
    acceptance test make)."""
    after = cache_stats() if after is None else after
    out = {}
    for cname, a in after.items():
        b = before.get(cname, {})
        out[cname] = {k: a.get(k, 0) - b.get(k, 0)
                      for k in ("hits", "misses", "evictions")}
    return out


# ---------------------------------------------------------------------------
# plan history: the last few executed plans, described, for dump_plan /
# scripts/plan_dump.py (the trace ring's analog for whole plans)
# ---------------------------------------------------------------------------

_HISTORY: list = []
_HISTORY_LOCK = threading.Lock()
_HISTORY_CAP = 64


def record_history(desc: dict) -> None:
    with _HISTORY_LOCK:
        _HISTORY.append(desc)
        del _HISTORY[:-_HISTORY_CAP]


def plan_history() -> list:
    with _HISTORY_LOCK:
        return list(_HISTORY)


def clear_history() -> None:
    with _HISTORY_LOCK:
        _HISTORY.clear()
