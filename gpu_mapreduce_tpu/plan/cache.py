"""Bounded caches for compiled artifacts + the plan cache.

Two problems, one mechanism:

* the shuffle/convert jit builders were ``functools.lru_cache(None)`` —
  unbounded, so long soak runs over many meshes/dest functions pin every
  executable forever (ISSUE 2 satellite);
* the plan fuser compiles whole pipelines and must reuse them across
  runs, with visible hit/miss/eviction telemetry (the production
  inference-stack shape: a compiled-plan cache keyed on program
  fingerprint + shapes).

:class:`LRUCache` is the shared policy: thread-safe (``-partition``
worlds record/execute plans from interpreter threads), move-to-back on
hit, evict-front past ``maxsize``, with cumulative hit/miss/eviction
counters that ``MapReduce.stats()`` and the obs spans report.

Key discipline: every knob that changes a compiled program's BYTES must
be in its cache key — the plan cache keys (fingerprint, frame
signature, backend, transport, outofcore, ``MRTPU_WIRE``), and the
shuffle/fused executable caches additionally key the wire codec's full
plan tuple (tier ladder + pack dtypes; ``parallel/wire.py``), so
flipping a knob can never replay a stale executable.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

from ..utils.env import env_flag, env_knob


class LRUCache:
    """Thread-safe LRU with telemetry.  ``get_or_build(key, build)`` is
    the only way entries appear; ``build()`` runs OUTSIDE the lock (it
    may trace/compile for seconds) — a racing builder for the same key
    wastes one build but never deadlocks or tears the dict."""

    def __init__(self, maxsize: int, name: str = "cache"):
        self.name = name
        self.maxsize = max(1, int(maxsize))
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                hit = self._d[key]
            else:
                self.misses += 1
                hit = None
        # per-request attribution (obs/context.py): the same hit/miss
        # lands on the active request account, so a serve session's
        # "did this recompile?" is ITS delta even with concurrent
        # neighbors warming the same process-global cache
        try:
            from ..obs.context import note_plan
            note_plan(self.name, hit is not None)
        except Exception:
            pass
        return hit

    def put(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)
                self.evictions += 1

    def get_or_build(self, key, build: Callable):
        hit = self.get(key)
        if hit is not None:
            return hit
        value = build()
        self.put(key, value)
        return value

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def resize(self, maxsize: int) -> None:
        with self._lock:
            self.maxsize = max(1, int(maxsize))
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)
                self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"size": len(self._d), "maxsize": self.maxsize,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


# ---------------------------------------------------------------------------
# the plan cache: (stage-chain fingerprint, frame shapes/dtypes, mesh,
# transport) → executable plan (see fuser.CompiledPlan)
# ---------------------------------------------------------------------------

_PLAN_CACHE: Optional[LRUCache] = None
_PLAN_LOCK = threading.Lock()


def plan_cache() -> LRUCache:
    global _PLAN_CACHE
    if _PLAN_CACHE is None:
        with _PLAN_LOCK:
            if _PLAN_CACHE is None:
                _PLAN_CACHE = LRUCache(
                    env_knob("MRTPU_PLAN_CACHE", int, 32),
                    name="plan")
    return _PLAN_CACHE


def cache_stats() -> dict:
    """Structured snapshot of every bounded compile cache — the plan
    cache plus the shuffle's phase1/phase2 jit caches — and the
    cumulative fusion-effectiveness counters (what
    ``MapReduce.stats()['plan']`` reports).  ``persistent`` is the
    on-disk plan tier (zeros when disarmed) so
    ``mrtpu_plan_cache_hit_ratio{cache="persistent"}`` and the serve
    per-request deltas cover restarts, not just this process."""
    out = {"plan": plan_cache().stats()}
    from ..parallel import shuffle
    out["shuffle_phase1"] = shuffle.PHASE1_CACHE.stats()
    out["shuffle_phase2"] = shuffle.PHASE2_CACHE.stats()
    out["fusion"] = fusion_stats()
    pp = persistent_cache()
    out["persistent"] = pp.stats() if pp is not None else {
        "enabled": 0, "entries": 0, "bytes": 0,
        "hits": 0, "misses": 0, "evictions": 0}
    return out


# ---------------------------------------------------------------------------
# fusion effectiveness: per-group fused program counts + dispatch
# savings (fusion v2, plan/fuser.py) — the "did megafusion actually
# shrink dispatches" half of mr.stats()["plan"], next to the cache
# hit/miss half above.  Also fed per-request into the active
# RequestAccount so GET /v1/jobs/<id>/profile shows it per job.
# ---------------------------------------------------------------------------

_FUSION_LOCK = threading.Lock()
_FUSION = {"groups": 0, "fused_groups": 0, "eager_groups": 0,
           "mega_groups": 0, "pallas_groups": 0, "dispatches": 0,
           "eager_dispatch_estimate": 0, "dispatches_saved": 0}


def note_fusion(kind: str, mode: str, dispatches: int, eager_est: int,
                pallas: bool = False) -> None:
    """One executed plan group: its fusion kind ("exchange"/"local"/
    "eager"), execution mode ("mega"/"local1" = single-dispatch warm,
    "v1"/"local" = cold or fallback, "eager" = replay), the compiled-
    program launches it actually made, and the eager tier's per-op
    baseline for the same stages."""
    # classify ONCE; the per-request twin (obs/context) receives the
    # derived booleans so the mode-string sets can never drift
    fused = kind != "eager"
    mega = fused and mode in ("mega", "local1")
    saved = max(0, int(eager_est) - int(dispatches)) if fused else 0
    with _FUSION_LOCK:
        _FUSION["groups"] += 1
        if not fused:
            _FUSION["eager_groups"] += 1
        else:
            _FUSION["fused_groups"] += 1
            if mega:
                _FUSION["mega_groups"] += 1
            if pallas:
                _FUSION["pallas_groups"] += 1
        _FUSION["dispatches"] += int(dispatches)
        _FUSION["eager_dispatch_estimate"] += int(eager_est)
        _FUSION["dispatches_saved"] += saved
    try:
        from ..obs.context import note_fusion as _ctx_note
        _ctx_note(fused, mega, int(dispatches), saved, pallas)
    except Exception:
        pass


def fusion_stats() -> dict:
    with _FUSION_LOCK:
        return dict(_FUSION)


def reset_fusion_stats() -> None:
    """Test/bench isolation: zero the cumulative fusion counters."""
    with _FUSION_LOCK:
        for k in _FUSION:
            _FUSION[k] = 0


def stats_delta(before: dict, after: Optional[dict] = None) -> dict:
    """Per-request compile-cache deltas: ``{cache: {hits, misses,
    evictions}}`` between two :func:`cache_stats` snapshots (``after``
    defaults to a fresh snapshot).  The caches are process-global —
    PR 2's LRU is a fleet-wide warm cache under the serve/ daemon — so
    a single request's "did this recompile?" question is only
    answerable as a delta: the serve/ session runner stamps one into
    every result (``misses == 0`` on a warm identical request is the
    no-recompile assertion bench's ``detail.serve_ab`` and the
    acceptance test make)."""
    after = cache_stats() if after is None else after
    out = {}
    for cname, a in after.items():
        b = before.get(cname, {})
        out[cname] = {k: a.get(k, 0) - b.get(k, 0)
                      for k in ("hits", "misses", "evictions")}
    return out


# ---------------------------------------------------------------------------
# the persistent plan tier (doc/perf.md#the-caching-tier): compiled-plan
# speculation state (exchange caps + megafuse plans) survives process
# restarts under <cas>/plan/, keyed by a STABLE digest of the in-memory
# plan-cache key (function objects render as module.qualname, live mesh
# objects as axis/size/platform signatures).  The actual XLA executables
# persist next door via JAX's compilation cache (<cas>/xla/ —
# enable_executable_cache), so a cold replica's first warm-shaped
# request re-traces against cached speculation state and every compile
# hits the on-disk executable cache: 0 recompiles.
#
# A digest collision (two different lambdas sharing a qualname) is
# SAFE: the payload is speculation state, validated against the fresh
# count matrices on every run (plan_holds / gcap checks) — at worst one
# mega-miss and a v1 re-run, never a wrong result.
# ---------------------------------------------------------------------------


def _mesh_stable(mesh) -> str:
    """Axis names/sizes + device platform: equal meshes on different
    hosts (or across restarts) share plan state; a width change keys
    separately (the caps/plans are per-width shapes)."""
    shape = dict(getattr(mesh, "shape", None) or {})
    kind = ""
    devs = getattr(mesh, "devices", None)
    if devs is not None:
        try:
            first = devs.reshape(-1)[0] if hasattr(devs, "reshape") \
                else list(devs)[0]
            kind = getattr(first, "platform", "") or ""
        except Exception:
            kind = ""
    return f"{sorted(shape.items())}|{kind}"


def _stable_part(x) -> str:
    if isinstance(x, (int, float, str, bytes, bool, type(None))):
        return repr(x)
    if isinstance(x, tuple):
        if len(x) == 2 and x[0] == "fn" and callable(x[1]):
            f = x[1]
            return (f"fn:{getattr(f, '__module__', '?')}."
                    f"{getattr(f, '__qualname__', None) or getattr(f, '__name__', '?')}")
        if len(x) == 2 and x[0] == "mesh" and not isinstance(x[1], str):
            return f"mesh:{_mesh_stable(x[1])}"
        return "(" + ",".join(_stable_part(e) for e in x) + ")"
    raise TypeError(f"no stable rendering for {type(x).__name__}")


def stable_plan_digest(key) -> Optional[str]:
    """Stable cross-process digest of an in-memory plan-cache key, or
    None when some component has no stable rendering (those plans stay
    process-local)."""
    try:
        text = _stable_part(key)
    except TypeError:
        return None
    return hashlib.sha256(text.encode()).hexdigest()


def to_jsonable(x):
    """Plan payloads → JSON-safe (tuples → lists, numpy scalars →
    python); raises TypeError on anything else so an unserializable
    plan skips persistence instead of storing garbage."""
    if isinstance(x, (list, tuple)):
        return [to_jsonable(e) for e in x]
    if isinstance(x, dict):
        return {str(k): to_jsonable(v) for k, v in x.items()}
    if isinstance(x, (str, bool, type(None), int, float)):
        return x
    import numpy as np
    if isinstance(x, np.integer):
        return int(x)
    if isinstance(x, np.floating):
        return float(x)
    if isinstance(x, np.dtype):
        return str(x)
    raise TypeError(f"not plan-serializable: {type(x).__name__}")


def from_jsonable(x):
    """Inverse of :func:`to_jsonable` for plan payloads: lists become
    tuples again (wire plans are compared and used as dict/cache keys,
    so tuple-ness is load-bearing)."""
    if isinstance(x, list):
        return tuple(from_jsonable(e) for e in x)
    if isinstance(x, dict):
        return {k: from_jsonable(v) for k, v in x.items()}
    return x


class PersistentPlanCache:
    """On-disk plan-state entries under ``<cas>/plan/``, one JSON file
    per stable key digest, each stamped (``utils/integrity``) and
    verified on read — a corrupt entry counts an
    ``mrtpu_integrity_failures_total{artifact="cas"}``, is removed, and
    reads as a miss (cold compile, never wrong state).  Bounded by
    ``MRTPU_PLAN_PERSIST_CAP`` entries, oldest-mtime evicted."""

    def __init__(self, root: str):
        self.dir = os.path.join(root, "plan")
        self.cap = max(1, env_knob("MRTPU_PLAN_PERSIST_CAP", int, 512))
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _path(self, digest: str) -> str:
        return os.path.join(self.dir, digest + ".json")

    def _note(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
        try:
            from ..obs.context import note_plan
            note_plan("persistent", hit)
        except Exception:
            pass

    def load(self, digest: str) -> Optional[dict]:
        from ..utils.integrity import (digest_bytes,
                                       record_integrity_failure,
                                       verify_enabled)
        path = self._path(digest)
        try:
            with open(path) as f:
                rec = json.load(f)
            payload = rec["payload"]
            body = json.dumps(payload, sort_keys=True).encode()
            if verify_enabled() and rec.get("c") != digest_bytes(body):
                raise ValueError("stamp mismatch")
        except OSError:
            self._note(False)
            return None
        except (ValueError, KeyError, TypeError):
            # bit-flipped / torn entry: quarantine-by-removal and fall
            # back to a cold compile — corruption degrades, never lies
            record_integrity_failure("cas")
            try:
                os.remove(path)
            except OSError:
                pass
            self._note(False)
            return None
        self._note(True)
        return payload

    def store(self, digest: str, payload: dict) -> bool:
        """Write (or refresh) one entry; no-op when the stored bytes
        already match (steady state costs one small read, no write)."""
        from ..utils.integrity import digest_bytes
        body = json.dumps(payload, sort_keys=True)
        rec = json.dumps({"c": digest_bytes(body.encode()),
                          "payload": payload}, sort_keys=True)
        path = self._path(digest)
        try:
            with open(path) as f:
                if f.read() == rec:
                    return False
        except OSError:
            pass
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "w") as f:
                f.write(rec)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            return False
        self._evict()
        return True

    def _evict(self) -> None:
        try:
            names = [n for n in os.listdir(self.dir)
                     if n.endswith(".json")]
        except OSError:
            return
        excess = len(names) - self.cap
        if excess <= 0:
            return
        aged = []
        for n in names:
            try:
                aged.append((os.path.getmtime(
                    os.path.join(self.dir, n)), n))
            except OSError:
                continue
        for _mt, n in sorted(aged)[:excess]:
            try:
                os.remove(os.path.join(self.dir, n))
                with self._lock:
                    self.evictions += 1
            except OSError:
                pass

    def stats(self) -> dict:
        entries = 0
        nbytes = 0
        try:
            for n in os.listdir(self.dir):
                if not n.endswith(".json"):
                    continue
                try:
                    nbytes += os.path.getsize(os.path.join(self.dir, n))
                except OSError:
                    continue
                entries += 1
        except OSError:
            pass
        with self._lock:
            return {"enabled": 1, "entries": entries, "bytes": nbytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


_PERSIST: Optional[PersistentPlanCache] = None
_PERSIST_ROOT: Optional[str] = None


def persistent_cache() -> Optional[PersistentPlanCache]:
    """The on-disk tier singleton (re-rooted when the env changes —
    tests); None when no CAS root is armed or ``MRTPU_PLAN_PERSIST=0``."""
    global _PERSIST, _PERSIST_ROOT
    from ..utils.cas import cas_enabled, cas_root
    if not cas_enabled() or not env_flag("MRTPU_PLAN_PERSIST", True):
        return None
    root = cas_root()
    with _PLAN_LOCK:
        if _PERSIST is None or _PERSIST_ROOT != root:
            _PERSIST = PersistentPlanCache(root)
            _PERSIST_ROOT = root
        return _PERSIST


def enable_executable_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at ``<cas>/xla/`` so
    the executables behind every jit/shard_map program survive process
    restarts (the other half of "0 recompiles on a warm-shaped cold
    replica").  Respects an operator's own ``JAX_COMPILATION_CACHE_DIR``
    (never overrides it), is disarmed with the tier
    (``MRTPU_JIT_PERSIST=0`` or no CAS root), and any failure keeps the
    uncached path — pure optimisation."""
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return None
    from ..utils.cas import cas_enabled, cas_root
    if not cas_enabled() or not env_flag("MRTPU_JIT_PERSIST", True):
        return None
    path = os.path.join(cas_root(), "xla")
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    except Exception:
        return None
    return path


# ---------------------------------------------------------------------------
# plan history: the last few executed plans, described, for dump_plan /
# scripts/plan_dump.py (the trace ring's analog for whole plans)
# ---------------------------------------------------------------------------

_HISTORY: list = []
_HISTORY_LOCK = threading.Lock()
_HISTORY_CAP = 64


def record_history(desc: dict) -> None:
    with _HISTORY_LOCK:
        _HISTORY.append(desc)
        del _HISTORY[:-_HISTORY_CAP]


def plan_history() -> list:
    with _HISTORY_LOCK:
        return list(_HISTORY)


def clear_history() -> None:
    with _HISTORY_LOCK:
        _HISTORY.clear()
