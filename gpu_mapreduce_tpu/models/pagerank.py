"""PageRank — the framework's flagship iterative-graph workload.

The reference names PageRank as a headline workload but ships only a
skeleton: ``oink/pagerank.cpp:53-55`` reads edges and builds the vertex
list, then the iteration body is empty.  This module *designs* it from the
reference's composition pattern (SURVEY.md §2.5): out-degree → per-edge
rank scatter (the collate) → damped sum per destination (the reduce),
iterated to a tolerance.

TPU-first design, not a transliteration:

* the graph is a static-shape edge array ``src[m], dst[m]`` (+ valid mask
  for padding); ranks are a dense f32 vector — all ops are vectorised
  segment-sums, no per-pair callbacks;
* one iteration = gather src ranks → scale by 1/out-degree →
  ``segment_sum`` onto dst → damp.  Under ``jit`` this fuses to a couple
  of HBM passes;
* the whole convergence loop runs on device in ``lax.while_loop`` — the
  only host traffic is the final result (the reference's iterative
  commands Allreduce a done-flag per round, e.g. ``oink/cc_find.cpp``;
  we keep even that on device);
* multi-chip: edges are sharded over the mesh axis, ranks replicated;
  each shard segment-sums its local contributions and one ``psum`` over
  ICI merges them (the analogue of aggregate()'s all-to-all, but
  all-reduce shaped because the rank vector is dense).

Numerics: everything is f32 (TPU-native); a ``tol`` below ~1e-7 is under
f32 resolution — the loop then runs to ``maxiter`` (or to an exact f32
fixpoint, depending on summation order).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import mesh_axes, mesh_axis_size, row_spec


def out_degrees(src: jax.Array, n: int, valid=None) -> jax.Array:
    """Out-degree per vertex from an edge list (the degree command's kernel,
    reference oink/degree.cpp:36-60)."""
    ones = jnp.ones_like(src, dtype=jnp.float32)
    if valid is not None:
        ones = jnp.where(valid, ones, 0.0)
    return jax.ops.segment_sum(ones, src, num_segments=n)


def inv_outdegrees(deg: jax.Array) -> jax.Array:
    """1/out-degree with 0 for dangling (degree-0) vertices."""
    return jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)


def _dangling_mass(ranks: jax.Array, inv_outdeg: jax.Array) -> jax.Array:
    """Rank mass sitting on dangling vertices, spread uniformly."""
    n = ranks.shape[0]
    return (jnp.sum(ranks) - jnp.sum(ranks * jnp.sign(inv_outdeg))) / n


def pagerank_step(ranks: jax.Array, src: jax.Array, dst: jax.Array,
                  inv_outdeg: jax.Array, damping: float = 0.85,
                  valid: Optional[jax.Array] = None) -> jax.Array:
    """One damped power-iteration step.  Dangling mass is redistributed
    uniformly so the ranks stay a probability distribution."""
    n = ranks.shape[0]
    contrib = ranks[src] * inv_outdeg[src]
    if valid is not None:
        contrib = jnp.where(valid, contrib, 0.0)
    inflow = jax.ops.segment_sum(contrib, dst, num_segments=n)
    return ((1.0 - damping) / n +
            damping * (inflow + _dangling_mass(ranks, inv_outdeg)))


@functools.partial(jax.jit, static_argnames=("n", "maxiter"))
def pagerank(src: jax.Array, dst: jax.Array, n: int, tol: float = 1e-6,
             maxiter: int = 100, damping: float = 0.85
             ) -> Tuple[jax.Array, jax.Array]:
    """Full on-device convergence loop.  Returns (ranks, iterations)."""
    deg = out_degrees(src, n)
    inv = inv_outdegrees(deg)
    r0 = jnp.full((n,), 1.0 / n, jnp.float32)

    def cond(state):
        _, delta, it = state
        return jnp.logical_and(delta > tol, it < maxiter)

    def body(state):
        r, _, it = state
        r2 = pagerank_step(r, src, dst, inv, damping)
        return r2, jnp.max(jnp.abs(r2 - r)), it + 1

    ranks, _, iters = lax.while_loop(cond, body, (r0, jnp.float32(jnp.inf),
                                                  jnp.int32(0)))
    return ranks, iters


# ---------------------------------------------------------------------------
# sharded (multi-chip) path
# ---------------------------------------------------------------------------

def _sharded_step(ranks, src, dst, inv_outdeg, valid, damping, axes):
    """shard_map body: local segment-sum of the shard's edges, then one
    psum (over every mesh axis — ICI within a slice, DCN across for a
    multi-slice mesh) merges per-shard inflows (replicated ranks in,
    replicated ranks out)."""
    n = ranks.shape[0]
    contrib = jnp.where(valid, ranks[src] * inv_outdeg[src], 0.0)
    inflow = lax.psum(jax.ops.segment_sum(contrib, dst, num_segments=n), axes)
    return ((1.0 - damping) / n +
            damping * (inflow + _dangling_mass(ranks, inv_outdeg)))


def pad_edges_for_mesh(src: np.ndarray, dst: np.ndarray, nprocs: int
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad the edge list to a multiple of nprocs rows; returns
    (src, dst, valid)."""
    m = len(src)
    mpad = -(-max(m, 1) // nprocs) * nprocs
    pad = mpad - m
    src = np.concatenate([src, np.zeros(pad, src.dtype)])
    dst = np.concatenate([dst, np.zeros(pad, dst.dtype)])
    valid = np.concatenate([np.ones(m, bool), np.zeros(pad, bool)])
    return src, dst, valid


@functools.lru_cache(maxsize=None)
def _sharded_run_fn(mesh: Mesh, n: int, tol: float, maxiter: int,
                    damping: float):
    """Compile-once (per mesh/shape/params) sharded convergence loop."""
    rep = NamedSharding(mesh, P())
    axes = mesh_axes(mesh)       # works for flat ("p",) and ("s","c")
    rspec = row_spec(mesh)

    @functools.partial(jax.jit, out_shardings=(rep, rep))
    def run(src_d, dst_d, valid_d):
        deg = jax.shard_map(
            lambda s, v: lax.psum(out_degrees(s, n, valid=v), axes),
            mesh=mesh, in_specs=(rspec, rspec), out_specs=P())(
                src_d, valid_d)
        inv = inv_outdegrees(deg)
        r0 = jnp.full((n,), 1.0 / n, jnp.float32)

        step = jax.shard_map(
            functools.partial(_sharded_step, damping=damping, axes=axes),
            mesh=mesh,
            in_specs=(P(), rspec, rspec, P(), rspec),
            out_specs=P())

        def cond(state):
            _, delta, it = state
            return jnp.logical_and(delta > tol, it < maxiter)

        def body(state):
            r, _, it = state
            r2 = step(r, src_d, dst_d, inv, valid_d)
            return r2, jnp.max(jnp.abs(r2 - r)), it + 1

        ranks, _, iters = lax.while_loop(
            cond, body, (r0, jnp.float32(jnp.inf), jnp.int32(0)))
        return ranks, iters

    return run


def pagerank_sharded(mesh: Mesh, src: np.ndarray, dst: np.ndarray, n: int,
                     tol: float = 1e-6, maxiter: int = 100,
                     damping: float = 0.85) -> Tuple[np.ndarray, int]:
    """Edge-parallel PageRank over a device mesh (flat or multi-slice).
    Edges are block-sharded over all mesh axes; ranks replicated; one
    psum per iteration rides ICI (+DCN across slices)."""
    nprocs = mesh_axis_size(mesh)
    src_p, dst_p, valid_p = pad_edges_for_mesh(src, dst, nprocs)
    edge_shard = NamedSharding(mesh, row_spec(mesh))
    # bounded per-device messages: a scale-22 edge column is ~134 MB,
    # past what a tunneled single device_put survives (r5)
    from ..parallel.mesh import device_put_chunked
    src_d = device_put_chunked(src_p, edge_shard)
    dst_d = device_put_chunked(dst_p, edge_shard)
    valid_d = device_put_chunked(valid_p, edge_shard)
    run = _sharded_run_fn(mesh, n, tol, maxiter, damping)
    ranks, iters = run(src_d, dst_d, valid_d)
    return np.asarray(ranks), int(iters)
