"""Luby maximal independent set — fused on-device rounds.

The reference iterates {edge_winner, vert_winner, vert_loser,
vert_emit} MapReduce stages until no edges remain
(``oink/luby_find.cpp:53-95``); the composed twin lives in
oink/commands/luby.py.  This model runs the whole thing in ONE jitted
``lax.while_loop`` over a dense vertex state vector:

* per-vertex priorities are the SAME splitmix64 stream as the composed
  engine (``vertex_rand(v, seed)`` on original ids); a vertex joins
  when its (priority, id) is lexicographically smaller than every
  UNDECIDED neighbour's.  With these shared priorities the two engines
  produce identical sets on the golden script input, but only the MIS
  property itself is contractual (the composed rounds cull edges in a
  different order — see the LubyFind docstring);
* one round = masked segment-mins (neighbour min priority, then min id
  among holders of it) + neighbour-of-winner exclusion, all
  vectorised; the mesh version pmin/pmax-combines over ICI.

States: 0 undecided, 1 in MIS, 2 excluded.  A vertex whose undecided
neighbourhood empties (everyone excluded) sees +inf and joins — the
maximality guarantee."""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import mesh_axes, mesh_axis_size, row_spec


def _both_dirs(src, dst, x_by_src):
    """Edge contributions in both directions: (values, targets) where
    value i is x evaluated at the *other* endpoint."""
    return (jnp.concatenate([x_by_src[src], x_by_src[dst]]),
            jnp.concatenate([dst, src]),
            jnp.concatenate([src, dst]))


def _round(state, prio, src, dst, valid, n, axes=None):
    und = state == 0
    idx = jnp.arange(n, dtype=jnp.int32)
    active = valid & und[src] & und[dst]
    act2 = jnp.concatenate([active, active])

    pv, tgt, other = _both_dirs(src, dst, prio)
    seg = jnp.where(act2, tgt, n)
    ov = other.astype(jnp.int32)

    # min neighbour priority among undecided neighbours
    m1 = jax.ops.segment_min(jnp.where(act2, pv, jnp.inf), seg,
                             num_segments=n + 1)[:n]
    if axes is not None:
        m1 = lax.pmin(m1, axes)
    # min neighbour id among holders of that priority (tie-break)
    hold = act2 & (pv == m1[tgt])
    mid = jax.ops.segment_min(jnp.where(hold, ov, n), seg,
                              num_segments=n + 1)[:n]
    if axes is not None:
        mid = lax.pmin(mid, axes)

    winner = und & ((prio < m1) | ((prio == m1) & (idx < mid)))

    # neighbours of winners become excluded (only undecided ones change)
    wv = jnp.concatenate([winner[src], winner[dst]]).astype(jnp.int32)
    seg_all = jnp.where(jnp.concatenate([valid, valid]), tgt, n)
    wn = jax.ops.segment_max(jnp.where(seg_all < n, wv, 0), seg_all,
                             num_segments=n + 1)[:n]
    if axes is not None:
        wn = lax.pmax(wn, axes)
    lose = und & ~winner & (wn > 0)
    return jnp.where(winner, 1, jnp.where(lose, 2, state)).astype(jnp.int8)


def _loop(step, n, maxiter):
    state0 = jnp.zeros(n, jnp.int8)

    def cond(s):
        state, it = s
        return jnp.logical_and(jnp.any(state == 0), it < maxiter)

    def body(s):
        state, it = s
        return step(state), it + 1

    return lax.while_loop(cond, body, (state0, jnp.int32(0)))


@functools.partial(jax.jit, static_argnames=("n", "maxiter"))
def luby_mis(src, dst, prio, n: int, maxiter: int = 0
             ) -> Tuple[jax.Array, jax.Array]:
    """Single device.  Returns (state[n] ∈ {1 MIS, 2 excluded}, rounds).
    ``prio``: per-vertex priorities (vertex_rand on original ids)."""
    maxiter = maxiter or max(n, 1)
    valid = jnp.ones(src.shape, bool)
    s32, d32 = src.astype(jnp.int32), dst.astype(jnp.int32)
    return _loop(lambda st: _round(st, prio, s32, d32, valid, n),
                 n, maxiter)


@functools.lru_cache(maxsize=None)
def _luby_sharded_fn(mesh: Mesh, n: int, maxiter: int):
    axes = mesh_axes(mesh)
    rspec = row_spec(mesh)
    rep = NamedSharding(mesh, P())

    @functools.partial(jax.jit, out_shardings=(rep, rep))
    def run(src_d, dst_d, valid_d, prio):
        body = jax.shard_map(
            lambda st, pr, s, d, v: _round(st, pr, s, d, v, n, axes),
            mesh=mesh, in_specs=(P(), P(), rspec, rspec, rspec),
            out_specs=P())
        return _loop(lambda st: body(st, prio, src_d, dst_d, valid_d),
                     n, maxiter)

    return run


def luby_mis_sharded(mesh: Mesh, src: np.ndarray, dst: np.ndarray,
                     prio: np.ndarray, n: int, maxiter: int = 0
                     ) -> Tuple[np.ndarray, int]:
    from ..models.pagerank import pad_edges_for_mesh

    nprocs = mesh_axis_size(mesh)
    src_p, dst_p, valid_p = pad_edges_for_mesh(
        src.astype(np.int32), dst.astype(np.int32), nprocs)
    shard = NamedSharding(mesh, row_spec(mesh))
    run = _luby_sharded_fn(mesh, n, maxiter or max(n, 1))
    from ..parallel.mesh import device_put_chunked, replicated
    state, iters = run(device_put_chunked(src_p, shard),
                       device_put_chunked(dst_p, shard),
                       device_put_chunked(valid_p, shard),
                       device_put_chunked(np.asarray(prio),
                                          replicated(mesh)))
    return np.asarray(state), int(iters)
