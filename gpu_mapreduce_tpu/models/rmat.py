"""R-MAT graph generation — vectorised on device.

The reference generates edges one at a time with drand48 in a serial map
callback (``oink/map_rmat_generate.cpp:14-67``): per edge, ``nlevels``
recursive quadrant choices with probabilities (a,b,c,d), optionally
perturbed per level by ``fraction`` noise and renormalised.

TPU-first: one ``lax.scan`` over levels, each level drawing a uniform per
*edge* (a [m] vector op), building vertex ids MSB-first by shifting bits
in — the batch equivalent of the reference's delta-halving walk.  Noise,
when enabled, perturbs per-edge per-level probability vectors exactly like
the reference's serial walk (a [m,4] op).  `jax.random` (threefry) replaces
drand48 — bit-identity with the reference is not a goal (SURVEY.md §7);
determinism under our own seeds is.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@functools.partial(jax.jit, static_argnames=("m", "nlevels", "noisy"))
def rmat_edges(key, m: int, nlevels: int, abcd, frac: float, noisy: bool
               ) -> Tuple[jax.Array, jax.Array]:
    """Generate m R-MAT edges in a 2^nlevels-vertex graph.

    Returns (vi[m], vj[m]) uint64.  ``abcd`` is a length-4 array of
    quadrant probabilities; ``noisy`` statically gates the per-level
    fraction perturbation (frac == 0 ⇒ pass noisy=False)."""
    abcd = jnp.asarray(abcd, jnp.float32)
    probs0 = jnp.broadcast_to(abcd, (m, 4)) if noisy else abcd[None, :]

    def level(carry, lkey):
        i, j, probs = carry
        ku, kn = jax.random.split(lkey)
        u = jax.random.uniform(ku, (m,), jnp.float32)
        t = jnp.cumsum(probs, axis=1)          # [*,4]: a, a+b, a+b+c, 1
        t = jnp.broadcast_to(t, (m, 4))
        # quadrant: 0=a (i0,j0)  1=b (j1)  2=c (i1)  3=d (i1,j1)
        jbit = ((u >= t[:, 0]) & (u < t[:, 1])) | (u >= t[:, 2])
        ibit = u >= t[:, 1]
        i = (i << np.uint64(1)) | ibit.astype(jnp.uint64)
        j = (j << np.uint64(1)) | jbit.astype(jnp.uint64)
        if noisy:
            nz = jax.random.uniform(kn, (m, 4), jnp.float32,
                                    minval=-0.5, maxval=0.5)
            probs = probs * (1.0 + frac * nz)
            probs = probs / jnp.sum(probs, axis=1, keepdims=True)
        return (i, j, probs), None

    zeros = jnp.zeros((m,), jnp.uint64)
    keys = jax.random.split(key, nlevels)
    (vi, vj, _), _ = lax.scan(level, (zeros, zeros, probs0), keys)
    return vi, vj


def generate_unique(seed: int, nlevels: int, nnonzero: int,
                    abcd=(0.25, 0.25, 0.25, 0.25), frac: float = 0.0,
                    add_edges=None) -> Tuple[np.ndarray, int]:
    """Host driver: regenerate until 2^nlevels * nnonzero unique edges exist
    (the reference RMAT command's cull loop, ``oink/rmat.cpp:46-60``) —
    used directly by tests; the OINK command runs the same loop through the
    MapReduce algebra instead.  Returns (edges [n,2] uint64, iterations)."""
    order = 1 << nlevels
    ntotal = order * nnonzero
    root = jax.random.PRNGKey(seed)
    niterate = 0
    # ONE generation shape for every round: a per-round pow2 of the
    # remaining need meant a fresh XLA compile per round (~7 compiles —
    # 20-40s each on real TPU); the full-size batch trimmed to `need`
    # keeps the exact reference semantics with a single compile
    m = max(8, 1 << (ntotal - 1).bit_length())
    # dedupe on packed u64 keys (vi<<nlevels | vj): scalar np.unique is
    # several times faster than 2-column row unique, and vertex ids
    # always fit — nlevels ≤ 32 means 2*nlevels ≤ 64 bits
    assert nlevels <= 32, "RMAT scale above 32 exceeds the u64 edge key"
    shift = np.uint64(nlevels)
    mask = np.uint64(order - 1)
    # first-come acceptance over the WHOLE m-candidate batch each round
    # (the reference accepts the first ntotal unique edges in generation
    # order, oink/rmat.cpp:46-60; trimming candidates to the remainder
    # wasted most of each batch and took ~2-3x the rounds)
    accepted: list = []
    naccepted = 0
    sorted_seen = np.zeros(0, np.uint64)
    while naccepted < ntotal:
        niterate += 1
        root, sub = jax.random.split(root)
        vi, vj = rmat_edges(sub, m, nlevels, jnp.asarray(abcd), frac,
                            noisy=frac > 0.0)
        keys = (np.asarray(vi) << shift) | np.asarray(vj)
        # first occurrence of each key within the batch, in batch order
        uniq, first_idx = np.unique(keys, return_index=True)
        if len(sorted_seen):
            pos = np.searchsorted(sorted_seen, uniq)
            pos = np.minimum(pos, len(sorted_seen) - 1)
            fresh_mask = sorted_seen[pos] != uniq
            uniq, first_idx = uniq[fresh_mask], first_idx[fresh_mask]
        take = uniq[np.argsort(first_idx)][: ntotal - naccepted]
        accepted.append(take)
        naccepted += len(take)
        sorted_seen = np.sort(np.concatenate([sorted_seen, take]))
        if add_edges is not None:
            add_edges(np.stack([take >> shift, take & mask], 1))
    seen_keys = np.sort(np.concatenate(accepted))
    seen = np.stack([seen_keys >> shift, seen_keys & mask], 1)
    return seen, niterate
