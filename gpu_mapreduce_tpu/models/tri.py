"""Triangle enumeration — vectorised degree-ordered wedge matching.

The reference's tri_find is Cohen's MapReduce algorithm
(``oink/tri_find.cpp:43-81``): augment edges with degrees, have the
low-degree endpoint of each edge emit its "angles" (neighbour pairs),
and match angles against the edge list — 6 shuffled MR stages.  The
composed twin lives in oink/commands/tri.py.

This model keeps Cohen's core insight (orient edges from the
lexicographically smaller (degree, id) endpoint, so every vertex's
out-neighbourhood is O(√m) and the total wedge count is Σ k_v(k_v-1)/2
≤ O(m^1.5)) but runs it as array programs:

* orientation, adjacency grouping and the triangular wedge expansion
  are vectorised index arithmetic (no per-vertex Python);
* wedges are generated in bounded-size batches (static pow2 caps) and
  matched against the sorted canonical edge-key array with
  ``searchsorted`` — the membership test runs on the default JAX
  backend when it is an accelerator, NumPy otherwise;
* each triangle is found exactly once: the wedge (u, w) at centre v
  exists only in v's out-neighbourhood, and the matching edge (u, w)
  closes it.

Output rows are (centre, u, w) like the composed engine (centre = the
emitting low-rank vertex)."""

from __future__ import annotations

from typing import Optional

import numpy as np

_BATCH = 1 << 24        # wedges per membership batch (bounds peak memory)


def _canonical(edges: np.ndarray) -> np.ndarray:
    """Unique undirected edges (a<b), self-loops dropped."""
    a = np.minimum(edges[:, 0], edges[:, 1])
    b = np.maximum(edges[:, 0], edges[:, 1])
    keep = a != b
    e = np.stack([a[keep], b[keep]], 1)
    return np.unique(e, axis=0)


def _pair_expand(tloc: np.ndarray):
    """Invert the triangular enumeration: local pair index t → (i, j)
    with 0 <= i < j, t = j(j-1)/2 + i.  Exact after float correction."""
    j = ((1.0 + np.sqrt(1.0 + 8.0 * tloc.astype(np.float64))) / 2.0)
    j = j.astype(np.int64)
    # float sqrt can be off by one either way at boundaries
    tj = j * (j - 1) // 2
    j = np.where(tj > tloc, j - 1, j)
    tj = j * (j - 1) // 2
    j = np.where(tloc - tj >= j, j + 1, j)
    i = tloc - j * (j - 1) // 2
    return i, j


def triangles(edges: np.ndarray, use_device: Optional[bool] = None
              ) -> np.ndarray:
    """All triangles of an undirected edge list, each exactly once.
    Returns [t, 3] uint64 rows (centre, u, w)."""
    e = _canonical(np.asarray(edges, np.uint64))
    if len(e) == 0:
        return np.zeros((0, 3), np.uint64)
    verts, inv = np.unique(e.reshape(-1), return_inverse=True)
    n = len(verts)
    a = inv.reshape(-1, 2)[:, 0]
    b = inv.reshape(-1, 2)[:, 1]
    return triangles_ranked(a, b, n, verts, use_device, canonical=True)


def triangles_ranked(a: np.ndarray, b: np.ndarray, n: int,
                     verts: np.ndarray,
                     use_device: Optional[bool] = None,
                     canonical: bool = False) -> np.ndarray:
    """Triangles from pre-ranked endpoints (0..n-1) plus the rank→id
    table ``verts`` — the entry point for device-staged edges
    (parallel/staging.py ranks on the mesh; only the int32 rank columns
    reach the host).  ``canonical=False`` dedupes/orients here."""
    if n == 0 or len(a) == 0:
        return np.zeros((0, 3), np.uint64)
    assert n < 2**32, f"triangles(): {n} vertices overflow u64 rank packing"
    if not canonical:
        lo0 = np.minimum(a, b).astype(np.uint64)
        hi0 = np.maximum(a, b).astype(np.uint64)
        keep = lo0 != hi0
        ek = np.unique(lo0[keep] * np.uint64(n) + hi0[keep])
        if len(ek) == 0:
            return np.zeros((0, 3), np.uint64)
        a = (ek // np.uint64(n)).astype(np.int64)
        b = (ek % np.uint64(n)).astype(np.int64)

    deg = np.bincount(a, minlength=n) + np.bincount(b, minlength=n)
    # orient a→b from the smaller (degree, id); rank = deg*n + id is a
    # total order and fits u64 for any n < 2^32 (asserted above)
    rank = deg.astype(np.uint64) * np.uint64(n) + np.arange(n, dtype=np.uint64)
    swap = rank[a] > rank[b]
    lo = np.where(swap, b, a)
    hi = np.where(swap, a, b)

    order = np.argsort(lo, kind="stable")
    grp = lo[order]                       # centre vertex per directed edge
    nbr = hi[order]                       # its out-neighbour
    k = np.bincount(grp, minlength=n)     # out-degree per vertex
    npairs = k.astype(np.int64) * (k - 1) // 2
    group_start = np.concatenate([[0], np.cumsum(k)[:-1]])
    pair_start = np.concatenate([[0], np.cumsum(npairs)])
    P = int(pair_start[-1])

    # sorted canonical edge keys for the membership probe
    ekey = np.sort(np.minimum(a, b).astype(np.uint64) * np.uint64(n)
                   + np.maximum(a, b))

    probe = _probe_fn(use_device)
    out = []
    # walk the global wedge index space in batches of ≤ _BATCH
    start = 0
    while start < P:
        stop = min(start + _BATCH, P)
        t = np.arange(start, stop, dtype=np.int64)
        # group of each wedge: searchsorted over the pair-offset table
        g = np.searchsorted(pair_start, t, side="right") - 1
        i, j = _pair_expand(t - pair_start[g])
        base = group_start[g]
        u = nbr[base + i]
        w = nbr[base + j]
        wkey = (np.minimum(u, w).astype(np.uint64) * np.uint64(n)
                + np.maximum(u, w))
        hit = probe(ekey, wkey)
        if hit.any():
            out.append(np.stack([verts[grp[base[hit]]], verts[u[hit]],
                                 verts[w[hit]]], 1))
        start = stop
    if not out:
        return np.zeros((0, 3), np.uint64)
    return np.concatenate(out).astype(np.uint64)


def _probe_fn(use_device: Optional[bool]):
    """Membership tester: sorted-array binary search.  On an accelerator
    backend the probe runs as one jitted searchsorted+gather dispatch."""
    import jax

    if use_device is None:
        use_device = jax.default_backend() not in ("cpu",)
    if not use_device:
        def probe(ekey, wkey):
            pos = np.searchsorted(ekey, wkey)
            pos = np.minimum(pos, len(ekey) - 1)
            return ekey[pos] == wkey
        return probe

    import jax.numpy as jnp

    @jax.jit
    def _hit(ekey, wkey):
        pos = jnp.clip(jnp.searchsorted(ekey, wkey), 0, ekey.shape[0] - 1)
        return jnp.take(ekey, pos) == wkey

    def probe(ekey, wkey):
        # pad the wedge batch to a pow2 so recompiles stay bounded
        m = len(wkey)
        cap = max(8, 1 << (m - 1).bit_length())
        pad = np.zeros(cap - m, wkey.dtype)
        res = np.asarray(_hit(jnp.asarray(ekey),
                              jnp.asarray(np.concatenate([wkey, pad]))))
        return res[:m]
    return probe
