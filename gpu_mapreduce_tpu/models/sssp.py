"""Single-source shortest paths — fused on-device Bellman-Ford.

The reference's sssp command relaxes distances through ~6 MapReduce
stages per round (``oink/sssp.cpp:49-180``); like cc_find, that
composition pays one compiled XLA program per stage per shape, and the
iterative driver drowns in recompiles (SURVEY.md §7).  The fused model
runs the whole relaxation to fixpoint in ONE jitted ``lax.while_loop``:

* ``dist`` is a dense replicated vector (vertices pre-densified by the
  command, like PageRank/cc);
* one round = one ``segment_min`` of ``dist[src] + w`` over the
  (sharded) edge list, plus a second masked ``segment_min`` that picks
  the smallest source achieving the new distance as the predecessor;
* the mesh version pmin-combines both over ICI; the only host traffic
  is the final (dist, pred).

The source vertex is a TRACED operand, so the ncnt-source experiment
(``sssp ncnt seed``) reuses one compiled program for every source.
Predecessor ties break to the smallest vertex index (any pred that
realises the shortest distance is valid — the oracle contract)."""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import mesh_axes, mesh_axis_size, row_spec


def _round(dist, pred, src, dst, w, valid, n, axes=None):
    """One relaxation round; with ``axes`` the partial mins combine
    across mesh shards via pmin."""
    seg = jnp.where(valid, dst, n)
    relax = jnp.where(valid, dist[src] + w, jnp.inf)
    m = jax.ops.segment_min(relax, seg, num_segments=n + 1)[:n]
    if axes is not None:
        m = lax.pmin(m, axes)
    nd = jnp.minimum(dist, m)
    improved = nd < dist
    cand = jnp.where(valid & (relax == nd[dst]), src, n).astype(jnp.int32)
    pm = jax.ops.segment_min(cand, seg, num_segments=n + 1)[:n]
    if axes is not None:
        pm = lax.pmin(pm, axes)
    npred = jnp.where(improved, pm, pred)
    return nd, npred, jnp.any(improved)


def _loop(step, n, maxiter, source):
    dist0 = jnp.full((n,), jnp.inf).at[source].set(0.0)
    pred0 = jnp.full((n,), -1, jnp.int32)

    def cond(state):
        return jnp.logical_and(state[2], state[3] < maxiter)

    def body(state):
        dist, pred, _, it = state
        nd, npred, changed = step(dist, pred)
        return nd, npred, changed, it + 1

    dist, pred, _, iters = lax.while_loop(
        cond, body, (dist0, pred0, jnp.bool_(True), jnp.int32(0)))
    return dist, pred, iters


@functools.partial(jax.jit, static_argnames=("n", "maxiter"))
def bellman_ford(src, dst, w, n: int, source, maxiter: int = 0):
    """Single device.  Returns (dist[n], pred[n], iterations); pred is
    -1 for the source and unreachable vertices."""
    maxiter = maxiter or max(n, 1)
    valid = jnp.ones(src.shape, bool)
    s32, d32 = src.astype(jnp.int32), dst.astype(jnp.int32)

    def step(dist, pred):
        return _round(dist, pred, s32, d32, w, valid, n)

    return _loop(step, n, maxiter, source)


@functools.lru_cache(maxsize=None)
def _bf_sharded_fn(mesh: Mesh, n: int, maxiter: int):
    axes = mesh_axes(mesh)
    rspec = row_spec(mesh)
    rep = NamedSharding(mesh, P())

    @functools.partial(jax.jit, out_shardings=(rep, rep, rep))
    def run(src_d, dst_d, w_d, valid_d, source):
        body = jax.shard_map(
            lambda dist, pred, s, d, w, v: _round(dist, pred, s, d, w, v,
                                                  n, axes),
            mesh=mesh, in_specs=(P(), P(), rspec, rspec, rspec, rspec),
            out_specs=(P(), P(), P()))

        def step(dist, pred):
            return body(dist, pred, src_d, dst_d, w_d, valid_d)

        return _loop(step, n, maxiter, source)

    return run


def prepare_bellman_ford(mesh: Mesh, src: np.ndarray, dst: np.ndarray,
                         w: np.ndarray, n: int, maxiter: int = 0):
    """Pad + upload the edge arrays ONCE; returns ``run(source) →
    (dist, pred, iters)`` — the ncnt-source experiment re-uses both the
    compiled program and the device-resident edges."""
    from ..models.pagerank import pad_edges_for_mesh

    nprocs = mesh_axis_size(mesh)
    src_p, dst_p, valid_p = pad_edges_for_mesh(
        src.astype(np.int32), dst.astype(np.int32), nprocs)
    w_p = np.concatenate([np.asarray(w, np.float64),
                          np.zeros(len(src_p) - len(w))])
    shard = NamedSharding(mesh, row_spec(mesh))
    fn = _bf_sharded_fn(mesh, n, maxiter or max(n, 1))
    from ..parallel.mesh import device_put_chunked
    args = (device_put_chunked(src_p, shard),
            device_put_chunked(dst_p, shard),
            device_put_chunked(w_p, shard),
            device_put_chunked(valid_p, shard))

    def run(source: int):
        dist, pred, iters = fn(*args, jnp.int32(source))
        return np.asarray(dist), np.asarray(pred), int(iters)

    return run


def bellman_ford_sharded(mesh: Mesh, src: np.ndarray, dst: np.ndarray,
                         w: np.ndarray, n: int, source: int,
                         maxiter: int = 0
                         ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Edge-parallel fused loop over a device mesh (single source; for
    many sources use :func:`prepare_bellman_ford`)."""
    return prepare_bellman_ford(mesh, src, dst, w, n, maxiter)(source)
