"""Connected components — fused on-device label propagation.

The reference's cc_find composes ~9 MapReduce stages per propagation
round (``oink/cc_find.cpp:38-109``) — free in C++, but on XLA every
stage is a compiled program and iterative re-compilation/dispatch
dominates (exactly the cost model SURVEY.md §7 warns about for
iterative graph drivers).  The TPU-first design runs the ENTIRE
convergence loop as one jitted ``lax.while_loop``, like the flagship
PageRank model: labels live in a dense replicated vector, each round is
two segment-mins over the (sharded) edge list plus one pointer-jumping
hop, and the only host traffic is the final labels.

Semantics match the composed command: the fixpoint labels every
component with its minimum vertex id (zone winner = min,
oink/commands/cc.py).  Pointer jumping (``lab = min(lab, lab[lab])``)
compresses label chains so convergence is ~O(log n) rounds instead of
O(diameter).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import mesh_axes, mesh_axis_size, row_spec


def _propagate(lab, src, dst, valid, n):
    """One round: every edge pulls its endpoints toward the smaller
    label, then one pointer-jump hop.  Padded edge rows route to the
    dropped segment n."""
    seg_dst = jnp.where(valid, dst, n)
    seg_src = jnp.where(valid, src, n)
    m1 = jax.ops.segment_min(lab[src], seg_dst, num_segments=n + 1)[:n]
    m2 = jax.ops.segment_min(lab[dst], seg_src, num_segments=n + 1)[:n]
    nl = jnp.minimum(lab, jnp.minimum(m1, m2))
    return jnp.minimum(nl, nl[nl])          # pointer jumping


@functools.partial(jax.jit, static_argnames=("n", "maxiter"))
def cc(src: jax.Array, dst: jax.Array, n: int, maxiter: int = 0
       ) -> Tuple[jax.Array, jax.Array]:
    """Single-device fused loop.  Returns (labels[n], iterations);
    labels[v] = smallest vertex index in v's component."""
    maxiter = maxiter or max(n, 1)
    lab0 = jnp.arange(n, dtype=jnp.int32)
    valid = jnp.ones(src.shape, bool)

    def cond(state):
        _, changed, it = state
        return jnp.logical_and(changed, it < maxiter)

    def body(state):
        lab, _, it = state
        nl = _propagate(lab, src.astype(jnp.int32), dst.astype(jnp.int32),
                        valid, n)
        return nl, jnp.any(nl != lab), it + 1

    lab, _, iters = lax.while_loop(
        cond, body, (lab0, jnp.bool_(n > 0), jnp.int32(0)))
    return lab, iters


@functools.lru_cache(maxsize=None)
def _cc_sharded_fn(mesh: Mesh, n: int, maxiter: int):
    axes = mesh_axes(mesh)
    rspec = row_spec(mesh)
    rep = NamedSharding(mesh, P())

    @functools.partial(jax.jit, out_shardings=(rep, rep))
    def run(src_d, dst_d, valid_d):
        lab0 = jnp.arange(n, dtype=jnp.int32)

        step = jax.shard_map(
            lambda lab, s, d, v: lax.pmin(
                _propagate(lab, s, d, v, n), axes),
            mesh=mesh, in_specs=(P(), rspec, rspec, rspec), out_specs=P())

        def cond(state):
            _, changed, it = state
            return jnp.logical_and(changed, it < maxiter)

        def body(state):
            lab, _, it = state
            nl = step(lab, src_d, dst_d, valid_d)
            return nl, jnp.any(nl != lab), it + 1

        return lax.while_loop(
            cond, body, (lab0, jnp.bool_(n > 0), jnp.int32(0)))[::2]

    return run


def cc_sharded(mesh: Mesh, src: np.ndarray, dst: np.ndarray, n: int,
               maxiter: int = 0) -> Tuple[np.ndarray, int]:
    """Edge-parallel fused loop over a device mesh (flat or multi-slice):
    edges block-sharded, labels replicated, one pmin per round over
    ICI(+DCN).  Returns (labels[n], iterations)."""
    from ..models.pagerank import pad_edges_for_mesh

    nprocs = mesh_axis_size(mesh)
    src_p, dst_p, valid_p = pad_edges_for_mesh(
        src.astype(np.int32), dst.astype(np.int32), nprocs)
    shard = NamedSharding(mesh, row_spec(mesh))
    run = _cc_sharded_fn(mesh, n, maxiter or max(n, 1))
    from ..parallel.mesh import device_put_chunked
    lab, iters = run(device_put_chunked(src_p, shard),
                     device_put_chunked(dst_p, shard),
                     device_put_chunked(valid_p, shard))
    return np.asarray(lab), int(iters)
