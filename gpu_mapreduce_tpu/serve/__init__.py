"""serve/ — the multi-tenant MR-as-a-service daemon.

Turns the one-script-one-process model inside out: a resident
:class:`~.daemon.Server` keeps the expensive state warm (backend/mesh
init, the plan/ compiled-plan LRU, shuffle jit caches, interned
dictionaries) and executes OINK scripts / JSON op batches submitted
over the obs/httpd loopback listener as isolated, journaled,
budget-scoped sessions.  ``python -m gpu_mapreduce_tpu.serve`` runs it
standalone; ``scripts/mrctl.py`` is the operator client.  doc/serve.md
is the contract.
"""

from .admission import AdmissionQueue
from .auth import TokenAuth
from .budget import TenantBudgets
from .client import ServeClient, ServeError
from .daemon import Server
from .fleet import FleetMember, owner_of, ring_route
from .overload import BurnShedder, CostProfiles, DiskMonitor
from .router import Router
from .session import Session, normalize_payload, run_session

__all__ = ["AdmissionQueue", "TenantBudgets", "ServeClient",
           "ServeError", "Server", "Session", "normalize_payload",
           "run_session", "FleetMember", "Router", "owner_of",
           "ring_route", "TokenAuth", "BurnShedder", "CostProfiles",
           "DiskMonitor"]
