"""Per-tenant page/HBM budgets for the serve/ daemon.

Two halves, both riding machinery that already exists:

* **enforcement** — a session's MapReduce objects are created with
  ``memsize``/``maxpage``/``outofcore`` defaults derived from the
  tenant's page allowance (``MRTPU_TENANT_PAGES``), so a dataset that
  outgrows the budget spills through ``core/dataset.py``'s page
  splitter into the session's own scratch directory.  The budget keys
  are PINNED on the session's ObjectManager (``pin``): the script's own
  ``set maxpage ...`` raises instead of lifting the allowance.  Budgets
  are per-MR settings, so one tenant exhausting its allowance can only
  ever spill its OWN frames — another tenant's resident pages are
  untouched by construction (the isolation test in
  tests/test_serve.py).
* **attribution** — a :class:`~..core.runtime.PageAccount` per tenant,
  installed as a thread scope around each session run, receives every
  byte charged through ``Counters.mem`` and feeds the
  ``mrtpu_tenant_pages{tenant}`` gauge plus the ``/v1/stats`` tenants
  section.

``MRTPU_TENANT_PAGES=0`` (the default) disables enforcement — sessions
run with the server's plain defaults and the accounts only attribute.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..core.runtime import PageAccount
from ..utils.env import env_knob


class TenantBudgets:
    """tenant name → :class:`PageAccount` registry + the MR settings
    defaults a session's ObjectManager starts from."""

    def __init__(self, pages: Optional[int] = None,
                 memsize: Optional[int] = None):
        self.pages = pages if pages is not None \
            else env_knob("MRTPU_TENANT_PAGES", int, 0)
        self.memsize = memsize if memsize is not None \
            else env_knob("MRTPU_MEMSIZE", int, 64)
        self._accounts: Dict[str, PageAccount] = {}
        self._lock = threading.Lock()

    def account(self, tenant: str) -> PageAccount:
        with self._lock:
            acct = self._accounts.get(tenant)
            if acct is None:
                acct = self._accounts[tenant] = PageAccount(
                    tenant, self.memsize * (1 << 20), self.pages)
            return acct

    def defaults_for(self, tenant: str, scratch: str) -> dict:
        """The ObjectManager ``set`` defaults a session starts from:
        spill always lands in the SESSION's scratch dir (never the
        daemon cwd), and a page allowance arms the core/ budget."""
        d: dict = {"fpath": scratch}
        if self.pages > 0:
            d.update(memsize=self.memsize, maxpage=self.pages,
                     outofcore=1)
        return d

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            accounts = dict(self._accounts)
        return {t: a.snapshot() for t, a in sorted(accounts.items())}
