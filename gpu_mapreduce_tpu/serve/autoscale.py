"""Mesh autoscaling for serve/ sessions — width from profiled volume.

The daemon owns one full-width mesh, but most requests don't need it:
a tiny wordfreq pays mesh dispatch + exchange latency for nothing,
while a shuffle-heavy job wants every shard it can get.  The PR 8 cost
profiles already measure exactly the deciding quantity — per-request
exchange volume — and PR 7's ``mr.reshard()`` makes width a LIVE
property of a dataset.  This module is the first autoscaler rung
(ROADMAP item 1): pick each session's mesh width from its tenant's
profiled exchange EWMA (narrow for tiny jobs, wide for shuffle-heavy),
and PROMOTE a session live — ``mr.reshard(full_mesh)`` on every named
MR at the next command boundary — when its observed volume outgrows
the prediction.

``MRTPU_SERVE_MESH_AUTO=1`` arms it (default off: an opt-in scheduling
policy, not a correctness feature).  Disarmed, every session runs on
the daemon's full mesh exactly as before.  Sizing rule: the smallest
power-of-two width that keeps the tenant's per-shard exchange volume
under ``_TARGET_PER_SHARD`` (~4 MiB), clamped to [1, full].  A tenant
with NO history gets the full mesh — the autoscaler only narrows on
evidence, never on a guess (doc/serve.md#mesh-autoscaling).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..utils.env import env_flag

_TARGET_PER_SHARD = 4 << 20      # exchange bytes per shard to aim for
_PROMOTE_FACTOR = 4              # observed > predicted×4 → go wide


class MeshAutoscaler:
    """Width chooser + live promoter for one daemon's mesh."""

    def __init__(self, comm, profiles, enabled: Optional[bool] = None):
        self.enabled = (enabled if enabled is not None
                        else env_flag("MRTPU_SERVE_MESH_AUTO", False))
        self.profiles = profiles
        self.full = comm
        self.full_width = 1
        self._meshes: Dict[int, object] = {}
        self._lock = threading.Lock()
        self.narrowed = 0
        self.promoted = 0
        self.dist_cap: Optional[int] = None
        if comm is None or isinstance(comm, int):
            self.enabled = False         # serial backend: nothing to size
            return
        from ..parallel.mesh import mesh_axis_size
        self.full_width = mesh_axis_size(comm)
        self._meshes[self.full_width] = comm
        # degraded data plane (parallel/dist.py): after a shrink the
        # fleet's surviving width caps every session mesh — "full" is
        # whatever actually survives, not what the hardware once was
        from ..parallel.dist import surviving_width
        cap = surviving_width()
        self.dist_cap = cap if cap and cap < self.full_width else None
        if self.dist_cap:
            self.full_width = self.dist_cap
            self.full = self.mesh_for(self.dist_cap)
        if self.full_width <= 1:
            self.enabled = False

    # -- sizing ------------------------------------------------------------
    def width_for(self, tenant: str) -> int:
        if not self.enabled:
            return self.full_width
        ewma = self.profiles.exchange_bytes(tenant)
        if ewma is None:
            return self.full_width       # no evidence → no narrowing
        width = 1
        while width < self.full_width and \
                ewma / width > _TARGET_PER_SHARD:
            width *= 2
        return min(width, self.full_width)

    def mesh_for(self, width: int):
        """A sub-mesh over the FIRST ``width`` devices of the full mesh
        (cached) — the same device prefix the reshard range program
        re-homes onto zero-copy."""
        width = max(1, min(int(width), self.full_width))
        with self._lock:
            mesh = self._meshes.get(width)
            if mesh is None:
                from ..parallel.mesh import make_mesh
                devices = list(self.full.devices.flat)[:width]
                mesh = make_mesh(devices=devices)
                self._meshes[width] = mesh
            return mesh

    def comm_for(self, tenant: str):
        """(comm, width) for a new session of ``tenant``."""
        if not self.enabled:
            return self.full, self.full_width
        width = self.width_for(tenant)
        if width < self.full_width:
            self.narrowed += 1
        return self.mesh_for(width), width

    # -- live promotion ----------------------------------------------------
    def promote_hook(self, account, width: int, on_promote=None):
        """A ``script.post_cmd`` hook: when the session's OBSERVED
        exchange volume outgrows the narrow mesh's budget, reshard
        every named MR onto the full mesh at this (host-side, between-
        commands) boundary and widen the namespace for MRs the script
        creates later.  One-shot: the hook removes itself after
        promoting (or when the session already runs full-width)."""
        if not self.enabled or width >= self.full_width:
            return None
        budget = _PROMOTE_FACTOR * _TARGET_PER_SHARD * max(1, width)

        def hook(script) -> None:
            observed = account.exchange_sent + account.exchange_pad
            if observed <= budget:
                return
            full = self.mesh_for(self.full_width)
            # per-MR, continue on failure: backends are per-MR, so a
            # partially-promoted namespace is legal (cross-MR ops move
            # through host frames) — widening the REST beats leaving
            # everything narrow because one MR was mid-open.  A failed
            # MR stays on its old mesh; the next trigger retries it.
            failed = 0
            for name in list(script.obj.named):
                try:
                    script.obj.named[name].reshard(full)
                except Exception as e:
                    failed += 1
                    import sys
                    print(f"mesh autoscaler: reshard of {name!r} to "
                          f"width {self.full_width} failed ({e!r}); "
                          f"will retry next command", file=sys.stderr)
            script.obj.comm = full    # later MRs are born wide
            if hasattr(script, "_nprocs_cache"):
                del script._nprocs_cache
            if failed:
                return                # keep the hook armed: retry
            self.promoted += 1
            if on_promote is not None:
                on_promote()
            script.post_cmd.remove(hook)

        return hook

    def snapshot(self) -> dict:
        return {"enabled": self.enabled, "full_width": self.full_width,
                "narrowed": self.narrowed, "promoted": self.promoted,
                "dist_cap": self.dist_cap}
