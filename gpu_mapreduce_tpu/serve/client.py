"""Thin HTTP client for the serve/ daemon (stdlib urllib only).

Used by ``scripts/mrctl.py``, ``bench.py --serve``, the soak serve
workload, and the tests — one implementation of the wire protocol so
"what does a 429 look like" has a single answer.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional

from ..core.runtime import MRError


class ServeError(MRError):
    """Non-2xx daemon response; carries the code and Retry-After."""

    def __init__(self, code: int, body: dict,
                 retry_after: Optional[int] = None):
        self.code = code
        self.body = body
        self.retry_after = retry_after
        super().__init__(f"serve HTTP {code}: "
                         f"{body.get('error') or body}")


class ServeClient:
    def __init__(self, base: str, timeout: float = 30.0,
                 retries: int = 0, state_dir: Optional[str] = None,
                 token: Optional[str] = None):
        self.base = base.rstrip("/")
        self.timeout = timeout
        # connection-level resilience (fleet clients, mrctl): retry a
        # refused/reset connection up to ``retries`` times with the ft/
        # backoff curve, re-discovering the fleet between attempts when
        # we know the state dir — a client pointed at a dead replica
        # finds the survivors instead of exiting
        self.retries = max(0, int(retries))
        self.state_dir = state_dir
        # tenant bearer token (MRTPU_SERVE_TOKENS on the daemon side):
        # rides every request, including the /events stream and the
        # healthz probe; defaults from MRTPU_SERVE_TOKEN so mrctl and
        # the soak/bench harnesses inherit it — doc/serve.md#tenant-auth
        if token is None:
            from ..utils.env import env_str
            token = env_str("MRTPU_SERVE_TOKEN", "") or None
        self.token = token

    @classmethod
    def local(cls, port: int, **kw) -> "ServeClient":
        return cls(f"http://127.0.0.1:{port}", **kw)

    @classmethod
    def from_state_dir(cls, state_dir: str, **kw) -> "ServeClient":
        """Discover the daemon's bound port from ``<state>/serve.json``
        (written atomically at start — ephemeral-port friendly).  A
        FLEET directory (``<state>/fleet/`` exists) discovers the
        router (``router.json``) first, then any live ready replica."""
        import os
        kw.setdefault("state_dir", state_dir)
        if os.path.isdir(os.path.join(state_dir, "fleet")):
            from .router import discover
            found = discover(state_dir)
            if found is not None:
                return cls.local(found[1], **kw)
            raise OSError(f"no live router or replica under "
                          f"{state_dir!r}")
        with open(os.path.join(state_dir, "serve.json")) as f:
            return cls.local(int(json.load(f)["port"]), **kw)

    def _rediscover(self) -> None:
        """Between connection retries: re-resolve who is serving (the
        dead replica's lease lapses; the router or a survivor answers)."""
        if self.state_dir is None:
            return
        try:
            fresh = ServeClient.from_state_dir(self.state_dir)
            self.base = fresh.base
        except (OSError, ValueError):
            pass              # nothing found YET — retry the old base

    @staticmethod
    def _refused(e: BaseException) -> bool:
        """A connection-level failure worth retrying (the ft/retry
        transient classification, applied to the socket layer)."""
        from ..ft.retry import classify
        reason = getattr(e, "reason", e)
        return classify("serve.connect", reason if isinstance(
            reason, BaseException) else e) == "transient"

    @staticmethod
    def _never_sent(e: BaseException) -> bool:
        """The CONNECT itself was refused: nothing was listening, so
        the request was never delivered anywhere.  Only this narrow
        class is safe to retry for a non-idempotent POST — a reset
        mid-exchange may have been ACCEPTED (journaled, 202 lost on
        the wire), and resubmitting would mint a second session for
        the same logical job."""
        reason = getattr(e, "reason", e)
        return isinstance(reason, ConnectionRefusedError)

    # -- wire --------------------------------------------------------------
    def _req(self, method: str, path: str,
             obj: Optional[dict] = None) -> dict:
        attempt = 0
        while True:
            try:
                return self._req_once(method, path, obj)
            except ServeError:
                raise
            except urllib.error.URLError as e:
                retryable = self._never_sent(e) if method == "POST" \
                    else self._refused(e)
                if attempt >= self.retries or not retryable:
                    raise
                from ..ft.retry import _backoff
                time.sleep(_backoff(attempt))
                attempt += 1
                self._rediscover()

    def _headers(self, data: bool = False) -> dict:
        h = {"Content-Type": "application/json"} if data else {}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _req_once(self, method: str, path: str,
                  obj: Optional[dict] = None, hops: int = 0) -> dict:
        data = json.dumps(obj).encode() if obj is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers=self._headers(data is not None))
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            if e.code in (307, 308) and hops < 4:
                # the fleet router's replica redirect: follow it to the
                # owning replica (urllib only auto-follows GET 30x; the
                # explicit hop also covers POST and keeps the count
                # bounded)
                loc = e.headers.get("Location")
                e.read()
                if loc:
                    from urllib.parse import urlsplit
                    u = urlsplit(loc)
                    base = f"{u.scheme}://{u.netloc}"
                    saved, self.base = self.base, base
                    try:
                        return self._req_once(
                            method, u.path + (f"?{u.query}" if u.query
                                              else ""), obj,
                            hops=hops + 1)
                    finally:
                        self.base = saved
            raw = e.read().decode(errors="replace")
            try:
                body = json.loads(raw)
            except ValueError:
                body = {"error": raw}
            ra = e.headers.get("Retry-After")
            raise ServeError(e.code, body,
                             int(ra) if ra and ra.isdigit() else None) \
                from None

    # -- API ---------------------------------------------------------------
    def submit(self, script: Optional[str] = None,
               ops: Optional[list] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None,
               session: Optional[str] = None,
               deadline_ms: Optional[int] = None,
               retry_after_wait: float = 0.0) -> dict:
        """Submit one job.  ``tenant`` omitted means "whatever my
        bearer token names" on an auth-armed daemon (else "default").
        ``deadline_ms`` bounds the session's EXECUTION time (cancelled
        at the next op barrier past it).

        ``retry_after_wait`` (seconds, opt-in): when the daemon answers
        429 **with a Retry-After** (rate limit, queue backpressure, SLO
        shed), sleep that hint and resubmit — but only while the TOTAL
        slept stays within the budget, so a shed client waits honestly
        instead of hot-looping, yet can never hang past its own bound.
        0 (default) = raise immediately, the pre-PR-14 behavior."""
        body: dict = {} if tenant is None else {"tenant": tenant}
        if script is not None:
            body["script"] = script
        if ops is not None:
            body["ops"] = ops
        if priority is not None:
            body["priority"] = int(priority)
        if deadline_ms is not None:
            body["deadline_ms"] = int(deadline_ms)
        if session is not None:
            # fleet-router affinity key: submissions sharing a key land
            # on the same replica of the healthy ring (serve/router.py)
            body["session"] = str(session)
        budget = max(0.0, float(retry_after_wait))
        slept = 0.0
        while True:
            try:
                return self._req("POST", "/v1/jobs", body)
            except ServeError as e:
                ra = e.retry_after
                if e.code != 429 or ra is None or ra <= 0 \
                        or slept + ra > budget:
                    raise
                time.sleep(ra)
                slept += ra

    def cancel(self, sid: str) -> dict:
        """``DELETE /v1/jobs/<sid>`` — cooperative cancel: queued
        sessions finalize ``cancelled`` immediately, running ones stop
        at their next op barrier.  Raises ServeError(409) once the
        session is terminal (the no-op contract — the result is never
        touched)."""
        return self._req("DELETE", f"/v1/jobs/{sid}")

    def jobs(self) -> list:
        return self._req("GET", "/v1/jobs")["jobs"]

    def status(self, sid: str) -> dict:
        return self._req("GET", f"/v1/jobs/{sid}")

    def result(self, sid: str) -> dict:
        """The result record; raises ServeError(202 body) only via
        :meth:`wait` — a not-done result returns the status summary."""
        return self._req("GET", f"/v1/jobs/{sid}/result")

    def wait(self, sid: str, timeout: float = 120.0,
             poll_s: float = 0.05) -> dict:
        """Poll until the session finishes; returns the result record."""
        deadline = time.monotonic() + timeout
        from .session import TERMINAL as terminal   # ONE definition
        while True:
            out = self._req("GET", f"/v1/jobs/{sid}/result")
            if out.get("status") in terminal or \
                    out.get("state") in terminal:
                return out
            if time.monotonic() > deadline:
                raise ServeError(408, {"error": f"session {sid} still "
                                       f"{out.get('state')!r} after "
                                       f"{timeout}s"})
            time.sleep(poll_s)

    def profile(self, sid: str) -> dict:
        """The per-request cost profile (live while running, durable
        once finished — doc/serve.md)."""
        return self._req("GET", f"/v1/jobs/{sid}/profile")

    def events(self, sid: str, timeout: Optional[float] = None):
        """Generator over ``GET /v1/jobs/<id>/events``: one dict per
        streamed JSON line (status transitions, top-level spans, the
        final profile) until the stream ends — ONE HTTP request, no
        polling.  ``timeout`` is the per-read socket timeout (the
        server heartbeats every ~15 s, so a dead daemon surfaces as an
        OSError rather than a hang)."""
        req = urllib.request.Request(self.base + f"/v1/jobs/{sid}/events",
                                     headers=self._headers())
        try:
            r = urllib.request.urlopen(
                req, timeout=timeout if timeout is not None else 60.0)
        except urllib.error.HTTPError as e:
            raw = e.read().decode(errors="replace")
            try:
                body = json.loads(raw)
            except ValueError:
                body = {"error": raw}
            raise ServeError(e.code, body) from None
        with r:
            for line in r:
                line = line.decode(errors="replace").strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue    # torn final line on daemon stop

    # -- standing queries (doc/streaming.md) -------------------------------
    def stream_open(self, sources: Optional[list] = None,
                    parser: str = "words", reduce: str = "count",
                    window: int = 0, tenant: Optional[str] = None,
                    deadline_ms: Optional[int] = None,
                    batch: Optional[dict] = None) -> dict:
        """``POST /v1/streams`` — open a standing query.  ``sources``
        omitted opens a FEED stream (push bytes via
        :meth:`stream_feed`); otherwise the daemon tails the given
        files/directories.  Returns ``{"id", "state", ...}``."""
        body: dict = {"parser": parser, "reduce": reduce}
        if sources is not None:
            body["sources"] = list(sources)
        if window:
            body["window"] = int(window)
        if tenant is not None:
            body["tenant"] = tenant
        if deadline_ms is not None:
            body["deadline_ms"] = int(deadline_ms)
        if batch:
            body["batch"] = dict(batch)
        return self._req("POST", "/v1/streams", body)

    def stream_feed(self, stid: str, data: bytes) -> dict:
        """``POST /v1/streams/<id>/feed`` — append raw bytes to a feed
        stream (newline-terminated records; a torn tail line waits for
        its newline)."""
        if isinstance(data, str):
            data = data.encode()
        req = urllib.request.Request(
            self.base + f"/v1/streams/{stid}/feed", data=data,
            method="POST", headers={**self._headers(),
                                    "Content-Type":
                                        "application/octet-stream"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            raw = e.read().decode(errors="replace")
            try:
                body = json.loads(raw)
            except ValueError:
                body = {"error": raw}
            ra = e.headers.get("Retry-After")
            raise ServeError(e.code, body,
                             int(ra) if ra and ra.isdigit() else None) \
                from None

    def streams(self) -> list:
        return self._req("GET", "/v1/streams")["streams"]

    def stream_status(self, stid: str) -> dict:
        return self._req("GET", f"/v1/streams/{stid}")

    def stream_close(self, stid: str, drain: bool = True) -> dict:
        """``POST /v1/streams/<id>/close`` — final-drain (unless
        ``drain=False``) and retire the query; returns the terminal
        summary."""
        return self._req("POST", f"/v1/streams/{stid}/close",
                         {"drain": bool(drain)})

    def stream_events(self, stid: str, timeout: Optional[float] = None):
        """Generator over ``GET /v1/streams/<id>/events``: one dict
        per streamed JSON line (status, per-batch commits, ticks)
        until a terminal status — same chunked contract as
        :meth:`events`."""
        req = urllib.request.Request(
            self.base + f"/v1/streams/{stid}/events",
            headers=self._headers())
        try:
            r = urllib.request.urlopen(
                req, timeout=timeout if timeout is not None else 60.0)
        except urllib.error.HTTPError as e:
            raw = e.read().decode(errors="replace")
            try:
                body = json.loads(raw)
            except ValueError:
                body = {"error": raw}
            raise ServeError(e.code, body) from None
        with r:
            for line in r:
                line = line.decode(errors="replace").strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue    # torn final line on daemon stop

    def slo(self) -> dict:
        return self._req("GET", "/v1/slo")

    def stats(self) -> dict:
        return self._req("GET", "/v1/stats")

    def fleet_metrics(self) -> dict:
        """``GET /metrics/fleet.json`` (router-only): every federation
        member — replicas and data-plane ranks — with liveness,
        staleness and its merged registry snapshot (``mrctl top``)."""
        return self._req("GET", "/metrics/fleet.json")

    def drain(self) -> dict:
        return self._req("POST", "/v1/drain")

    def shutdown(self) -> dict:
        return self._req("POST", "/v1/shutdown")

    def healthz(self) -> bool:
        """READY (200 ``{"status": "ok"}``), not merely alive: a
        draining/paused/fenced replica answers 503 here and reads
        False — the router/LB routing predicate."""
        try:
            req = urllib.request.Request(self.base + "/healthz",
                                         headers=self._headers())
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status == 200
        except (urllib.error.URLError, OSError):
            return False
