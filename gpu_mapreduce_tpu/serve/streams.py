"""Serve-plane standing queries: the daemon side of ``/v1/streams``.

One :class:`StreamManager` per Server owns every open stream: minting
ids (fleet: ``<rid>.st<seq>`` — globally unique AND routable, like
sids), the ``stream_open``/``stream_close`` serve-journal records that
make streams recoverable (journal before the 202, same discipline as
submits), one runner thread per stream driving the engine's scheduler,
and the tenant plumbing — budget defaults pin the resident dataset's
page settings, the per-stream :class:`~..obs.context.RequestAccount`
carries the deadline and charges every batch's spans/counters to the
tenant, and ``page_account_scope`` bills resident pages to the tenant
gauge.

Recovery and failover ride the session machinery's rails: a restarted
daemon re-opens every stream whose ``stream_open`` has no
``stream_close`` (the engine resumes from ITS journal's last committed
cursor), and a fleet takeover (serve/daemon._takeover) copies the dead
replica's stream directories, re-journals ``stream_open`` here with
the ``fo`` flag, and resumes them like any mid-run session —
doc/streaming.md#the-serve-surface.

Memoization never applies to streams: a standing query's result is a
moving target, not a pure function of its submission
(serve/memo.py skips any script that mentions ``stream`` for the same
reason).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..core.runtime import CancelledError, MRError
from ..utils.env import env_knob

ST_OPEN, ST_CLOSED, ST_FAILED = "open", "closed", "failed"
ST_TERMINAL = (ST_CLOSED, ST_FAILED)


class StreamSession:
    """One open stream on this daemon: engine + runner thread +
    tenant account."""

    def __init__(self, stid: str, tenant: str, spec: dict,
                 sources: List[str], dir: str,
                 deadline_ms: Optional[int], trace_id: str,
                 failed_over: bool = False):
        self.stid = stid
        self.tenant = tenant
        self.spec = dict(spec)
        self.sources = list(sources)
        self.dir = dir
        self.deadline_ms = deadline_ms
        self.trace_id = trace_id
        self.failed_over = failed_over
        self.state = ST_OPEN
        self.error: Optional[str] = None
        self.created_utc = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime())
        self.feed_path: Optional[str] = None
        self.engine = None
        self.account = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()

    def summary(self) -> dict:
        out = {"id": self.stid, "tenant": self.tenant,
               "state": self.state, "error": self.error,
               "created_utc": self.created_utc,
               "deadline_ms": self.deadline_ms,
               "failed_over": self.failed_over,
               "trace_id": self.trace_id,
               "feed": bool(self.feed_path)}
        eng = self.engine
        if eng is not None:
            out["stream"] = eng.status()
        return out


class StreamManager:
    """The Server's stream registry + lifecycle driver."""

    def __init__(self, server):
        self.server = server
        self.streams: Dict[str, StreamSession] = {}
        self._order: List[str] = []
        self._lock = threading.Lock()
        self._seq = 0
        self.max_open = max(1, env_knob("MRTPU_SERVE_STREAMS", int, 8))
        self.poll_s = max(0.005,
                          env_knob("MRTPU_STREAM_POLL_MS", int, 20)
                          / 1000.0)

    # -- id minting --------------------------------------------------------
    def _mint(self) -> str:
        self._seq += 1
        base = f"st{self._seq:06d}"
        srv = self.server
        return f"{srv.rid}.{base}" if srv.fleet_dir is not None \
            else base

    def note_seq(self, rec: dict) -> None:
        """Recovery: keep the mint counter ahead of every journaled
        stream id."""
        self._seq = max(self._seq, int(rec.get("stseq", 0)))

    def stream_dir(self, stid: str) -> str:
        return os.path.join(self.server.state_dir, "streams", stid)

    # -- open --------------------------------------------------------------
    def open(self, body: dict) -> tuple:
        """→ (code, dict, extra_headers).  Journal before the 202,
        admission gates first — same shape as Server.submit."""
        srv = self.server
        if srv._draining:
            return 503, {"error": "draining: not admitting new "
                                  "streams"}, {"Retry-After": 60}
        if srv._fenced:
            return 503, {"error": f"replica {srv.rid!r} is fenced"}, \
                {"Retry-After": 5}
        pressure = srv.disk.check()
        if pressure:
            srv._note_shed(str(body.get("tenant") or "default"),
                           "disk")
            return 503, {"error": f"degraded: {pressure}"}, \
                {"Retry-After": 30}
        tenant = str(body.get("tenant") or "default")
        from ..stream.engine import ACCUMULATORS, PARSERS
        parser = str(body.get("parser") or "words")
        reduce = str(body.get("reduce") or "count")
        if parser not in PARSERS:
            return 400, {"error": f"unknown parser {parser!r}"}, None
        if reduce not in ACCUMULATORS:
            return 400, {"error": f"unknown reduce {reduce!r}"}, None
        try:
            window = max(0, int(body.get("window") or 0))
        except (TypeError, ValueError):
            return 400, {"error": "window must be an integer"}, None
        sources = body.get("sources")
        if sources is not None and (
                not isinstance(sources, list)
                or not all(isinstance(s, str) for s in sources)):
            return 400, {"error": "sources must be a list of "
                                  "paths"}, None
        deadline_ms = body.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline_ms = int(deadline_ms)
                if deadline_ms <= 0:
                    raise ValueError(deadline_ms)
            except (TypeError, ValueError):
                return 400, {"error": "deadline_ms must be a "
                                      "positive integer"}, None
        batch = body.get("batch") or {}
        spec = {"parser": parser, "reduce": reduce, "window": window,
                "batch": {k: batch[k] for k in
                          ("rows", "bytes", "wait_ms")
                          if isinstance(batch, dict) and k in batch}}
        with self._lock:
            live = sum(1 for s in self.streams.values()
                       if s.state == ST_OPEN)
            if live >= self.max_open:
                return 429, {"error": f"stream cap reached "
                                      f"({self.max_open} open)"}, \
                    {"Retry-After": 30}
        with srv._submit_lock:
            if srv._journal is None:
                return 503, {"error": "shutting down"}, \
                    {"Retry-After": 60}
            stid = self._mint()
            sdir = self.stream_dir(stid)
            feed = sources is None
            src_list = [os.path.join(sdir, "feed.dat")] if feed \
                else [os.path.abspath(s) for s in sources]
            from ..obs.context import new_trace_id
            trace_id = new_trace_id()
            # the record lands BEFORE the client's 202 — a crash after
            # this line re-opens the stream on restart, before it the
            # client never heard "open"
            srv._journal.append({
                "kind": "stream_open", "stid": stid, "tenant": tenant,
                "stseq": self._seq, "spec": spec,
                "sources": src_list, "feed": feed,
                "dl": deadline_ms, "trace": trace_id})
        ss = StreamSession(stid, tenant, spec, src_list, sdir,
                           deadline_ms, trace_id)
        if feed:
            ss.feed_path = src_list[0]
            os.makedirs(sdir, exist_ok=True)
            with open(ss.feed_path, "ab"):
                pass
        try:
            self._boot(ss)
        except Exception as e:        # noqa: BLE001 — isolate the open
            ss.state = ST_FAILED
            ss.error = f"{type(e).__name__}: {e}"
        with self._lock:
            self.streams[stid] = ss
            self._order.append(stid)
        with srv._watch_lock:
            srv._trace_sids[trace_id] = stid
        if ss.state == ST_FAILED:
            return 500, ss.summary(), None
        return 202, {"id": stid, "state": ss.state, "tenant": tenant,
                     "feed": bool(ss.feed_path),
                     "trace_id": trace_id}, None

    def _boot(self, ss: StreamSession,
              start_runner: Optional[bool] = None) -> None:
        """Construct the engine (resuming from its journal when the
        directory has committed batches) and start the runner."""
        from ..obs import context as obs_context
        from ..stream import Stream
        srv = self.server
        os.makedirs(ss.dir, exist_ok=True)
        spill = os.path.join(ss.dir, "spill")
        os.makedirs(spill, exist_ok=True)
        settings = srv.budgets.defaults_for(ss.tenant, spill)
        batch = ss.spec.get("batch") or {}
        wait_ms = batch.get("wait_ms")
        ss.engine = Stream(
            ss.dir, ss.sources, parser=ss.spec["parser"],
            reduce=ss.spec["reduce"],
            window=int(ss.spec.get("window") or 0),
            comm=srv.comm, settings=settings,
            rows=batch.get("rows"), nbytes=batch.get("bytes"),
            wait_s=None if wait_ms is None
            else max(0.0, int(wait_ms) / 1000.0),
            name=ss.stid)
        req = obs_context.RequestAccount(trace_id=ss.trace_id,
                                         tenant=ss.tenant,
                                         label=f"stream:{ss.stid}")
        if ss.deadline_ms:
            req.set_deadline(ss.deadline_ms / 1000.0)
        ss.account = req
        if start_runner is None:
            start_runner = not srv.paused
        if start_runner:
            t = threading.Thread(target=self._runner, args=(ss,),
                                 name=f"mrtpu-stream-{ss.stid}",
                                 daemon=True)
            t.start()
            ss._thread = t

    def _runner(self, ss: StreamSession) -> None:
        """One stream's scheduler loop: poll under the tenant's page
        account + request context, push a ``batch`` event per commit,
        finalize on deadline/cancel/failure."""
        from ..core.runtime import page_account_scope
        from ..obs import context as obs_context
        srv = self.server
        acct = srv.budgets.account(ss.tenant)
        eng = ss.engine
        try:
            while not ss._stop.is_set() and ss.state == ST_OPEN:
                with page_account_scope(acct), \
                        obs_context.use(ss.account):
                    rows = eng.poll_once()
                if rows > 0:
                    st = eng.status()
                    srv._push_event(ss.stid, {
                        "event": "batch", "id": ss.stid,
                        "seq": st["batches"], "rows": rows,
                        "pending_bytes": st["pending_bytes"],
                        "lag_s": st["lag_s"]})
                    continue            # drain hot: no sleep mid-burst
                ss._wake.wait(self.poll_s)
                ss._wake.clear()
        except CancelledError as e:
            ss.state = ST_CLOSED
            ss.error = f"cancelled ({e.reason})"
            self._journal_close(ss)
            srv._push_event(ss.stid,
                            {"event": "status", **ss.summary()})
        except Exception as e:          # noqa: BLE001 — isolation
            ss.state = ST_FAILED
            ss.error = f"{type(e).__name__}: {e}"
            disk = getattr(srv, "disk", None)
            if disk is not None:
                disk.note_error(e)
            srv._push_event(ss.stid,
                            {"event": "status", **ss.summary()})

    # -- feed / status / close ---------------------------------------------
    def feed(self, stid: str, data: bytes) -> tuple:
        ss = self.get(stid)
        if ss is None:
            return 404, {"error": f"no stream {stid!r}"}
        if ss.state != ST_OPEN:
            return 409, {"error": f"stream {stid!r} is {ss.state}"}
        if not ss.feed_path:
            return 409, {"error": f"stream {stid!r} tails external "
                                  f"sources; append to those instead"}
        with open(ss.feed_path, "ab") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        ss._wake.set()
        return 202, {"id": stid, "bytes": len(data),
                     "pending_bytes":
                         ss.engine.tailer.pending_bytes()}

    def get(self, stid: str) -> Optional[StreamSession]:
        with self._lock:
            return self.streams.get(stid)

    def list(self) -> List[dict]:
        with self._lock:
            order = list(self._order)
            return [self.streams[s].summary() for s in order
                    if s in self.streams]

    def close(self, stid: str, drain: bool = True) -> tuple:
        """Stop the runner, final-drain, journal ``stream_close`` —
        the stream's terminal record (recovery stops re-opening it)."""
        ss = self.get(stid)
        if ss is None:
            return 404, {"error": f"no stream {stid!r}"}
        if ss.state in ST_TERMINAL:
            return 409, {"error": f"stream {stid!r} already "
                                  f"{ss.state}"}
        ss._stop.set()
        ss._wake.set()
        if ss._thread is not None:
            ss._thread.join(timeout=60.0)
        from ..core.runtime import page_account_scope
        from ..obs import context as obs_context
        acct = self.server.budgets.account(ss.tenant)
        try:
            with page_account_scope(acct), \
                    obs_context.use(ss.account):
                ss.engine.close(drain=drain)
            ss.state = ST_CLOSED if ss.engine.state != "failed" \
                else ST_FAILED
            ss.error = ss.error or ss.engine.error
        except Exception as e:          # noqa: BLE001
            ss.state = ST_FAILED
            ss.error = f"{type(e).__name__}: {e}"
        self._journal_close(ss)
        self.server._push_event(stid,
                                {"event": "status", **ss.summary()})
        return 200, ss.summary()

    def _journal_close(self, ss: StreamSession) -> None:
        srv = self.server
        with srv._submit_lock:
            if srv._journal is not None:
                try:
                    srv._journal.append({"kind": "stream_close",
                                         "stid": ss.stid,
                                         "state": ss.state,
                                         "trace": ss.trace_id})
                except (ValueError, OSError):
                    pass

    # -- recovery / failover -----------------------------------------------
    def recover(self, opens: List[dict]) -> None:
        """Re-open every journaled stream without a close record: the
        engine resumes from ITS journal (last committed cursors +
        state), so the re-opened stream picks up exactly where the
        dead process stopped."""
        for rec in opens:
            self.note_seq(rec)
            stid = rec.get("stid", "")
            if not stid:
                continue
            ss = StreamSession(
                stid, rec.get("tenant", "default"),
                rec.get("spec") or {}, list(rec.get("sources") or []),
                self.stream_dir(stid), rec.get("dl") or None,
                rec.get("trace") or "", failed_over=bool(rec.get("fo")))
            if rec.get("feed"):
                ss.feed_path = ss.sources[0] if ss.sources else None
            try:
                self._boot(ss)
            except Exception as e:      # noqa: BLE001
                ss.state = ST_FAILED
                ss.error = f"{type(e).__name__}: {e}"
            with self._lock:
                self.streams[stid] = ss
                self._order.append(stid)
            if ss.trace_id:
                with self.server._watch_lock:
                    self.server._trace_sids[ss.trace_id] = stid

    def adopt(self, rec: dict, dead_state: str, dead_rid: str) -> bool:
        """Fleet takeover of ONE dead-replica stream: copy its durable
        directory (journal + committed checkpoints + feed file),
        re-journal ``stream_open`` HERE (our own death is then covered
        by normal recovery), resume.  Idempotent per stid."""
        import shutil
        srv = self.server
        stid = rec.get("stid", "")
        if not stid:
            return False
        with self._lock:
            if stid in self.streams:
                return False
        src = os.path.join(dead_state, "streams", stid)
        dst = self.stream_dir(stid)
        if os.path.isdir(src) and not os.path.isdir(dst):
            shutil.copytree(src, dst)
            # the copied journal's cursors name paths under the DEAD
            # replica's home; a rehome record rebases them so the
            # engine resumes the moved feed file at its committed
            # offset instead of re-reading from 0 (stream/engine.py
            # ``_restore``) — journaled, so OUR later restarts rebase
            # the same way
            from ..ft.journal import Journal
            j = Journal(dst, script_mode=True)
            try:
                j.append({"kind": "stream_rehome", "map": {src: dst}})
            finally:
                j.close()
        sources = list(rec.get("sources") or [])
        if rec.get("feed") and sources:
            # the feed file moved with the directory copy
            sources = [os.path.join(dst, os.path.basename(sources[0]))]
        with srv._submit_lock:
            if srv._journal is None:
                return False
            srv._journal.append({
                "kind": "stream_open", "stid": stid,
                "tenant": rec.get("tenant", "default"),
                "stseq": int(rec.get("stseq", 0)),
                "spec": rec.get("spec") or {}, "sources": sources,
                "feed": bool(rec.get("feed")),
                "dl": rec.get("dl"), "trace": rec.get("trace"),
                "fo": dead_rid})
        ss = StreamSession(stid, rec.get("tenant", "default"),
                           rec.get("spec") or {}, sources, dst,
                           rec.get("dl") or None,
                           rec.get("trace") or "", failed_over=True)
        if rec.get("feed"):
            ss.feed_path = sources[0] if sources else None
        try:
            self._boot(ss)
        except Exception as e:          # noqa: BLE001
            ss.state = ST_FAILED
            ss.error = f"{type(e).__name__}: {e}"
        with self._lock:
            self.streams[stid] = ss
            self._order.append(stid)
        if ss.trace_id:
            with srv._watch_lock:
                srv._trace_sids[ss.trace_id] = stid
        return True

    def suspend_all(self) -> None:
        """Daemon shutdown: stop runners and release journal handles
        WITHOUT stream_close records — open streams are durable state,
        and the next start (or a fleet survivor) resumes them."""
        with self._lock:
            sessions = list(self.streams.values())
        for ss in sessions:
            ss._stop.set()
            ss._wake.set()
        for ss in sessions:
            if ss._thread is not None:
                ss._thread.join(timeout=10.0)
            eng = ss.engine
            if eng is not None:
                try:
                    eng.suspend()
                except Exception:
                    pass

    def snapshot(self) -> dict:
        with self._lock:
            by_state: Dict[str, int] = {}
            for s in self.streams.values():
                by_state[s.state] = by_state.get(s.state, 0) + 1
            return {"open": by_state.get(ST_OPEN, 0),
                    "by_state": by_state,
                    "total": len(self._order),
                    "cap": self.max_open}

    # -- events ------------------------------------------------------------
    def events_stream(self, stid: str, timeout: float = 600.0):
        """NDJSON generator behind ``GET /v1/streams/<id>/events`` —
        the PR 8 chunked-stream shape: subscribe before snapshot,
        per-batch events as they commit, 15 s ticks, ends at a
        terminal state, daemon stop, or the timeout."""
        import json as _json
        import queue as _queue

        from ..obs.sinks import _jsonable

        def line(obj) -> str:
            return _json.dumps(obj, default=_jsonable) + "\n"

        srv = self.server
        q: _queue.Queue = _queue.Queue(maxsize=512)
        with srv._watch_lock:
            srv._watch.setdefault(stid, []).append(q)
        try:
            ss = self.get(stid)
            if ss is None:
                yield line({"event": "error",
                            "error": f"no stream {stid!r}"})
                return
            yield line({"event": "status", **ss.summary()})
            if ss.state in ST_TERMINAL:
                return
            deadline = time.monotonic() + timeout
            last_beat = time.monotonic()
            while time.monotonic() < deadline \
                    and not srv._stopped.is_set():
                try:
                    item = q.get(timeout=0.25)
                except _queue.Empty:
                    if time.monotonic() - last_beat >= 15.0:
                        last_beat = time.monotonic()
                        yield line({"event": "tick"})
                    continue
                yield line(item)
                if item.get("event") == "status" and \
                        item.get("state") in ST_TERMINAL:
                    return
        finally:
            with srv._watch_lock:
                qs = srv._watch.get(stid)
                if qs is not None and q in qs:
                    qs.remove(q)
                    if not qs:
                        del srv._watch[stid]
