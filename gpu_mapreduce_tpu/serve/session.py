"""One accepted request = one session.

A session owns: its own OINK namespace (a caller-owned ObjectManager —
two tenants both running ``mr x`` never collide), a private directory
under ``<state>/sessions/<sid>/`` holding its output files (``out/``),
its spill scratch (``spill/``), and its ft/ journal + auto-checkpoints
(``journal.jsonl``, ``ckpt-*``), and a tenant page account installed as
a thread scope for the whole run.

Crash recovery: a session that was RUNNING when the daemon died left a
journal with a ``begin`` record (and usually a checkpoint) in its
directory; :func:`run_session` detects that on the replayed attempt and
drives ``ft.resume_into`` instead of a fresh ``run_string`` — the
recorded command prefix is skipped, the MRs restore from the last
durable checkpoint, and the remaining commands re-execute, reproducing
the session's output FILES byte-identically (screen output of already-
checkpointed commands is not replayed — doc/serve.md#recovery).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Optional

from ..core.runtime import CancelledError, MRError, page_account_scope

QUEUED, RUNNING, DONE, FAILED, CANCELLED = \
    "queued", "running", "done", "failed", "cancelled"
# the states a session never leaves (and the only ones whose result
# files exist): terminal-ness has ONE definition so a new state can't
# silently leak out of half the checks
TERMINAL = (DONE, FAILED, CANCELLED)

# result files stay fetchable but must not become a covert bulk store:
# bigger payloads ship as sha256 + size only
_INLINE_FILE_CAP = 256 * 1024
# same discipline for captured screen output: one print-happy tenant
# must not grow the daemon's heap (or the fsync'd result file) without
# bound — the tail past the cap is dropped with a marker
_OUTPUT_CAP = 4 * _INLINE_FILE_CAP


class _CappedScreen:
    """A write-only text sink that keeps the first ``cap`` characters
    and counts the rest (bounds both worker heap and result size)."""

    def __init__(self, cap: int = _OUTPUT_CAP):
        self.cap = cap
        self._parts: list = []
        self._len = 0
        self.dropped = 0

    def write(self, s: str) -> int:
        room = self.cap - self._len
        if room > 0:
            kept = s[:room]
            self._parts.append(kept)
            self._len += len(kept)
            self.dropped += len(s) - len(kept)
        else:
            self.dropped += len(s)
        return len(s)

    def flush(self) -> None:
        pass

    def getvalue(self) -> str:
        text = "".join(self._parts)
        if self.dropped:
            text += f"\n...[output truncated: {self.dropped} more " \
                    f"characters dropped past the {self.cap} cap]\n"
        return text


@dataclass
class Session:
    sid: str
    tenant: str
    payload: str                  # the OINK script text (ops batches
    #                               normalize to one at submit time)
    fmt: str = "oink"
    state: str = QUEUED
    submitted_utc: str = ""
    error: Optional[str] = None
    wall_s: Optional[float] = None
    resumed: bool = False
    priority: int = 0             # admission priority (higher first)
    resharded: bool = False       # resumed onto a different mesh width
    failed_over: bool = False     # replayed here from a dead replica's
    #                               claimed journal (serve/fleet.py)
    finished_ts: Optional[float] = None   # TTL GC clock (epoch seconds)
    trace_id: str = ""            # request trace context (obs/context)
    deadline_ms: Optional[int] = None     # execution budget (submit body
    #                               `deadline_ms`; rides the journal)
    cancel_requested: Optional[str] = None  # reason, set by DELETE /
    #                               watchdog before the account exists
    cancel_reason: Optional[str] = None   # why a CANCELLED session died
    stalled: bool = False         # watchdog: no barrier progress for
    #                               MRTPU_SERVE_STALL seconds
    mesh_width: Optional[int] = None      # autoscaler-chosen width
    account: Optional[object] = field(default=None, repr=False,
                                      compare=False)   # live profile

    def summary(self) -> dict:
        return {"id": self.sid, "tenant": self.tenant,
                "state": self.state,
                "submitted_utc": self.submitted_utc,
                "wall_s": self.wall_s, "error": self.error,
                "resumed": self.resumed, "priority": self.priority,
                "resharded": self.resharded,
                "failed_over": self.failed_over,
                "deadline_ms": self.deadline_ms,
                "cancel_reason": self.cancel_reason,
                "stalled": self.stalled,
                "trace_id": self.trace_id}


def normalize_payload(body: dict) -> str:
    """Accept either an OINK script (``{"script": "..."}``) or a JSON
    batch of MR op lines (``{"ops": ["mr x", "x map/file ...", ...]}``)
    and return the script text both execute as."""
    script = body.get("script")
    ops = body.get("ops")
    if isinstance(script, str) and script.strip():
        if ops is not None:
            raise MRError("submit takes script OR ops, not both")
        return script
    if isinstance(ops, list) and ops and \
            all(isinstance(o, str) for o in ops):
        return "\n".join(ops) + "\n"
    raise MRError("submit body needs a non-empty 'script' string or "
                  "'ops' list of command strings")


def _resumable(sdir: str) -> bool:
    from ..ft.journal import read_journal
    try:
        return any(r.get("kind") == "begin" for r in read_journal(sdir))
    except MRError:
        return False


def _collect_files(outdir: str) -> dict:
    out = {}
    for root, _dirs, files in os.walk(outdir):
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, outdir)
            try:
                # stream the hash: a multi-GB -o dump must not spike
                # the worker's heap by its own size
                h = hashlib.sha256()
                nbytes = 0
                head = b""
                with open(path, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        if nbytes <= _INLINE_FILE_CAP:
                            head += chunk
                        h.update(chunk)
                        nbytes += len(chunk)
            except OSError:
                continue
            rec = {"sha256": h.hexdigest(), "bytes": nbytes}
            if nbytes <= _INLINE_FILE_CAP:
                try:
                    rec["text"] = head.decode()
                except UnicodeDecodeError:
                    pass
            out[rel] = rec
    return out


def cancelled_record(sid: str, tenant: str, reason: str,
                     trace_id: Optional[str] = None,
                     deadline_ms: Optional[int] = None,
                     failed_over: bool = False) -> dict:
    """The terminal result record of a session cancelled WITHOUT ever
    running — one builder for the DELETE-while-queued finalize, the
    recovery finalize, and the fleet-takeover store write, so the
    record shape cannot drift between them (a session cancelled
    mid-run gets its full record from run_session instead)."""
    return {"id": sid, "tenant": tenant, "status": CANCELLED,
            "error": f"cancelled ({reason})",
            "output": "", "files": {}, "mrs": {},
            "meta": {"trace_id": trace_id, "cancel_reason": reason,
                     "deadline_ms": deadline_ms,
                     "failed_over": failed_over, "ran": False}}


def atomic_write_json(path: str, obj: dict) -> None:
    """tmp + fsync + rename: a crash mid-write leaves only ``*.tmp``,
    never a torn result a restarted daemon would serve."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _serve_memoized(server, sess: Session, mkey: str,
                    prior: dict) -> dict:
    """Serve one session from the memo store (serve/memo.py): the
    stored output/files/mrs verbatim — byte-identical to the recompute
    by the exactness contract — with 0 plan compiles, 0 dispatches and
    0 MR ops executed.  The worker loop sees ``meta.memo.hit`` and
    journals a ``cache_hit`` record next to the ``serve_done``."""
    from ..obs import context as obs_context
    t0 = time.perf_counter()
    if not sess.trace_id:
        sess.trace_id = obs_context.new_trace_id()
    sess.resumed = False
    prior_meta = prior.get("meta") or {}
    result = {
        "id": sess.sid, "tenant": sess.tenant, "status": DONE,
        "error": None,
        "output": prior.get("output", ""),
        "files": prior.get("files", {}),
        "mrs": prior.get("mrs", {}),
        "meta": {
            "wall_s": None,           # stamped below (routing+verify)
            "trace_id": sess.trace_id,
            "resumed": False,
            "resharded": False,
            "failed_over": sess.failed_over,
            "cancel_reason": None,
            "deadline_ms": sess.deadline_ms,
            "mesh_width": sess.mesh_width,
            "dispatches": 0,
            "plan_cache": {"plan": {"hits": 0, "misses": 0}},
            "pages": {},
            "profile": {"dispatches": 0},
            "memo": {"hit": True, "key": mkey,
                     "source_wall_s": prior_meta.get("wall_s"),
                     "source_trace_id": prior_meta.get("trace_id")},
        },
    }
    sess.wall_s = round(time.perf_counter() - t0, 6)
    result["meta"]["wall_s"] = sess.wall_s
    atomic_write_json(server.result_path(sess.sid), result)
    sess.state = DONE
    return result


def run_session(server, sess: Session) -> dict:
    """Execute one session on a worker thread; returns (and durably
    writes) the result record.  Never raises — a failing script is a
    FAILED session, not a dead worker.

    The whole run executes under the session's request trace context
    (obs/context.py): every span, journal record, quarantine record and
    counter bump — including those from the exec/ prefetch producer,
    the background spill writer, and the shared ingest pool — carries
    the session's trace_id and charges its :class:`RequestAccount`, so
    the ``meta`` deltas are EXACT under concurrency, not
    "exact only when idle"."""
    from ..ft.journal import Journal, resume_into
    from ..obs import context as obs_context
    from ..oink.objects import ObjectManager
    from ..oink.script import OinkScript
    from . import memo as memo_mod

    sdir = server.session_dir(sess.sid)
    outdir = os.path.join(sdir, "out")
    spill = os.path.join(sdir, "spill")
    os.makedirs(outdir, exist_ok=True)
    os.makedirs(spill, exist_ok=True)

    # result memoization (serve/memo.py): a previously-seen submission
    # — same script bytes, same input-file bytes — serves the stored,
    # integrity-verified result without executing anything.  Checked
    # BEFORE the resume probe on purpose: a failed-over or replayed
    # session whose payload a peer already computed is also a hit.
    mkey = memo_mod.memo_key(sess.payload) \
        if memo_mod.memoize_enabled() else None
    if mkey is not None:
        prior = memo_mod.lookup(mkey)
        if prior is not None:
            return _serve_memoized(server, sess, mkey, prior)

    screen = _CappedScreen()
    # mesh autoscaling (serve/autoscale.py): the daemon may hand this
    # session a NARROW sub-mesh sized from its tenant's profiled
    # exchange volume; plain servers (and tests driving run_session
    # directly) fall back to the daemon's full comm
    session_comm = getattr(server, "session_comm", None)
    if session_comm is not None:
        comm, sess.mesh_width = session_comm(sess)
    else:
        comm = server.comm
    om = ObjectManager(comm=comm)
    defaults = server.budgets.defaults_for(sess.tenant, spill)
    if server.budgets.pages > 0:
        # an armed tenant budget is PINNED: the script's own `set`
        # cannot lift maxpage/memsize/outofcore (or redirect fpath out
        # of the session scratch) past the allowance
        om.pin(**defaults)
    else:
        for k, v in defaults.items():
            om.set_default(k, v)
    script = OinkScript(screen=screen, obj=om)
    script._path_prepend = outdir    # -o files land in the session dir
    script._path_root = outdir       # `set prepend` re-roots UNDER it
    if script._ft_journal is not None:
        # MRTPU_JOURNAL in the daemon's environment armed a script
        # journal pointing somewhere global — sessions journal into
        # their OWN directory, always.  Deactivate it BEFORE closing:
        # from_env installed it as the process-global op sink, and a
        # barrier op writing to the closed handle would fail the
        # session (ft/journal.note_op reads the active journal)
        from ..ft.journal import activate, active
        env_j = script._ft_journal
        script._ft_journal = None
        if active() is env_j:
            activate(None)
        env_j.close()

    acct = server.budgets.account(sess.tenant)
    if not sess.trace_id:
        sess.trace_id = obs_context.new_trace_id()
    req = obs_context.RequestAccount(trace_id=sess.trace_id,
                                     tenant=sess.tenant,
                                     label=f"serve:{sess.sid}")
    # deadlines + cancellation (doc/serve.md#deadlines-and-cancel):
    # the account is the flag the barrier sites check.  deadline_ms
    # budgets EXECUTION time (from here), not queue time — a replayed
    # session after a crash must not be dead on arrival.
    if sess.deadline_ms:
        req.set_deadline(sess.deadline_ms / 1000.0)
    sess.account = req          # the /v1/jobs/<id>/profile live view
    # re-check AFTER publishing the account (store-then-load on both
    # sides): a concurrent DELETE either saw the account just published
    # (it arms the flag itself) or set cancel_requested before this
    # load (we arm it here) — either way the cancel is never lost
    if sess.cancel_requested:
        req.cancel(sess.cancel_requested)
    sess.state = RUNNING
    sess.resumed = _resumable(sdir)
    # autoscaler live promotion: if this session runs NARROW and its
    # observed exchange volume outgrows the prediction, reshard wide at
    # the next command boundary (oink post_cmd hook)
    autoscaler = getattr(server, "autoscaler", None)
    if autoscaler is not None and sess.mesh_width is not None:
        def _note_promoted() -> None:
            sess.resharded = True
            sess.mesh_width = autoscaler.full_width
        hook = autoscaler.promote_hook(req, sess.mesh_width,
                                       on_promote=_note_promoted)
        if hook is not None:
            script.post_cmd.append(hook)
    t0 = time.perf_counter()
    error: Optional[str] = None
    cancelled: Optional[str] = None
    try:
        with page_account_scope(acct), obs_context.use(req):
            if sess.resumed:
                # degraded-mode recovery: the replay runs on WHATEVER
                # mesh this daemon instance carries; resume_into flags
                # a checkpoint taken on a different width (the restored
                # frames are host-side, so the restore itself is
                # topology-portable — doc/serve.md#recovery)
                resume_into(script, sdir)
                sess.resharded = bool(getattr(script, "_ft_resharded",
                                              False))
            else:
                script._ft_journal = Journal(sdir, script_mode=True)
                try:
                    script.run_string(sess.payload)
                finally:
                    if script._ft_journal is not None:
                        script._ft_journal.close()
            cur = script.obj      # a script-level `clear` REPLACES the
            #                       manager; report/clean the live one
            mrs = {name: (cur.named[name].kv.nkv
                          if cur.named[name].kv is not None else None)
                   for name in sorted(cur.named)}
    except CancelledError as e:
        # a cooperative stop at an op barrier: NOT a failure.  The
        # journal + auto-checkpoints written so far stay in the session
        # dir, so the work is resumable at the exact boundary it
        # stopped (doc/serve.md#deadlines-and-cancel)
        cancelled = e.reason
        sess.cancel_reason = e.reason
        mrs = {}
        # the cancel may have tripped with DEFERRED stages recorded
        # (fuse=1): discard them — the release path below reads kv/kmv
        # (flush barriers) AFTER disarm_cancel, and a cancelled chain
        # must never dispatch from its own cleanup
        try:
            cur = script.obj
            for m in list(cur.named.values()) + list(cur._temps):
                m.discard_plan()
        except Exception:
            pass
    except Exception as e:       # noqa: BLE001 — session isolation
        error = f"{type(e).__name__}: {e}"
        mrs = {}
        # resource-pressure latch (serve/overload.py): an ENOSPC in
        # this session's failure chain flips the daemon DEGRADED so it
        # sheds new admissions instead of failing more sessions the
        # same way
        disk = getattr(server, "disk", None)
        if disk is not None:
            disk.note_error(e)
    finally:
        # sessions are one-shot: release every frame the namespace
        # still holds (inside the account scope callers of free() run
        # on this thread, so the tenant gauge deflates too — and inside
        # the request context, so the release bills THIS session).
        # Disarm the cancel flag FIRST: the release path crosses the
        # same barrier sites and must never itself be cancelled
        req.disarm_cancel()
        with page_account_scope(acct), obs_context.use(req):
            try:
                cur = script.obj
                cur.cleanup()
                for name in list(cur.named):
                    cur.delete_mr(name)
            except Exception:
                pass
    wall = time.perf_counter() - t0

    sess.wall_s = round(wall, 4)
    if cancelled:
        status = CANCELLED
        error = f"cancelled ({cancelled})"
    else:
        status = FAILED if error else DONE
    sess.error = error
    # the meta deltas come from the session's OWN RequestAccount — fed
    # from the same funnels as the process-global counters, scoped to
    # this request's context — so they are exact with any number of
    # concurrent sessions (the two-session regression test's contract;
    # doc/serve.md)
    profile = req.profile()
    profile["wall_s"] = sess.wall_s
    plan_delta = {c: dict(v) for c, v in profile["plan_cache"].items()}
    plan_delta.setdefault("plan", {"hits": 0, "misses": 0})
    result = {
        "id": sess.sid, "tenant": sess.tenant, "status": status,
        "error": error,
        "output": screen.getvalue(),
        "files": _collect_files(outdir),
        "mrs": mrs,
        "meta": {
            "wall_s": sess.wall_s,
            "trace_id": sess.trace_id,
            "resumed": sess.resumed,
            "resharded": sess.resharded,
            "failed_over": sess.failed_over,
            "cancel_reason": cancelled,
            "deadline_ms": sess.deadline_ms,
            "mesh_width": sess.mesh_width,
            "dispatches": profile["dispatches"],
            "plan_cache": plan_delta,
            "pages": acct.snapshot(),
            "profile": profile,
            "memo": {"hit": False, "key": mkey},
        },
    }
    # memoize a clean fresh run: byte-identical resubmissions anywhere
    # in the fleet are served from this record (serve/memo.py).  Resumed
    # sessions are excluded — their output may reflect a partial replay
    # boundary, and the contract is "what a fresh run produces".
    if mkey is not None and status == DONE and not sess.resumed:
        try:
            memo_mod.store(mkey, result,
                           writer=getattr(server, "rid", ""),
                           payload=sess.payload)
        except Exception:
            pass
    # the durable result lands BEFORE the state flips: a client polling
    # at 50 ms must never observe state=done while the result file is
    # still unwritten (it would read a bogus "result file unavailable"
    # final record)
    try:
        atomic_write_json(server.result_path(sess.sid), result)
    except OSError as e:
        # the MOST likely ENOSPC site (inode/quota exhaustion passes
        # the free-byte probe): latch the pressure monitor so the
        # daemon degrades instead of admitting more work that fails
        # at this exact line, then let the worker's belt record FAILED
        disk = getattr(server, "disk", None)
        if disk is not None:
            disk.note_error(e)
        raise
    sess.state = status
    return result
