"""Fleet membership: heartbeat leases, epoch fencing, journal claims.

One daemon process is a single point of failure for the "millions of
users" north star.  This module is the coordination substrate that lets
N replicas serve one fleet with nothing shared but a directory:

* **leases** — every replica heartbeats an fsync'd lease file under
  ``<fleet>/fleet/`` (``<rid>.lease.json``: epoch, port, state dir,
  readiness state, expiry).  A replica whose lease passes its expiry
  (plus a clock-skew margin, ``MRTPU_FLEET_SKEW``) is presumed dead;
  writes are tmp + fsync + rename so a reader never sees a torn lease.
* **epochs** — a replica joins at ``max(every epoch in the fleet
  dir) + 1``.  Epochs totally order membership events, which is what
  makes fencing a comparison instead of a guess.
* **claims** — a survivor that observes an expired lease takes over the
  dead peer's journal by creating ``<rid>.claim-<gen>.json`` with
  ``O_CREAT|O_EXCL``: the filesystem arbitrates the race, exactly one
  survivor wins, every loser's replay is a no-op.  The claim carries
  the claimant's (strictly newer) epoch; a paused-then-revived replica
  sees a claim with ``epoch > its own`` and must not execute any
  session it accepted before the claim (``fenced()``) — double
  execution is structurally impossible, not just unlikely.  A claimant
  that itself dies mid-takeover leaves a claim without its ``done``
  flag; once the CLAIMANT's lease expires too, another survivor may
  supersede with the next generation (again ``O_EXCL`` — every claim
  transition is exclusive).
* **ring** — session routing hashes over the healthy replicas with a
  vnode consistent-hash ring, so one replica's death remaps only its
  own arc (serve/router.py).

Everything is plain files on a shared directory (one host's disk, NFS,
or anything rename-atomic): failover needs no state from the dead
process, which is the same "kill -9 at any point" contract the ft/
journal already keeps (doc/serve.md#the-serve-fleet).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core.runtime import MRError
from ..utils.env import env_knob

_LEASE_SUF = ".lease.json"
_CLAIM_MID = ".claim-"


def _atomic_write(path: str, obj: dict) -> None:
    """tmp + fsync + rename + parent-dir fsync (utils/fsio) — a crash
    mid-heartbeat can tear only the ``.tmp``, never the lease a peer's
    expiry decision reads, and a crash after the rename cannot lose the
    directory entry either."""
    from ..utils.fsio import atomic_write_json
    atomic_write_json(path, obj)


def _read_json(path: str) -> Optional[dict]:
    from ..utils.fsio import read_json
    return read_json(path)


def ring_hash(key: str) -> int:
    """Stable cross-process hash (Python's ``hash`` is salted)."""
    return int(hashlib.sha1(key.encode()).hexdigest()[:15], 16)


# the sorted vnode point lists, keyed by (rids, vnodes): membership
# changes only on join/leave/expiry, so the per-submission hot path is
# one SHA1 + a bisect instead of rebuilding N×vnodes hashes per request
_RING_CACHE: Dict[Tuple, List[Tuple[int, str]]] = {}
_RING_LOCK = threading.Lock()


def ring_route(key: str, rids: List[str],
               vnodes: Optional[int] = None) -> Optional[str]:
    """Consistent-hash ``key`` onto one of ``rids``: each replica owns
    ``vnodes`` points on a circle, the key lands on the first point at
    or past its own hash.  A replica leaving remaps only the arcs it
    owned — warm sessions and result affinity on the survivors stay
    put."""
    if not rids:
        return None
    v = max(1, vnodes if vnodes is not None
            else env_knob("MRTPU_FLEET_VNODES", int, 64))
    ck = (tuple(rids), v)
    with _RING_LOCK:
        points = _RING_CACHE.get(ck)
    if points is None:
        points = sorted((ring_hash(f"{rid}#{i}"), rid)
                        for rid in rids for i in range(v))
        with _RING_LOCK:
            if len(_RING_CACHE) >= 64:      # churny fleets stay bounded
                _RING_CACHE.clear()
            _RING_CACHE[ck] = points
    h = ring_hash(key)
    import bisect
    i = bisect.bisect_left(points, (h, ""))
    return points[i % len(points)][1]


def owner_of(sid: str) -> Optional[str]:
    """The replica a fleet session id names (``<rid>.s<seq>``), or
    None for a single-daemon sid (``s<seq>``)."""
    if "." not in sid:
        return None
    return sid.rsplit(".", 1)[0]


class FleetMember:
    """One replica's membership handle: join/heartbeat/leave its own
    lease, observe peers, claim the dead.  All methods are safe to call
    from the daemon's fleet thread plus its workers (reads are lock-free
    file reads; the only mutation races — claim creation — are settled
    by ``O_EXCL``)."""

    def __init__(self, root: str, rid: str, *,
                 heartbeat_s: Optional[float] = None,
                 lease_s: Optional[float] = None,
                 skew_s: Optional[float] = None):
        if not rid or any(c in rid for c in "./\\ \t\n"):
            raise MRError(f"fleet replica id {rid!r} must be a plain "
                          f"name (no '.', path separators or spaces — "
                          f"it prefixes session ids and names files)")
        self.root = root
        self.dir = os.path.join(root, "fleet")
        os.makedirs(self.dir, exist_ok=True)
        self.rid = rid
        self.heartbeat_s = heartbeat_s if heartbeat_s is not None \
            else env_knob("MRTPU_FLEET_HEARTBEAT", float, 1.0)
        self.lease_s = lease_s if lease_s is not None \
            else env_knob("MRTPU_FLEET_LEASE", float, 5.0)
        self.skew_s = skew_s if skew_s is not None \
            else env_knob("MRTPU_FLEET_SKEW", float, 1.0)
        self.epoch = 0
        self._meta: dict = {}
        self._last_renew = 0.0

    # -- paths -------------------------------------------------------------
    def lease_path(self, rid: str) -> str:
        return os.path.join(self.dir, rid + _LEASE_SUF)

    def claim_path(self, rid: str, gen: int) -> str:
        return os.path.join(self.dir, f"{rid}{_CLAIM_MID}{gen:04d}.json")

    # -- membership --------------------------------------------------------
    def _next_epoch(self) -> int:
        """Strictly newer than every epoch any lease or claim in the
        fleet dir has ever recorded."""
        top = 0
        for name in self._listdir():
            if name.endswith(".json"):
                rec = _read_json(os.path.join(self.dir, name))
                if rec:
                    try:
                        top = max(top, int(rec.get("epoch", 0)))
                    except (TypeError, ValueError):
                        pass
        return max(top, self.epoch) + 1

    def _listdir(self) -> List[str]:
        try:
            return os.listdir(self.dir)
        except OSError:
            return []

    def join(self, port: int, state_dir: str, state: str = "ready") -> int:
        """Write our first lease; returns the epoch we joined at."""
        self.epoch = self._next_epoch()
        self._meta = {"port": int(port), "pid": os.getpid(),
                      "state_dir": os.path.abspath(state_dir)}
        self.renew(state=state)
        return self.epoch

    def renew(self, state: str = "ready") -> bool:
        """Heartbeat: extend our lease ``lease_s`` into the future.
        Returns False when we are fenced (the lease is still written —
        a fenced replica stays observable — but the caller must stop
        executing claimed work)."""
        now = time.time()
        _atomic_write(self.lease_path(self.rid), {
            "rid": self.rid, "epoch": self.epoch, "state": state,
            "ts": now, "ttl": self.lease_s, "expires": now + self.lease_s,
            **self._meta})
        self._last_renew = now
        return not self.fenced()

    def self_expired(self, now: Optional[float] = None) -> bool:
        """Our OWN lease judged by our OWN clock, with NO skew
        allowance: the executing side of the lease discipline.  Peers
        wait ``skew_s`` past our published expiry before claiming; we
        stop starting work the moment we can no longer prove the lease
        is ours — the two margins can't both be wrong at once."""
        now = time.time() if now is None else now
        return now > self._last_renew + self.lease_s

    def leave(self) -> None:
        """Graceful exit: drop the lease so peers never see an expiry
        (a clean shutdown is not a failure — nothing to claim)."""
        try:
            os.remove(self.lease_path(self.rid))
        except OSError:
            pass

    # -- observation -------------------------------------------------------
    def lease(self, rid: str) -> Optional[dict]:
        return _read_json(self.lease_path(rid))

    def peers(self) -> Dict[str, dict]:
        """Every lease in the fleet dir (including our own)."""
        out: Dict[str, dict] = {}
        for name in self._listdir():
            if name.endswith(_LEASE_SUF):
                rec = _read_json(os.path.join(self.dir, name))
                if rec and rec.get("rid"):
                    out[rec["rid"]] = rec
        return out

    def expired(self, lease: dict, now: Optional[float] = None) -> bool:
        """Expiry with skew tolerance: a lease is only DEAD once past
        ``expires + skew_s`` — two hosts' clocks disagreeing by less
        than the margin can never fail over a live replica."""
        now = time.time() if now is None else now
        try:
            return now > float(lease["expires"]) + self.skew_s
        except (KeyError, TypeError, ValueError):
            return True        # an unreadable lease protects nobody

    def replica_state(self, rid: str, lease: Optional[dict] = None,
                      now: Optional[float] = None) -> str:
        """ready | draining | expired | fenced — the router's (and the
        ``mrtpu_fleet_replicas`` gauge's) view of one replica."""
        lease = self.lease(rid) if lease is None else lease
        if lease is None:
            return "expired"
        cur = self.current_claim(rid)
        if cur is not None and self._claim_fences(cur[1], lease):
            return "fenced"
        if self.expired(lease, now):
            return "expired"
        return str(lease.get("state", "ready"))

    def healthy(self, now: Optional[float] = None) -> List[str]:
        """Replica ids routable right now: live lease, ``ready`` state,
        not fenced — sorted for a deterministic ring."""
        return sorted(rid for rid, lease in self.peers().items()
                      if self.replica_state(rid, lease, now) == "ready")

    # -- claims (journal takeover) -----------------------------------------
    def claims(self, rid: str) -> List[Tuple[int, dict]]:
        out = []
        prefix = rid + _CLAIM_MID
        for name in self._listdir():
            if name.startswith(prefix) and name.endswith(".json"):
                try:
                    gen = int(name[len(prefix):-len(".json")])
                except ValueError:
                    continue
                rec = _read_json(os.path.join(self.dir, name))
                # an existing-but-unreadable claim still FENCES (it
                # was mid-write a moment ago; treat as pending)
                out.append((gen, rec if rec is not None else {}))
        return sorted(out)

    def current_claim(self, rid: str) -> Optional[Tuple[int, dict]]:
        cs = self.claims(rid)
        return cs[-1] if cs else None

    def _claim_fences(self, claim: dict, lease: dict) -> bool:
        """A claim fences the lease it names when its epoch is strictly
        newer — a replica that REJOINED after being claimed (new epoch)
        carries newer work the old claim does not cover."""
        try:
            return int(claim.get("epoch", 1 << 62)) > \
                int(lease.get("epoch", 0))
        except (TypeError, ValueError):
            return True

    def fenced(self) -> bool:
        """Whether a peer has claimed OUR journal at a newer epoch: if
        so, every session we accepted before the claim belongs to the
        claimant and we must not execute it (the revived-replica
        double-execution guard)."""
        cur = self.current_claim(self.rid)
        if cur is None:
            return False
        lease = self.lease(self.rid) or {"epoch": self.epoch}
        return self._claim_fences(cur[1], lease)

    def claim(self, dead_rid: str) -> Optional[dict]:
        """Try to take over ``dead_rid``'s journal.  Returns the claim
        record when WE hold the claim (fresh win, or resuming our own
        unfinished takeover after a restart), None when a peer does —
        the loser of the race treats None as "someone else's replay".

        Supersede: a claim whose ``done`` flag never landed and whose
        claimant's own lease has since expired is a takeover that died
        mid-flight — the next generation is up for grabs (``O_EXCL``
        again, so every transition has exactly one winner)."""
        cur = self.current_claim(dead_rid)
        gen = 0
        if cur is not None:
            cgen, crec = cur
            if crec.get("by") == self.rid and not crec.get("done"):
                return {**crec, "gen": cgen}      # finish our own
            if crec.get("done"):
                # the previous takeover COMPLETED; a new claim means
                # the replica rejoined (newer epoch) and died again —
                # its post-rejoin work needs the next generation
                gen = cgen + 1
            else:
                claimant = crec.get("by")
                lease = self.lease(claimant) if claimant else None
                if lease is not None and not self.expired(lease):
                    return None                   # takeover in flight
                gen = cgen + 1
        rec = {"claimed": dead_rid, "by": self.rid,
               "epoch": self._next_epoch(), "gen": gen,
               "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
        path = self.claim_path(dead_rid, gen)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return None                           # lost the race
        try:
            os.write(fd, json.dumps(rec).encode())
            os.fsync(fd)
        finally:
            os.close(fd)
        # the claim's EXISTENCE is the fence — make the directory entry
        # durable before acting on the takeover (utils/fsio discipline)
        from ..utils.fsio import fsync_dir
        fsync_dir(self.dir)
        return rec

    def claim_done(self, dead_rid: str, gen: int) -> None:
        """Mark a takeover complete: the claimed sessions are durably
        re-journaled under the claimant, so the claim can never be
        superseded again."""
        rec = _read_json(self.claim_path(dead_rid, gen)) or {}
        _atomic_write(self.claim_path(dead_rid, gen),
                      {**rec, "done": True})
        # retire the dead lease: the replica is no longer a member, so
        # the monitor stops seeing an eternally-expired peer.  Only the
        # OLD lease goes — a replica that already REJOINED (epoch newer
        # than the claim) keeps its fresh lease untouched
        lease = self.lease(dead_rid)
        try:
            if lease is not None and \
                    int(lease.get("epoch", 0)) <= int(rec.get(
                        "epoch", 0)):
                os.remove(self.lease_path(dead_rid))
        except (OSError, TypeError, ValueError):
            pass

# ---------------------------------------------------------------------------
# fleet metrics: one collector per process, scanning every enabled root
# ---------------------------------------------------------------------------

_ROOTS: Dict[str, FleetMember] = {}
_ROOTS_LOCK = threading.Lock()


def enable_fleet_metrics(member: FleetMember) -> None:
    """Register (once) the scrape-time collector refreshing
    ``mrtpu_fleet_replicas{state}`` from the fleet dir — the router and
    every replica call this, so whichever process an operator scrapes
    reports the same membership truth."""
    from ..obs.metrics import get_registry
    with _ROOTS_LOCK:
        _ROOTS[os.path.abspath(member.root)] = member
    get_registry().register_collector(_collect_fleet)


def _collect_fleet(reg) -> None:
    with _ROOTS_LOCK:
        members = list(_ROOTS.values())
    g = reg.gauge("mrtpu_fleet_replicas",
                  "fleet replicas by membership state "
                  "(ready/draining/expired/fenced)", ("state",))
    counts = {"ready": 0, "draining": 0, "expired": 0, "fenced": 0}
    for m in members:
        for rid, lease in m.peers().items():
            st = m.replica_state(rid, lease)
            counts[st] = counts.get(st, 0) + 1
    for state, n in counts.items():
        g.set(n, state=state)


def note_failover(seconds: float) -> None:
    """One completed journal takeover: count + duration histogram (the
    adopted-session count rides the ``fleet.failover`` span)."""
    try:
        from ..obs.metrics import get_registry
        reg = get_registry()
        reg.counter("mrtpu_fleet_failovers_total",
                    "journal takeovers completed (a survivor claimed "
                    "and replayed a dead replica's sessions)").inc()
        reg.histogram("mrtpu_fleet_failover_seconds",
                      "expired-lease observation to takeover complete"
                      ).observe(float(seconds))
    except Exception:
        pass


def note_fenced_drop(rid: str) -> None:
    """A fenced replica declined to execute a claimed session — the
    no-op that proves double execution cannot happen."""
    try:
        from ..obs.metrics import get_registry
        get_registry().counter(
            "mrtpu_fleet_fenced_total",
            "sessions a fenced (claimed) replica declined to execute",
            ("rid",)).inc(rid=rid)
    except Exception:
        pass
