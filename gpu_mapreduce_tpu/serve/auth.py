"""Per-tenant bearer-token auth for the serve/ ``/v1/`` plane.

``MRTPU_SERVE_TOKENS`` arms it, two grammars:

* **inline spec** — ``tenant=token[,tenant2=token2,...]`` (commas or
  whitespace separate pairs);
* **file path** — when the value names an existing file, one
  ``tenant=token`` pair per line (``#`` comments, blank lines ok).
  A file is the production shape: the secret never sits in ``ps``
  output, and every fleet replica plus the router read the SAME file,
  so the fleet shares one token set by construction.

``*=token`` declares an **admin** token: any tenant, plus the
operator verbs (drain / shutdown).  With auth armed, every ``/v1/``
request needs ``Authorization: Bearer <token>`` — a missing/unknown
token is **401**, a valid token acting outside its tenant is **403**
— and both are decided BEFORE any journal write or queue mutation
(doc/serve.md#tenant-auth).  The telemetry plane (``/metrics``,
``/healthz``) stays open: it is a loopback operator surface and the
fleet router's readiness probe must never need a secret.

Unset/empty = disarmed (every request passes, tenant comes from the
body) — the pre-PR-14 behavior, and what every existing test runs
under.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional, Tuple

from ..utils.env import env_str

ADMIN = "*"


def _parse_pairs(text: str, source: str) -> Dict[str, str]:
    """``tenant=token`` pairs → {token: tenant}.  Malformed pairs warn
    and are skipped — a typo must not silently disarm auth for the
    well-formed tenants (and must never ADMIT anyone: an unparsed pair
    grants nothing)."""
    out: Dict[str, str] = {}
    for raw in text.replace(",", "\n").splitlines():
        pair = raw.split("#", 1)[0].strip()
        if not pair:
            continue
        if "=" not in pair:
            print(f"MRTPU_SERVE_TOKENS: bad pair {pair!r} in {source} "
                  f"(need tenant=token); skipped", file=sys.stderr)
            continue
        tenant, token = (s.strip() for s in pair.split("=", 1))
        if not tenant or not token:
            print(f"MRTPU_SERVE_TOKENS: empty tenant or token in "
                  f"{pair!r} ({source}); skipped", file=sys.stderr)
            continue
        out[token] = tenant
    return out


class TokenAuth:
    """The token set + the authorization decisions.

    ``spec`` defaults to ``MRTPU_SERVE_TOKENS``.  Thread-safe and
    cheap: the set is parsed once (a file re-reads when its mtime
    changes, so token rotation needs no daemon restart)."""

    def __init__(self, spec: Optional[str] = None):
        self.spec = spec if spec is not None \
            else (env_str("MRTPU_SERVE_TOKENS", "") or "")
        self._lock = threading.Lock()
        self._tokens: Dict[str, str] = {}
        self._file: Optional[str] = None
        self._mtime: float = -1.0
        if self.spec:
            if os.path.isfile(self.spec):
                self._file = self.spec
            else:
                self._tokens = _parse_pairs(self.spec, "inline spec")

    @property
    def armed(self) -> bool:
        return bool(self.spec)

    def _table(self) -> Dict[str, str]:
        if self._file is None:
            return self._tokens
        with self._lock:
            try:
                mtime = os.path.getmtime(self._file)
                if mtime != self._mtime:
                    with open(self._file) as f:
                        self._tokens = _parse_pairs(f.read(), self._file)
                    self._mtime = mtime
            except OSError as e:
                # unreadable file: keep the last good set (rotation
                # safety) but say so — an EMPTY last-good set means
                # nobody authenticates, which is fail-closed
                print(f"MRTPU_SERVE_TOKENS file unreadable: {e!r}; "
                      f"keeping previous token set", file=sys.stderr)
            return self._tokens

    # -- decisions ---------------------------------------------------------
    @staticmethod
    def bearer(headers: dict) -> Optional[str]:
        """The presented token (``Authorization: Bearer x``), else
        None.  Header lookup is case-insensitive like HTTP."""
        for k, v in (headers or {}).items():
            if str(k).lower() == "authorization":
                parts = str(v).split(None, 1)
                if len(parts) == 2 and parts[0].lower() == "bearer":
                    return parts[1].strip()
                return None
        return None

    def identify(self, headers: dict) -> Optional[str]:
        """The tenant a request's token proves — ``"*"`` for an admin
        token, None for a missing or unknown token."""
        tok = self.bearer(headers)
        if tok is None:
            return None
        return self._table().get(tok)

    def gate_ident(self, ident: Optional[str],
                   tenant: Optional[str] = None,
                   admin: bool = False) -> Tuple[int, Optional[dict]]:
        """The auth decision given an already-resolved identity (one
        token lookup per request — the handler resolves once and scopes
        per route): ``(0, None)`` = allowed, else ``(401|403, body)``.
        ``tenant`` scopes the action to a tenant (submit/cancel/read of
        a session); ``admin`` marks operator verbs.  Disarmed auth
        allows everything."""
        if not self.armed:
            return 0, None
        if ident is None:
            return 401, {"error": "missing or invalid bearer token"}
        if ident == ADMIN:
            return 0, None
        if admin:
            return 403, {"error": f"token for tenant {ident!r} cannot "
                                  f"perform operator actions"}
        if tenant is not None and tenant != ident:
            return 403, {"error": f"token for tenant {ident!r} cannot "
                                  f"act on tenant {tenant!r}"}
        return 0, None

    def gate(self, headers: dict,
             tenant: Optional[str] = None,
             admin: bool = False) -> Tuple[int, Optional[dict]]:
        """:meth:`gate_ident` with the lookup included — for callers
        holding only headers (the router's store-fallback paths)."""
        ident = self.identify(headers) if self.armed else None
        return self.gate_ident(ident, tenant=tenant, admin=admin)

    def snapshot(self) -> dict:
        table = self._table() if self.armed else {}
        return {"armed": self.armed,
                "tenants": sorted(set(table.values())),
                "source": "file" if self._file else
                          ("inline" if self.armed else None)}
