"""Bounded admission with backpressure, priorities and tenant quotas.

The daemon's front door, three gates in order:

* **per-tenant rate limit** — a token bucket per tenant
  (``MRTPU_SERVE_RATE`` requests/sec, burst ``MRTPU_SERVE_BURST``;
  0 = off): a tenant past its refill rate gets 429 with a
  ``Retry-After`` computed from ITS OWN bucket deficit, so one noisy
  tenant's backpressure never shows up on its neighbors' clocks;
* **bounded queue** — submissions past ``MRTPU_SERVE_QUEUE`` pending
  sessions are REJECTED (429 + drain-time ``Retry-After``) instead of
  buffered without bound — under sustained overload the queue depth,
  not the daemon's memory, is the thing that saturates;
* **priority** — an accepted session carries a ``priority`` (higher
  first, FIFO within a priority): workers drain urgent tenants ahead
  of batch backfill without starving equal-priority arrivals.

Recovery replay uses ``force=True``: a session the journal says was
accepted must re-enter the queue (at its recorded priority) even when
the restart finds it already full.  Decisions count into
``mrtpu_serve_admission_total{outcome,tenant}``.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, Optional, Tuple


class TenantRateLimiter:
    """Token bucket per tenant.  ``rate`` requests/sec refill, ``burst``
    bucket size; rate 0 disables (every check passes).  Thread-safe."""

    def __init__(self, rate: float = 0.0, burst: Optional[float] = None):
        self.rate = max(0.0, float(rate))
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate * 2)
        self._buckets: Dict[str, Tuple[float, float]] = {}  # (tokens, t)
        self._lock = threading.Lock()

    def check(self, tenant: str, now: Optional[float] = None
              ) -> Tuple[bool, float]:
        """(allowed, retry_after_seconds).  Consumes one token when
        allowed; the retry hint is the time until this tenant's bucket
        refills one token — per-tenant honesty, not a global constant."""
        if self.rate <= 0:
            return True, 0.0
        now = time.monotonic() if now is None else now
        with self._lock:
            if len(self._buckets) > 256:
                # tenant names come from the request body: prune
                # buckets that have refilled to full (reconstructible
                # from the default) so a client cycling unique names
                # cannot grow the daemon's memory without bound
                self._buckets = {
                    t: (tok, ts) for t, (tok, ts) in
                    self._buckets.items()
                    if tok + (now - ts) * self.rate < self.burst}
            tokens, t0 = self._buckets.get(tenant, (self.burst, now))
            tokens = min(self.burst, tokens + (now - t0) * self.rate)
            if tokens >= 1.0:
                self._buckets[tenant] = (tokens - 1.0, now)
                return True, 0.0
            self._buckets[tenant] = (tokens, now)
            return False, (1.0 - tokens) / self.rate

    def snapshot(self) -> dict:
        with self._lock:
            return {"rate": self.rate, "burst": self.burst,
                    "tenants": {t: round(b[0], 3)
                                for t, b in self._buckets.items()}}


class AdmissionQueue:
    """Thread-safe bounded priority queue (higher priority first, FIFO
    within).  ``offer`` never blocks — admission control means telling
    the client "not now", not making it wait on a server thread."""

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        self._q: list = []        # heap of (-priority, seq, item)
        self._seq = 0
        self._cv = threading.Condition()
        self._closed = False
        self.rejects = 0          # cumulative admission rejections

    def offer(self, item, force: bool = False, priority: int = 0) -> bool:
        with self._cv:
            if self._closed:
                return False
            if len(self._q) >= self.cap and not force:
                self.rejects += 1
                return False
            self._seq += 1
            heapq.heappush(self._q, (-int(priority), self._seq, item))
            self._cv.notify()
            return True

    def take(self, timeout: Optional[float] = None):
        """Next session (highest priority, then admission order), or
        None on timeout / after close-and-drained.  A closed queue
        still hands out its remaining items — shutdown finishes
        accepted work unless the process dies first (the journal
        covers that case)."""
        with self._cv:
            if not self._q and not self._closed:
                self._cv.wait(timeout)
            if self._q:
                return heapq.heappop(self._q)[2]
            return None

    def reject(self) -> None:
        """Count an admission rejection made by a caller that checked
        capacity itself (the daemon holds its submit lock across the
        check + journal + offer, so it probes ``full()`` rather than
        letting ``offer`` race) — the counter mutation stays under the
        queue's own lock either way."""
        with self._cv:
            self.rejects += 1

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    def full(self) -> bool:
        with self._cv:
            return len(self._q) >= self.cap

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return {"depth": len(self._q), "cap": self.cap,
                    "rejects": self.rejects, "closed": self._closed}
