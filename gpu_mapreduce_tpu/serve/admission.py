"""Bounded admission queue with backpressure.

The daemon's front door: submissions past ``MRTPU_SERVE_QUEUE`` pending
sessions are REJECTED at admission (HTTP 429 + ``Retry-After``) instead
of being buffered without bound — under sustained overload the queue
depth, not the daemon's memory, is the thing that saturates.  Recovery
replay uses ``force=True``: a session the journal says was accepted
must re-enter the queue even when the restart finds it already full.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional


class AdmissionQueue:
    """Thread-safe bounded FIFO.  ``offer`` never blocks — admission
    control means telling the client "not now", not making it wait on
    a server thread."""

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.rejects = 0          # cumulative admission rejections

    def offer(self, item, force: bool = False) -> bool:
        with self._cv:
            if self._closed:
                return False
            if len(self._q) >= self.cap and not force:
                self.rejects += 1
                return False
            self._q.append(item)
            self._cv.notify()
            return True

    def take(self, timeout: Optional[float] = None):
        """Next session, or None on timeout / after close-and-drained.
        A closed queue still hands out its remaining items — shutdown
        finishes accepted work unless the process dies first (the
        journal covers that case)."""
        with self._cv:
            if not self._q and not self._closed:
                self._cv.wait(timeout)
            if self._q:
                return self._q.popleft()
            return None

    def reject(self) -> None:
        """Count an admission rejection made by a caller that checked
        capacity itself (the daemon holds its submit lock across the
        check + journal + offer, so it probes ``full()`` rather than
        letting ``offer`` race) — the counter mutation stays under the
        queue's own lock either way."""
        with self._cv:
            self.rejects += 1

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    def full(self) -> bool:
        with self._cv:
            return len(self._q) >= self.cap

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return {"depth": len(self._q), "cap": self.cap,
                    "rejects": self.rejects, "closed": self._closed}
