"""The fleet front tier: consistent-hash routing + degraded-mode reads.

A thin, stateless process (or embedded object) that fronts N serve/
replicas sharing one fleet directory (serve/fleet.py).  It holds no
queue and no sessions — everything it knows it re-reads from the lease
files — so the router itself restarts in milliseconds and can be
replicated behind any plain TCP LB.

* ``POST /v1/jobs`` routes by consistent hash of the submission's
  session key (``body["session"]`` when the client wants affinity,
  else a per-request key) over the HEALTHY ring — replicas with a live
  lease in the ``ready`` state.  A connection failure mid-submit
  reroutes to the next healthy replica (the body was not yet accepted
  anywhere — no double accept is possible).
* reads (``status`` / ``result`` / ``profile`` / ``events``) resolve
  the owner straight from the fleet session id (``<rid>.s<seq>``),
  follow the claim chain to wherever the session lives NOW, and proxy
  there; when no live replica answers, the shared result store
  (``<fleet>/results/``) serves terminal sessions directly — reads
  survive ownership moves and even a fully-dead fleet.
* **degraded mode is honest**: with zero healthy replicas the router
  answers ``503`` with a ``Retry-After`` derived from the lease TTL —
  never a hang, never a 500 — and ``mrtpu_fleet_replicas{state}`` /
  ``mrtpu_fleet_router_total{outcome}`` say exactly what happened.

``python -m gpu_mapreduce_tpu.serve --router --fleet DIR`` runs it
standalone; its port lands in ``<fleet>/router.json`` so
``mrctl --state <fleet_dir>`` discovers it first (doc/serve.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Iterable, List, Optional, Tuple

from ..utils.env import env_flag, env_knob
from .auth import TokenAuth
from .fleet import FleetMember, enable_fleet_metrics, owner_of, ring_route
from .session import atomic_write_json


class Router:
    def __init__(self, fleet_dir: str, port: Optional[int] = None,
                 vnodes: Optional[int] = None,
                 redirect_reads: Optional[bool] = None,
                 proxy_timeout: float = 30.0):
        self.fleet_dir = fleet_dir
        # an OBSERVER member: reads leases/claims, never joins the ring
        self.fleet = FleetMember(fleet_dir, f"router{os.getpid()}")
        self.port = port if port is not None \
            else env_knob("MRTPU_ROUTER_PORT", int, 0)
        self.vnodes = vnodes
        # 307 reads instead of proxying: one less hop for fat results
        # when clients (mrctl / ServeClient) follow redirects
        self.redirect_reads = redirect_reads if redirect_reads is not None \
            else env_flag("MRTPU_ROUTER_REDIRECT", False)
        self.proxy_timeout = proxy_timeout
        # the SAME token set the replicas arm (one MRTPU_SERVE_TOKENS
        # file fleet-wide): proxied paths are enforced by the replica
        # that answers, but the shared-result-store FALLBACKS answer
        # from disk with no replica in the loop — the router must
        # enforce there itself or a dead owner becomes an auth bypass
        self.auth = TokenAuth()
        self._listener = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        from ..obs import httpd, metrics
        metrics.enable_metrics()
        enable_fleet_metrics(self.fleet)
        self._listener = httpd.MetricsServer(
            port=self.port,
            routes=[("/v1/", self._handle),
                    # fleet-wide metrics federation (doc/observability
                    # .md "Fleet & mesh"); prefix-matches the .json
                    # variant too, and cannot shadow the builtin
                    # /metrics (exact-matched before routes)
                    ("/metrics/fleet", self._metrics_fleet)],
            health=self._health)
        self.port = self._listener.start()
        atomic_write_json(os.path.join(self.fleet_dir, "router.json"),
                          {"port": self.port, "pid": os.getpid()})
        return self.port

    def stop(self) -> None:
        if self._listener is not None:
            self._listener.stop()
            self._listener = None
        # retire our discovery record so clients fall through to the
        # replica leases instead of hammering a gone router (only OUR
        # record — a replacement router may have already overwritten it)
        path = os.path.join(self.fleet_dir, "router.json")
        try:
            with open(path) as f:
                if json.load(f).get("pid") == os.getpid():
                    os.remove(path)
        except (OSError, ValueError):
            pass

    def _health(self) -> str:
        """The router is ready when it can route somewhere; otherwise
        it AGGREGATES the replica states so "every replica is shedding
        under resource pressure" reads ``degraded`` (one curl tells the
        operator which runbook page to open) while "every lease
        expired" reads ``unavailable``."""
        if self.fleet.healthy():
            return "ok"
        states = {self.fleet.replica_state(rid, lease)
                  for rid, lease in self.fleet.peers().items()}
        if "degraded" in states:
            return "degraded"
        return "unavailable"

    # -- plumbing ----------------------------------------------------------
    def _metric(self, outcome: str) -> None:
        try:
            from ..obs.metrics import get_registry
            get_registry().counter(
                "mrtpu_fleet_router_total",
                "router decisions (routed/rerouted/proxied/fallback/"
                "unavailable)", ("outcome",)).inc(outcome=outcome)
        except Exception:
            pass

    def _replica_port(self, rid: str) -> Optional[int]:
        lease = self.fleet.lease(rid)
        if lease is None:
            return None
        try:
            return int(lease["port"])
        except (KeyError, TypeError, ValueError):
            return None

    def _unavailable(self) -> tuple:
        """The honest zero-replicas answer: 503 + a Retry-After a lease
        revival could actually meet, never a hang or a 500."""
        self._metric("unavailable")
        ra = max(1, int(self.fleet.lease_s + self.fleet.skew_s + 0.999))
        return 503, {"error": "no fleet replica holds a valid lease"}, \
            "application/json", {"Retry-After": ra}

    @staticmethod
    def _fwd_headers(headers: Optional[dict],
                     body: bytes = b"") -> dict:
        """Headers a proxied hop forwards VERBATIM: the bearer token
        (replicas enforce auth — the router holds no secrets) plus the
        content type.  Everything else (Host, connection management)
        belongs to the router's own hop."""
        out = {"Content-Type": "application/json"} if body else {}
        for k, v in (headers or {}).items():
            if str(k).lower() == "authorization":
                out["Authorization"] = v
        return out

    def _proxy(self, rid: str, method: str, path: str, body: bytes,
               headers: Optional[dict] = None) -> Optional[tuple]:
        """One proxied hop to ``rid``; None when the replica did not
        answer at the TCP level (caller reroutes or falls back).  HTTP
        error codes — 401/403/429 included — pass through VERBATIM,
        body and Retry-After untouched: the client must see the
        replica's own story, not a router paraphrase."""
        port = self._replica_port(rid)
        if port is None:
            return None
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=body if method in ("POST", "DELETE") and body else None,
            method=method,
            headers=self._fwd_headers(headers, body))
        try:
            with urllib.request.urlopen(
                    req, timeout=self.proxy_timeout) as r:
                payload = r.read()
                return r.status, payload, \
                    r.headers.get("Content-Type") or "application/json", \
                    {"X-Mrtpu-Replica": rid}
        except urllib.error.HTTPError as e:
            payload = e.read()
            extra = {"X-Mrtpu-Replica": rid}
            ra = e.headers.get("Retry-After")
            if ra is not None:
                extra["Retry-After"] = ra
            return e.code, payload, \
                e.headers.get("Content-Type") or "application/json", extra
        except (urllib.error.URLError, OSError):
            return None

    def _proxy_stream(self, rid: str, path: str,
                      headers: Optional[dict] = None
                      ) -> Optional[Iterable]:
        """Pass-through for the /events NDJSON stream: yield the
        replica's lines as they arrive (the router adds no buffering)."""
        port = self._replica_port(rid)
        if port is None:
            return None
        try:
            resp = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                headers=self._fwd_headers(headers)), timeout=120.0)
        except (urllib.error.URLError, OSError):
            return None

        def gen():
            with resp:
                for line in resp:
                    yield line
        return gen()

    # -- result-store fallback ---------------------------------------------
    def _auth_fallback(self, headers: Optional[dict],
                       res: dict) -> Optional[tuple]:
        """Auth for answers served straight from the shared result
        store (no replica in the loop to enforce): same decision a
        replica would make — the stored record's tenant scopes it."""
        code, err = self.auth.gate(dict(headers or {}),
                                   tenant=str(res.get("tenant")
                                              or "default"))
        if not code:
            return None
        if code == 403:
            # match the daemons: a foreign sid reads as nonexistent
            # (403-vs-404 would be an existence oracle)
            return 404, {"error": f"no session "
                                  f"{res.get('id')!r}"}, \
                "application/json", None
        return code, err, "application/json", \
            {"WWW-Authenticate": "Bearer"}

    def _stored_result(self, sid: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.fleet_dir, "results",
                                   sid + ".json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _owner_candidates(self, sid: str) -> List[str]:
        """Live replicas that may hold ``sid``, most likely first: the
        END of its claim chain (the current owner after failovers),
        then the chain's predecessors back to the minting replica.
        The predecessors matter because a claim is per-EPOCH, not
        forever: a minter that REJOINED after a completed claim owns
        every sid it minted since, while its old claimant still serves
        the sids it adopted — only trying both finds a live session on
        either side of the failover."""
        rid = owner_of(sid)
        if rid is None:
            return []
        chain = [rid]
        for _ in range(16):
            claim = self.fleet.current_claim(chain[-1])
            nxt = claim[1].get("by") if claim is not None else None
            if not nxt or nxt in chain:
                break
            chain.append(nxt)
        out = []
        for r in reversed(chain):
            lease = self.fleet.lease(r)
            if lease is not None and not self.fleet.expired(lease):
                out.append(r)
        return out

    # -- the handler --------------------------------------------------------
    def _handle(self, method: str, path: str, body: bytes,
                headers: dict) -> tuple:
        parts = [p for p in path.split("/") if p]
        if len(parts) < 2 or parts[0] != "v1":
            return 404, {"error": "not found"}, "application/json", None
        rest = parts[1:]
        if method == "POST" and rest == ["jobs"]:
            return self._route_submit(body, headers)
        if rest == ["stats"] and method == "GET":
            return self._fleet_stats(headers)
        if rest == ["slo"] and method == "GET":
            return self._any_healthy(method, path, body, headers)
        if rest == ["jobs"] and method == "GET":
            return self._merged_jobs(headers)
        if method == "POST" and rest[0] in ("drain", "shutdown") \
                and len(rest) == 1:
            return self._broadcast(method, path, body, headers)
        if rest[0] == "jobs" and len(rest) == 2 and method == "DELETE":
            return self._route_cancel(rest[1], path, headers)
        if rest[0] == "jobs" and len(rest) in (2, 3) and method == "GET":
            return self._route_read(rest, path, headers)
        return 404, {"error": "not found"}, "application/json", None

    def _route_cancel(self, sid: str, path: str,
                      headers: Optional[dict]) -> tuple:
        """``DELETE /v1/jobs/<sid>``: walk the claim chain like a read
        and proxy the cancel to whichever live replica knows the
        session.  A 404 from one candidate falls through to the next;
        when nobody live knows it but the shared store holds a terminal
        result, answer the daemon's own 409 no-op contract."""
        for owner in self._owner_candidates(sid):
            out = self._proxy(owner, "DELETE", path, b"", headers)
            if out is not None and out[0] != 404:
                self._metric("proxied")
                return out
        res = self._stored_result(sid)
        if res is not None:
            denied = self._auth_fallback(headers, res)
            if denied:
                return denied
            return 409, {"error": f"session {sid!r} already "
                                  f"{res.get('status')}; cancel is a "
                                  f"no-op"}, "application/json", None
        if not self.fleet.healthy():
            return self._unavailable()
        return 404, {"error": f"no session {sid!r} reachable"}, \
            "application/json", None

    def _route_submit(self, body: bytes,
                      headers: Optional[dict] = None) -> tuple:
        try:
            obj = json.loads(body.decode() or "{}")
            if not isinstance(obj, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            return 400, {"error": f"bad JSON body: {e}"}, \
                "application/json", None
        healthy = self.fleet.healthy()
        if not healthy:
            return self._unavailable()
        # the routing key: client-chosen affinity key, else a fresh one
        # per submission (uniform spread); the chosen replica mints the
        # real <rid>.s<seq> id the client keeps
        key = str(obj.get("session") or uuid.uuid4().hex)
        first = ring_route(key, healthy, vnodes=self.vnodes)
        order = [first] + [r for r in healthy if r != first]
        for i, rid in enumerate(order):
            out = self._proxy(rid, "POST", "/v1/jobs", body, headers)
            if out is None:
                continue        # dead mid-route: next healthy replica
            code, payload, ctype, extra = out
            if code == 503 and i + 1 < len(order):
                continue        # draining/fenced since its last beat
            self._metric("routed" if i == 0 else "rerouted")
            return code, payload, ctype, extra
        return self._unavailable()

    def _route_read(self, rest: List[str], path: str,
                    headers: Optional[dict] = None) -> tuple:
        sid = rest[1]
        sub = rest[2] if len(rest) == 3 else ""
        candidates = self._owner_candidates(sid)
        # redirect only straight to the sid's MINTING replica when it
        # heads the candidate list (it always knows its own sessions);
        # a claim-chain owner may never have adopted an already-
        # finished sid — proxy those so the 404 fallthrough below can
        # try the rest of the chain and the result store
        if self.redirect_reads and sub != "events" and candidates \
                and candidates[0] == owner_of(sid):
            port = self._replica_port(candidates[0])
            self._metric("proxied")
            return 307, {"redirect": candidates[0]}, \
                "application/json", \
                {"Location": f"http://127.0.0.1:{port}{path}"}
        for owner in candidates:
            if sub == "events":
                stream = self._proxy_stream(owner, path, headers)
                if stream is not None:
                    self._metric("proxied")
                    return 200, stream, "application/x-ndjson", \
                        {"X-Mrtpu-Replica": owner}
            else:
                out = self._proxy(owner, "GET", path, b"", headers)
                # a live candidate may not know this sid (a claimant
                # never adopts sessions that FINISHED before their
                # owner died; a rejoined minter dropped its claimed
                # ones) — its 404 is not the final answer while the
                # rest of the chain or the result store may hold it
                if out is not None and out[0] != 404:
                    self._metric("proxied")
                    return out
        # every candidate dead, unreachable or answering 404: the
        # shared result store still serves every TERMINAL session
        # (reads survive ownership moves)
        res = self._stored_result(sid)
        if res is None:
            if not self.fleet.healthy():
                return self._unavailable()
            return 404, {"error": f"no session {sid!r} reachable "
                                  f"(owner down, no stored result)"}, \
                "application/json", None
        denied = self._auth_fallback(headers, res)
        if denied:
            return denied
        self._metric("fallback")
        if sub == "result":
            return 200, res, "application/json", None
        summary = {"id": res.get("id"), "tenant": res.get("tenant"),
                   "state": res.get("status"),
                   "error": res.get("error"),
                   "failed_over": (res.get("meta") or {}).get(
                       "failed_over", False),
                   "trace_id": (res.get("meta") or {}).get("trace_id")}
        if sub == "profile":
            prof = (res.get("meta") or {}).get("profile")
            if prof:
                return 200, {"id": sid, "live": False,
                             "trace_id": summary["trace_id"],
                             "profile": prof}, "application/json", None
            return 200, {**summary, "error": "profile unavailable"}, \
                "application/json", None
        if sub == "events":
            lines = []
            prof = (res.get("meta") or {}).get("profile")
            if prof:
                lines.append(json.dumps({"event": "profile",
                                         "profile": prof}) + "\n")
            lines.append(json.dumps({"event": "status", **summary})
                         + "\n")
            return 200, iter(lines), "application/x-ndjson", None
        return 200, summary, "application/json", None

    def _any_healthy(self, method: str, path: str, body: bytes,
                     headers: Optional[dict] = None) -> tuple:
        for rid in self.fleet.healthy():
            out = self._proxy(rid, method, path, body, headers)
            if out is not None:
                return out
        return self._unavailable()

    def _merged_jobs(self, headers: Optional[dict] = None) -> tuple:
        jobs: List[dict] = []
        seen = set()
        for rid in self.fleet.healthy():
            out = self._proxy(rid, "GET", "/v1/jobs", b"", headers)
            if out is not None and out[0] in (401, 403):
                # a replica refused the credentials: pass its answer
                # through verbatim — 200 {"jobs": []} would disguise a
                # bad token as an empty fleet
                return out
            if out is None or out[0] != 200:
                continue
            try:
                for j in json.loads(out[1].decode()).get("jobs", []):
                    if j.get("id") not in seen:
                        seen.add(j.get("id"))
                        jobs.append(j)
            except (ValueError, AttributeError):
                continue
        return 200, {"jobs": jobs}, "application/json", None

    def _fleet_stats(self, headers: Optional[dict] = None) -> tuple:
        # the daemons gate /v1/stats admin-only; the router's SELF-
        # composed topology answer (replica ids/ports/epochs/ring)
        # must hold the same line — no replica is in the loop to
        # enforce it for us
        if self.auth.armed:
            code, err = self.auth.gate(dict(headers or {}), admin=True)
            if code:
                extra = {"WWW-Authenticate": "Bearer"} \
                    if code == 401 else None
                return code, err, "application/json", extra
        replicas = {}
        for rid, lease in sorted(self.fleet.peers().items()):
            state = self.fleet.replica_state(rid, lease)
            row = {"state": state, "port": lease.get("port"),
                   "epoch": lease.get("epoch")}
            if state in ("ready", "draining", "degraded"):
                out = self._proxy(rid, "GET", "/v1/stats", b"", headers)
                if out is not None and out[0] == 200:
                    try:
                        row["stats"] = json.loads(out[1].decode())
                    except ValueError:
                        pass
            replicas[rid] = row
        return 200, {"fleet_dir": self.fleet_dir,
                     "healthy": self.fleet.healthy(),
                     "replicas": replicas}, "application/json", None

    # -- metrics federation -------------------------------------------------
    def _fleet_members(self, headers: Optional[dict] = None
                       ) -> List[dict]:
        """Every federation member with its registry snapshot: the
        replicas from the lease table (live ones scraped over
        ``/metrics.json``), the data-plane ranks from the run dir's
        dump channel (``metrics-r<rank>.json``).  A member that is dead
        or unreachable is STILL a row — up=0, stale=1 — never silently
        absent."""
        from ..obs.fleetobs import (member_row, rank_dump_stale,
                                    read_rank_dumps)
        from ..utils.env import env_str
        now = time.time()
        members: List[dict] = []
        for rid, lease in sorted(self.fleet.peers().items()):
            state = self.fleet.replica_state(rid, lease)
            try:
                age = max(0.0, now - float(lease.get("ts", now)))
            except (TypeError, ValueError):
                age = 0.0
            snap = None
            if state in ("ready", "draining", "degraded"):
                out = self._proxy(rid, "GET", "/metrics.json", b"",
                                  headers)
                if out is not None and out[0] == 200:
                    try:
                        snap = json.loads(out[1].decode())
                    except ValueError:
                        snap = None
            members.append(member_row(
                replica=rid, up=snap is not None,
                stale=snap is None, age_s=age, metrics=snap,
                state=state))
        rundir = env_str("MRTPU_FLEET_RUNDIR", "") \
            or env_str("MRTPU_DIST_RUNDIR", "")
        if rundir:
            for rank, doc in sorted(read_rank_dumps(rundir).items()):
                age = min(rank_dump_stale(doc, now), 9e9)
                try:
                    every = float(doc.get("every_s", 5.0))
                except (TypeError, ValueError):
                    every = 5.0
                fresh = age <= 3.0 * every + 1.0
                members.append(member_row(
                    rank=str(rank), up=fresh, stale=not fresh,
                    age_s=age, metrics=doc.get("metrics"),
                    state=str(doc.get("reason", ""))))
        return members

    def _metrics_fleet(self, method: str, path: str, body: bytes,
                       headers: Optional[dict] = None) -> tuple:
        """``GET /metrics/fleet`` (Prometheus text) and
        ``/metrics/fleet.json`` — the whole fleet's series under one
        scrape, ``{replica,rank}``-labeled, with honest staleness.
        Ungated, like the builtin ``/metrics`` it federates."""
        if method != "GET":
            return 405, {"error": "GET only"}, "application/json", None
        from ..obs.fleetobs import federate_text
        members = self._fleet_members(headers)
        if path.endswith(".json"):
            return 200, {"fleet_dir": self.fleet_dir,
                         "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime()),
                         "members": members}, "application/json", None
        return 200, federate_text(members), \
            "text/plain; version=0.0.4; charset=utf-8", None

    def _broadcast(self, method: str, path: str, body: bytes,
                   headers: Optional[dict] = None) -> tuple:
        out = {}
        for rid, lease in sorted(self.fleet.peers().items()):
            if self.fleet.expired(lease):
                continue
            got = self._proxy(rid, method, path, body, headers)
            out[rid] = None if got is None else got[0]
        if not out:
            return self._unavailable()
        return 200, {"sent": out}, "application/json", None


def discover(fleet_dir: str) -> Optional[Tuple[str, int]]:
    """Find SOMETHING serving this fleet: the router first
    (``router.json``), else any live ready replica's lease.  Returns
    ``(kind, port)`` or None — the client-side half of "a client
    pointed at a dead replica finds the fleet"."""
    import socket
    rec = None
    try:
        with open(os.path.join(fleet_dir, "router.json")) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        pass
    if rec and rec.get("port"):
        # a kill -9'd router leaves its record behind — probe before
        # trusting, else every re-discovery retry would loop back to
        # the same dead port while live replicas hold valid leases
        port = int(rec["port"])
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.5).close()
            return ("router", port)
        except OSError:
            pass                # stale record: fall through to leases
    member = FleetMember(fleet_dir, f"probe{os.getpid()}")
    now = time.time()
    for rid in member.healthy(now):
        lease = member.lease(rid)
        if lease and lease.get("port"):
            return ("replica", int(lease["port"]))
    return None
