"""The MR-as-a-service daemon.

One resident process holds what a cold script run pays for on every
invocation: the initialized backend (and mesh, when one is configured),
the process-global compiled-plan LRU and shuffle jit caches (PR 2's
cache becomes a fleet-wide warm cache — a second identical request
compiles NOTHING), and the interned-dictionary state of the bytes
domain.  Requests arrive over the obs/httpd loopback listener as
sessions (serve/session.py) through a bounded admission queue
(serve/admission.py) into a small worker pool.

Durability: every ACCEPTED session lands in an fsync'd ft/ journal
(``<state>/journal.jsonl``) before the client sees its 202, and its
completion is recorded after the result file is durably on disk — so a
``kill -9`` at any point leaves a state directory from which a
restarted daemon replays exactly the accepted-but-unfinished sessions,
in admission order, resuming any that were mid-run from their last
auto-checkpoint (doc/serve.md#recovery).

HTTP API (all JSON; see doc/serve.md):

* ``POST /v1/jobs``               — submit ``{"script"| "ops", "tenant"
  [, "priority", "deadline_ms"]}`` → 202 ``{"id", "state"}``; 429 +
  ``Retry-After`` when the queue is full, the tenant is rate-limited,
  or the tenant is being SLO-burn shed; 503 when draining or degraded.
* ``GET  /v1/jobs``               — session summaries.
* ``GET  /v1/jobs/<id>``          — one session's status.
* ``GET  /v1/jobs/<id>/result``   — the result record (202 while
  pending/running).
* ``DELETE /v1/jobs/<id>``        — cancel: queued sessions finalize
  ``cancelled`` immediately, running ones stop at their next op
  barrier; 409 once terminal.
* ``GET  /v1/stats``              — queue/sessions/tenants/plan-cache.
* ``POST /v1/drain``              — stop admitting, keep executing.
* ``POST /v1/shutdown``           — drain, finish the queue, stop.

With ``MRTPU_SERVE_TOKENS`` armed every route needs ``Authorization:
Bearer <token>`` — 401/403 are decided BEFORE any journal write;
drain/shutdown need the admin (``*``) token (serve/auth.py).

Serve-journal record kinds: ``serve_submit`` (before the 202),
``serve_done`` (after the durable result), ``serve_cancel``
(acknowledged cancels), ``cache_hit`` (the session was served from
the memo store — replay re-serves, never recomputes), ``serve_gc`` /
``memo_gc`` / ``cas_gc`` (sweep intents, written BEFORE deletion so a
kill -9 mid-GC finishes on restart), and ``fleet_claimed``.  Unknown
kinds are ignored by recovery, so journals roll forward.

Fleet mode (``fleet_dir`` / ``MRTPU_FLEET_DIR`` — doc/serve.md#the-
serve-fleet): N replicas share one directory tree.  Each replica
heartbeats a lease (serve/fleet.py), mints globally-unique session ids
(``<rid>.s<seq>``), writes results into the SHARED ``<fleet>/results/``
store, and watches its peers: an expired lease triggers a journal
claim — fenced record into the dead journal, then the dead's
accepted-but-unfinished sessions replay here (mid-run ones resume from
their copied auto-checkpoints), flagged ``meta.failed_over``.  Fencing
discipline: a worker executes a session only while this replica's OWN
lease is current and unclaimed — a paused-then-revived replica finds
the claim and drops its stale queue instead of double-executing.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import Dict, List, Optional

from ..core.runtime import MRError
from ..utils.env import env_flag, env_knob, env_str
from .admission import AdmissionQueue
from .auth import TokenAuth
from .budget import TenantBudgets
from .overload import BurnShedder, CostProfiles, DiskMonitor
from .session import (CANCELLED, DONE, FAILED, QUEUED, RUNNING, TERMINAL,
                      Session, atomic_write_json, cancelled_record,
                      normalize_payload, run_session)

_CURRENT: Optional["Server"] = None     # the metrics collector's target


def _collect_serve(reg) -> None:
    """obs/metrics collector: refresh the serve gauges at scrape time."""
    srv = _CURRENT
    if srv is None:
        return
    reg.gauge("mrtpu_sessions_active",
              "sessions currently executing on serve/ workers"
              ).set(srv.active_count())
    reg.gauge("mrtpu_serve_queue_depth",
              "sessions admitted but not yet running"
              ).set(srv.queue.depth())
    g = reg.gauge("mrtpu_tenant_pages",
                  "per-tenant dataset pages currently resident "
                  "(bytes_in_use / memsize)", ("tenant",))
    for tenant, snap in srv.budgets.snapshot().items():
        g.set(snap["pages_in_use"], tenant=tenant)
    reg.gauge("mrtpu_serve_degraded",
              "1 while the daemon sheds admissions under resource "
              "pressure (low disk / ENOSPC), else 0"
              ).set(1 if srv.disk.check() else 0)
    # caching-tier shape (utils/cas.py): scrape-time store census
    try:
        from ..utils.cas import cas_store
        store = cas_store()
        if store is not None:
            st = store.stats()
            reg.gauge("mrtpu_cas_chunks",
                      "objects resident in the content-addressed store"
                      ).set(st["chunks"])
            reg.gauge("mrtpu_cas_bytes",
                      "bytes resident in the content-addressed store"
                      ).set(st["bytes"])
    except Exception:
        pass


class Server:
    """The daemon object.  ``start()`` recovers the state directory,
    mounts the HTTP routes, and spins up the worker pool; it is safe to
    embed in-process (tests, bench.py --serve) or drive via
    ``python -m gpu_mapreduce_tpu.serve``."""

    def __init__(self, port: Optional[int] = None,
                 workers: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 state_dir: Optional[str] = None,
                 comm=None, paused: Optional[bool] = None,
                 budgets: Optional[TenantBudgets] = None,
                 fleet_dir: Optional[str] = None,
                 replica_id: Optional[str] = None,
                 heartbeat_s: Optional[float] = None,
                 lease_s: Optional[float] = None):
        self.port = port if port is not None \
            else env_knob("MRTPU_SERVE_PORT", int, 0)
        self.nworkers = workers if workers is not None \
            else env_knob("MRTPU_SERVE_WORKERS", int, 2)
        cap = queue_cap if queue_cap is not None \
            else env_knob("MRTPU_SERVE_QUEUE", int, 16)
        # fleet membership (serve/fleet.py): replicas of one fleet
        # share a directory; each keeps its own state dir under
        # <fleet>/replicas/<rid> (unless overridden) and its results in
        # the SHARED <fleet>/results/ store
        self.fleet_dir = fleet_dir or env_str("MRTPU_FLEET_DIR", "") \
            or None
        self.rid = replica_id or env_str("MRTPU_FLEET_ID", "") \
            or f"r{os.getpid()}"
        self._fleet = None
        if self.fleet_dir is not None:
            from .fleet import FleetMember
            self._fleet = FleetMember(self.fleet_dir, self.rid,
                                      heartbeat_s=heartbeat_s,
                                      lease_s=lease_s)
        self._fenced = False
        self.fenced_drops = 0           # claimed sessions we declined
        self._fleet_suspended = False   # test hook: a stalled replica
        if self.fleet_dir is not None and state_dir is None:
            state_dir = os.path.join(self.fleet_dir, "replicas",
                                     self.rid)
        self.state_dir = state_dir \
            or env_str("MRTPU_SERVE_STATE", "mrtpu-serve")
        # paused = admit + journal but do not execute (maintenance /
        # pre-drain staging; also what makes the kill-mid-queue replay
        # test deterministic)
        self.paused = paused if paused is not None \
            else env_flag("MRTPU_SERVE_PAUSED", False)
        self.comm = comm
        self.queue = AdmissionQueue(cap)
        # per-tenant request-rate quota (ROADMAP item 1): 0 = off
        from .admission import TenantRateLimiter
        self.ratelimit = TenantRateLimiter(
            env_knob("MRTPU_SERVE_RATE", float, 0.0),
            env_knob("MRTPU_SERVE_BURST", float, None))
        # session TTL/GC: terminal session state past this age is
        # swept by a background thread (0 = keep forever)
        self.ttl_s = max(0.0, env_knob("MRTPU_SERVE_TTL", float, 0.0))
        self.gc_count = 0
        # caching-tier GC (doc/perf.md#the-caching-tier), folded into
        # the same TTL sweep: memoized results age out after
        # MRTPU_MEMO_TTL (0 = keep forever) and unreferenced CAS chunks
        # are collected after MRTPU_CAS_GRACE seconds unlinked
        self.memo_ttl_s = max(0.0,
                              env_knob("MRTPU_MEMO_TTL", float, 0.0))
        self.cas_grace_s = max(0.0,
                               env_knob("MRTPU_CAS_GRACE", float, 3600.0))
        self.cache_gc_count = 0         # entries removed (memo + chunks)
        self.budgets = budgets or TenantBudgets()
        # -- PR 14: the self-protection plane ------------------------------
        # tenant bearer tokens on /v1/ (serve/auth.py; disarmed when
        # MRTPU_SERVE_TOKENS is unset)
        self.auth = TokenAuth()
        # per-tenant session-cost evidence + the SLO-burn admission
        # shedder it feeds (serve/overload.py)
        self.profiles = CostProfiles()
        self.shedder = BurnShedder(self.profiles)
        # "tenant|reason" → monotonic ts of the latest shed: the
        # rising-edge / episode tracker behind _note_shed's journaling
        # (own lock: mutated by concurrent HTTP handler threads)
        self._shed_edges: Dict[str, float] = {}
        self._shed_lock = threading.Lock()
        # resource-pressure degradation: state dir + shared result
        # store are the paths whose filesystems must keep room
        self.disk = DiskMonitor([self.state_dir,
                                 os.path.dirname(self.result_path("x"))])
        # hung-session watchdog: no barrier progress for MRTPU_SERVE_
        # STALL seconds flags the session (and cancels it under
        # MRTPU_SERVE_STALL_CANCEL=1), arming the flight recorder
        self.stall_s = max(0.0, env_knob("MRTPU_SERVE_STALL", float, 0.0))
        self.stall_cancel = env_flag("MRTPU_SERVE_STALL_CANCEL", False)
        self.stall_count = 0
        # server-side default execution deadline (ms) for submits that
        # carry none (0 = unlimited)
        self.default_deadline_ms = max(
            0, env_knob("MRTPU_SERVE_DEADLINE", int, 0))
        # mesh autoscaler (serve/autoscale.py): session width from the
        # tenant's profiled exchange volume, MRTPU_SERVE_MESH_AUTO=1
        from .autoscale import MeshAutoscaler
        self.autoscaler = MeshAutoscaler(comm, self.profiles)
        self.sessions: Dict[str, Session] = {}
        self._order: List[str] = []        # admission order, for /v1/jobs
        self._lock = threading.Lock()
        self._submit_lock = threading.Lock()
        self._seq = 0
        self._draining = False
        self._stopped = threading.Event()
        self._workers: List[threading.Thread] = []
        self._active = 0
        self._ewma_wall = 1.0              # Retry-After estimator
        self._journal = None
        self._owns_httpd = False
        self._listener = None              # fleet mode: private httpd
        # request-scoped observability (obs/context.py): trace_id →
        # sid routing for the span feed, and per-session watcher queues
        # behind /v1/jobs/<id>/events
        self._watch: Dict[str, List] = {}
        self._trace_sids: Dict[str, str] = {}
        self._watch_lock = threading.Lock()
        # standing queries (PR 20): POST /v1/streams open micro-batch
        # streams that outlive any one request (serve/streams.py +
        # stream/engine.py); journaled like submits, recovered like
        # sessions, adopted on fleet takeover
        from .streams import StreamManager
        self.streams = StreamManager(self)

    # -- paths -------------------------------------------------------------
    def session_dir(self, sid: str) -> str:
        return os.path.join(self.state_dir, "sessions", sid)

    def result_path(self, sid: str) -> str:
        # fleet mode: ONE shared result store for every replica —
        # takeover dedupe ("is this session already finished?") and the
        # router's read fallback both need results findable without the
        # replica that wrote them (sids are rid-prefixed, no collisions)
        if self.fleet_dir is not None:
            return os.path.join(self.fleet_dir, "results", sid + ".json")
        return os.path.join(self.state_dir, "results", sid + ".json")

    def _mint_sid(self) -> str:
        """Caller holds ``_submit_lock``.  Fleet sids carry the replica
        id (``<rid>.s<seq>``) so they are fleet-unique AND routable —
        the router parses the owner straight out of the id."""
        self._seq += 1
        base = f"s{self._seq:06d}"
        return f"{self.rid}.{base}" if self.fleet_dir is not None \
            else base

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        """Recover + serve; returns the bound port."""
        global _CURRENT
        from ..ft.journal import Journal
        os.makedirs(self.state_dir, exist_ok=True)
        # mrlint: disable=lock-unguarded-mutation — start() runs before
        # any worker/http thread exists; shutdown's locked close is the
        # only concurrent writer
        self._journal = Journal(self.state_dir, script_mode=True)
        self._recover()
        from ..obs import httpd, metrics
        reg = metrics.enable_metrics()
        reg.register_collector(_collect_serve)
        # the span→events feed: finished top-level spans route to any
        # watcher of the session whose trace_id they carry (enable_
        # metrics above already turned tracing on for the bridge)
        from ..obs.tracer import get_tracer
        get_tracer().subscribe_once(self._span_feed)
        _CURRENT = self
        if self._fleet is not None:
            # fleet replicas ALWAYS listen privately: two in-process
            # replicas (tests, embedded fleets) must not fight over the
            # process-global /v1/ route table, and each replica's
            # /healthz must report ITS readiness
            self._listener = httpd.MetricsServer(
                port=self.port, routes=[("/v1/", self._handle)],
                health=self._health_status)
            self.port = self._listener.start()
        else:
            httpd.register_routes("/v1/", self._handle)
            httpd.set_health(self._health_status)
            prev = httpd.get_server()
            self._owns_httpd = prev is None or not prev.running
            self.port = httpd.ensure_server(self.port)
        atomic_write_json(os.path.join(self.state_dir, "serve.json"),
                          {"port": self.port, "pid": os.getpid(),
                           "paused": self.paused, "rid": self.rid})
        self._warm_imports()
        # arm the persistent caching tier (utils/cas.py): route XLA's
        # own executable cache under <cas>/xla so a cold replica's first
        # warm-shaped request recompiles nothing (doc/perf.md)
        try:
            from ..plan.cache import enable_executable_cache
            enable_executable_cache()
        except Exception:
            pass
        if self._fleet is not None:
            from . import fleet as _fleet_mod
            self._fleet.join(self.port, self.state_dir,
                             state="draining" if self.paused
                             else "ready")
            _fleet_mod.enable_fleet_metrics(self._fleet)
            t = threading.Thread(target=self._fleet_loop,
                                 name=f"mrtpu-fleet-{self.rid}",
                                 daemon=True)
            t.start()
        if not self.paused:
            self._start_workers()
        if self.ttl_s > 0:
            t = threading.Thread(target=self._gc_loop,
                                 name="mrtpu-serve-gc", daemon=True)
            t.start()
        if self.stall_s > 0:
            t = threading.Thread(target=self._stall_loop,
                                 name="mrtpu-serve-watchdog",
                                 daemon=True)
            t.start()
        return self.port

    def _start_workers(self) -> None:
        for i in range(max(0, self.nworkers)):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"mrtpu-serve-w{i}",
                                 daemon=True)
            t.start()
            self._workers.append(t)

    def _health_status(self) -> str:
        """/healthz readiness (obs/httpd.set_health): liveness is the
        response existing at all; the STATUS tells LBs and the fleet
        router whether to send work here."""
        if self._fenced:
            return "fenced"
        if self._draining or self.paused or self._stopped.is_set():
            # paused is a maintenance drain too: admitted work queues
            # but does not execute, so routers/LBs must look elsewhere
            return "draining"
        if self.disk.check():
            # resource pressure: alive, running sessions finish, but
            # new work must go elsewhere (doc/reliability.md#daemon-
            # under-overload) — fleet replicas publish this state on
            # their lease, so the router drops them from the ring
            return "degraded"
        return "ok"

    def session_comm(self, sess: Session) -> tuple:
        """(comm, width) for one session — the mesh autoscaler's pick
        (full mesh when disarmed; serve/autoscale.py)."""
        if not self.autoscaler.enabled:
            return self.comm, None
        return self.autoscaler.comm_for(sess.tenant)

    def _warm_imports(self) -> None:
        """Import the session execution stack on the main thread BEFORE
        any worker exists: two workers lazily importing the same module
        tree can hit CPython's partially-initialized-module window, and
        a warm daemon should pay import cost at start, not on the first
        tenant's request."""
        from ..oink.command import COMMANDS  # noqa: F401
        from ..oink.script import OinkScript  # noqa: F401
        from ..ft.journal import read_journal  # noqa: F401
        from .session import run_session  # noqa: F401
        from ..plan.cache import cache_stats
        cache_stats()       # pulls parallel/shuffle (the /v1/stats path)

    def _recover(self) -> None:
        """Replay the serve journal: accepted-but-unfinished sessions
        re-enter the queue in admission order (``force=True`` — the
        journal's accept beats the restart's queue cap) at their
        recorded priority, ONTO WHATEVER MESH this restart carries —
        degraded-mode recovery: a daemon restarted with fewer (or more)
        devices still finishes every accepted session, and a resumed
        session whose checkpoint came from a different mesh width
        reports ``meta.resharded`` (ft/journal.resume_into).  Finished
        sessions reload as DONE/FAILED stubs whose results serve from
        disk; GC'd sessions (``serve_gc`` intent records) are neither
        listed nor replayed, and their leftover directories are swept
        to completion (a kill -9 mid-GC resumes the delete, never
        orphans a live session — live sessions are never journaled for
        GC in the first place)."""
        from ..ft.journal import read_journal
        try:
            recs = read_journal(self.state_dir)
        except MRError:
            return
        done: Dict[str, str] = {}
        gcd: set = set()
        cancels: Dict[str, str] = {}    # acknowledged mid-run cancels
        submits: List[dict] = []
        claim_recs: List[tuple] = []    # (idx, fleet_claimed record)
        cas_intents: List[list] = []    # interrupted CAS chunk sweeps
        memo_intents: List[list] = []   # interrupted memo-entry sweeps
        stream_opens: List[dict] = []   # standing queries (streams.py)
        stream_closes: set = set()
        for i, r in enumerate(recs):
            if r.get("kind") == "serve_submit":
                submits.append({**r, "_idx": i})
                # mrlint: disable=lock-unguarded-mutation — _recover
                # runs inside start(), before the worker pool spawns
                self._seq = max(self._seq, int(r.get("seq", 0)))
            elif r.get("kind") == "stream_open":
                stream_opens.append({**r, "_idx": i})
                self.streams.note_seq(r)
            elif r.get("kind") == "stream_close":
                stream_closes.add(r.get("stid", ""))
            elif r.get("kind") == "serve_done":
                done[r.get("sid", "")] = r.get("status", DONE)
            elif r.get("kind") == "serve_cancel":
                cancels[r.get("sid", "")] = r.get("reason", "client")
            elif r.get("kind") == "serve_gc":
                gcd.add(r.get("sid", ""))
            elif r.get("kind") == "cas_gc":
                cas_intents.append(list(r.get("digests") or []))
            elif r.get("kind") == "memo_gc":
                memo_intents.append(list(r.get("keys") or []))
            elif r.get("kind") == "fleet_claimed":
                claim_recs.append((i, r))
        if cas_intents or memo_intents:
            # finish interrupted cache sweeps (journaled-intent replay:
            # both halves are idempotent — an entry already removed is
            # skipped, one re-referenced since the intent survives)
            try:
                from ..utils.cas import cas_store
                from . import memo as memo_mod
                store = cas_store()
                for digests in cas_intents:
                    if store is not None:
                        store.gc_finish(digests)
                for keys in memo_intents:
                    memo_mod.sweep_finish(keys)
            except Exception:
                pass
        if claim_recs and self._fleet is None:
            # restarted OUTSIDE fleet mode with a claimed journal: no
            # lease/claim state to arbitrate with — conservatively
            # leave everything before the last claim to its claimant
            submits = [r for r in submits
                       if r["_idx"] > claim_recs[-1][0]]
            stream_opens = [r for r in stream_opens
                            if r["_idx"] > claim_recs[-1][0]]
        elif claim_recs:
            # a peer claimed this journal (we died, it took over).
            # Every submit before a COMPLETED claim belongs to that
            # claimant — replaying it here would be the double
            # execution fencing exists to prevent.  Submits after it
            # (post-revival work at a newer epoch) replay normally.
            done_gens = {gen for gen, crec in
                         self._fleet.claims(self.rid)
                         if crec.get("done")}
            boundary = max((i for i, r in claim_recs
                            if r.get("gen", -1) in done_gens),
                           default=-1)
            submits = [r for r in submits if r["_idx"] > boundary]
            stream_opens = [r for r in stream_opens
                            if r["_idx"] > boundary]
            cur = self._fleet.current_claim(self.rid)
            if cur is not None and not cur[1].get("done"):
                # an UNFINISHED claim: those sessions are in takeover
                # limbo — if we simply dropped them and rejoined at a
                # newer epoch, a claimant that died mid-takeover would
                # orphan them forever (we look alive, so no peer ever
                # supersedes).  Re-claim our own journal through the
                # same O_EXCL arbitration every survivor uses: a LIVE
                # claimant keeps the claim (it replays, we drop), a
                # dead one loses the supersede race to us and the
                # sessions stay ours
                reclaim = self._fleet.claim(self.rid)
                if reclaim is None:
                    last = max(i for i, r in claim_recs)
                    submits = [r for r in submits if r["_idx"] > last]
                    stream_opens = [r for r in stream_opens
                                    if r["_idx"] > last]
                else:
                    # ours again — already durably journaled HERE,
                    # which is exactly what claim_done certifies
                    self._fleet.claim_done(self.rid, reclaim["gen"])
        for r in submits:
            sid = r["sid"]
            if done.get(sid) == "rejected":
                # compensated submit (a shutdown race): the client was
                # told "not accepted" — never replay or list it
                continue
            if sid in gcd:
                self._gc_files(sid)       # finish an interrupted GC
                continue
            from ..obs.context import new_trace_id
            sess = Session(sid=sid, tenant=r.get("tenant", "default"),
                           payload=r.get("payload", ""),
                           fmt=r.get("fmt", "oink"),
                           submitted_utc=r.get("utc", ""),
                           priority=int(r.get("priority", 0)),
                           failed_over=bool(r.get("fo")),
                           deadline_ms=r.get("dl") or None,
                           # the replayed session keeps its original
                           # trace_id (pre-trace journals get a fresh
                           # one) so the pre-crash artifacts still link
                           trace_id=r.get("trace") or new_trace_id())
            if sid in done:
                sess.state = done[sid]
                try:    # TTL ages from the durable result's mtime
                    sess.finished_ts = os.path.getmtime(
                        self.result_path(sid))
                except OSError:
                    sess.finished_ts = time.time()
            elif sid in cancels and \
                    os.path.exists(self.result_path(sid)):
                # crash between the result write and its serve_done
                # record, with an acknowledged cancel in flight: the
                # durable result wins (never overwrite completed work
                # with an empty cancelled record) — reload it as a
                # terminal stub
                try:
                    import json as _json
                    with open(self.result_path(sid)) as f:
                        sess.state = _json.load(f).get("status", DONE)
                    sess.finished_ts = os.path.getmtime(
                        self.result_path(sid))
                except (OSError, ValueError):
                    sess.state = CANCELLED
                    sess.finished_ts = time.time()
            elif sid in cancels:
                # the client was told "cancelling" before the crash:
                # the replay must honor that, not resurrect and run
                # the session to completion.  Register first (the
                # finalize pushes events/metrics), then finalize —
                # result + serve_done + CANCELLED state
                with self._lock:
                    self.sessions[sid] = sess
                    self._order.append(sid)
                with self._watch_lock:
                    self._trace_sids[sess.trace_id] = sid
                self._finalize_cancelled(sess, cancels[sid])
                continue
            else:
                self.queue.offer(sess, force=True,
                                 priority=sess.priority)
            with self._lock:
                self.sessions[sid] = sess
                self._order.append(sid)
            with self._watch_lock:
                self._trace_sids[sess.trace_id] = sid
        # standing queries without a stream_close re-open here: each
        # engine resumes from ITS journal's last committed cursors, so
        # a kill -9 mid-batch restarts at exactly-once state
        self.streams.recover(
            [r for r in stream_opens
             if r.get("stid", "") not in stream_closes])

    # -- fleet: heartbeat, failover, fencing -------------------------------
    def _fleet_loop(self) -> None:
        """Heartbeat our lease, notice our own fencing, and claim any
        peer whose lease expired.  Membership upkeep must never take
        the daemon down."""
        fleet = self._fleet
        while not self._stopped.wait(fleet.heartbeat_s):
            if self._fleet_suspended:     # test hook: a stalled replica
                continue
            try:
                if not self._fenced and fleet.fenced():
                    self._fenced = True   # a peer owns our old work now
                st = self._health_status()
                fleet.renew(state="ready" if st == "ok" else st)
                # only a replica that can actually EXECUTE work claims:
                # paused/draining/fenced replicas would sit on a claim,
                # and a disk-degraded one would adopt sessions straight
                # into the ENOSPC failures its own submit path sheds —
                # leave the dead peer to a healthy survivor
                if self._fenced or self.paused or self._draining \
                        or not self._workers or self.disk.check():
                    continue
                now = time.time()
                for rid, lease in fleet.peers().items():
                    if rid == self.rid:
                        continue
                    st = fleet.replica_state(rid, lease, now)
                    if st == "expired":
                        self._takeover(rid, lease)
                    elif st == "fenced" and fleet.expired(lease, now):
                        # a DEAD peer under an UNFINISHED claim: the
                        # claimant died mid-takeover (or it is our own
                        # claim, resuming after a restart) — without
                        # this branch the supersede path in claim()
                        # is unreachable and the dead peer's
                        # un-re-journaled sessions are orphaned.  A
                        # fenced-but-RENEWING lease (revived zombie)
                        # fails the expired() check and stays skipped;
                        # claim() itself arbitrates a live claimant
                        # (returns None while the takeover is in
                        # flight)
                        cur = fleet.current_claim(rid)
                        if cur is not None and not cur[1].get("done"):
                            self._takeover(rid, lease)
            except Exception:
                pass

    def _fence_ok(self) -> bool:
        """The lease discipline a worker checks before EVERY session:
        execute only while our own lease is current (by our own clock —
        no skew allowance on ourselves) and no peer has claimed our
        journal.  A paused-then-revived replica fails this check and
        drops its stale queue instead of double-executing sessions the
        claimant already owns."""
        if self._fleet is None:
            return True
        if self._fenced or self._fleet.fenced():
            self._fenced = True
            return False
        return not self._fleet.self_expired()

    def _takeover(self, dead_rid: str, lease: dict) -> None:
        """Claim + replay one dead peer's journal.  The claim file
        (O_EXCL — serve/fleet.py) settles the survivor race; the
        ``fleet_claimed`` record lands in the DEAD journal before any
        replay so a restarted/revived dead replica skips the sessions
        we now own; each replayed session is re-journaled HERE before
        it enters the queue, so our own death mid- or post-takeover is
        covered by the normal recovery path."""
        import shutil
        claim = self._fleet.claim(dead_rid)
        if claim is None:
            return                        # peer won (or already done)
        t0 = time.monotonic()
        from ..ft.journal import Journal, read_journal
        from ..obs import get_tracer
        from . import fleet as fleet_mod
        dead_state = lease.get("state_dir") or os.path.join(
            self.fleet_dir, "replicas", dead_rid)
        with get_tracer().span("fleet.failover", cat="fleet",
                               dead=dead_rid, by=self.rid,
                               epoch=claim["epoch"]) as sp:
            try:
                recs = read_journal(dead_state)
            except MRError:
                recs = []                 # died before its first record
            # sids an EARLIER (superseded) claimant already re-journaled
            # belong to ITS claim chain — its own failover replays them
            owned_elsewhere: set = set()
            done_gens: set = set()
            for gen, crec in self._fleet.claims(dead_rid):
                if crec.get("done"):
                    done_gens.add(gen)
                prev = crec.get("by")
                if gen >= claim["gen"] or not prev or prev == self.rid:
                    continue
                please = self._fleet.lease(prev) or {}
                pstate = please.get("state_dir") or os.path.join(
                    self.fleet_dir, "replicas", prev)
                try:
                    prs = read_journal(pstate)
                    owned_elsewhere.update(
                        pr.get("sid", "") for pr in prs
                        if pr.get("kind") == "serve_submit")
                    owned_elsewhere.update(
                        pr.get("stid", "") for pr in prs
                        if pr.get("kind") == "stream_open")
                except MRError:
                    pass
            # the fence record, BEFORE any replay
            fj = Journal(dead_state, script_mode=True)
            try:
                fj.append({"kind": "fleet_claimed", "dead": dead_rid,
                           "by": self.rid, "epoch": claim["epoch"],
                           "gen": claim["gen"]})
            finally:
                fj.close()
            done: Dict[str, str] = {}
            gcd: set = set()
            cancels: Dict[str, str] = {}
            submits: List[dict] = []
            stream_opens: List[dict] = []
            stream_closes: set = set()
            boundary = -1
            for i, r in enumerate(recs):
                kind = r.get("kind")
                if kind == "serve_submit":
                    submits.append({**r, "_idx": i})
                elif kind == "stream_open":
                    stream_opens.append({**r, "_idx": i})
                elif kind == "stream_close":
                    stream_closes.add(r.get("stid", ""))
                elif kind == "serve_done":
                    done[r.get("sid", "")] = r.get("status", DONE)
                elif kind == "serve_cancel":
                    cancels[r.get("sid", "")] = r.get("reason",
                                                      "client")
                elif kind == "serve_gc":
                    gcd.add(r.get("sid", ""))
                elif kind == "fleet_claimed" and \
                        r.get("by") != self.rid and \
                        r.get("gen", -1) in done_gens:
                    # only a COMPLETED prior claim is a hard boundary
                    # (its submits were fully re-journaled under the
                    # claimant — the rejoin-then-die case).  An
                    # UNFINISHED claim we are superseding must NOT
                    # hide the dead replica's submits: the ones its
                    # claimant did adopt are excluded per-sid via
                    # owned_elsewhere, the rest replay here
                    boundary = i
            n = 0
            for r in submits:
                sid = r.get("sid", "")
                if not sid or done.get(sid) is not None or sid in gcd \
                        or sid in owned_elsewhere:
                    continue
                if r["_idx"] <= boundary:
                    continue              # a prior claim chain owns it
                if os.path.exists(self.result_path(sid)):
                    continue              # finished; shared store has it
                if sid in cancels:
                    # the dead replica ACKNOWLEDGED this cancel but
                    # died before the barrier finalized it: honor it —
                    # write the terminal record into the shared store
                    # (reads keep working fleet-wide) and never adopt
                    try:
                        atomic_write_json(
                            self.result_path(sid),
                            cancelled_record(
                                sid, r.get("tenant", "default"),
                                cancels[sid],
                                trace_id=r.get("trace"),
                                deadline_ms=r.get("dl") or None,
                                failed_over=True))
                    except Exception:
                        pass
                    continue
                with self._lock:
                    if sid in self.sessions:
                        continue          # idempotent takeover resume
                src = os.path.join(dead_state, "sessions", sid)
                dst = self.session_dir(sid)
                if os.path.isdir(src) and not os.path.isdir(dst):
                    # a mid-run session's journal + auto-checkpoints
                    # ride along; run_session detects them and resumes
                    shutil.copytree(src, dst)
                from ..obs.context import new_trace_id
                sess = Session(
                    sid=sid, tenant=r.get("tenant", "default"),
                    payload=r.get("payload", ""),
                    fmt=r.get("fmt", "oink"),
                    submitted_utc=r.get("utc", ""),
                    priority=int(r.get("priority", 0)),
                    failed_over=True,
                    deadline_ms=r.get("dl") or None,
                    trace_id=r.get("trace") or new_trace_id())
                with self._submit_lock:
                    if self._journal is None:
                        return            # shutting down mid-takeover
                    self._journal.append(
                        {"kind": "serve_submit", "sid": sid,
                         "tenant": sess.tenant, "fmt": sess.fmt,
                         "payload": sess.payload, "seq": 0,
                         "priority": sess.priority,
                         "utc": sess.submitted_utc, "fo": dead_rid,
                         "dl": sess.deadline_ms,
                         "trace": sess.trace_id})
                    self.queue.offer(sess, force=True,
                                     priority=sess.priority)
                    with self._lock:
                        self.sessions[sid] = sess
                        self._order.append(sid)
                    with self._watch_lock:
                        self._trace_sids[sess.trace_id] = sid
                n += 1
            # the dead replica's OPEN streams move here too: copy each
            # durable stream directory, re-journal stream_open under
            # OUR journal, resume from its last committed cursor
            nst = 0
            for r in stream_opens:
                stid = r.get("stid", "")
                if not stid or stid in stream_closes \
                        or stid in owned_elsewhere \
                        or r["_idx"] <= boundary:
                    continue
                if self.streams.adopt(r, dead_state, dead_rid):
                    nst += 1
            self._fleet.claim_done(dead_rid, claim["gen"])
            sp.set(sessions=n, streams=nst)
        fleet_mod.note_failover(time.monotonic() - t0)

    def drain(self) -> None:
        self._draining = True

    def shutdown(self, timeout: float = 60.0) -> None:
        """Drain, finish the queue, stop workers and (if we bound it)
        the HTTP listener.  Idempotent."""
        global _CURRENT
        self.drain()
        self.queue.close()
        self._stopped.set()
        # open streams SUSPEND (runners stop, engine journals close, no
        # stream_close record): they are durable state the next start —
        # or a fleet survivor — resumes from the last committed cursor
        try:
            self.streams.suspend_all()
        except Exception:
            pass
        for t in self._workers:
            t.join(timeout=timeout)
        self._workers = []
        from ..obs import httpd
        from ..obs.tracer import get_tracer
        try:
            get_tracer().unsubscribe(self._span_feed)
        except Exception:
            pass
        if self._fleet is not None:
            # graceful exit is not a failure: drop the lease so no
            # survivor claims a journal whose queue we just drained
            self._fleet.leave()
        if self._listener is not None:
            self._listener.stop()
            self._listener = None
        else:
            httpd.unregister_routes("/v1/")
            httpd.set_health(None)
        if _CURRENT is self:
            _CURRENT = None
        if self._owns_httpd:
            httpd.stop_server()
        # the submit lock serializes the close against an in-flight
        # submit's journal append (an embedded daemon that does not own
        # the HTTP listener has no handler drain to rely on)
        with self._submit_lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    # -- submission --------------------------------------------------------
    def submit(self, body: dict) -> tuple:
        """→ (http_code, response_dict, extra_headers_or_None)."""
        if self._draining:
            return 503, {"error": "draining: not admitting new work"}, \
                {"Retry-After": 60}
        if self._fenced:
            # a fenced replica's journal belongs to its claimant; new
            # accepts here could never be claimed coherently — refuse
            # and let the client's retry find the healthy ring
            return 503, {"error": f"replica {self.rid!r} is fenced "
                                  f"(its journal was claimed)"}, \
                {"Retry-After": 5}
        try:
            payload = normalize_payload(body)
        except MRError as e:
            return 400, {"error": str(e)}, None
        tenant = str(body.get("tenant") or "default")
        fmt = "ops" if body.get("ops") is not None else "oink"
        try:
            # clamp: priority is a scheduling hint, not a weapon
            priority = max(-9, min(9, int(body.get("priority") or 0)))
        except (TypeError, ValueError):
            return 400, {"error": "priority must be an integer"}, None
        deadline_ms = body.get("deadline_ms")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms or None
        else:
            try:
                deadline_ms = int(deadline_ms)
                if deadline_ms <= 0:
                    raise ValueError(deadline_ms)
            except (TypeError, ValueError):
                return 400, {"error": "deadline_ms must be a positive "
                                      "integer (milliseconds)"}, None
        # resource-pressure degradation (serve/overload.py): low disk /
        # recent ENOSPC sheds NEW admissions while running sessions
        # keep their pages and finish — accepting work we cannot
        # durably journal or spill would fail it mid-run instead
        pressure = self.disk.check()
        if pressure:
            self._note_shed(tenant, "disk")
            return 503, {"error": f"degraded: {pressure}"}, \
                {"Retry-After": 30}
        # per-tenant rate quota BEFORE the shared queue: a throttled
        # tenant's Retry-After reflects its OWN bucket, and its 429
        # never consumes shared queue capacity
        ok, ra = self.ratelimit.check(tenant)
        if not ok:
            self._metric_admission("throttled", tenant)
            return 429, {"error": f"tenant {tenant!r} over its "
                                  f"request rate"}, \
                {"Retry-After": max(1, int(ra + 0.999))}
        # SLO-burn shedding (serve/overload.py): a tenant burning its
        # error budget in every window absorbs the backpressure FIRST —
        # its expensive-profile submits shed with an honest per-tenant
        # Retry-After, its cheap ones lose priority — before the shared
        # queue's 429 starts hitting polite tenants
        action, priority, shed_ra = self.shedder.decide(tenant, priority)
        if action == "shed":
            self._note_shed(tenant, "slo_burn")
            return 429, {"error": f"tenant {tenant!r} is over its SLO "
                                  f"error budget; new work is shed"}, \
                {"Retry-After": max(1, int(shed_ra + 0.999))}
        with self._submit_lock:
            if self._journal is None:       # shutdown closed it
                return 503, {"error": "shutting down"}, \
                    {"Retry-After": 60}
            if self.queue.full():
                self.queue.reject()
                self._metric_admission("rejected", tenant)
                return 429, {"error": "admission queue full"}, \
                    {"Retry-After": self.retry_after()}
            sid = self._mint_sid()
            from ..obs.context import new_trace_id
            sess = Session(
                sid=sid, tenant=tenant, payload=payload, fmt=fmt,
                priority=priority, trace_id=new_trace_id(),
                deadline_ms=deadline_ms,
                submitted_utc=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()))
            # the journal record lands BEFORE the queue sees the
            # session (and before the client's 202): a crash after
            # this line replays the session; a crash before it means
            # the client never heard "accepted" — either way the
            # journal and the promise agree.  The trace_id rides the
            # record so a REPLAYED session keeps the id the original
            # 202's artifacts already carry
            self._journal.append(
                {"kind": "serve_submit", "sid": sid, "tenant": tenant,
                 "fmt": fmt, "payload": payload, "seq": self._seq,
                 "priority": priority, "utc": sess.submitted_utc,
                 "dl": deadline_ms, "trace": sess.trace_id})
            if not self.queue.offer(sess, force=True,
                                    priority=priority):
                # capacity is held by the submit lock, so the only way
                # force-offer fails is a shutdown() that closed the
                # queue after the drain check above — compensate the
                # already-journaled submit so a restart never replays
                # a session whose client heard "not accepted"
                self._journal.append({"kind": "serve_done", "sid": sid,
                                      "status": "rejected"})
                return 503, {"error": "shutting down"}, \
                    {"Retry-After": 60}
            with self._lock:
                self.sessions[sid] = sess
                self._order.append(sid)
            with self._watch_lock:
                self._trace_sids[sess.trace_id] = sid
        self._metric_admission("accepted", tenant)
        # an admitted submit ends any shed episode for this tenant —
        # the NEXT shed is a fresh rising edge worth a journal record
        self._clear_shed_edge(tenant, "slo_burn")
        self._clear_shed_edge(tenant, "disk")
        return 202, {"id": sid, "state": QUEUED, "tenant": tenant,
                     "deadline_ms": deadline_ms,
                     "trace_id": sess.trace_id}, None

    # Retry-After floor for a replica with NO draining capacity (paused
    # / 0 workers): depth × wall / workers is 0 × anything or a divide
    # by zero there — and any finite estimate would be a lie, since the
    # queue is not draining at all.  A constant says "come back when an
    # operator has unpaused me".
    _RETRY_AFTER_IDLE = 30

    def retry_after(self) -> int:
        """Honest backpressure: the queue's expected drain time under
        the rolling mean session wall — clamped to a sane floor, never
        a division by zero or a 0s "immediately" hint."""
        workers = len(self._workers)
        if workers <= 0 or self.paused:
            return self._RETRY_AFTER_IDLE
        per = max(0.05, self._ewma_wall) / workers
        return max(1, int(self.queue.depth() * per + 0.5))

    # a shed more than this long after the previous one for the same
    # (tenant, reason) is a NEW episode and journals a fresh rising
    # edge — a tenant whose clients gave up (so no admit ever cleared
    # the edge) must not have its next week's episode go unrecorded
    _SHED_EPISODE_S = 600.0

    def _note_shed(self, tenant: str, reason: str) -> None:
        """One shed decision: count it (every shed response bumps
        ``mrtpu_serve_shed_total{tenant,reason}``) and journal the
        RISING EDGE per (tenant, reason) episode — post-mortems need
        "when did shedding start", not one fsync per rejected
        request."""
        try:
            from ..obs.metrics import get_registry
            get_registry().counter(
                "mrtpu_serve_shed_total",
                "admissions shed by the self-protection plane "
                "(reason: slo_burn/disk)",
                ("tenant", "reason")).inc(tenant=tenant, reason=reason)
        except Exception:
            pass
        key = f"{tenant}|{reason}"
        now = time.monotonic()
        with self._shed_lock:
            last = self._shed_edges.get(key)
            if len(self._shed_edges) > 512 and last is None:
                # tenant names come from request bodies: expire
                # finished episodes (and, failing that, everything) so
                # a client cycling names against a degraded daemon
                # can't grow this
                self._shed_edges = {
                    k: t for k, t in self._shed_edges.items()
                    if now - t < self._SHED_EPISODE_S}
                if len(self._shed_edges) > 512:
                    self._shed_edges.clear()
            self._shed_edges[key] = now
        if last is not None and now - last < self._SHED_EPISODE_S:
            return              # same episode: already journaled
        with self._submit_lock:
            if self._journal is not None:
                try:
                    self._journal.append({"kind": "serve_shed",
                                          "tenant": tenant,
                                          "reason": reason})
                except (ValueError, OSError):
                    pass    # a full disk must not turn shedding into 500s

    def _clear_shed_edge(self, tenant: str, reason: str) -> None:
        with self._shed_lock:
            self._shed_edges.pop(f"{tenant}|{reason}", None)

    def _metric_admission(self, outcome: str, tenant: str = "default"
                          ) -> None:
        try:
            from ..obs.metrics import get_registry
            get_registry().counter(
                "mrtpu_serve_admission_total",
                "admission decisions by outcome and tenant "
                "(accepted/rejected/throttled)",
                ("outcome", "tenant")).inc(outcome=outcome,
                                           tenant=tenant)
        except Exception:
            pass

    # -- cancellation (DELETE /v1/jobs/<id>) -------------------------------
    def cancel(self, sid: str, reason: str = "client") -> tuple:
        """→ (code, body).  QUEUED sessions finalize as ``cancelled``
        right here (they never run); RUNNING ones get their request
        account flagged and stop cooperatively at the next op barrier
        (obs/context.barrier_check).  A cancel landing after the
        terminal record is a 409 no-op — it never touches the result
        (doc/serve.md#deadlines-and-cancel)."""
        with self._lock:
            sess = self.sessions.get(sid)
            if sess is None:
                return 404, {"error": f"no session {sid!r}"}
            st = sess.state
            if st in TERMINAL:
                return 409, {"error": f"session {sid!r} already "
                                      f"{st}; cancel is a no-op"}
            if st == QUEUED:
                if sess.cancel_requested is None:
                    sess.cancel_requested = reason
                    claim = True
                else:
                    claim = False     # an earlier cancel owns finalize
            else:                     # RUNNING
                claim = False
                first = sess.cancel_requested is None
                sess.cancel_requested = sess.cancel_requested or reason
                acct = sess.account
        if st == QUEUED:
            if claim:
                self._finalize_cancelled(sess, reason)
            return 202, {"id": sid, "state": CANCELLED,
                         "cancel_reason": reason}
        # RUNNING: journal the acknowledged cancel BEFORE arming the
        # flag — a kill -9 between this 202 and the session's next
        # barrier must not resurrect and complete a session its client
        # was told is cancelling (recovery finalizes serve_cancel'd
        # sids as cancelled instead of re-queueing them).  Only the
        # FIRST cancel journals: a client hammering DELETE while the
        # barrier approaches must not grow the journal one fsync per
        # request
        if first:
            with self._submit_lock:
                if self._journal is not None:
                    try:
                        self._journal.append(
                            {"kind": "serve_cancel", "sid": sid,
                             "reason": reason, "trace": sess.trace_id})
                    except (ValueError, OSError):
                        pass
        # arm the account (it may lag sess.state by a few lines in
        # run_session — cancel_requested covers that window:
        # run_session re-checks it after PUBLISHING the account, so one
        # side always sees the other)
        if acct is not None:
            acct.cancel(reason)
        self._push_event(sid, {"event": "status", "id": sid,
                               "state": "cancelling",
                               "cancel_reason": reason})
        return 202, {"id": sid, "state": "cancelling",
                     "cancel_reason": reason}

    def _finalize_cancelled(self, sess: Session, reason: str) -> None:
        """Terminal bookkeeping for a session cancelled BEFORE it ran:
        the ``serve_cancel`` intent record FIRST (a crash anywhere past
        it recovers to ``cancelled``, never to a resurrected run that
        overwrites this result), then the durable result, then the
        ``serve_done`` record, then the state flip — same ordering
        discipline as the worker path."""
        sess.cancel_reason = reason
        sess.error = f"cancelled ({reason})"
        with self._submit_lock:
            if self._journal is not None:
                try:
                    self._journal.append(
                        {"kind": "serve_cancel", "sid": sess.sid,
                         "reason": reason, "trace": sess.trace_id})
                except (ValueError, OSError):
                    pass
        try:
            atomic_write_json(
                self.result_path(sess.sid),
                cancelled_record(sess.sid, sess.tenant, reason,
                                 trace_id=sess.trace_id,
                                 deadline_ms=sess.deadline_ms,
                                 failed_over=sess.failed_over))
        except Exception:
            pass
        with self._submit_lock:
            if self._journal is not None:
                try:
                    self._journal.append(
                        {"kind": "serve_done", "sid": sess.sid,
                         "status": CANCELLED, "trace": sess.trace_id})
                except (ValueError, OSError):
                    pass
        sess.state = CANCELLED
        sess.finished_ts = time.time()
        self._metric_cancel(sess.tenant, reason)
        self._metric_session(sess)
        self._push_event(sess.sid, {"event": "status", **sess.summary()})

    def _metric_cancel(self, tenant: str, reason: str) -> None:
        try:
            from ..obs.metrics import get_registry
            get_registry().counter(
                "mrtpu_serve_cancel_total",
                "sessions cancelled, by reason "
                "(client/deadline/stall)",
                ("tenant", "reason")).inc(tenant=tenant, reason=reason)
        except Exception:
            pass

    # -- hung-session watchdog ---------------------------------------------
    def _stall_loop(self) -> None:
        """MRTPU_SERVE_STALL armed: flag any RUNNING session with no
        barrier progress for that long (a wedged collective, a hung
        input read), arm the flight recorder so the forensic ring is
        already collecting, and — under MRTPU_SERVE_STALL_CANCEL=1 —
        cancel it so the worker comes back.  The flag clears itself
        when progress resumes: a slow op is not a hang."""
        interval = max(0.05, min(self.stall_s / 4.0, 5.0))
        while not self._stopped.wait(interval):
            try:
                self._stall_scan(time.monotonic())
            except Exception:
                pass    # the watchdog must never take the daemon down

    def _stall_scan(self, now: float) -> None:
        """One watchdog pass (split from the loop so tests drive it
        with a synthetic clock)."""
        with self._lock:
            running = [s for s in self.sessions.values()
                       if s.state == RUNNING and s.account is not None]
        for sess in running:
            acct = sess.account
            idle = now - acct.last_barrier
            if idle < self.stall_s:
                sess.stalled = False
                continue
            if sess.stalled:
                continue              # already flagged this episode
            sess.stalled = True
            self.stall_count += 1
            try:
                from ..obs import flight as _flight
                _flight.enable()
            except Exception:
                pass
            try:
                from ..obs.metrics import get_registry
                get_registry().counter(
                    "mrtpu_serve_stalled_total",
                    "sessions flagged by the stall watchdog (no "
                    "barrier progress for MRTPU_SERVE_STALL)",
                    ("tenant",)).inc(tenant=sess.tenant)
            except Exception:
                pass
            self._push_event(sess.sid, {
                "event": "stalled", "id": sess.sid,
                "idle_s": round(idle, 3),
                "cancelling": self.stall_cancel})
            if self.stall_cancel:
                acct.cancel("stall")

    # -- session TTL / GC --------------------------------------------------
    def _gc_files(self, sid: str) -> None:
        """Delete one session's durable footprint (idempotent — also
        the recovery path that finishes an interrupted GC)."""
        import shutil
        shutil.rmtree(self.session_dir(sid), ignore_errors=True)
        try:
            os.remove(self.result_path(sid))
        except OSError:
            pass

    def _gc_once(self) -> int:
        """One TTL sweep: journal the GC intent per expired DONE/FAILED
        session FIRST (the intent record is what makes a kill -9
        mid-delete resumable — and only terminal sessions are ever
        journaled, so a live session can never be orphaned), then
        delete its directories and drop it from the listing.  The
        caching-tier half (:meth:`_gc_cache`) rides the same sweep."""
        if self.ttl_s <= 0:
            return self._gc_cache()
        now = time.time()
        expired: List[Session] = []
        with self._lock:
            for sess in self.sessions.values():
                if sess.state in TERMINAL and \
                        sess.finished_ts is not None and \
                        now - sess.finished_ts >= self.ttl_s:
                    expired.append(sess)
        n = 0
        for sess in expired:
            with self._submit_lock:
                if self._journal is None:
                    return n           # shutting down: next restart GCs
                self._journal.append({"kind": "serve_gc",
                                      "sid": sess.sid,
                                      "tenant": sess.tenant})
            self._gc_files(sess.sid)
            with self._lock:
                self.sessions.pop(sess.sid, None)
                try:
                    self._order.remove(sess.sid)
                except ValueError:
                    pass
                self.gc_count += 1
            with self._watch_lock:
                self._trace_sids.pop(sess.trace_id, None)
            n += 1
            try:
                from ..obs.metrics import get_registry
                get_registry().counter(
                    "mrtpu_serve_gc_total",
                    "expired sessions swept by the TTL GC",
                    ("tenant",)).inc(tenant=sess.tenant)
            except Exception:
                pass
        return n + self._gc_cache()

    def _gc_cache(self) -> int:
        """Caching-tier half of the TTL sweep: memoized results past
        ``MRTPU_MEMO_TTL`` (0 = keep forever), then CAS chunks with no
        external hardlink untouched past ``MRTPU_CAS_GRACE``.  Each
        batch journals its intent record (``memo_gc`` / ``cas_gc``)
        BEFORE removing anything — a kill -9 mid-sweep finishes on
        restart (_recover), and both finish halves are idempotent, so
        a chunk re-referenced after the intent survives and a refcount
        can never go negative."""
        from ..utils.cas import cas_store
        from . import memo as memo_mod
        n = 0
        try:
            keys = memo_mod.sweep_candidates(self.memo_ttl_s) \
                if self.memo_ttl_s > 0 else []
            if keys:
                with self._submit_lock:
                    if self._journal is None:
                        return n   # shutting down: next restart sweeps
                    self._journal.append({"kind": "memo_gc",
                                          "keys": keys})
                n += memo_mod.sweep_finish(keys)
            store = cas_store()
            digests = store.gc_candidates(self.cas_grace_s) \
                if store is not None else []
            if digests:
                with self._submit_lock:
                    if self._journal is None:
                        return n
                    self._journal.append({"kind": "cas_gc",
                                          "digests": digests})
                n += store.gc_finish(digests)
        except Exception:
            return n          # cache GC must never take the daemon down
        if n:
            with self._lock:
                self.cache_gc_count += n
            try:
                from ..obs.metrics import get_registry
                get_registry().counter(
                    "mrtpu_cas_gc_total",
                    "caching-tier entries swept (expired memo records "
                    "+ unreferenced CAS chunks)").inc(n)
            except Exception:
                pass
        return n

    def _gc_loop(self) -> None:
        interval = max(0.2, min(self.ttl_s / 4.0, 60.0))
        while not self._stopped.wait(interval):
            try:
                self._gc_once()
            except Exception:
                pass               # the GC must never take the daemon down

    # -- workers -----------------------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            sess = self.queue.take(timeout=0.25)
            if sess is None:
                if self._stopped.is_set() and self.queue.depth() == 0:
                    return
                continue
            if not self._fence_ok():
                # our lease lapsed or a peer claimed our journal: this
                # session belongs to the claimant now.  Dropping it is
                # the fence — executing it would be the double run
                from . import fleet as fleet_mod
                with self._lock:
                    self.fenced_drops += 1
                fleet_mod.note_fenced_drop(self.rid)
                continue
            with self._lock:
                if sess.cancel_requested is not None and \
                        sess.state != RUNNING:
                    # cancelled while QUEUED: the DELETE handler owns
                    # (or already finished) the terminal bookkeeping —
                    # executing it now would be the double run the 202
                    # "state: cancelled" promised against
                    continue
                # the RUNNING flip happens UNDER the lock so a
                # concurrent DELETE always sees either "still queued"
                # (it finalizes, we skip above) or "running" (it arms
                # the account) — never a gap between the two
                sess.state = RUNNING
                self._active += 1
            self._push_event(sess.sid,
                             {"event": "status", "id": sess.sid,
                              "state": RUNNING,
                              "trace_id": sess.trace_id})
            try:
                result = run_session(self, sess)
            except Exception as e:    # run_session already shields; belt
                sess.error = f"{type(e).__name__}: {e}"
                self.disk.note_error(e)   # a result-write ENOSPC
                #                           must flip us degraded
                try:
                    atomic_write_json(
                        self.result_path(sess.sid),
                        {"id": sess.sid, "tenant": sess.tenant,
                         "status": FAILED, "error": sess.error})
                except Exception:
                    pass
                sess.state = FAILED    # after the durable result, like
                #                        run_session's flip ordering
            finally:
                sess.finished_ts = time.time()   # the TTL GC's clock
                with self._lock:
                    self._active -= 1
            self._ewma_wall = 0.7 * self._ewma_wall + \
                0.3 * float(sess.wall_s or 1.0)
            if sess.state == CANCELLED:
                self._metric_cancel(sess.tenant,
                                    sess.cancel_reason or "client")
            # cost-profile evidence (serve/overload.py): what the SLO
            # shedder ranks expensive-vs-cheap by, and what the mesh
            # autoscaler sizes the next session's width from
            acct0 = sess.account
            if acct0 is not None:
                self.profiles.record(
                    sess.tenant, sess.wall_s or 0.0,
                    acct0.exchange_sent + acct0.exchange_pad)
            # completion record follows the durable result file.  A
            # worker draining past shutdown's join timeout may find the
            # journal closed — the missing done record only costs one
            # redundant (idempotent) replay on the next restart
            try:
                meta = {}
                try:
                    meta = result.get("meta") or {}
                except NameError:
                    pass
                memo_meta = meta.get("memo") or {}
                if memo_meta.get("hit"):
                    # durable proof the session was memo-served: a
                    # kill -9 replay sees cache_hit+serve_done and
                    # re-serves from the store — never recomputes.
                    # mrlint: disable=lock-unguarded-mutation —
                    # documented drain race (comment above): a closed
                    # journal costs one idempotent replay;
                    # Journal.append has its own write lock
                    self._journal.append({"kind": "cache_hit",
                                          "sid": sess.sid,
                                          "key": memo_meta.get("key"),
                                          "trace": sess.trace_id})
                # mrlint: disable=lock-unguarded-mutation — documented
                # drain race (comment above): a closed journal costs
                # one idempotent replay; Journal.append has its own
                # write lock
                self._journal.append({"kind": "serve_done",
                                      "sid": sess.sid,
                                      "status": sess.state,
                                      "trace": sess.trace_id})
            except (ValueError, OSError, AttributeError):
                pass
            self._metric_session(sess)
            # watchers see the profile BEFORE the terminal status —
            # the terminal status is the stream's end-of-feed marker
            acct = sess.account
            if acct is not None:
                self._push_event(sess.sid, {"event": "profile",
                                            "profile": acct.profile()})
            self._push_event(sess.sid,
                             {"event": "status", **sess.summary()})

    def _metric_session(self, sess: Session) -> None:
        try:
            from ..obs.metrics import get_registry
            reg = get_registry()
            reg.counter("mrtpu_serve_sessions_total",
                        "finished sessions by tenant and status",
                        ("tenant", "status")).inc(
                            tenant=sess.tenant, status=sess.state)
            reg.histogram("mrtpu_serve_session_seconds",
                          "session wall time by tenant and status",
                          ("tenant", "status")).observe(
                              float(sess.wall_s or 0.0),
                              tenant=sess.tenant, status=sess.state)
        except Exception:
            pass

    def active_count(self) -> int:
        with self._lock:
            return self._active

    def _mesh_width(self) -> int:
        """Shards of the mesh this daemon instance runs sessions on —
        after a degraded restart this is "whatever is available now"."""
        if self.comm is None or isinstance(self.comm, int):
            return 1
        from ..parallel.mesh import mesh_axis_size
        return mesh_axis_size(self.comm)

    def _mesh_status(self) -> dict:
        """The stats()/mrctl view of the mesh, including whether the
        data plane is running DEGRADED (shrunk after a rank loss —
        parallel/dist.py): operators must see a narrowed fleet in the
        same place they see width, not infer it from missing ranks."""
        from ..parallel.dist import surviving_width
        out = {"nprocs": self._mesh_width()}
        cap = surviving_width()
        if cap is not None and cap < out["nprocs"]:
            out["degraded"] = True
            out["surviving_width"] = cap
        elif getattr(self.autoscaler, "dist_cap", None):
            out["degraded"] = True
            out["surviving_width"] = self.autoscaler.dist_cap
        return out

    # -- request-scoped observability (obs/context.py) ---------------------
    def _span_feed(self, ev: dict) -> None:
        """Tracer sink: a finished TOP-LEVEL span whose trace_id maps
        to a watched session becomes one event on that session's
        stream.  Must never raise (the tracer drops raising sinks) and
        must stay cheap — it runs on every span emission process-wide."""
        try:
            tid = ev.get("trace")
            if not tid or ev.get("parent"):
                return
            with self._watch_lock:
                sid = self._trace_sids.get(tid)
                if sid is None or sid not in self._watch:
                    return
            self._push_event(sid, {
                "event": "span", "name": ev.get("name"),
                "cat": ev.get("cat"),
                "dur_ms": round(float(ev.get("dur", 0.0)) / 1000.0, 3),
                "args": ev.get("args") or {}})
        except Exception:
            pass

    def _push_event(self, sid: str, item: dict) -> None:
        with self._watch_lock:
            qs = list(self._watch.get(sid, ()))
        for q in qs:
            try:
                q.put_nowait(item)
            except _queue.Full:
                pass    # a stalled watcher drops events, never blocks
                #         the worker (the stream is telemetry, not a
                #         durable log — the result record is)

    def _events_stream(self, sid: str, timeout: float = 600.0):
        """Generator behind ``GET /v1/jobs/<id>/events``: one JSON line
        per event (status transitions, top-level spans, the final cost
        profile), pushed as they happen — the no-polling exposure.  The
        subscription attaches BEFORE the state snapshot is read, so a
        transition in the gap arrives on the queue instead of being
        missed; ends at terminal state, daemon stop, or the timeout."""
        import json as _json

        from ..obs.sinks import _jsonable

        def line(obj) -> str:
            return _json.dumps(obj, default=_jsonable) + "\n"

        q: _queue.Queue = _queue.Queue(maxsize=512)
        with self._watch_lock:
            self._watch.setdefault(sid, []).append(q)
        try:
            with self._lock:
                sess = self.sessions.get(sid)
            if sess is None:
                yield line({"event": "error",
                            "error": f"no session {sid!r}"})
                return
            if sess.state in TERMINAL:
                # already finished: replay the durable profile, THEN
                # the terminal status — same order as the live path
                # (worker pushes profile before the final status), so
                # a client that stops at the terminal marker still got
                # the whole story
                code, prof = self.profile(sid)
                if code == 200 and prof.get("profile"):
                    yield line({"event": "profile",
                                "profile": prof["profile"]})
                yield line({"event": "status", **sess.summary()})
                return
            yield line({"event": "status", **sess.summary()})
            deadline = time.monotonic() + timeout
            last_beat = time.monotonic()
            while time.monotonic() < deadline \
                    and not self._stopped.is_set():
                try:
                    item = q.get(timeout=0.25)
                except _queue.Empty:
                    if time.monotonic() - last_beat >= 15.0:
                        last_beat = time.monotonic()
                        yield line({"event": "tick"})
                    continue
                yield line(item)
                if item.get("event") == "status" and \
                        item.get("state") in TERMINAL:
                    return
        finally:
            with self._watch_lock:
                qs = self._watch.get(sid)
                if qs is not None and q in qs:
                    qs.remove(q)
                    if not qs:
                        del self._watch[sid]

    def profile(self, sid: str) -> tuple:
        """→ (code, dict): the per-request cost profile.  RUNNING
        sessions serve the LIVE account snapshot (partial, marked
        ``live``); terminal sessions serve the durable one from the
        result record; queued sessions 202 like /result."""
        with self._lock:
            sess = self.sessions.get(sid)
        if sess is None:
            return 404, {"error": f"no session {sid!r}"}
        if sess.state == QUEUED:
            return 202, sess.summary()
        if sess.state == RUNNING:
            acct = sess.account
            if acct is None:        # racing the worker's first line
                return 202, sess.summary()
            return 200, {"id": sid, "trace_id": sess.trace_id,
                         "live": True, "profile": acct.profile()}
        import json
        try:
            with open(self.result_path(sid)) as f:
                res = json.load(f)
            prof = (res.get("meta") or {}).get("profile")
            if prof:
                return 200, {"id": sid, "trace_id": sess.trace_id,
                             "live": False, "profile": prof}
        except (OSError, ValueError):
            pass
        return 200, {**sess.summary(),
                     "error": "profile unavailable"}

    # -- reads -------------------------------------------------------------
    def status(self, sid: str) -> Optional[dict]:
        with self._lock:
            sess = self.sessions.get(sid)
        return sess.summary() if sess else None

    def result(self, sid: str) -> tuple:
        """→ (code, dict): 200 done/failed, 202 pending, 404 unknown."""
        with self._lock:
            sess = self.sessions.get(sid)
        if sess is None:
            return 404, {"error": f"no session {sid!r}"}
        if sess.state in (QUEUED, RUNNING):
            return 202, sess.summary()
        import json
        try:
            with open(self.result_path(sid)) as f:
                return 200, json.load(f)
        except (OSError, ValueError):
            # done per journal but the result file is missing/torn (a
            # crash window) — surface the summary rather than a 500
            return 200, {**sess.summary(),
                         "error": sess.error or "result file unavailable"}

    def _cache_stats(self) -> dict:
        """The caching-tier section of /v1/stats (mrctl cache): CAS
        store shape, memoization counters, and sweep totals."""
        from ..utils.cas import cas_store
        from . import memo as memo_mod
        store = cas_store()
        cas = store.stats() if store is not None \
            else {"enabled": 0, "chunks": 0, "bytes": 0}
        with self._lock:
            swept = self.cache_gc_count
        return {"cas": cas,
                "memo": memo_mod.memo_stats(),
                "gc": {"memo_ttl_s": self.memo_ttl_s,
                       "cas_grace_s": self.cas_grace_s,
                       "swept": swept}}

    def stats(self) -> dict:
        from ..plan.cache import cache_stats
        with self._lock:
            states: Dict[str, int] = {}
            for s in self.sessions.values():
                states[s.state] = states.get(s.state, 0) + 1
            active = self._active
        fleet = None
        if self._fleet is not None:
            fleet = {"rid": self.rid, "epoch": self._fleet.epoch,
                     "fenced": self._fenced,
                     "fenced_drops": self.fenced_drops,
                     "replicas": {rid: self._fleet.replica_state(rid, l)
                                  for rid, l in
                                  self._fleet.peers().items()}}
        return {"queue": self.queue.stats(),
                "fleet": fleet,
                "sessions": {"active": active, "by_state": states,
                             "total": len(self._order)},
                "streams": self.streams.snapshot(),
                "tenants": self.budgets.snapshot(),
                "ratelimit": self.ratelimit.snapshot(),
                "gc": {"ttl_s": self.ttl_s, "swept": self.gc_count},
                "mesh": self._mesh_status(),
                "plan": cache_stats(),
                "cache": self._cache_stats(),
                # the self-protection plane (doc/serve.md): auth arming,
                # shed/deprioritize counts, cost evidence, disk
                # pressure, watchdog and autoscaler state
                "overload": {
                    "auth": self.auth.snapshot(),
                    "shed": self.shedder.snapshot(),
                    "profiles": self.profiles.snapshot(),
                    "disk": self.disk.snapshot(),
                    "stall": {"stall_s": self.stall_s,
                              "cancel": self.stall_cancel,
                              "flagged": self.stall_count},
                    "deadline_default_ms": self.default_deadline_ms,
                    "autoscale": self.autoscaler.snapshot()},
                "draining": self._draining, "paused": self.paused,
                "workers": len(self._workers), "port": self.port,
                "state_dir": self.state_dir}

    # -- HTTP routing (obs/httpd.register_routes handler) ------------------
    def _session_tenant(self, sid: str) -> Optional[str]:
        with self._lock:
            sess = self.sessions.get(sid)
        return sess.tenant if sess else None

    def _authz(self, ident: Optional[str],
               tenant: Optional[str] = None,
               admin: bool = False) -> Optional[tuple]:
        """Route-level auth gate over the ONE token resolution the
        handler already did: None = allowed, else a full response tuple
        (401 missing/invalid token, 403 out-of-tenant or non-admin
        operator verb) — decided BEFORE any journal write or queue
        mutation (serve/auth.py)."""
        code, err = self.auth.gate_ident(ident, tenant=tenant,
                                         admin=admin)
        if not code:
            return None
        extra = {"WWW-Authenticate": "Bearer"} if code == 401 else None
        return code, err, "application/json", extra

    def _handle(self, method: str, path: str, body: bytes,
                headers: dict) -> tuple:
        import json
        parts = [p for p in path.split("/") if p]      # ["v1", ...]
        if len(parts) < 2 or parts[0] != "v1":
            return 404, {"error": "not found"}, "application/json", None
        rest = parts[1:]
        # every /v1/ request needs a VALID token when auth is armed
        # (tenant scoping per route below); the telemetry plane
        # (/metrics, /healthz) stays open — doc/serve.md#tenant-auth
        ident = self.auth.identify(headers) if self.auth.armed else None
        if self.auth.armed and ident is None:
            return 401, {"error": "missing or invalid bearer token"}, \
                "application/json", {"WWW-Authenticate": "Bearer"}
        if method == "POST" and rest == ["jobs"]:
            try:
                obj = json.loads(body.decode() or "{}")
                if not isinstance(obj, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, UnicodeDecodeError) as e:
                return 400, {"error": f"bad JSON body: {e}"}, \
                    "application/json", None
            if ident is not None and ident != "*" \
                    and not obj.get("tenant"):
                obj["tenant"] = ident     # the token names the tenant
            denied = self._authz(
                ident, tenant=str(obj.get("tenant") or "default"))
            if denied:
                return denied
            code, out, extra = self.submit(obj)
            return code, out, "application/json", extra
        if method == "DELETE" and len(rest) == 2 and rest[0] == "jobs":
            owner = self._session_tenant(rest[1])
            if owner is None:
                return 404, {"error": f"no session {rest[1]!r}"}, \
                    "application/json", None
            denied = self._authz(ident, tenant=owner)
            if denied:
                if denied[0] == 403:
                    # foreign sid reads as NONEXISTENT: sids are
                    # sequential, so 403-vs-404 would be an existence
                    # oracle over other tenants' session volume
                    return 404, {"error": f"no session {rest[1]!r}"}, \
                        "application/json", None
                return denied
            code, out = self.cancel(rest[1])
            return code, out, "application/json", None
        if method == "GET" and rest == ["jobs"]:
            with self._lock:
                out = [self.sessions[sid].summary()
                       for sid in self._order]
            if ident is not None and ident != "*":
                # a tenant token lists its OWN sessions only
                out = [s for s in out if s.get("tenant") == ident]
            return 200, {"jobs": out}, "application/json", None
        if method == "GET" and len(rest) in (2, 3) and rest[0] == "jobs":
            # tenant tokens read only their own sessions (admin: all);
            # a foreign sid answers 404, not 403 — no existence oracle
            owner = self._session_tenant(rest[1])
            if owner is not None:
                denied = self._authz(ident, tenant=owner)
                if denied:
                    if denied[0] == 403:
                        return 404, {"error": f"no session "
                                              f"{rest[1]!r}"}, \
                            "application/json", None
                    return denied
        if method == "GET" and len(rest) == 2 and rest[0] == "jobs":
            st = self.status(rest[1])
            if st is None:
                return 404, {"error": f"no session {rest[1]!r}"}, \
                    "application/json", None
            return 200, st, "application/json", None
        if method == "GET" and len(rest) == 3 and rest[0] == "jobs" \
                and rest[2] == "result":
            code, out = self.result(rest[1])
            return code, out, "application/json", None
        if method == "GET" and len(rest) == 3 and rest[0] == "jobs" \
                and rest[2] == "profile":
            code, out = self.profile(rest[1])
            return code, out, "application/json", None
        if method == "GET" and len(rest) == 3 and rest[0] == "jobs" \
                and rest[2] == "events":
            with self._lock:
                known = rest[1] in self.sessions
            if not known:
                return 404, {"error": f"no session {rest[1]!r}"}, \
                    "application/json", None
            return 200, self._events_stream(rest[1]), \
                "application/x-ndjson", None
        if rest and rest[0] == "streams":
            return self._handle_streams(method, rest[1:], body, ident)
        if method == "GET" and rest == ["slo"]:
            # burn rates cover EVERY tenant — operator surface, like
            # /v1/stats below (a tenant token must not read its
            # neighbors' cost profiles or traffic shape)
            denied = self._authz(ident, admin=True)
            if denied:
                return denied
            from ..obs import slo as _slo
            eng = _slo.get_engine()
            if eng is None:
                return 200, {"objectives": [], "burn": {},
                             "firing": [], "alerts": []}, \
                    "application/json", None
            # force: an explicit operator ask must never serve a burn
            # snapshot the scrape-path rate limiter left stale
            eng.tick(force=True)
            return 200, eng.snapshot(), "application/json", None
        if method == "GET" and rest == ["stats"]:
            # stats spans every tenant (page accounts, cost profiles,
            # shed state) — admin-only when auth is armed
            denied = self._authz(ident, admin=True)
            if denied:
                return denied
            return 200, self.stats(), "application/json", None
        if method == "POST" and rest == ["drain"]:
            denied = self._authz(ident, admin=True)
            if denied:
                return denied
            self.drain()
            return 200, {"draining": True}, "application/json", None
        if method == "POST" and rest == ["shutdown"]:
            denied = self._authz(ident, admin=True)
            if denied:
                return denied
            # respond first, stop after: the stop path drains in-flight
            # HTTP handlers, and THIS handler is one of them
            threading.Thread(target=self._deferred_shutdown,
                             daemon=True).start()
            return 200, {"shutting_down": True}, "application/json", None
        return 404, {"error": "not found"}, "application/json", None

    def _handle_streams(self, method: str, rest: List[str],
                        body: bytes, ident: Optional[str]) -> tuple:
        """``/v1/streams`` routing (serve/streams.py): open / list /
        status / feed / events / close.  Tenant scoping mirrors jobs:
        a foreign stream id answers 404, never 403 (no existence
        oracle over sequential ids)."""
        import json
        if method == "POST" and not rest:
            try:
                obj = json.loads(body.decode() or "{}")
                if not isinstance(obj, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, UnicodeDecodeError) as e:
                return 400, {"error": f"bad JSON body: {e}"}, \
                    "application/json", None
            if ident is not None and ident != "*" \
                    and not obj.get("tenant"):
                obj["tenant"] = ident
            denied = self._authz(
                ident, tenant=str(obj.get("tenant") or "default"))
            if denied:
                return denied
            code, out, extra = self.streams.open(obj)
            return code, out, "application/json", extra
        if method == "GET" and not rest:
            out = self.streams.list()
            if ident is not None and ident != "*":
                out = [s for s in out if s.get("tenant") == ident]
            return 200, {"streams": out}, "application/json", None
        if not rest:
            return 404, {"error": "not found"}, "application/json", None
        stid = rest[0]
        ss = self.streams.get(stid)
        if ss is None:
            return 404, {"error": f"no stream {stid!r}"}, \
                "application/json", None
        denied = self._authz(ident, tenant=ss.tenant)
        if denied:
            if denied[0] == 403:
                return 404, {"error": f"no stream {stid!r}"}, \
                    "application/json", None
            return denied
        if method == "GET" and len(rest) == 1:
            return 200, ss.summary(), "application/json", None
        if method == "GET" and rest[1:] == ["events"]:
            return 200, self.streams.events_stream(stid), \
                "application/x-ndjson", None
        if method == "POST" and rest[1:] == ["feed"]:
            code, out = self.streams.feed(stid, body)
            return code, out, "application/json", None
        if (method == "DELETE" and len(rest) == 1) or \
                (method == "POST" and rest[1:] == ["close"]):
            drain = True
            if method == "POST" and body:
                try:
                    drain = bool(json.loads(body.decode() or "{}")
                                 .get("drain", True))
                except (ValueError, UnicodeDecodeError):
                    pass
            code, out = self.streams.close(stid, drain=drain)
            return code, out, "application/json", None
        return 404, {"error": "not found"}, "application/json", None

    def _deferred_shutdown(self) -> None:
        time.sleep(0.2)          # let the 200 flush to the client
        try:
            self.shutdown()
        except Exception:
            pass
