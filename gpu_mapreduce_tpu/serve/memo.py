"""Job-result memoization: a byte-identical resubmission never
recomputes (doc/serve.md#result-memoization).

The key is the sha256 of one canonical document: the normalized script
text, the schema version, and the **input manifest** — (path, size,
crc) of every existing file the script's tokens name (glob patterns
expanded, so ``variable``-driven file lists are covered).  Change one
input byte and the key changes; resubmit the same bytes and any
replica of the fleet serves the stored result from
``<cas>/memo/<key>.json`` without executing a single op.

The **exactness contract** (doc/perf.md#the-caching-tier): the key
deliberately EXCLUDES ``fuse``/``wire``/``megafuse``/mesh width —
those tiers are byte-identical by construction (the repo's standing
invariant, re-asserted by the memo acceptance tests), so a shrunk
fleet reuses what a wide fleet produced.  Anything that could make a
rerun differ makes the submission *non-memoizable* instead of keyed:
``set timer`` / ``set verbosity`` (wall-clock text on the screen
channel) and ``save``/``load`` (checkpoint side effects outside the
result record).

Integrity: entries are stamped on write and verified on read — the
record's own crc AND the sha256 of every inline output file must agree
with what run_session recorded.  A bit-flip bumps
``mrtpu_integrity_failures_total{artifact="cas"}``, removes the entry,
and reads as a miss: corruption degrades to recompute, never to a
wrong answer.

``MRTPU_MEMOIZE=0`` opts the tier out; without a CAS root
(``utils/cas.py``) it is off by construction.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import threading
import time
from typing import List, Optional, Tuple

from ..utils.env import env_flag

MEMO_SCHEMA = 1

# script features that break the exactness contract (module docstring).
# ``stream`` is here because a standing query's answer is a moving
# target over growing inputs — never a pure function of the submission
# (doc/streaming.md#memoization)
_NONDET_SET = ("timer", "verbosity")
_SIDE_EFFECT_CMDS = ("save", "load", "stream")

_LOCK = threading.Lock()
_COUNTS = {"hits": 0, "misses": 0, "stores": 0, "corrupt": 0}


def memoize_enabled() -> bool:
    from ..utils.cas import cas_enabled
    return cas_enabled() and env_flag("MRTPU_MEMOIZE", True)


def memo_dir() -> Optional[str]:
    from ..utils.cas import cas_root
    root = cas_root()
    return os.path.join(root, "memo") if root else None


def _memo_path(key: str) -> Optional[str]:
    d = memo_dir()
    return os.path.join(d, key + ".json") if d else None


def _note(outcome: str) -> None:
    with _LOCK:
        if outcome in _COUNTS:
            _COUNTS[outcome] += 1
    try:
        from ..obs.metrics import get_registry
        get_registry().counter(
            "mrtpu_memo_total",
            "result-memoization events by outcome "
            "(hit/miss/store/corrupt)", ("outcome",)).inc(outcome=outcome)
    except Exception:
        pass


def input_manifest(payload: str) -> Optional[List[Tuple[str, int, str]]]:
    """(abspath, bytes, crc) per existing file any script token names —
    conservative on purpose: a token the script never reads only makes
    the key stricter (a spurious recompute), never a wrong hit.  None =
    non-memoizable (a token names a directory, or an input vanished
    mid-scan)."""
    from ..utils.integrity import file_digest
    files = {}
    for raw in payload.split():
        tok = raw.strip("\"'").rstrip(",;")
        if not tok or tok.startswith("-"):
            continue
        if any(c in tok for c in "*?["):
            matches = sorted(glob.glob(tok))
        elif os.path.exists(tok):
            matches = [tok]
        else:
            continue
        for m in matches:
            if os.path.isdir(m):
                return None
            if not os.path.isfile(m):
                continue
            try:
                files[os.path.abspath(m)] = (os.path.getsize(m),
                                             file_digest(m))
            except OSError:
                return None
    return sorted((p, s, d) for p, (s, d) in files.items())


def stat_manifest(payload: str) -> List[Tuple[str, int, float]]:
    """(abspath, size, mtime) per existing input file — the CHEAP
    staleness probe stored alongside the result.  Unlike
    :func:`input_manifest` (which feeds the key and pays a crc per
    file), this one only stats: it exists so :func:`lookup` can detect
    a file that GREW between key computation and the hit being served
    (append-only inputs under a standing query do exactly that) and
    fall through to recompute instead of serving a stale record."""
    files = {}
    for raw in payload.split():
        tok = raw.strip("\"'").rstrip(",;")
        if not tok or tok.startswith("-"):
            continue
        if any(c in tok for c in "*?["):
            matches = sorted(glob.glob(tok))
        elif os.path.exists(tok):
            matches = [tok]
        else:
            continue
        for m in matches:
            if not os.path.isfile(m):
                continue
            try:
                st = os.stat(m)
                files[os.path.abspath(m)] = (st.st_size, st.st_mtime)
            except OSError:
                continue
    return sorted((p, s, t) for p, (s, t) in files.items())


def manifest_stale(manifest) -> bool:
    """True when any recorded input changed shape since the record was
    stored — grew, shrank, vanished, or was rewritten in place (mtime
    moved)."""
    for ent in manifest or ():
        try:
            path, size, mtime = ent[0], int(ent[1]), float(ent[2])
        except (TypeError, ValueError, IndexError):
            return True
        try:
            st = os.stat(path)
        except OSError:
            return True
        if st.st_size != size or st.st_mtime != mtime:
            return True
    return False


def memo_key(payload: str) -> Optional[str]:
    """Stable key of one submission, or None when the script is not
    memoizable under the exactness contract.  Reads NO env knobs by
    design — every key input is in the returned expression (the mrlint
    ``cache-key`` CAS-builder rule holds this to account)."""
    for line in payload.splitlines():
        toks = line.split()
        if len(toks) >= 2 and toks[0] == "set" \
                and toks[1] in _NONDET_SET:
            return None
        if any(t in _SIDE_EFFECT_CMDS for t in toks[:2]):
            return None
    manifest = input_manifest(payload)
    if manifest is None:
        return None
    doc = {"schema": MEMO_SCHEMA, "script": payload,
           "inputs": manifest}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


def _verify_record(rec: dict) -> Optional[dict]:
    """Stamp + inline-file verification; the stored result dict on
    success, None on any mismatch."""
    from ..utils.integrity import digest_bytes, verify_enabled
    result = rec.get("result")
    if not isinstance(result, dict):
        return None
    if not verify_enabled():
        return result
    body = json.dumps(result, sort_keys=True).encode()
    if rec.get("c") != digest_bytes(body):
        return None
    for frec in (result.get("files") or {}).values():
        text = frec.get("text")
        if text is not None and hashlib.sha256(
                text.encode()).hexdigest() != frec.get("sha256"):
            return None
    return result


def lookup(key: str) -> Optional[dict]:
    """The stored result for ``key`` — integrity-verified; a corrupt
    entry is removed, counted
    (``mrtpu_integrity_failures_total{artifact="cas"}``), and reads as
    a miss so the session recomputes."""
    from ..utils.integrity import record_integrity_failure
    path = _memo_path(key)
    if path is None:
        return None
    try:
        with open(path) as f:
            rec = json.load(f)
    except OSError:
        _note("misses")
        return None
    except ValueError:
        rec = None
    result = _verify_record(rec) if rec is not None else None
    if result is None:
        record_integrity_failure("cas")
        _note("corrupt")
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    # staleness re-stat (size+mtime) BEFORE serving the hit: an input
    # that grew since the record was stored (append-only files under a
    # standing query do) must recompute, not serve the old answer.  Not
    # corruption — the entry stays for the key that still matches it
    if manifest_stale(rec.get("manifest")):
        _note("misses")
        return None
    _note("hits")
    return result


def store(key: str, result: dict, writer: str = "",
          payload: Optional[str] = None) -> bool:
    """Persist one DONE result under its key (atomic + stamped).  The
    record keeps the full result — output, files (inline text included)
    and mrs — because a hit must reproduce all of them byte-for-byte.
    ``payload`` (the script text) adds the stat manifest
    (:func:`stat_manifest`) that :func:`lookup` re-checks before
    serving: a grown input reads as a miss."""
    from ..utils.integrity import digest_bytes
    path = _memo_path(key)
    if path is None or result.get("status") != "done":
        return False
    body = json.dumps(result, sort_keys=True).encode()
    rec = {"c": digest_bytes(body), "schema": MEMO_SCHEMA, "key": key,
           "writer": writer,
           "manifest": stat_manifest(payload) if payload else [],
           "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "result": result}
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        return False
    _note("stores")
    return True


# -- GC (driven by serve/daemon._gc_cache with journaled intents) ----------

def sweep_candidates(ttl_s: float,
                     now: Optional[float] = None) -> List[str]:
    """Memo keys whose entries aged past ``ttl_s`` (by mtime)."""
    d = memo_dir()
    if d is None or ttl_s <= 0:
        return []
    now = time.time() if now is None else now
    out: List[str] = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for n in names:
        if not n.endswith(".json") or ".tmp" in n:
            continue
        try:
            if now - os.path.getmtime(os.path.join(d, n)) >= ttl_s:
                out.append(n[:-len(".json")])
        except OSError:
            continue
    return out


def sweep_finish(keys: List[str]) -> int:
    """Second half of a journaled memo sweep — idempotent removal (the
    kill -9 recovery path re-runs it; a missing entry just skips)."""
    removed = 0
    for key in keys:
        path = _memo_path(key)
        if path is None:
            continue
        try:
            os.remove(path)
            removed += 1
        except OSError:
            continue
    return removed


def memo_stats() -> dict:
    entries = 0
    nbytes = 0
    d = memo_dir()
    enabled = 1 if memoize_enabled() else 0
    if d is not None:
        try:
            for n in os.listdir(d):
                if not n.endswith(".json") or ".tmp" in n:
                    continue
                try:
                    nbytes += os.path.getsize(os.path.join(d, n))
                except OSError:
                    continue
                entries += 1
        except OSError:
            pass
    with _LOCK:
        return {"enabled": enabled, "entries": entries, "bytes": nbytes,
                **dict(_COUNTS)}


def reset_counts() -> None:
    """Test isolation."""
    with _LOCK:
        for k in _COUNTS:
            _COUNTS[k] = 0
