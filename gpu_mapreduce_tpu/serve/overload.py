"""Self-protection under overload: SLO-burn shedding + disk pressure.

Two gates the daemon consults on every submit, both built from
evidence it already collects:

* :class:`BurnShedder` — closes the loop from the tenant SLO burn
  engine (obs/slo.py, PR 8) back into admission.  A tenant burning its
  error budget in EVERY window of its objective (the same multi-window
  AND that raises the burn alert) gets its NEW submits handled first,
  before the shared queue starts rejecting everyone: its
  expensive-profile jobs (per-tenant session-cost EWMA from the PR 8
  request accounts) are SHED with an honest per-tenant ``Retry-After``,
  its cheap ones are DEPRIORITIZED below every polite tenant.  The
  queue-full 429 remains the backstop — this gate just makes the
  *greedy* tenant absorb the backpressure instead of the polite ones
  (doc/serve.md#slo-burn-shedding).

* :class:`DiskMonitor` — resource-pressure degradation.  ENOSPC on a
  session path, or free space under ``MRTPU_SERVE_DISK_MIN`` MB on the
  state/result filesystems, flips the daemon to DEGRADED: new
  admissions shed with ``Retry-After``, running sessions keep their
  pages and finish (they own the space they already hold), and
  ``/healthz`` answers 503 ``{"status": "degraded"}`` so LBs and the
  fleet router re-route.  Degradation clears itself when space
  returns — no operator restart (doc/reliability.md#daemon-under-
  overload).

Shed decisions land in ``mrtpu_serve_shed_total{tenant,reason}`` (one
count per shed response) and, on the rising edge per (tenant, reason),
as a ``serve_shed`` journal record — forensics without journal spam.
"""

from __future__ import annotations

import errno
import os
import threading
import time
from typing import Dict, Optional, Tuple

from ..utils.env import env_flag, env_knob

# deprioritization floor: a burning-but-cheap tenant's submits sort
# below any default-priority work but keep FIFO among themselves
SHED_PRIORITY = -5


class CostProfiles:
    """Per-tenant EWMA of session cost — the *evidence* the shedder and
    the mesh autoscaler act on.  Fed by the daemon after every finished
    session from that session's own RequestAccount profile (exact under
    concurrency, PR 8); thread-safe; bounded like the rate-limiter's
    bucket table (tenant names come from request bodies)."""

    _ALPHA = 0.3
    _CAP = 512

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # tenant → (wall_s EWMA, exchange-bytes EWMA, sessions seen)
        self._rows: Dict[str, Tuple[float, float, int]] = {}
        self._global_wall = 0.0
        self._n = 0

    def record(self, tenant: str, wall_s: float,
               exchange_bytes: float) -> None:
        wall_s = max(0.0, float(wall_s or 0.0))
        exchange_bytes = max(0.0, float(exchange_bytes or 0.0))
        a = self._ALPHA
        with self._lock:
            if len(self._rows) >= self._CAP and tenant not in self._rows:
                # drop the least-seen row: a client cycling tenant
                # names cannot grow the table without bound
                victim = min(self._rows, key=lambda t: self._rows[t][2])
                del self._rows[victim]
            w, x, n = self._rows.get(tenant, (wall_s, exchange_bytes, 0))
            self._rows[tenant] = (w + a * (wall_s - w),
                                  x + a * (exchange_bytes - x), n + 1)
            self._global_wall += a * (wall_s - self._global_wall) \
                if self._n else wall_s - self._global_wall
            self._n += 1

    def wall(self, tenant: str) -> Optional[float]:
        with self._lock:
            row = self._rows.get(tenant)
            return row[0] if row else None

    def exchange_bytes(self, tenant: str) -> Optional[float]:
        with self._lock:
            row = self._rows.get(tenant)
            return row[1] if row else None

    def global_wall(self) -> float:
        with self._lock:
            return self._global_wall

    def snapshot(self) -> dict:
        with self._lock:
            return {t: {"wall_s": round(w, 4),
                        "exchange_bytes": int(x), "sessions": n}
                    for t, (w, x, n) in sorted(self._rows.items())}


class BurnShedder:
    """The admission-side half of the SLO loop.  ``decide(tenant,
    priority)`` → ``(action, priority, retry_after_s)`` with action one
    of ``"admit"`` / ``"deprioritize"`` / ``"shed"``."""

    def __init__(self, profiles: CostProfiles,
                 enabled: Optional[bool] = None):
        self.profiles = profiles
        self.enabled = enabled if enabled is not None \
            else env_flag("MRTPU_SERVE_SHED", True)
        self.shed_count = 0
        self.deprioritized = 0
        self._last_force = 0.0

    def decide(self, tenant: str, priority: int
               ) -> Tuple[str, int, float]:
        if not self.enabled:
            return "admit", priority, 0.0
        from ..obs import slo as _slo
        eng = _slo.get_engine()
        if eng is None:
            return "admit", priority, 0.0
        # the engine's own tick rate-limit (min_window/10, >=6 s) is a
        # scrape-storm guard; an ADMISSION decision reading that stale
        # a burn would admit a whole burst before noticing it.  Force a
        # re-evaluation at ~1/60th of the shortest window (>= 1 s) —
        # fresh enough to catch a burst, bounded enough that the
        # snapshot ring stays ~90 entries at any window size.
        now = time.monotonic()
        if now - self._last_force >= max(1.0, eng.min_window() / 60.0):
            self._last_force = now
            eng.tick(force=True)
        else:
            eng.tick()
        if not eng.burning(tenant):
            return "admit", priority, 0.0
        # the tenant is burning in every window.  Its own cost profile
        # decides HOW it absorbs backpressure: expensive sessions shed
        # outright (each admit would burn serious capacity), cheap ones
        # only lose priority (they still run, after everyone else).  An
        # unknown profile counts as expensive — a burning tenant with
        # no history gets no benefit of the doubt.
        wall = self.profiles.wall(tenant)
        baseline = self.profiles.global_wall()
        if wall is None or baseline <= 0 or wall >= baseline:
            self.shed_count += 1
            # honest horizon: the burn is a windowed rate, so it decays
            # over the shortest objective window — suggest a fraction
            # of it, bounded to something a client will actually honor
            ra = min(60.0, max(1.0, eng.min_window() / 4.0))
            return "shed", priority, ra
        self.deprioritized += 1
        return "deprioritize", min(priority, SHED_PRIORITY), 0.0

    def snapshot(self) -> dict:
        return {"enabled": self.enabled, "shed": self.shed_count,
                "deprioritized": self.deprioritized}


class DiskMonitor:
    """Free-space floor + ENOSPC latch over the daemon's durable paths.

    ``check()`` returns a reason string while degraded, else None —
    cached ~2 s so per-submit probing costs one lock + clock read.  An
    observed ENOSPC (``note_error``) degrades immediately and stays
    degraded for ``_ENOSPC_HOLD`` seconds past the last occurrence,
    then clears if the free-space probe passes — self-healing, no
    restart."""

    _CACHE_S = 2.0
    _ENOSPC_HOLD = 30.0

    def __init__(self, paths, floor_mb: Optional[int] = None):
        self.paths = [p for p in paths if p]
        self.floor_mb = floor_mb if floor_mb is not None \
            else env_knob("MRTPU_SERVE_DISK_MIN", int, 64)
        self._lock = threading.Lock()
        self._last_probe = 0.0
        self._reason: Optional[str] = None
        self._last_enospc = 0.0
        self.trips = 0

    # the out-of-space errno class: plain full disk AND quota
    # exhaustion (EDQUOT passes the free-byte probe, so the latch is
    # the ONLY way it ever degrades the daemon)
    _SPACE_ERRNOS = frozenset(
        {errno.ENOSPC} | ({errno.EDQUOT} if hasattr(errno, "EDQUOT")
                          else set()))

    def note_error(self, exc: BaseException) -> bool:
        """Latch ENOSPC/EDQUOT seen anywhere in a failure chain."""
        seen = set()
        e: Optional[BaseException] = exc
        while e is not None and id(e) not in seen:
            seen.add(id(e))
            if isinstance(e, OSError) and e.errno in self._SPACE_ERRNOS:
                with self._lock:
                    self._last_enospc = time.monotonic()
                    self._last_probe = 0.0      # re-evaluate now
                return True
            e = e.__cause__ or e.__context__
        return False

    def _probe(self) -> Optional[str]:
        if self.floor_mb <= 0:
            return None
        floor = self.floor_mb * (1 << 20)
        for path in self.paths:
            p = path
            while p and not os.path.isdir(p):
                p = os.path.dirname(p)
            try:
                st = os.statvfs(p or ".")
            except OSError:
                continue
            free = st.f_bavail * st.f_frsize
            if free < floor:
                return (f"low disk under {path!r}: "
                        f"{free // (1 << 20)} MB free < "
                        f"{self.floor_mb} MB floor")
        return None

    def check(self) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            if now - self._last_probe < self._CACHE_S:
                return self._reason
            self._last_probe = now
            held = now - self._last_enospc < self._ENOSPC_HOLD
        reason = self._probe()
        if reason is None and held:
            reason = "recent ENOSPC on a session path"
        with self._lock:
            if reason and not self._reason:
                self.trips += 1
            self._reason = reason
        return reason

    @property
    def degraded(self) -> bool:
        return self.check() is not None

    def snapshot(self) -> dict:
        return {"floor_mb": self.floor_mb, "reason": self.check(),
                "trips": self.trips}
