"""``python -m gpu_mapreduce_tpu.serve`` — run the daemon standalone.

Prints one JSON line (``{"serving": <port>, ...}``) once the listener
is up, then blocks until ``POST /v1/shutdown`` (or ``mrctl shutdown``)
stops it.  SIGTERM drains and exits cleanly; ``kill -9`` is the case
the journal exists for (doc/serve.md#recovery).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gpu_mapreduce_tpu.serve",
        description="MR-as-a-service daemon (doc/serve.md)")
    p.add_argument("--port", type=int, default=None,
                   help="listen port (default MRTPU_SERVE_PORT or 0 "
                        "= ephemeral; the bound port lands in "
                        "<state>/serve.json)")
    p.add_argument("--workers", type=int, default=None,
                   help="worker pool size (default MRTPU_SERVE_WORKERS "
                        "or 2)")
    p.add_argument("--queue", type=int, default=None,
                   help="admission queue capacity (default "
                        "MRTPU_SERVE_QUEUE or 16)")
    p.add_argument("--state", default=None,
                   help="state directory: journal, sessions, results "
                        "(default MRTPU_SERVE_STATE or ./mrtpu-serve)")
    p.add_argument("--mesh", type=int, default=0,
                   help="build an N-device mesh at start (0 = serial "
                        "backend)")
    p.add_argument("--paused", action="store_true",
                   help="admit + journal but do not execute "
                        "(maintenance staging)")
    p.add_argument("--fleet", default=None, metavar="DIR",
                   help="join the replica fleet rooted at DIR "
                        "(default MRTPU_FLEET_DIR; doc/serve.md)")
    p.add_argument("--replica-id", default=None, metavar="RID",
                   help="stable replica id within the fleet "
                        "(default MRTPU_FLEET_ID or r<pid>)")
    p.add_argument("--heartbeat", type=float, default=None,
                   metavar="SECS", help="fleet lease heartbeat "
                   "interval (default MRTPU_FLEET_HEARTBEAT)")
    p.add_argument("--lease", type=float, default=None, metavar="SECS",
                   help="fleet lease TTL (default MRTPU_FLEET_LEASE)")
    p.add_argument("--router", action="store_true",
                   help="run the fleet ROUTER instead of a replica "
                        "(requires --fleet; serve/router.py)")
    args = p.parse_args(argv)

    if args.router:
        if not args.fleet:
            p.error("--router requires --fleet DIR")
        from .router import Router
        rt = Router(args.fleet, port=args.port)
        port = rt.start()
        print(json.dumps({"serving": port, "router": True,
                          "fleet": args.fleet}), flush=True)
        stop = [False]

        def _term_r(signum, frame):
            stop[0] = True

        signal.signal(signal.SIGTERM, _term_r)
        try:
            import time as _time
            while not stop[0]:
                _time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        rt.stop()
        return 0

    comm = None
    if args.mesh > 0:
        from ..parallel.mesh import make_mesh
        comm = make_mesh(args.mesh)

    from .daemon import Server
    srv = Server(port=args.port, workers=args.workers,
                 queue_cap=args.queue, state_dir=args.state,
                 comm=comm, paused=args.paused or None,
                 fleet_dir=args.fleet, replica_id=args.replica_id,
                 heartbeat_s=args.heartbeat, lease_s=args.lease)
    port = srv.start()
    print(json.dumps({"serving": port, "state": srv.state_dir,
                      "workers": srv.nworkers, "paused": srv.paused,
                      "rid": srv.rid, "fleet": srv.fleet_dir}),
          flush=True)

    def _term(signum, frame):
        srv.shutdown()

    signal.signal(signal.SIGTERM, _term)
    try:
        while not srv.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
