"""Purpose-built Pallas kernels (TPU twins of the CUDA hot paths).

Modules: :mod:`.match` (substring mark / compaction — the InvertedIndex
GPU kernels), :mod:`.group` (paged segment-group + fused segment-reduce
— the grouping hot path the plan/ megafused programs compose instead of
a full ``lax.sort``).

Kernel-launch accounting: every *eager* ``pallas_call`` invocation is a
compiled-program launch exactly like a jit dispatch, so it must land in
``Counters.ndispatch`` — otherwise "N dispatches per pipeline" could be
faked by moving work into uncounted kernels (doc/perf.md).  Call sites
route through :func:`note_kernel_launch`; launches traced *inside* an
enclosing jit program ride that program's dispatch count (the whole
point of megafusion) and are skipped via the tracer check.
"""

from __future__ import annotations


def note_kernel_launch(*operands) -> None:
    """Count one eager ``pallas_call`` launch in ``Counters.ndispatch``.

    No-op when any operand is a tracer: the launch is then part of an
    enclosing jit program whose dispatch the caller already counted
    (``bump_dispatch`` at its call site), so counting here would
    double-bill the same executable."""
    import jax.core
    if any(isinstance(o, jax.core.Tracer) for o in operands):
        return
    from ...core.runtime import bump_dispatch
    bump_dispatch()
