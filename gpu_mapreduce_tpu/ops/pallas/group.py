"""Paged Pallas segment-group / segment-reduce kernels (ROADMAP item 3).

The grouping hot path pays a FULL per-shard sort today: convert (and the
plan/ fused group bodies) run ``jnp.lexsort`` over every received row
just to find group boundaries, then segment ops reduce them — O(n log²n)
bitonic work for what is semantically a hash-aggregate.  Ragged Paged
Attention (PAPERS.md) makes the case that purpose-built Pallas kernels
beat generic XLA lowering on exactly this ragged/segmented shape; this
module applies that to grouping:

* **paged segment-group kernel** (:func:`segment_table`): a bucketed
  scatter of interned-u64 (or any ≤8-byte integer) keys into an
  open-addressed accumulation table — one linear pass over the rows in
  page-sized tiles honoring the core page budget (``Settings.memsize``,
  the same budget that sizes dataset frames), each page one
  ``pallas_call`` over VMEM-resident refs.  No row sort ever runs.
* **fused segment-reduce** (the ``with_sum`` variant): the same pass
  accumulates the value column next to the key as two u32 limbs with
  explicit carry, so integer sums are exact mod 2⁶⁴ — byte-identical to
  the eager ``segment_sum`` (which wraps the same way at the value
  dtype's width).  Float sums are order-sensitive and stay on the sort
  path (``group_supported``).

The table epilogue (``ops/segment.table_to_groups``) then orders ONLY
the table slots — O(T) = O(groups), not O(rows) — so the sorted-unique-
key output layout is bit-identical to the sort path's by construction:
eager grouping emits ascending unique keys with zero-fill, and so does
a slot sort.  Overflow (more distinct keys than table slots) and
per-row probe exhaustion are counted into a trash slot the caller
validates host-side — the megafused executor (plan/fuser.py) re-runs
the sort path when the count is nonzero, so a bad capacity guess can
never drop a group.

64-bit values never enter the kernel: keys and sums travel as u32
hi/lo limb pairs (TPU VPUs have no native 64-bit lanes — the same
constraint that shaped ``match.py``'s word-packed kernels).  The
``interpret=True`` path is the tested one on this CPU-only container
(tier-1 and the fake mesh run it for real); the Mosaic lowering of the
scalar probe loop is untested until a TPU returns and is gated off by
simply flipping ``MRTPU_PALLAS_GROUP=0`` (doc/perf.md has the fallback
matrix).
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...utils.env import env_flag, env_str
from . import note_kernel_launch

# multiplicative-hash constants (Fibonacci / murmur3 finalizer mixers)
_GOLD1 = np.uint32(0x9E3779B1)
_GOLD2 = np.uint32(0x85EBCA6B)

# trace-size bound: one program embeds at most this many page calls
MAX_PAGES = 32


def pallas_group_enabled() -> bool:
    """``MRTPU_PALLAS_GROUP``: route supported fused group chains
    through the table kernels instead of the per-shard sort.

    Default ``auto`` = on exactly where the kernels compile natively
    (the TPU backend).  On CPU the kernels only exist in interpret
    mode — a correctness/test vehicle that trades the sort for a
    sequential emulated scatter and loses badly on wall — so auto
    keeps the sort path and ``1`` forces the kernels (what the unit
    goldens and the soak/bench A/Bs do).  Read at call time like
    ``MRTPU_WIRE``; the resolved flag is threaded into every builder
    cache key."""
    raw = env_str("MRTPU_PALLAS_GROUP", "auto")
    if raw == "auto":
        import jax
        return jax.default_backend() == "tpu"
    return env_flag("MRTPU_PALLAS_GROUP", False)


def group_supported(key, value, out_kind: str, reduce_op) -> tuple:
    """(ok, reason) — which fused group chains the table kernels cover.
    ``reason`` feeds the warn-once fallback (doc/perf.md fallback
    matrix); unsupported chains stay on the sort path, still fused."""
    if out_kind != "kv":
        return False, ("grouped KMV layout needs the full row "
                       "permutation (values stay with their groups)")
    if reduce_op not in ("count", "sum"):
        return False, (f"reduce op {reduce_op!r} is not "
                       f"table-accumulable (only count/sum)")
    if key.ndim != 1 or key.dtype.kind not in "iu" \
            or key.dtype.itemsize > 8:
        return False, "keys are not a 1-D <=8-byte integer column"
    if reduce_op == "sum" and (value.ndim != 1
                               or value.dtype.kind not in "iu"
                               or value.dtype.itemsize > 8):
        return False, ("sum needs a 1-D integer value column — float "
                       "sums are order-sensitive and would drift from "
                       "the sorted segment_sum")
    return True, ""


_WARNED: set = set()


def warn_fallback(reason: str) -> None:
    """One warning per distinct fallback reason per process — the
    'warn once, correct output' contract: the sort path runs instead."""
    if reason in _WARNED:
        return
    _WARNED.add(reason)
    warnings.warn(
        f"MRTPU_PALLAS_GROUP: group kernels falling back to the "
        f"sort path ({reason})", stacklevel=3)


def page_rows_for(cap: int, memsize_mb: int, rowbytes: int = 16) -> int:
    """Rows per kernel page: the largest power of two whose page
    (key+value limbs, ``rowbytes``/row) fits the core ``memsize`` frame
    budget, clamped to [256, 1M] and raised so one program never embeds
    more than :data:`MAX_PAGES` page calls (trace-size bound)."""
    budget = max(1, (int(memsize_mb) << 20) // max(rowbytes, 1))
    page = 1 << max(8, budget.bit_length() - 1)
    page = min(page, 1 << 20)
    min_page = -(-max(cap, 1) // MAX_PAGES)
    while page < min_page:
        page <<= 1
    return page


def table_slots(gcap: int) -> int:
    """Open-addressing table size for an expected group capacity: the
    next power of two at ≤50% load, so probe chains stay short and a
    ~2× group-count miss still fits (overflow is detected, not UB)."""
    g = max(int(gcap), 8)
    t = 1
    while t < g:
        t <<= 1
    return 2 * t


# ---------------------------------------------------------------------------
# 64-bit <-> u32 limb views (the TPU-lane-width contract, see module doc)
# ---------------------------------------------------------------------------

def split_limbs(col):
    """Integer column [n] → (hi, lo) uint32 limb views of its 64-bit
    widening (sign-extended for signed dtypes, so truncating the limbs
    back is exact)."""
    w = col
    if w.dtype.itemsize < 8:
        w = w.astype(jnp.int64 if w.dtype.kind == "i" else jnp.uint64)
    words = lax.bitcast_convert_type(w, jnp.uint32)   # [n, 2] LE
    return words[..., 1], words[..., 0]


def join_limbs(hi, lo, dtype):
    """(hi, lo) u32 limbs → values in ``dtype`` (exact inverse of
    :func:`split_limbs` for values that fit; sums truncate with the
    same mod-2^width wrap the eager ``segment_sum`` has)."""
    u = (hi.astype(jnp.uint64) << np.uint64(32)) | lo.astype(jnp.uint64)
    dt = jnp.dtype(dtype)
    if dt.kind == "u":
        return u.astype(dt)
    return lax.bitcast_convert_type(u, jnp.int64).astype(dt)


# ---------------------------------------------------------------------------
# the table kernel (one page per pallas_call)
# ---------------------------------------------------------------------------

def _seg_table_kernel(T: int, page_rows: int, base: int, with_sum: bool,
                      *refs):
    """Insert one page of rows into the accumulation table.

    Layout: slots [0, T) are the live table, slot T absorbs invalid
    (past-``nvalid``) rows, slot T+1 counts probe-exhausted rows (the
    overflow evidence the host validates).  The table rides page to
    page as plain input→output arrays (copied at page entry; an
    ``input_output_aliases`` zero-copy variant is a TPU follow-up)."""
    if with_sum:
        (kh_ref, kl_ref, vh_ref, vl_ref, nv_ref,
         itkh, itkl, iocc, icnt, ishi, islo,
         tkh, tkl, occ, cnt, shi, slo) = refs
    else:
        (kh_ref, kl_ref, nv_ref, itkh, itkl, iocc, icnt,
         tkh, tkl, occ, cnt) = refs
    tkh[:] = itkh[:]
    tkl[:] = itkl[:]
    occ[:] = iocc[:]
    cnt[:] = icnt[:]
    if with_sum:
        shi[:] = ishi[:]
        slo[:] = islo[:]
    nvalid = nv_ref[0]

    def insert(i, carry):
        valid = (base + i) < nvalid
        kh = kh_ref[i]
        kl = kl_ref[i]
        h = (kl ^ (kh * _GOLD1)) * _GOLD2
        slot0 = (h & np.uint32(T - 1)).astype(jnp.int32)

        def probing(c):
            _s, steps, done = c
            return jnp.logical_and(~done, steps < T)

        def probe(c):
            s, steps, done = c
            o = occ[s]
            hit = (o == 1) & (tkh[s] == kh) & (tkl[s] == kl)
            done2 = hit | (o == 0)
            return (jnp.where(done2, s, (s + 1) & (T - 1)),
                    steps + 1, done2)

        slot, _steps, done = lax.while_loop(
            probing, probe, (slot0, jnp.int32(0), jnp.bool_(False)))
        # found/empty → the slot; probe-exhausted → overflow slot T+1;
        # invalid (padding) rows → trash slot T
        tgt = jnp.where(valid & done, slot,
                        jnp.where(valid, jnp.int32(T + 1), jnp.int32(T)))
        occ[tgt] = jnp.int32(1)
        tkh[tgt] = kh
        tkl[tgt] = kl
        cnt[tgt] = cnt[tgt] + 1
        if with_sum:
            vl = vl_ref[i]
            nlo = slo[tgt] + vl
            slo[tgt] = nlo
            # explicit carry: exact two's-complement 64-bit accumulate
            shi[tgt] = shi[tgt] + vh_ref[i] + (nlo < vl).astype(jnp.uint32)
        return carry

    lax.fori_loop(0, page_rows, insert, 0)


def segment_table(key, value, nvalid, T: int, page_rows: int,
                  with_sum: bool, interpret: bool):
    """Run the paged table kernel over a shard's rows.

    ``key``/``value`` are the shard-local columns ([cap] rows, rows at
    index ≥ ``nvalid`` ignored); returns the table arrays
    ``(tkh, tkl, occ, cnt[, shi, slo])`` of length T+2 (see kernel doc
    for the two trailing trash/overflow slots).  Jit-composable: under
    a trace the page calls ride the enclosing program; called eagerly,
    every page counts one kernel launch in ``Counters.ndispatch``."""
    from jax.experimental import pallas as pl
    cap = key.shape[0]
    kh, kl = split_limbs(key)
    cols = [kh, kl]
    if with_sum:
        vh, vl = split_limbs(value)
        cols += [vh, vl]
    npages = max(1, -(-cap // page_rows))
    pad = npages * page_rows - cap
    if pad:
        cols = [jnp.concatenate([c, jnp.zeros(pad, jnp.uint32)])
                for c in cols]
    nv = jnp.reshape(nvalid, ()).astype(jnp.int32)[None]
    dtypes = (jnp.uint32, jnp.uint32, jnp.int32, jnp.int32) \
        + ((jnp.uint32, jnp.uint32) if with_sum else ())
    table = [jnp.zeros(T + 2, d) for d in dtypes]
    shapes = [jax.ShapeDtypeStruct((T + 2,), d) for d in dtypes]
    for p in range(npages):
        s = slice(p * page_rows, (p + 1) * page_rows)
        page_cols = [c[s] for c in cols]
        note_kernel_launch(*page_cols, *table)
        table = list(pl.pallas_call(
            functools.partial(_seg_table_kernel, T, page_rows,
                              p * page_rows, with_sum),
            out_shape=shapes,
            interpret=interpret,
        )(*page_cols, nv, *table))
    return tuple(table)


def segment_group_reduce(key, value, nrecv, gcap: int, reduce_op: str,
                         cfg: tuple):
    """The kernel-backed fused group(+reduce) shard body: bucketed
    table scatter + slot-ordered extraction → ``(ukey, uval, g,
    overflow)`` with ``ukey``/``uval`` in the exact layout the sort
    path emits (ascending unique keys, zero fill past the shard's
    group count).  ``cfg`` is the hashable ("tbl", T, page_rows,
    interpret) tuple the builder caches key on (plan/fuser)."""
    from ..segment import table_to_groups
    _tag, T, page_rows, interpret = cfg
    if T < gcap:
        raise ValueError(f"table T={T} smaller than group cap {gcap}")
    with_sum = reduce_op == "sum"
    table = segment_table(key, value, nrecv, T, page_rows, with_sum,
                          interpret)
    return table_to_groups(table, T, gcap, reduce_op, key.dtype,
                           value.dtype)
