"""Pallas substring matching — the TPU twin of the CUDA ``mark`` kernel.

The reference marks every occurrence of ``<a href="`` in an HTML buffer with
a 0/1 segmask via a 9-char stencil compare on the GPU
(``cuda/InvertedIndex.cu:79-107``), then compacts the mask with Thrust
(``:321-362``) and scans each hit forward to the closing quote
(``compute_url_length``, ``:109-135``).

TPU re-design: the byte buffer is laid out ``[rows, 128]`` (one byte per
lane, widened to int32 in VMEM — the VPU has no sub-word lanes).  For each
pattern offset j the shifted view ``x[i+j]`` is assembled from two
``pltpu.roll``s (same-row lane roll + next-row carry), and the stencil
compare ANDs across offsets.  One kernel pass over the buffer produces the
match mask; compaction and length-scan stay in XLA (`jnp.nonzero` /
windowed gather), where fusion already does the right thing.

``mark_xla`` is the compiler-twin used for CPU tests and as a fallback —
bit-identical output by construction.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

LANES = 128
BLOCK_ROWS = 256  # 32 KB of bytes per grid step


def _i32(x: int):
    """Index-map constants must stay i32: under jax_enable_x64 a bare python
    int traces as i64, which Mosaic refuses to return from an index map."""
    return np.int32(x)


def _pad_to(buf: jnp.ndarray, mult: int) -> jnp.ndarray:
    n = buf.shape[0]
    pad = (-n) % mult
    if pad:
        buf = jnp.concatenate([buf, jnp.zeros(pad, buf.dtype)])
    return buf


def mark_xla(buf, pattern: bytes):
    """Reference implementation: mask[i]=1 iff pattern starts at byte i.
    Nine shifted compares; XLA fuses them into one elementwise pass."""
    n = buf.shape[0]
    acc = jnp.ones(n, dtype=bool)
    for j, p in enumerate(pattern):
        shifted = jnp.concatenate(
            [buf[j:], jnp.zeros(j, buf.dtype)]) if j else buf
        acc = acc & (shifted == np.uint8(p))
    return acc


def _mark_kernel(pattern: bytes, buf_ref, nxt_ref, mask_ref):
    x = buf_ref[:].astype(jnp.int32)                  # [BR, 128]
    nxt = nxt_ref[0:1].astype(jnp.int32)              # next block's first row
    # next-row view of x (row r+1; last row fed by the next block's head)
    from jax.experimental.pallas import tpu as pltpu
    # pltpu.roll requires non-negative shifts: roll by (size - j) ≡ roll by -j
    # (shifts as np.int32 — x64 mode would make a weak i64 that mosaic rejects)
    xr = pltpu.roll(x, np.int32(x.shape[0] - 1), axis=0)
    xr = jnp.where(jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
                   == x.shape[0] - 1, nxt, xr)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    acc = jnp.ones(x.shape, dtype=jnp.bool_)
    for j, p in enumerate(pattern):
        if j == 0:
            shifted = x
        else:
            a = pltpu.roll(x, np.int32(LANES - j), axis=1)   # x[r, c+j mod 128]
            b = pltpu.roll(xr, np.int32(LANES - j), axis=1)  # x[r+1, c+j mod 128]
            shifted = jnp.where(lane < LANES - j, a, b)
        acc = acc & (shifted == p)
    mask_ref[:] = acc.astype(jnp.int8)


def mark_pallas(buf, pattern: bytes, interpret: bool = False):
    """Pallas mark kernel over a uint8 buffer [n] → int8 mask [n]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = buf.shape[0]
    blk = BLOCK_ROWS * LANES
    buf_p = _pad_to(buf, blk)
    rows = buf_p.shape[0] // LANES
    grid = rows // BLOCK_ROWS
    # one extra zero block so the "next block head" index map stays in range
    buf_2d = jnp.concatenate(
        [buf_p.reshape(rows, LANES),
         jnp.zeros((BLOCK_ROWS, LANES), buf_p.dtype)])
    out = pl.pallas_call(
        functools.partial(_mark_kernel, pattern),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, _i32(0)),
                         memory_space=pltpu.VMEM),
            # 8-row block (TPU min sublane tile); kernel uses its first row
            pl.BlockSpec((8, LANES),
                         lambda i: ((i + _i32(1)) * _i32(BLOCK_ROWS // 8),
                                    _i32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, _i32(0)),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(buf_2d, buf_2d)
    return out.reshape(-1)[:n]


def compact_matches(mask, max_hits: int):
    """Mask → sorted start offsets [max_hits] (fill = len(mask)) + count.
    The Thrust sequence/count/copy_if stage (cuda/InvertedIndex.cu:321-362)
    collapses to one jnp.nonzero."""
    n = mask.shape[0]
    idx = jnp.nonzero(mask.astype(bool), size=max_hits, fill_value=n)[0]
    return idx, jnp.sum(mask.astype(jnp.int32))


def url_lengths(buf, starts, terminator: int, max_len: int):
    """For each start offset, distance to the terminator byte (the
    compute_url_length kernel, cuda/InvertedIndex.cu:109-135).

    Returns lengths [k] (-1 if no terminator within max_len — the reference
    would run off the buffer; we flag and let the caller drop) and the
    gathered windows [k, max_len].  A length of 0 is a real empty URL
    (``href=""``), distinct from the no-terminator case."""
    n = buf.shape[0]
    pos = starts[:, None] + jnp.arange(max_len)[None, :]
    windows = jnp.take(buf, jnp.minimum(pos, n - 1), axis=0)
    windows = jnp.where(pos < n, windows, 0)
    hit = windows == np.uint8(terminator)
    any_hit = jnp.any(hit, axis=1)
    length = jnp.where(any_hit, jnp.argmax(hit, axis=1), -1)
    return length.astype(jnp.int32), windows


